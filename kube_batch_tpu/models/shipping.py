"""Host->device shipping of SolverInputs: packed full ships + dirty-row
delta updates against a device-resident buffer.

The TPU tunnel charges a fixed latency per host->device transfer (measured
~6-60 ms), so shipping SolverInputs' ~30 arrays individually dominates the
session. ``ship_inputs`` packs all leaves into one flat byte buffer,
performs ONE transfer, and reconstructs the pytree on device inside one
jitted unpack call — a single dispatch regardless of leaf count.  The
unpack program is compiled once per padded-bucket layout.

``DeviceResidentShipper`` is the steady-state form (doc/PIPELINE.md): the
flat buffer stays device-resident across sessions, and each cycle ships
only the 512-byte blocks whose contents changed — in the steady protocol
(~1% churn) that is the node rows the informer echo touched, the shifted
task rows of churned jobs, and the fairness vectors, a small fraction of
the buffer.  The update is scattered into the DONATED previous buffer
(no reallocation) and re-unpacked on device.  A layout change (bucket,
dtype, leaf spec) or a solver-config key change falls back to a full
ship.  Delta-shipped inputs are bit-identical to a fresh full ship by
construction: dirty blocks are detected by comparing against the exact
bytes previously shipped (tests/test_pipeline.py pins this).

When ``ops.solver.choose_solver_mesh`` routes the solve to the node-
sharded mesh engine, the shipper switches to the SHARDED resident layout
(doc/SHARDING.md): node-major leaves are regrouped per mesh device —
each device's buffer row holds ITS contiguous node rows of every
node-major leaf, leaf-padded to 512-byte block boundaries, with the
[S, N] signature leaves stored transposed (node-major) so one dirty
node touches O(1) blocks — and placed with a ``NamedSharding`` over the
mesh's node axis; the replicated remainder (task/job/queue/cluster
leaves) broadcasts once.  Dirty-block detection and the donated scatter
then run PER SHARD: a churn cycle ships bytes only to the devices whose
node rows changed (clean shards receive nothing and their resident
buffers stay put), the unpacked leaves come back carrying exactly the
shardings ``parallel.sharded_solver`` declares (no implicit reshard
between consecutive sharded solves), and the clean⇒byte-identical
``generation`` contract is unchanged, so the incremental engine's
solve-result reuse works on the mesh as-is
(tests/test_shard_ship.py pins delta ≡ full bit-parity per leaf).
"""

from __future__ import annotations

import functools
import warnings

import jax
import jax.numpy as jnp
import numpy as np

from .. import knobs
from ..metrics import memledger
from ..ops.compile_cache import bucket
from ..ops.solver import SolverInputs

# Dirty-detection granularity.  Smaller blocks ship fewer clean bytes but
# lengthen the scatter index; 512 B holds 64 int64 words — a handful of
# node/task rows — and keeps the block count of a kubemark-scale buffer
# (~10 MB) at ~20k, so the host compare is one vectorized pass.
_BLOCK = 512
# Beyond this dirty fraction a full ship moves fewer total bytes than
# blocks + index + scatter.
_DELTA_MAX_FRACTION = 0.5
# Escape hatch for A/B measurement and field debugging: =0 disables the
# device-resident path entirely (every session full-ships, no state kept).
DELTA_SHIP_ENV = knobs.DELTA_SHIP.env


def _kind_of(dtype: np.dtype) -> str:
    if dtype == np.bool_:
        return "b"
    if dtype.kind in "iu":
        return "i"
    return "f"


def _unpack_body(spec, float_dtype, flat_u8):
    """Slice each leaf's byte range out of the one shipped buffer and
    bitcast it back to its dtype on device."""
    leaves = []
    for kind, byte_off, size, shape in spec:
        if kind == "b":
            seg = jax.lax.dynamic_slice(flat_u8, (byte_off,), (size,))
            leaves.append((seg != 0).reshape(shape))
            continue
        width = 4 if kind == "i" else np.dtype(float_dtype).itemsize
        seg = jax.lax.dynamic_slice(flat_u8, (byte_off,), (size * width,))
        seg = jax.lax.bitcast_convert_type(
            seg.reshape(size, width),
            jnp.int32 if kind == "i" else float_dtype)
        leaves.append(seg.reshape(shape))
    return leaves


_unpack = functools.partial(jax.jit, static_argnums=(0, 1))(_unpack_body)


@functools.partial(jax.jit, static_argnums=(0, 1))
def _unpack_blocks(spec, float_dtype, flat2d):
    """Unpack from the shipper's block-major resident buffer."""
    return _unpack_body(spec, float_dtype, flat2d.reshape(-1))


@functools.partial(jax.jit, donate_argnums=(0,))
def _scatter_blocks(flat2d, idx, blocks):
    """Overwrite the dirty blocks of the DONATED resident buffer in place
    (duplicate padding indices carry identical rows, so last-write-wins
    is value-deterministic)."""
    return flat2d.at[idx].set(blocks)


@functools.partial(jax.jit, donate_argnums=(0,))
def _scatter_shard(shard_blk, idx, blocks):
    """Overwrite the dirty blocks of ONE mesh device's [1, B, _BLOCK]
    resident node-shard in place (donated; same padding contract as
    _scatter_blocks).  Runs per dirty device only — clean shards are
    never touched, which is the per-shard O(dirty-blocks) steady-state
    contract (doc/SHARDING.md)."""
    return shard_blk.at[0, idx].set(blocks)


# ---------------------------------------------------------------------------
# Sharded resident layout (doc/SHARDING.md): node-major leaves regrouped
# per mesh device, replicated remainder packed exactly like _pack_host.
# ---------------------------------------------------------------------------

# SolverInputs leaves with a LEADING node axis: each device's buffer row
# carries its contiguous node rows of these.
_NODE_FIELDS = frozenset({
    "node_idle", "node_releasing", "node_used", "node_alloc",
    "node_count", "node_max_tasks", "node_exists", "node_ports",
    "node_selcnt", "node_coords"})
# [S, N] leaves (TRAILING node axis): stored transposed per shard
# (node-major, [n_local, S]) so a dirty node row touches O(S bytes), not
# one block per signature row; the device unpack transposes back.
_SIG_FIELDS = frozenset({"sig_mask", "sig_bonus"})


def _pack_host_sharded(inp, float_dtype, n_dev: int):
    """Stage ``inp`` for the mesh-sharded resident layout.

    Returns (spec_rep, spec_shard, rep_pos, node_pos, rep_flat,
    shard_flat, treedef): ``rep_flat`` is the replicated region's bytes
    (same packing discipline as _pack_host, block-padded); ``shard_flat``
    is [n_dev, shard_bytes] — row *s* holds device *s*'s node rows of
    every node-major leaf, each leaf zero-padded to a _BLOCK boundary so
    leaf offsets are shard-uniform.  ``spec_shard`` rows are
    (kind, local_byte_off, local_size, packed_local_shape, is_sig);
    ``rep_pos``/``node_pos`` map each region's leaves back to their
    SolverInputs flatten positions."""
    from ..ops.solver import SolverInputs as _SI

    fwidth = np.dtype(float_dtype).itemsize
    leaves, treedef = jax.tree.flatten(inp)
    fields = _SI._fields  # NamedTuple flatten order == field order
    n_total = int(np.asarray(inp.node_idle).shape[0])
    n_local = n_total // n_dev

    rep_spec, rep_bufs, rep_off = [], [], 0
    shard_spec = []
    shard_parts = [[] for _ in range(n_dev)]
    local_off = 0
    rep_pos, node_pos = [], []
    for i, (name, leaf) in enumerate(zip(fields, leaves)):
        arr = np.asarray(leaf)
        kind = _kind_of(arr.dtype)
        if kind == "f":
            arr = arr.astype(float_dtype, copy=False)
            width = fwidth
        elif kind == "i":
            arr = arr.astype(np.int32, copy=False)
            width = 4
        else:
            arr = arr.astype(np.uint8, copy=False)
            width = 1
        if name in _NODE_FIELDS or name in _SIG_FIELDS:
            node_pos.append(i)
            sig = name in _SIG_FIELDS
            if sig:
                lshape = (n_local, arr.shape[0])  # packed node-major
            else:
                lshape = (n_local,) + arr.shape[1:]
            lsize = 1
            for d in lshape:
                lsize *= int(d)
            shard_spec.append((kind, local_off, lsize, tuple(lshape), sig))
            seg = lsize * width
            pad = (-seg) % _BLOCK
            for s in range(n_dev):
                sl = slice(s * n_local, (s + 1) * n_local)
                piece = arr[:, sl].T if sig else arr[sl]
                flat = np.ascontiguousarray(piece).reshape(-1)
                flat = flat.view(np.uint8)
                if pad:
                    flat = np.concatenate(
                        [flat, np.zeros(pad, np.uint8)])
                shard_parts[s].append(flat)
            local_off += seg + pad
        else:
            rep_pos.append(i)
            flat = np.ravel(arr)
            rep_spec.append((kind, rep_off, flat.size, arr.shape))
            rep_bufs.append(flat.view(np.uint8))
            rep_off += flat.size * width
    if rep_off % _BLOCK:
        rep_bufs.append(np.zeros(_BLOCK - rep_off % _BLOCK, np.uint8))
    rep_flat = np.concatenate(rep_bufs)
    shard_flat = np.stack([np.concatenate(parts) for parts in shard_parts])
    return (tuple(rep_spec), tuple(shard_spec), tuple(rep_pos),
            tuple(node_pos), rep_flat, shard_flat, treedef)


@functools.partial(jax.jit, static_argnums=(0, 1, 2, 3))
def _unpack_sharded(spec_rep, spec_shard, float_dtype, mesh, rep2d,
                    shard3d):
    """Reconstruct SolverInputs leaves from the two resident buffers
    WITHOUT moving node bytes off their owning devices: the replicated
    region unpacks as before (every device holds the same bytes), and
    the node region unpacks under shard_map — each device slices and
    bitcasts only its own [1, B, _BLOCK] shard, and the outputs come
    back carrying exactly the shardings parallel.sharded_solver's
    in_specs declare (node-major split, sig leaves P(None, nodes)), so
    the sharded solve never reshards its inputs."""
    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import NODE_AXIS, shard_map_kwargs

    rep_leaves = _unpack_body(spec_rep, float_dtype, rep2d.reshape(-1))

    def local(blk):
        flat = blk.reshape(-1)
        outs = []
        for kind, off, size, lshape, sig in spec_shard:
            if kind == "b":
                seg = jax.lax.dynamic_slice(flat, (off,), (size,))
                a = (seg != 0).reshape(lshape)
            else:
                width = 4 if kind == "i" else np.dtype(float_dtype).itemsize
                seg = jax.lax.dynamic_slice(flat, (off,), (size * width,))
                a = jax.lax.bitcast_convert_type(
                    seg.reshape(size, width),
                    jnp.int32 if kind == "i" else float_dtype)
                a = a.reshape(lshape)
            outs.append(a.T if sig else a)
        return tuple(outs)

    out_specs = tuple(
        P(None, NODE_AXIS) if sig
        else (P(NODE_AXIS, None) if len(lshape) == 2 else P(NODE_AXIS))
        for _kind, _off, _size, lshape, sig in spec_shard)
    fn = shard_map(local, mesh=mesh,
                   in_specs=(P(NODE_AXIS, None, None),),
                   out_specs=out_specs, **shard_map_kwargs())
    return rep_leaves, list(fn(shard3d))


def _pack_host(inp, float_dtype, pad_to: int = 1, out=None):
    """Flatten every leaf into one host byte buffer with final device
    dtypes applied; returns (spec, flat_u8, treedef).  ``pad_to`` zero-pads
    the tail so the buffer length is a stable multiple (the shipper's
    block layout must not retrace per session).

    ``out`` (wire fast path): a retired host buffer to pack into when
    its length matches, so the steady cycle stops allocating a fresh
    multi-MB flat buffer per ship.  Only buffers that never reached
    ``jnp.asarray`` may be recycled — the CPU PJRT client zero-copies
    aligned numpy arrays, so writing into a device-visible buffer would
    corrupt the resident image.  Enforced by the shipper's
    ``host_recyclable`` bookkeeping: full-ship baselines are stamped
    non-recyclable and only delta/clean-path buffers re-enter
    ``_scratch`` (see _ShipState and _ship_delta)."""
    fwidth = np.dtype(float_dtype).itemsize
    leaves, treedef = jax.tree.flatten(inp)
    spec = []
    bufs = []
    byte_off = 0
    for leaf in leaves:
        arr = np.asarray(leaf)
        kind = _kind_of(arr.dtype)
        if kind == "f":
            arr = arr.astype(float_dtype, copy=False)
            width = fwidth
        elif kind == "i":
            arr = arr.astype(np.int32, copy=False)
            width = 4
        else:
            arr = arr.astype(np.uint8, copy=False)
            width = 1
        flat = np.ravel(arr)
        spec.append((kind, byte_off, flat.size, np.asarray(leaf).shape))
        bufs.append(flat.view(np.uint8))
        byte_off += flat.size * width
    if not bufs:
        bufs.append(np.zeros(1, np.uint8))
        byte_off = 1
    total = byte_off
    if pad_to > 1 and byte_off % pad_to:
        pad = pad_to - byte_off % pad_to
        bufs.append(np.zeros(pad, np.uint8))
        total += pad
    if out is not None and out.nbytes == total:
        off = 0
        for b in bufs:
            out[off:off + b.size] = b
            off += b.size
        return tuple(spec), out, treedef
    return tuple(spec), np.concatenate(bufs), treedef


def _default_float_dtype():
    return (np.float64 if jnp.asarray(np.float64(1.0)).dtype == jnp.float64
            else np.float32)


def ship_inputs(inp: SolverInputs, float_dtype=None) -> SolverInputs:
    """Pack numpy-staged SolverInputs into ONE byte buffer and ship it as
    a single transfer (the tunnel charges fixed latency per transfer;
    one beats three), reconstructing every leaf on device with bitcasts
    inside one jitted unpack call.  Stateless: every call moves the whole
    buffer (DeviceResidentShipper is the steady-state delta form)."""
    if float_dtype is None:
        float_dtype = _default_float_dtype()
    spec, flat_u8, treedef = _pack_host(inp, float_dtype)
    out_leaves = _unpack(spec, float_dtype, jnp.asarray(flat_u8))
    return jax.tree.unflatten(treedef, out_leaves)


class _ShipState:
    """The device-resident image of the last shipped layout.
    ``host_recyclable``: whether host_flat never reached jnp.asarray —
    only such buffers may be recycled as pack scratch (the CPU PJRT
    client zero-copies aligned numpy arrays into device buffers, so a
    device-visible baseline must never be written again)."""
    __slots__ = ("layout", "spec", "treedef", "float_dtype",
                 "host_flat", "device_flat", "inputs", "host_recyclable")


class _ShardShipState:
    """The mesh-sharded resident image: per-device node-shard buffers
    (single-device arrays, scattered into individually so clean shards
    are never touched), the replicated-region buffer (one NamedSharding
    broadcast), and the exact host bytes last shipped per region."""
    __slots__ = ("layout", "spec_rep", "spec_shard", "rep_pos", "node_pos",
                 "treedef", "float_dtype", "mesh", "host_rep", "host_shard",
                 "rep_flat", "shard_arrays", "inputs")


def _buf_nbytes(x) -> int:
    """Bytes of an array (numpy or jax) or a container of arrays; 0 for
    anything else.  Shared by the resident ledger's set-hook and its
    memledger auditor."""
    n = getattr(x, "nbytes", None)
    if n is not None:
        return int(n)
    if isinstance(x, dict):
        return sum(_buf_nbytes(v) for v in x.values())
    if isinstance(x, (list, tuple)):
        return sum(_buf_nbytes(v) for v in x)
    return 0


def _resident_nbytes(sh: "DeviceResidentShipper") -> int:
    """Host + device bytes pinned by the resident image (full or
    sharded) plus the recycled host pack scratch."""
    n = _buf_nbytes(sh._scratch)
    st = sh._state
    if isinstance(st, _ShipState):
        n += _buf_nbytes(st.host_flat) + _buf_nbytes(st.device_flat)
    elif isinstance(st, _ShardShipState):
        n += (_buf_nbytes(st.host_rep) + _buf_nbytes(st.host_shard)
              + _buf_nbytes(st.rep_flat) + _buf_nbytes(st.shard_arrays))
    return n


class DeviceResidentShipper:
    """Delta shipping against a device-resident SolverInputs buffer.

    Memory accounting (metrics/memledger.py):
    # mem-ledger: resident

    Contract (doc/PIPELINE.md "dirty-row invalidation"): the host stages
    the session's tensors exactly as a full ship would (the TensorCache's
    epoch/mutated-set tracking already bounds how much of that staging is
    rebuilt per cycle); the shipper then compares the packed bytes against
    the image it last shipped and moves only the changed blocks.  Full
    re-ship triggers: first session, any layout-key change (padded bucket,
    leaf spec, float dtype — e.g. churn crossing a bucket boundary), any
    solver-config key change, dirty fraction above _DELTA_MAX_FRACTION,
    or the env gate disabling residency.  The returned leaves are
    bit-identical to ``ship_inputs`` of the same staging in every mode.
    """

    def __init__(self):
        self._state: _ShipState | None = None
        # Retired host-only pack buffer (wire fast path): the steady
        # delta cycle packs into it instead of allocating a fresh
        # multi-MB flat per ship; _pack_host's docstring carries the
        # never-device-visible recycling contract.
        self._scratch = None
        self.last_mode: str = ""  # "full" | "delta" | "clean" (tests/obs)
        # Byte-generation of the resident image: moves whenever the
        # shipped bytes change (full or delta ship, or an invalidation)
        # and stays put on a clean ship.  The generation keys the
        # incremental solve-result cache (models/incremental.py): a
        # clean ship at an unchanged generation proves the solver inputs
        # are byte-identical to the previous dispatch, so the
        # deterministic solve result may be reused without a device
        # round-trip (doc/INCREMENTAL.md).
        self.generation: int = 0
        # Owning cache/view identity (resident_shipper's cross-shard
        # aliasing guard); None for throwaway/direct-constructed
        # shippers, which are never shared.
        self._owner_id = None
        self._mem_key = memledger.ledger("resident").track(
            self, sizer=_resident_nbytes)

    def _mem_refresh(self) -> None:
        """Set-hook: re-price the resident ledger (every ship() return
        and invalidate() — the chokepoints where the resident image or
        the pack scratch is rebound)."""
        memledger.ledger("resident").set(self._mem_key,
                                         _resident_nbytes(self))

    def invalidate(self) -> None:
        """Drop the resident image so the next ship is a full one.  The
        degradation paths call this after any device-pipeline failure: a
        ship that died midway (or a device left in an unknown state by an
        injected fault) must not serve as the delta baseline, or the
        bit-parity guarantee silently breaks (doc/CHAOS.md).  Bumps the
        generation: nothing keyed to the dropped image may be reused."""
        self._state = None
        self.generation += 1
        self._mem_refresh()

    def ship(self, inp: SolverInputs, cfg=None,
             float_dtype=None) -> SolverInputs:
        out = self._ship(inp, cfg, float_dtype)
        self._mem_refresh()
        return out

    def _ship(self, inp: SolverInputs, cfg=None,
              float_dtype=None) -> SolverInputs:
        from ..metrics import metrics
        from ..trace import spans as trace

        if float_dtype is None:
            float_dtype = _default_float_dtype()
        if not knobs.DELTA_SHIP.enabled():
            self._state = None  # clean A/B: no stale image survives
            self.generation += 1
            spec, flat, treedef = _pack_host(inp, float_dtype)
            out = jax.tree.unflatten(
                treedef, _unpack(spec, float_dtype, jnp.asarray(flat)))
            self.last_mode = "full"
            metrics.note_ship("full", flat.nbytes)
            trace.note_ship("full", flat.nbytes)
            return out

        # One routing chokepoint (ops/solver.py): when the solve will run
        # node-sharded over the mesh, the resident buffer must live there
        # too — same gates, so the bytes always land pre-sharded exactly
        # where the dispatch reads them.
        from ..ops.solver import choose_solver_mesh
        route, mesh = choose_solver_mesh(inp)
        if route == "sharded":
            return self._ship_sharded(inp, cfg, float_dtype, mesh)

        from ..models.incremental import wire_fast_enabled
        recycle = wire_fast_enabled()
        scratch = None
        if recycle:
            scratch, self._scratch = self._scratch, None
        spec, flat, treedef = _pack_host(inp, float_dtype, pad_to=_BLOCK,
                                         out=scratch)
        layout = (spec, np.dtype(float_dtype).str, cfg)
        st = self._state
        if isinstance(st, _ShipState) and st.layout == layout:
            idx = self._dirty_blocks(st.host_flat, flat)
            if idx.size == 0:
                self.last_mode = "clean"
                if recycle:
                    # flat never reached the device: recycle it (its
                    # bytes equal the resident baseline anyway).  The
                    # control arm keeps the old allocation profile.
                    self._scratch = flat
                metrics.note_ship("clean", 0)
                trace.note_ship("clean", 0)
                return st.inputs
            if idx.size * _BLOCK <= _DELTA_MAX_FRACTION * flat.nbytes:
                return self._ship_delta(st, flat, idx)
        return self._ship_full(layout, spec, treedef, float_dtype, flat)

    @staticmethod
    def _dirty_blocks(old: np.ndarray, new: np.ndarray) -> np.ndarray:
        diff = (old.view(np.int64) != new.view(np.int64))
        return np.nonzero(diff.reshape(-1, _BLOCK // 8).any(axis=1))[0]

    def _ship_full(self, layout, spec, treedef, float_dtype,
                   flat: np.ndarray) -> SolverInputs:
        from ..metrics import metrics
        from ..trace import spans as trace

        st = _ShipState()
        st.layout = layout
        st.spec = spec
        st.treedef = treedef
        st.float_dtype = float_dtype
        # The shipped image: dirty-block detection compares against these
        # exact bytes, so in-place mutation after the ship silently breaks
        # the delta ≡ full-ship bit-parity guarantee.  graftlint flags any
        # in-place write (doc/LINT.md rule 4); rebinding stays legal.
        st.host_flat = flat         # frozen-after: ship
        # jnp.asarray below may ZERO-COPY flat on the CPU PJRT client:
        # this buffer is device-visible and must never re-enter the
        # pack-scratch pool.
        st.host_recyclable = False
        st.device_flat = jnp.asarray(flat.reshape(-1, _BLOCK))
        # The reconstructed SolverInputs leaves are shared with every
        # consumer of this session's solve — same no-mutate contract.
        st.inputs = jax.tree.unflatten(  # frozen-after: ship
            treedef, _unpack_blocks(spec, float_dtype, st.device_flat))
        self._state = st
        self.generation += 1
        self.last_mode = "full"
        metrics.note_ship("full", flat.nbytes)
        trace.note_ship("full", flat.nbytes)
        return st.inputs

    def _ship_delta(self, st: _ShipState, flat: np.ndarray,
                    idx: np.ndarray) -> SolverInputs:
        from ..metrics import metrics
        from ..trace import spans as trace

        k = idx.size
        # Pad the update to a bucketed row count so the scatter compiles
        # once per bucket, not once per distinct dirty count; padding rows
        # repeat the last real row (same index, same bytes — a no-op).
        kb = bucket(k)
        idx_p = np.full((kb,), idx[-1], np.int32)
        idx_p[:k] = idx
        new2d = flat.reshape(-1, _BLOCK)
        upd = np.empty((kb, _BLOCK), np.uint8)
        upd[:k] = new2d[idx]
        upd[k:] = new2d[idx[-1]]
        with warnings.catch_warnings():
            # CPU backends that cannot honor donation warn per call; the
            # fallback (copy) is correct, just not free.
            warnings.simplefilter("ignore")
            st.device_flat = _scatter_blocks(
                st.device_flat, jnp.asarray(idx_p), jnp.asarray(upd))
        # Retire the replaced baseline into the pack-scratch pool when it
        # was host-only (a baseline installed by a FULL ship may be
        # zero-copy-aliased by the device and stays quarantined).  The
        # control arm (WIRE_FAST=0) keeps the old allocation profile.
        from ..models.incremental import wire_fast_enabled
        old_flat = st.host_flat
        if getattr(st, "host_recyclable", False) and wire_fast_enabled():
            self._scratch = old_flat
        st.host_flat = flat
        st.host_recyclable = True  # flat was only compared and sliced
        st.inputs = jax.tree.unflatten(
            st.treedef,
            _unpack_blocks(st.spec, st.float_dtype, st.device_flat))
        self.generation += 1
        self.last_mode = "delta"
        metrics.note_ship("delta", upd.nbytes + idx_p.nbytes)
        trace.note_ship("delta", upd.nbytes + idx_p.nbytes)
        return st.inputs

    # -- mesh-sharded resident layout (doc/SHARDING.md) ---------------------

    def _ship_sharded(self, inp, cfg, float_dtype, mesh) -> SolverInputs:
        from ..metrics import metrics
        from ..trace import spans as trace

        (spec_rep, spec_shard, rep_pos, node_pos, rep_flat, shard_flat,
         treedef) = _pack_host_sharded(inp, float_dtype, mesh.size)
        layout = ("sharded", spec_rep, spec_shard,
                  np.dtype(float_dtype).str, cfg, mesh)
        st = self._state
        if isinstance(st, _ShardShipState) and st.layout == layout:
            rep_idx = self._dirty_blocks(st.host_rep, rep_flat)
            shard_idx = self._dirty_shard_blocks(st.host_shard, shard_flat)
            dirty = int(rep_idx.size) + sum(int(ix.size) for ix in shard_idx)
            if dirty == 0:
                self.last_mode = "clean"
                metrics.note_ship("clean", 0)
                trace.note_ship("clean", 0)
                return st.inputs
            total = rep_flat.nbytes + shard_flat.nbytes
            if dirty * _BLOCK <= _DELTA_MAX_FRACTION * total:
                return self._ship_sharded_delta(st, rep_flat, shard_flat,
                                                rep_idx, shard_idx)
        return self._ship_sharded_full(
            layout, spec_rep, spec_shard, rep_pos, node_pos, treedef,
            float_dtype, mesh, rep_flat, shard_flat)

    @staticmethod
    def _dirty_shard_blocks(old: np.ndarray, new: np.ndarray):
        """Per-shard dirty block indices ([n_dev, shard_bytes] mirrors)."""
        diff = (old.view(np.int64) != new.view(np.int64)).reshape(
            old.shape[0], -1, _BLOCK // 8).any(axis=2)
        return [np.nonzero(diff[s])[0] for s in range(old.shape[0])]

    @staticmethod
    def _pad_update(new2d: np.ndarray, idx: np.ndarray):
        """Bucket one region's dirty-block update (repeat-last padding:
        same index, same bytes — a no-op on device) so the scatter
        compiles per bucket, not per distinct dirty count."""
        k = idx.size
        kb = bucket(k)
        idx_p = np.full((kb,), idx[-1], np.int32)
        idx_p[:k] = idx
        upd = np.empty((kb, _BLOCK), np.uint8)
        upd[:k] = new2d[idx]
        upd[k:] = new2d[idx[-1]]
        return idx_p, upd

    def _assemble_sharded(self, st: "_ShardShipState") -> SolverInputs:
        """Merge the two resident regions back into SolverInputs leaves.
        The per-device shard buffers are stitched into one global array
        (``make_array_from_single_device_arrays`` — metadata only, no
        bytes move) and unpacked under shard_map, so every node leaf
        comes back sharded over the mesh's node axis in place."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..parallel.mesh import NODE_AXIS

        n_dev = st.mesh.size
        b = st.host_shard.shape[1] // _BLOCK
        shard3d = jax.make_array_from_single_device_arrays(
            (n_dev, b, _BLOCK),
            NamedSharding(st.mesh, P(NODE_AXIS, None, None)),
            st.shard_arrays)
        rep_leaves, node_leaves = _unpack_sharded(
            st.spec_rep, st.spec_shard, st.float_dtype, st.mesh,
            st.rep_flat, shard3d)
        leaves = [None] * (len(st.rep_pos) + len(st.node_pos))
        for i, pos in enumerate(st.rep_pos):
            leaves[pos] = rep_leaves[i]
        for i, pos in enumerate(st.node_pos):
            leaves[pos] = node_leaves[i]
        return jax.tree.unflatten(st.treedef, leaves)

    def _ship_sharded_full(self, layout, spec_rep, spec_shard, rep_pos,
                           node_pos, treedef, float_dtype, mesh,
                           rep_flat: np.ndarray,
                           shard_flat: np.ndarray) -> SolverInputs:
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..metrics import metrics
        from ..trace import spans as trace

        st = _ShardShipState()
        st.layout = layout
        st.spec_rep = spec_rep
        st.spec_shard = spec_shard
        st.rep_pos = rep_pos
        st.node_pos = node_pos
        st.treedef = treedef
        st.float_dtype = float_dtype
        st.mesh = mesh
        # Exact shipped bytes per region: the delta baseline, same
        # no-mutate contract as the single-chip image.
        st.host_rep = rep_flat      # frozen-after: ship
        st.host_shard = shard_flat  # frozen-after: ship
        st.rep_flat = jax.device_put(
            rep_flat.reshape(-1, _BLOCK), NamedSharding(mesh, P()))
        n_dev = mesh.size
        blk3 = shard_flat.reshape(n_dev, -1, _BLOCK)
        devices = list(mesh.devices.flat)
        st.shard_arrays = [jax.device_put(blk3[s:s + 1], devices[s])
                           for s in range(n_dev)]
        for s in range(n_dev):
            metrics.note_ship_shard(s, blk3.shape[1] * _BLOCK)
        st.inputs = self._assemble_sharded(st)  # frozen-after: ship
        self._state = st
        self.generation += 1
        self.last_mode = "full"
        nbytes = rep_flat.nbytes + shard_flat.nbytes
        metrics.note_ship("full", nbytes)
        trace.note_ship("full", nbytes)
        return st.inputs

    def _ship_sharded_delta(self, st: "_ShardShipState",
                            rep_flat: np.ndarray, shard_flat: np.ndarray,
                            rep_idx: np.ndarray,
                            shard_idx) -> SolverInputs:
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..metrics import metrics
        from ..trace import spans as trace

        mesh = st.mesh
        nbytes = 0
        with warnings.catch_warnings():
            # CPU backends that cannot honor donation warn per call; the
            # fallback (copy) is correct, just not free.
            warnings.simplefilter("ignore")
            if rep_idx.size:
                # Replicated region: every device patches its replica in
                # place — the small bucketed update broadcasts, the
                # resident buffer itself never moves.
                idx_p, upd = self._pad_update(
                    rep_flat.reshape(-1, _BLOCK), rep_idx)
                rep_sh = NamedSharding(mesh, P())
                st.rep_flat = _scatter_blocks(
                    st.rep_flat, jax.device_put(idx_p, rep_sh),
                    jax.device_put(upd, rep_sh))
                nbytes += upd.nbytes + idx_p.nbytes
            n_dev = mesh.size
            devices = list(mesh.devices.flat)
            new3d = shard_flat.reshape(n_dev, -1, _BLOCK)
            for s in range(n_dev):
                idx = shard_idx[s]
                if idx.size == 0:
                    continue  # clean shard: untouched, zero bytes shipped
                idx_p, upd = self._pad_update(new3d[s], idx)
                buf = st.shard_arrays[s]
                buf = _scatter_shard(buf,
                                     jax.device_put(idx_p, devices[s]),
                                     jax.device_put(upd, devices[s]))
                st.shard_arrays[s] = buf
                shard_bytes = upd.nbytes + idx_p.nbytes
                metrics.note_ship_shard(s, shard_bytes)
                nbytes += shard_bytes
        st.host_rep = rep_flat
        st.host_shard = shard_flat
        st.inputs = self._assemble_sharded(st)
        self.generation += 1
        self.last_mode = "delta"
        metrics.note_ship("delta", nbytes)
        trace.note_ship("delta", nbytes)
        return st.inputs


def dirty_shard_probe(inp: SolverInputs, cfg=None) -> dict:
    """The deterministic per-shard O(dirty-blocks) proof shared by the
    ``make bench-shard`` CI gate and tools/shard_bench.py's multichip
    artifact tail: full-ship ``inp`` through a throwaway resident
    shipper, dirty ONE node row (row 0, owned by shard 0), delta-ship,
    and report which devices the bytes actually reached.  Under the
    sharded route the owning shard receives one bucketed update and
    every clean shard receives ZERO bytes (doc/SHARDING.md)."""
    from ..metrics.metrics import ship_shard_counts
    from ..ops.solver import choose_solver_mesh

    staged = jax.tree.map(np.asarray, inp)
    route, mesh = choose_solver_mesh(staged)
    probe = {"route": route, "mesh_devices": mesh.size if mesh else 1}
    if route != "sharded":
        return probe
    if not knobs.DELTA_SHIP.enabled():
        # Residency disabled (the A/B escape hatch): there is no resident
        # image to delta against — report the misconfiguration instead
        # of crashing on the stateless ship.
        probe["mode"] = "disabled"
        return probe
    shipper = DeviceResidentShipper()
    shipper.ship(staged, cfg)
    probe["full_bytes"] = int(shipper._state.host_rep.nbytes
                              + shipper._state.host_shard.nbytes)
    dirty = staged._replace(node_used=staged.node_used.copy())
    dirty.node_used[0, 0] += 1  # one row, owned by shard 0
    before = ship_shard_counts()
    shipper.ship(dirty, cfg)
    after = ship_shard_counts()
    probe["mode"] = shipper.last_mode
    probe["per_shard_delta_bytes"] = {
        k: after.get(k, 0) - before.get(k, 0) for k in after}
    return probe


def resident_shipper(cache) -> DeviceResidentShipper:
    """The cache's persistent shipper, created on first use; a throwaway
    instance (always full ship) for cache objects that refuse attributes
    — mirroring tensor_snapshot._tensor_cache's persistence gate.

    Cross-shard aliasing guard (doc/TENANCY.md "Concurrent
    micro-sessions"): each tenancy ShardView declares ``_ship_cache``
    as its OWN attachment point, so every shard owns an independent
    resident image — that independence is what lets the concurrent
    pipeline keep several dispatches in flight without their delta
    baselines corrupting each other.  A shipper observed under two
    different owners means a view delegated the attribute to the shared
    cache (or an embedder wired one shipper into two views): that is a
    delta-parity time bomb, so it fails LOUDLY here instead."""
    sh = getattr(cache, "_ship_cache", None)
    if sh is None:
        sh = DeviceResidentShipper()
        try:
            cache._ship_cache = sh
        except AttributeError:
            pass
        else:
            sh._owner_id = id(cache)
    elif sh._owner_id is not None and sh._owner_id != id(cache):
        raise RuntimeError(
            "DeviceResidentShipper aliased across caches/shard-views: "
            "each shard must own its resident image (a shared delta "
            "baseline would silently corrupt bit-parity)")
    return sh
