"""Host->device shipping of SolverInputs.

The TPU tunnel charges a fixed latency per host->device transfer (measured
~6-60 ms), so shipping SolverInputs' ~30 arrays individually dominates the
session. ``ship_inputs`` packs all leaves into three flat host buffers (one
per dtype family), performs three transfers, and reconstructs the pytree on
device inside one jitted unpack call — a single dispatch regardless of leaf
count.  The unpack program is compiled once per padded-bucket layout.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.solver import SolverInputs


def _kind_of(dtype: np.dtype) -> str:
    if dtype == np.bool_:
        return "b"
    if dtype.kind in "iu":
        return "i"
    return "f"


@functools.partial(jax.jit, static_argnums=(0, 1))
def _unpack(spec, float_dtype, flat_u8):
    """Slice each leaf's byte range out of the one shipped buffer and
    bitcast it back to its dtype on device."""
    leaves = []
    for kind, byte_off, size, shape in spec:
        if kind == "b":
            seg = jax.lax.dynamic_slice(flat_u8, (byte_off,), (size,))
            leaves.append((seg != 0).reshape(shape))
            continue
        width = 4 if kind == "i" else np.dtype(float_dtype).itemsize
        seg = jax.lax.dynamic_slice(flat_u8, (byte_off,), (size * width,))
        seg = jax.lax.bitcast_convert_type(
            seg.reshape(size, width),
            jnp.int32 if kind == "i" else float_dtype)
        leaves.append(seg.reshape(shape))
    return leaves


def ship_inputs(inp: SolverInputs, float_dtype=None) -> SolverInputs:
    """Pack numpy-staged SolverInputs into ONE byte buffer and ship it as
    a single transfer (the tunnel charges fixed latency per transfer;
    one beats three), reconstructing every leaf on device with bitcasts
    inside one jitted unpack call."""
    if float_dtype is None:
        float_dtype = np.float64 if jnp.asarray(
            np.float64(1.0)).dtype == jnp.float64 else np.float32
    fwidth = np.dtype(float_dtype).itemsize
    leaves, treedef = jax.tree.flatten(inp)
    spec = []
    bufs = []
    byte_off = 0
    for leaf in leaves:
        arr = np.asarray(leaf)
        kind = _kind_of(arr.dtype)
        if kind == "f":
            arr = arr.astype(float_dtype, copy=False)
            width = fwidth
        elif kind == "i":
            arr = arr.astype(np.int32, copy=False)
            width = 4
        else:
            arr = arr.astype(np.uint8, copy=False)
            width = 1
        flat = np.ravel(arr)
        spec.append((kind, byte_off, flat.size, np.asarray(leaf).shape))
        bufs.append(flat.view(np.uint8))
        byte_off += flat.size * width
    flat_u8 = (np.concatenate(bufs) if bufs
               else np.zeros(1, np.uint8))
    out_leaves = _unpack(tuple(spec), float_dtype, jnp.asarray(flat_u8))
    return jax.tree.unflatten(treedef, out_leaves)
