"""Per-node Running-resident index for the eviction actions.

The reference's preempt/reclaim loops walk every candidate node per
pending task and collect victim candidates by filtering the node's
residents (preempt.go:190-211, reclaim.go:115-138).  On a cluster where
a queue owns nothing (the permanently starved queue the reclaim e2e
scenario models, test/e2e/queue.go:26-70), that walk is O(tasks x nodes
x residents) of guaranteed-empty work.  This index — one pass over the
session's residents — answers "can node X possibly yield a candidate
for filter F?" so the actions skip nodes (and whole walks) that cannot
produce victims.  It is a SUPERSET filter: statement evicts during the
action only remove Running residents, so a node the index rejects has
no candidates under the action's filter, while a node it admits is
still filtered exactly as before — behavior is unchanged, only
provably-empty work is skipped (discard/restore re-adds candidates the
index still counts, keeping the superset property).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..api import TaskStatus


def _rank_victim_columns(node_names: List[str], prio: List[float],
                         ts: List[float], uids: List[str],
                         node_index: Dict[str, int]
                         ) -> Tuple[np.ndarray, np.ndarray, List[str]]:
    """Exact int32 victim-order ranks for the batched eviction dispatch:
    reversed task order — priority ascending, creation-time descending,
    uid descending (preempt.go:213-218 via Session.victims_queue) — via
    one vectorized host lexsort over exact f64/str columns, so device
    float width can never reorder a tie; the device then only groups by
    node (ops/evict_solver.evict_batch_solve)."""
    keep = [i for i, name in enumerate(node_names) if name in node_index]
    m = len(keep)
    if m == 0:
        return np.zeros(0, np.int32), np.zeros(0, np.int32), [], keep
    if m != len(node_names):
        prio = [prio[i] for i in keep]
        ts = [ts[i] for i in keep]
        uids = [uids[i] for i in keep]
        node_names = [node_names[i] for i in keep]
    node_ix = np.asarray([node_index[n] for n in node_names], np.int32)
    prio_a = np.asarray(prio, np.float64)
    ts_a = np.asarray(ts, np.float64)
    order = np.lexsort((-ts_a, prio_a))
    # uid ranks (an O(M log M) string sort) are the tie-break of last
    # resort; compute them only inside actual (priority, ts) tie runs —
    # rare outside adversarial fixtures, so the common storm pays two
    # float lexsort keys and nothing else.
    op, ot = prio_a[order], ts_a[order]
    tie = (op[1:] == op[:-1]) & (ot[1:] == ot[:-1])
    if tie.any():
        order = order.tolist()
        i = 0
        while i < m - 1:
            if not tie[i]:
                i += 1
                continue
            j = i + 1
            while j < m - 1 and tie[j]:
                j += 1
            run = order[i:j + 1]
            run.sort(key=lambda k: uids[k], reverse=True)  # uid descending
            order[i:j + 1] = run
            i = j + 1
        order = np.asarray(order)
    rank = np.empty(m, np.int32)
    rank[order] = np.arange(m, dtype=np.int32)
    return node_ix, rank, uids, keep


class VictimIndex:
    """Counts of Running residents per node, by queue and by job.

    Thread discipline: a VictimIndex belongs to ONE session and is
    mutated only by that session's action thread.  The vectorized
    admissibility matrix is nevertheless ``# guarded-by: _mutex`` so the
    contract is machine-checked (graftlint rule 1, doc/LINT.md): any new
    code path touching the matrix off the documented mutation sites —
    e.g. a /debug reader or a background repair walking live sessions —
    fails ``make lint`` instead of racing silently."""

    @classmethod
    def for_session(cls, ssn):
        """The session's shared index, built on first use.  Sharing is
        exact: within one session only the eviction actions change the
        Running resident set, and every evict/restore path updates the
        index (reclaim.py on_evict; preempt.py on_evict/on_restore) —
        allocate/backfill add Pipelined/Binding residents, which the
        index deliberately does not count.  Reclaim and preempt each
        paid the full O(residents) rebuild per cycle before this."""
        idx = getattr(ssn, "_victim_index", None)
        if idx is None:
            idx = cls(ssn)
            ssn._victim_index = idx
        return idx

    def __init__(self, ssn):
        self.node_queue: Dict[str, Dict[str, int]] = {}
        self.node_job: Dict[str, Dict[str, int]] = {}
        self.node_total: Dict[str, int] = {}
        self.queue_total: Dict[str, int] = {}
        self.job_total: Dict[str, int] = {}
        self.total = 0
        # Vectorized admissibility (attach_nodes): [N, Q] count matrix
        # in the scanner's node order, so a preemptor's whole node walk
        # filters as one numpy mask instead of a per-node lambda.
        self._names = None
        self._row: Dict[str, int] = {}
        self._qcol: Dict[str, int] = {}
        self._mutex = threading.Lock()
        self._mat: Optional[np.ndarray] = None   # guarded-by: _mutex
        self._tot: Optional[np.ndarray] = None   # guarded-by: _mutex
        # Observability (tests + /metrics): how often the matrix was
        # (re)built and how many live evict/restore updates it absorbed.
        self.rebuilds = 0
        self.invalidations = 0
        self.restores = 0
        # Victim-candidate columns for the batched eviction dispatch,
        # collected in the SAME resident walk (a second O(residents)
        # pass cost more than the per-preemptor sorts it replaced).
        # Only under the engine: the sequential control pays nothing.
        from .scanner import batch_evict_enabled
        collect = batch_evict_enabled()
        self._vic_node: List[str] = []
        self._vic_prio: List[float] = []
        self._vic_ts: List[float] = []
        self._vic_uid: List[str] = []
        # Post-eviction leg detail (ops/fused_solver storm half): the
        # victim's steady resreq plus its queue/job uids, collected in
        # the same walk so the slot order is identical by construction.
        self._vic_res: List = []
        self._vic_queue: List[str] = []
        self._vic_job: List[str] = []
        jobs_get = ssn.jobs.get
        running = TaskStatus.Running
        for name, node in ssn.nodes.items():
            nq: Dict[str, int] = {}
            nj: Dict[str, int] = {}
            for t in node.tasks.values():
                if t.status is not running:
                    continue
                j = jobs_get(t.job)
                if j is None:
                    continue
                nq[j.queue] = nq.get(j.queue, 0) + 1
                nj[t.job] = nj.get(t.job, 0) + 1
                if collect:
                    self._vic_node.append(name)
                    self._vic_prio.append(t.priority)
                    self._vic_ts.append(t.pod.metadata.creation_timestamp)
                    self._vic_uid.append(t.uid)
                    self._vic_res.append(t.resreq)
                    self._vic_queue.append(j.queue)
                    self._vic_job.append(t.job)
            if nq:
                self.node_queue[name] = nq
                self.node_job[name] = nj
                n = sum(nq.values())
                self.node_total[name] = n
                self.total += n
                for q, c in nq.items():
                    self.queue_total[q] = self.queue_total.get(q, 0) + c
                for ju, c in nj.items():
                    self.job_total[ju] = self.job_total.get(ju, 0) + c

    def victim_tensors(self, node_index: Dict[str, int]
                       ) -> Tuple[np.ndarray, np.ndarray, List[str]]:
        """[M] (node row, victim-order rank, uid) of every job-backed
        Running resident, in the scanner's node order — the victim side
        of the batched eviction dispatch (residents without a session
        job can never be chosen by any victim filter, so omitting them
        is exact).  Cached per node_index identity (one ranking per
        session; the ranking is open-state by design — live evictions
        only shrink the candidate set, never reorder it)."""
        cached = getattr(self, "_vic_cache", None)
        if cached is not None and cached[0] is node_index:
            return cached[1]
        node_ix, rank, uids, keep = _rank_victim_columns(
            self._vic_node, self._vic_prio, self._vic_ts, self._vic_uid,
            node_index)
        out = (node_ix, rank, uids)
        self._vic_cache = (node_index, out, keep)
        return out

    def victim_detail(self, node_index: Dict[str, int], axis: List[str],
                      queue_index: Dict[str, int], job_index: Dict[str, int]
                      ) -> Optional[Tuple[np.ndarray, np.ndarray,
                                          np.ndarray]]:
        """Post-eviction staging columns for the fused storm leg
        (ops/fused_solver), slot-aligned with victim_tensors(node_index):
        quantized [M, R] resreq rows plus snapshot queue/job indices
        (-1 when the snapshot axis does not carry that uid — the device
        scatter drops those updates, matching the host, whose absent
        rows cannot be in the solve universe).  None when a victim's
        quanta overflow int32 (the tensorize path falls back there too,
        so the storm leg must not be served)."""
        self.victim_tensors(node_index)
        keep = self._vic_cache[2]
        m = len(keep)
        r = max(2, len(axis))
        res = np.zeros((m, r), np.float64)
        if m:
            res[:, 0] = [self._vic_res[i].milli_cpu for i in keep]
            res[:, 1] = [self._vic_res[i].memory for i in keep]
            for d in range(2, len(axis)):
                name = axis[d]
                res[:, d] = [self._vic_res[i].scalar_resources.get(name, 0.0)
                             for i in keep]
        from ..ops.resources import quantize_columns
        res_q = quantize_columns(res)
        if res_q.size and int(res_q.max()) > np.iinfo(np.int32).max:
            return None
        qix = np.asarray([queue_index.get(self._vic_queue[i], -1)
                          for i in keep], np.int32).reshape(m)
        jix = np.asarray([job_index.get(self._vic_job[i], -1)
                          for i in keep], np.int32).reshape(m)
        return np.ascontiguousarray(res_q, dtype=np.int32), qix, jix

    # -- per-node admissibility ---------------------------------------------

    def node_for_queue(self, name: str, queue: str, exclude_job: str) -> bool:
        """Node has a Running resident in ``queue`` from another job
        (the inter-job preempt filter, preempt.go:190-199)."""
        nq = self.node_queue.get(name)
        if not nq:
            return False
        count = nq.get(queue, 0)
        if not count:
            return False
        return count > self.node_job.get(name, {}).get(exclude_job, 0)

    def node_for_job(self, name: str, job: str) -> bool:
        """Node has a Running resident of ``job`` (intra-job preempt,
        preempt.go:136-165)."""
        return self.node_job.get(name, {}).get(job, 0) > 0

    def node_for_other_queues(self, name: str, queue: str) -> bool:
        """Node has a Running resident outside ``queue`` (reclaim,
        reclaim.go:126-138)."""
        total = self.node_total.get(name, 0)
        if not total:
            return False
        return total > self.node_queue.get(name, {}).get(queue, 0)

    # -- vectorized admissibility -------------------------------------------

    def attach_nodes(self, node_names) -> None:
        """Build the [N, Q] count matrix in ``node_names`` order (the
        scanner's), enabling whole-walk masks."""
        if self._names is node_names:
            return
        self._names = node_names
        self._row = {n: i for i, n in enumerate(node_names)}
        queues = sorted(self.queue_total)
        self._qcol = {q: i for i, q in enumerate(queues)}
        mat = np.zeros((len(node_names), max(1, len(queues))), np.int32)
        tot = np.zeros((len(node_names),), np.int32)
        for name, nq in self.node_queue.items():
            r = self._row.get(name)
            if r is None:
                continue
            for q, c in nq.items():
                mat[r, self._qcol[q]] = c
            tot[r] = self.node_total.get(name, 0)
        with self._mutex:
            self._mat = mat
            self._tot = tot
        self.rebuilds += 1
        from ..metrics import metrics
        metrics.note_victim_index("rebuild")

    def queue_mask(self, queue: str, exclude_job: str):
        """bool[N] admissibility for inter-job preempt, or None when the
        vectorized form doesn't apply (no matrix, unknown queue, or the
        preemptor's own job has Running residents — then the caller
        falls back to the exact per-node check)."""
        if self._mat is None:
            return None
        col = self._qcol.get(queue)
        if col is None or self.job_total.get(exclude_job, 0):
            return None
        with self._mutex:
            return self._mat[:, col] > 0

    def other_queues_mask(self, queue: str):
        """bool[N] of nodes with a Running resident outside ``queue``
        (reclaim), or None when no matrix is attached."""
        if self._mat is None:
            return None
        col = self._qcol.get(queue)
        with self._mutex:
            mine = self._mat[:, col] if col is not None else 0
            return self._tot > mine

    # -- live updates (keep the index exact as the actions evict) -----------

    def on_evict(self, node: str, queue: str, job: str) -> None:
        """A Running resident of ``job``/``queue`` on ``node`` was
        evicted (Running -> Releasing): without this, every drained node
        keeps getting admitted and the walk degenerates back to the
        O(tasks x nodes) empty scan."""
        nq = self.node_queue.get(node)
        if nq is not None and nq.get(queue, 0) > 0:
            nq[queue] -= 1
            self.node_job[node][job] = self.node_job[node].get(job, 1) - 1
            self.node_total[node] -= 1
            self.total -= 1
            self.queue_total[queue] = self.queue_total.get(queue, 1) - 1
            self.job_total[job] = self.job_total.get(job, 1) - 1
            self.invalidations += 1
            from ..metrics import metrics
            metrics.note_victim_index("evict")
            with self._mutex:
                self._mat_delta(node, queue, -1)

    def on_restore(self, node: str, queue: str, job: str) -> None:
        """Inverse of on_evict (Statement.discard rolled the evict back)."""
        nq = self.node_queue.setdefault(node, {})
        nq[queue] = nq.get(queue, 0) + 1
        nj = self.node_job.setdefault(node, {})
        nj[job] = nj.get(job, 0) + 1
        self.node_total[node] = self.node_total.get(node, 0) + 1
        self.total += 1
        self.queue_total[queue] = self.queue_total.get(queue, 0) + 1
        self.job_total[job] = self.job_total.get(job, 0) + 1
        self.restores += 1
        from ..metrics import metrics
        metrics.note_victim_index("restore")
        with self._mutex:
            self._mat_delta(node, queue, +1)

    def _mat_delta(self, node: str, queue: str, sign: int) -> None:  # holds-lock: _mutex
        if self._mat is None:
            return
        r = self._row.get(node)
        c = self._qcol.get(queue)
        if r is None or c is None:
            return
        self._mat[r, c] += sign
        self._tot[r] += sign

    # -- whole-walk admissibility -------------------------------------------

    def any_for_queue(self, queue: str, exclude_job: str) -> bool:
        count = self.queue_total.get(queue, 0)
        return count > self.job_total.get(exclude_job, 0) if count else False

    def any_for_job(self, job: str) -> bool:
        return self.job_total.get(job, 0) > 0

    def any_for_other_queues(self, queue: str) -> bool:
        return self.total > self.queue_total.get(queue, 0)
