"""O(churn) incremental sessions: persistent, generation-keyed solver state.

The steady-cycle cost model this module attacks (ROADMAP open item #2,
doc/INCREMENTAL.md): a 1% churn cycle used to pay O(cluster) four times —
the ``_resource_axis`` full-task scan, the drf/proportion plugin opens
(one Resource.add per allocated task), the [S, N] static predicate mask,
and a fresh device solve even when the shipped bytes were identical to
the previous cycle's.  The dirty set is already computed exactly (the
cache's ``mod_epoch`` stamps, the TensorCache's block/pack epochs,
``Session.mutated_nodes``); this module extends that invalidation
contract to the remaining O(cluster) stages:

* ``begin_tensorize`` — the per-session *plan*: decides micro vs full vs
  fallback from the dirty sets BEFORE any heavy work, revalidates the
  resource axis by scanning only dirty objects, and hands the
  precomputed dirty-node rows to the tensorizer so the epoch walk runs
  once.  Full-rebuild fallback mirrors the delta shipper's policy
  (models/shipping.py): layout/config change, >50% dirty, or the
  periodic full-session floor.
* persistent ``sig_mask``/``sig_bonus`` — the [S, N] static predicate
  mask survives across sessions; only dirty node COLUMNS re-enter the
  predicate chain (the per-(signature, node) evaluation is a pure
  function, so a patched column equals the profile build's bit for bit).
* generation-keyed solve reuse — ``DeviceResidentShipper.generation``
  moves whenever shipped bytes change; a *clean* ship at an unchanged
  generation means the solver inputs are byte-identical to the previous
  dispatch, so the deterministic solve result is reused without a device
  round-trip (actions/tpu_allocate.py).
* plugin-open aggregate caches — drf/proportion per-job open aggregates
  cached on the job CLONE (clone identity is the validity token: a
  session that mutates a clone discards it from the snapshot pool, so a
  reused clone is bit-unchanged).  drf reuse is exact by construction
  (the cached Resource is cloned); proportion reuse is gated on every
  contributing task value being an exact binary integer, so collapsing
  the per-task adds into one per-job add cannot reassociate floats.

Everything gates behind ``KUBE_BATCH_TPU_INCREMENTAL=0`` — the
sequential control arm whose placements/events/binds the CI churn sweep
(`make bench-churn`) pins bit-identical at every churn level.

Thread model: all state here is touched only by the scheduling thread
(session open/execute/close); no locks needed.  The chaos site
``incremental.stale_generation`` forces a mid-cycle generation mismatch
so the fallback-to-full-rebuild path stays exercised (doc/CHAOS.md).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from .. import knobs
from ..chaos import plan as chaos_plan
from ..metrics import memledger, metrics
from ..trace import spans as trace

# =0 restores the sequential control: full tensorize scans, uncached
# plugin opens, a fresh solve every cycle, fixed-period scheduling.
INCREMENTAL_ENV = knobs.INCREMENTAL.env
# Wire-to-tensor fast path (doc/INCREMENTAL.md "Wire fast path"): =0 is
# the sequential control for the L1 columnar watch-delta decode
# (edge/codec), the persistent candidate-row staging buffers
# (tensor_snapshot), and the vectorized drf/job-valid/gang-close walks
# below — `make bench-wire` pins binds+events bit-identical across it.
WIRE_FAST_ENV = knobs.WIRE_FAST.env
# Periodic full-session floor (scheduler.py): every K cycles the loop
# requests a full rebuild so incremental drift cannot accumulate
# silently.  0 disables the floor.
FULL_EVERY_ENV = knobs.FULL_EVERY.env
DEFAULT_FULL_EVERY = knobs.FULL_EVERY.default

# Above this dirty fraction the micro patch moves more rows than a full
# rebuild saves — mirror of the delta shipper's _DELTA_MAX_FRACTION.
_DIRTY_MAX_FRACTION = 0.5

# Exactness bound for the proportion aggregate cache: integer-valued f64
# below this stays exactly representable through every partial sum a
# realistic cluster can accumulate (cluster totals stay far under 2^53).
_EXACT_LIMIT = float(2 ** 50)


def incremental_enabled() -> bool:
    return knobs.INCREMENTAL.enabled()


def wire_fast_enabled() -> bool:
    return knobs.WIRE_FAST.enabled()


def full_session_every() -> int:
    return knobs.FULL_EVERY.value()


def resource_exact(res) -> bool:
    """True when every dimension of ``res`` is an exact binary integer
    small enough that float addition of such values cannot round — the
    condition under which per-job partial sums equal the per-task add
    sequence bit for bit (see ProportionPlugin.on_session_open)."""
    mc = float(res.milli_cpu)
    mem = float(res.memory)
    if not (mc.is_integer() and mem.is_integer()):
        return False
    if abs(mc) > _EXACT_LIMIT or abs(mem) > _EXACT_LIMIT:
        return False
    if res.scalar_resources:
        for v in res.scalar_resources.values():
            fv = float(v)
            if not fv.is_integer() or abs(fv) > _EXACT_LIMIT:
                return False
    return True


def _inc_state_nbytes(st: "IncrementalState") -> int:
    """Array bytes retained across sessions: the persistent signature
    mask/bonus and the per-job aggregate columns.  Shared by the
    finish_tensorize set-hook and the memledger auditor."""
    n = 0
    for a in (st.sig_mask, st.sig_bonus):
        n += int(getattr(a, "nbytes", 0) or 0)
    agg = st.job_agg
    if agg is not None:
        for name in ("epochs", "min_avail", "ready", "valid", "alloc",
                     "shares"):
            n += int(getattr(getattr(agg, name, None), "nbytes", 0) or 0)
    return n


class IncrementalState:
    """Cross-session incremental bookkeeping, attached to an
    epoch-stamped SchedulerCache (mirror of tensor_snapshot's
    TensorCache persistence gate).  Scheduling-thread only.

    Memory accounting (metrics/memledger.py):
    # mem-ledger: incremental
    """

    def __init__(self):
        # Monotonic build counter: bumps once per COMPLETED tensorize
        # (micro or full).  Observability + test hooks; the solve cache
        # below keys on the shipper's byte-generation instead.
        self.generation: int = 0
        # Last completed build's layout facts (micro-plan validation).
        self.axis: Optional[Tuple[str, ...]] = None
        self.struct: Optional[dict] = None
        self.node_count: int = 0
        self.job_count: int = 0
        # Persistent static predicate mask: [S, n_pad] + the sig tuples
        # and node list it was built for.  Dirty node columns are
        # re-evaluated in place (micro path); anything else rebuilds.
        self.sig_tuples: Optional[tuple] = None
        self.sig_mask = None            # np.ndarray [S, n_pad] bool
        self.sig_bonus = None           # np.ndarray [S, n_pad] int64
        self.sig_examples: Dict[tuple, tuple] = {}
        # Generation-keyed solve-result cache (actions/tpu_allocate.py):
        # valid while the shipper's resident bytes are unchanged.  The
        # byte-generation contract is layout-blind on purpose: the
        # per-shard mesh layout (doc/SHARDING.md) moves the generation
        # through the same full/delta/clean discipline, so a clean ship
        # on the mesh proves byte-identical inputs exactly as on one
        # chip and the cached result stays reusable.  ``solve_route``
        # records which engine produced the cached result (sharded |
        # pallas | xla) purely for observability — the parity suite
        # makes every route placement-identical, so a route flip never
        # invalidates the cache.
        self.solve_gen: int = -1
        self.solve_cfg = None
        self.solve_result: Optional[tuple] = None
        self.solve_route: str = ""
        # One-shot full-rebuild request (the scheduler's periodic floor,
        # and the chaos stale-generation recovery path).
        self.force_full: bool = False
        # True between begin_tensorize and finish_tensorize.  Still True
        # at the NEXT begin means the previous build aborted mid-way
        # (tensorizer fallback_reason early-return, or an exception)
        # AFTER the pack refresh may have advanced node epochs but
        # BEFORE the mask was patched/stored — the persisted mask and
        # solve cache can then be stale for nodes that now look clean,
        # so both are dropped before planning (the pack itself is safe:
        # its refreshed rows were staged from live truth).
        self.build_open: bool = False
        # Accumulated churn footprint of the last closed session
        # (framework/session.py close_session) — observability.
        self.last_mutated_jobs: int = 0
        self.last_mutated_nodes: int = 0
        self.last_kind: str = ""
        self.last_reason: str = ""
        self.stats = {"micro": 0, "full": 0, "fallback": 0}
        # Persistent per-job aggregate columns (the wire-to-tensor fast
        # path's plugin-layer leg, doc/INCREMENTAL.md "Wire fast path"):
        # min_available / ready / valid task counts and the DRF open
        # allocation vectors, patched for dirty jobs only and consumed
        # as numpy column ops by plugins/drf.py's share computation, the
        # open_session job_valid gate, and plugins/gang.py's close walk.
        self.job_agg: Optional["JobAggregates"] = None
        self._mem_key = memledger.ledger("incremental").track(
            self, sizer=_inc_state_nbytes)

    def _mem_refresh(self) -> None:
        """Set-hook: re-price the incremental ledger (finish_tensorize
        — the chokepoint where the persistent arrays are rebound)."""
        memledger.ledger("incremental").set(self._mem_key,
                                            _inc_state_nbytes(self))

    def invalidate_solve(self) -> None:
        self.solve_gen = -1
        self.solve_result = None
        self.solve_cfg = None


def state_for(cache, create: bool = True) -> Optional[IncrementalState]:
    """The cache's persistent IncrementalState, or None for cache objects
    without epoch stamping (same gate as tensor_snapshot._tensor_cache:
    reuse without invalidation keys would serve stale tensors)."""
    st = getattr(cache, "_inc_state", None)
    if st is not None or not create:
        return st
    if hasattr(cache, "epoch") and isinstance(getattr(cache, "jobs", None),
                                              dict):
        st = IncrementalState()
        try:
            cache._inc_state = st
        except AttributeError:
            return None
        return st
    return None


def request_full(cache) -> None:
    """Force the next tensorize to run a full rebuild (the scheduler's
    periodic full-session floor; doc/INCREMENTAL.md 'micro vs full').
    The same floor revalidates the incremental snapshot map and the
    quiet-close bookkeeping: the next cache.snapshot() runs the full
    walk, so close_session re-walks every job too — no skip survives
    more than KUBE_BATCH_TPU_FULL_EVERY cycles unrevalidated."""
    st = state_for(cache)
    if st is not None:
        st.force_full = True
    req = getattr(cache, "request_full_snapshot", None)
    if req is not None:
        req()


def note_session_mutations(cache, mutated_jobs: int,
                           mutated_nodes: int) -> None:
    """Record the closed session's mutation footprint (close_session):
    the accumulated churn the next cycle's plan reports alongside its
    own dirty counts."""
    st = state_for(cache, create=False)
    if st is not None:
        st.last_mutated_jobs = int(mutated_jobs)
        st.last_mutated_nodes = int(mutated_nodes)


def plugin_cache_enabled(cache) -> bool:
    """Whether the plugin-open aggregate caches may be consulted.  Pure
    env gate: clone identity alone keys validity, so non-pooled caches
    simply never hit (fresh clones every cycle)."""
    return incremental_enabled()


def node_open_aggregates(ssn):
    """The snapshot map's node-open aggregates for this session —
    (total_allocatable | None, grid_cap, grid_used, shift) — or None
    when unavailable (control arm, cold map, foreign cache).  Each call
    returns PRIVATE copies: two GridUsage consumers in one session (e.g.
    nodeorder + tpu-score) mutate their ``used`` mirrors independently,
    exactly like two control-path instances (doc/INCREMENTAL.md
    "floors")."""
    if not incremental_enabled():
        return None
    fn = getattr(ssn.cache, "node_open_aggregates", None)
    if fn is None:
        return None
    return fn()


def cluster_total_allocatable(ssn):
    """Exact-integer cached sum of every session node's allocatable, or
    None (fractional dimension somewhere / aggregates unavailable): the
    O(nodes) open walk of drf and proportion, served from the snapshot
    map.  Each caller gets a private clone (plugins own their total)."""
    agg = node_open_aggregates(ssn)
    if agg is None or agg[0] is None:
        return None
    return agg[0].clone()


class SessionPlan:
    """One session's incremental decision, computed before any heavy
    tensorize work.  ``kind``:

    * ``micro``    — axis + persistent mask reused; only dirty rows
                      re-enter the staging (``axis`` is set).
    * ``full``     — no previous state, or the periodic floor forced a
                      rebuild (``axis`` None: full scans run).
    * ``fallback`` — a micro attempt was invalidated (layout/cfg change,
                      >50% dirty, injected stale generation); full
                      scans run and the reason is recorded.
    """

    __slots__ = ("state", "kind", "reason", "axis", "node_dirty",
                 "dirty_jobs", "dirty_nodes", "mask_reusable")

    def __init__(self, state: IncrementalState, kind: str, reason: str,
                 axis=None, node_dirty=None, dirty_jobs: int = 0,
                 dirty_nodes: int = 0, mask_reusable: bool = False):
        self.state = state
        self.kind = kind
        self.reason = reason
        self.axis = axis
        self.node_dirty = node_dirty    # [(ix, epoch|None)] reusable rows
        self.dirty_jobs = dirty_jobs
        self.dirty_nodes = dirty_nodes
        self.mask_reusable = mask_reusable


def _dirty_node_rows(node_names, node_objs, mutated_nodes,
                     pack) -> List[tuple]:
    """The node rows whose snapshot epoch moved past the pack's stamp
    (plus session-mutated ones) — the exact walk the tensorizer's pack
    refresh performs, extracted so plan and refresh share one pass."""
    dirty = []
    for ix, name in enumerate(node_names):
        if name in mutated_nodes:
            dirty.append((ix, None))
            continue
        ep = getattr(node_objs[ix], "snap_epoch", None)
        if ep is not None and pack.epochs[ix] == ep:
            continue
        dirty.append((ix, ep))
    return dirty


def _job_is_dirty(tc, uid, job, mutated_jobs) -> bool:
    if uid in mutated_jobs:
        return True
    snap_epoch = getattr(job, "snap_epoch", None)
    if snap_epoch is None:
        return True
    block = tc.jobs.get(uid)
    return block is None or block.epoch != snap_epoch


def _scalars_in_job(job) -> bool:
    for t in job.tasks.values():
        if t.resreq.scalar_resources or t.init_resreq.scalar_resources:
            return True
    return False


def _struct_key(struct: dict) -> tuple:
    """Hashable form of plugin_structure's output: the conf-derived
    facts the persisted mask/bonus (and the whole micro plan) are only
    valid under.  A session opened with different tiers on the same
    cache must rebuild."""
    return (tuple(struct["job_order"]), tuple(struct["queue_order"]),
            struct["has_gang"], struct["has_proportion"],
            struct["has_predicates"], struct["weights"],
            struct["w_podaff"], struct["w_nodeaff"])


def begin_tensorize(ssn, tc, node_names, node_objs,
                    mutated_jobs, mutated_nodes,
                    struct) -> Optional[SessionPlan]:
    """Plan this session's tensorize.  Returns None when incremental
    sessions are disabled or the cache cannot persist state — the
    tensorizer then runs exactly the pre-incremental path."""
    if not incremental_enabled():
        return None
    st = state_for(ssn.cache)
    if st is None or not getattr(tc, "persistent", False):
        return None

    if st.build_open:
        # The previous build never reached finish_tensorize (see the
        # field's docstring): drop everything that could be stale
        # relative to the advanced pack epochs.
        st.sig_tuples = None
        st.sig_mask = None
        st.sig_bonus = None
        st.invalidate_solve()
        st._mem_refresh()  # the dropped arrays must leave the books too
    st.build_open = True

    struct_key = _struct_key(struct)
    if st.force_full:
        st.force_full = False
        st.struct = struct_key
        return SessionPlan(st, "full", "periodic full-session floor")
    if st.axis is None:
        st.struct = struct_key
        return SessionPlan(st, "full", "first session")
    if st.struct != struct_key:
        # Conf change on a live cache: every persisted tensor (mask
        # bonus weights, predicate enablement) — and the example cache
        # the mask patcher probes the predicate chain with — is keyed
        # to the old tiers.
        st.struct = struct_key
        st.sig_examples.clear()
        st.invalidate_solve()
        return SessionPlan(st, "fallback", "plugin/tier structure changed")

    def fallback(reason: str, dirty_jobs=0, dirty_nodes=0) -> SessionPlan:
        return SessionPlan(st, "fallback", reason, dirty_jobs=dirty_jobs,
                           dirty_nodes=dirty_nodes)

    # Chaos site: forces a generation mismatch mid-cycle so the
    # degraded path (full rebuild + solve-cache invalidation) stays
    # exercised under the soak harness (doc/CHAOS.md).
    plan = chaos_plan.PLAN
    if plan is not None and plan.fire("incremental.stale_generation"):
        st.invalidate_solve()
        trace.note_degraded(
            "incremental generation stale (injected): full rebuild")
        return fallback("chaos: stale generation (injected)")

    # Layout/config-key validation (mirror of the shipper's full-reship
    # triggers): any mismatch means the persisted rows describe a
    # different tensor layout.
    if tc.axis != st.axis:
        return fallback("tensor-cache axis flushed")
    if (len(tc.sig_list) + len(tc.port_list) + len(tc.sel_list)
            > 4096):  # _MAX_GLOBAL_IDS: the tensorizer will flush tables
        return fallback("global id tables at flush threshold")
    pack = tc.pack
    if pack is None or pack.names != node_names:
        return fallback("node membership changed",
                        dirty_nodes=len(node_names))
    if set(ssn.task_order_fns) - {"priority"}:
        return fallback("non-stock task order")

    node_dirty = _dirty_node_rows(node_names, node_objs, mutated_nodes,
                                  pack)
    n_real = len(node_names)

    dirty_jobs = 0
    dirty_job_objs = []
    for uid, job in ssn.jobs.items():
        if _job_is_dirty(tc, uid, job, mutated_jobs):
            dirty_jobs += 1
            dirty_job_objs.append(job)
    j_total = max(len(ssn.jobs), 1)

    if (len(node_dirty) > _DIRTY_MAX_FRACTION * max(n_real, 1)
            or dirty_jobs > _DIRTY_MAX_FRACTION * j_total):
        return fallback(
            f"dirty fraction above {_DIRTY_MAX_FRACTION:.0%} "
            f"({len(node_dirty)}/{n_real} nodes, "
            f"{dirty_jobs}/{j_total} jobs)",
            dirty_jobs=dirty_jobs, dirty_nodes=len(node_dirty))

    # Axis revalidation by dirty-only scan: the last completed build
    # proved no scalar resource existed anywhere; clean objects are
    # bit-unchanged since, so only dirty ones can introduce one.  A
    # scalar appearing (or a previous axis that already had scalars —
    # removal could shrink it) means the axis must be re-derived from
    # the full scan.
    if st.axis != ("cpu", "memory"):
        return fallback("scalar resources present: axis not provable "
                        "from the dirty set",
                        dirty_jobs=dirty_jobs,
                        dirty_nodes=len(node_dirty))
    for ix, _ep in node_dirty:
        if node_objs[ix].allocatable.scalar_resources:
            return fallback("dirty node introduces a scalar resource",
                            dirty_jobs=dirty_jobs,
                            dirty_nodes=len(node_dirty))
    for job in dirty_job_objs:
        if _scalars_in_job(job):
            return fallback("dirty job introduces a scalar resource",
                            dirty_jobs=dirty_jobs,
                            dirty_nodes=len(node_dirty))

    return SessionPlan(st, "micro", "", axis=st.axis,
                       node_dirty=node_dirty, dirty_jobs=dirty_jobs,
                       dirty_nodes=len(node_dirty), mask_reusable=True)


def patch_sig_mask(plan: SessionPlan, ssn, sig_tuples, node_objs,
                   n_pad: int, w_nodeaff: int):
    """Serve the persistent [S, n_pad] sig_mask/sig_bonus with dirty
    node columns re-evaluated in place, or None when a full rebuild is
    required (sig set changed, shape moved, plan not micro).

    Bit parity: the per-(signature, node) evaluation below is the same
    pure function the profile build memoizes (tensor_snapshot's
    prof_mask/prof_bonus loop), so a patched column equals a rebuilt
    one exactly; clean columns cannot have drifted because every input
    of the function (node labels/taints/conditions/unschedulable,
    allocatable cap, resident count) moves the node's epoch or lands in
    Session.mutated_nodes — both enter ``node_dirty``."""
    import numpy as np

    st = plan.state
    key = tuple(sig_tuples)
    if (not plan.mask_reusable or st.sig_mask is None
            or st.sig_tuples != key
            or st.sig_mask.shape != (len(sig_tuples), n_pad)):
        return None
    if len(plan.node_dirty) * len(sig_tuples) > 4096:
        # The patch path evaluates the predicate chain per (signature,
        # dirty node) with no static-profile dedup; past this budget the
        # profile build (O(S x distinct profiles) evaluations plus one
        # vector scatter) is cheaper than the patch it would replace —
        # mirror of the pack refresh's own full-rebuild cutover.
        return None
    from ..plugins.nodeorder import node_affinity_score
    from .tensor_snapshot import _sig_example, _static_example

    sig_mask = st.sig_mask
    sig_bonus = st.sig_bonus
    examples = st.sig_examples
    for si, sig in enumerate(sig_tuples):
        cached = examples.get(sig)
        if cached is None:
            example = _sig_example(sig)
            stripped = _static_example(example)
            cached = (example, stripped)
            examples[sig] = cached
        example, stripped = cached
        # has_pref derives from the CURRENT conf's w_nodeaff, never the
        # cached tuple: a weight change must not serve zero bonuses for
        # dirty columns after the struct fallback rebuilt the mask.
        affinity = example.pod.spec.affinity
        has_pref = (w_nodeaff and affinity is not None
                    and affinity.preferred_node_terms)
        for ix, _ep in plan.node_dirty:
            node = node_objs[ix]
            bonus = 0
            if has_pref:
                bonus = w_nodeaff * node_affinity_score(example, node)
            sig_bonus[si, ix] = bonus
            ok = True
            try:
                ssn.predicate_fn(stripped, node)
            except Exception:  # lint: allow-swallow(predicate veto: any raise means infeasible, exactly like the profile build treats it)
                ok = False
            sig_mask[si, ix] = ok
    return sig_mask, sig_bonus


def store_sig_mask(plan: Optional[SessionPlan], sig_tuples, sig_mask,
                   sig_bonus) -> None:
    """Persist a freshly built mask for the next session's patch path.
    Only non-empty signature sets persist (the featureless all-True row
    is cheaper to rebuild than to key); an empty set drops any older
    persisted mask so it cannot be served after the signatures return."""
    if plan is None:
        return
    st = plan.state
    if not sig_tuples:
        st.sig_tuples = None
        st.sig_mask = None
        st.sig_bonus = None
        st.sig_examples.clear()
        return
    st.sig_tuples = tuple(sig_tuples)
    st.sig_mask = sig_mask
    st.sig_bonus = sig_bonus
    # Drop example cache entries for signatures that left the session.
    live = set(st.sig_tuples)
    for sig in [s for s in st.sig_examples if s not in live]:
        del st.sig_examples[sig]


# ---------------------------------------------------------------------------
# Per-job aggregate columns (the plugin-layer leg of the wire-to-tensor
# fast path).  The drf open used to recompute every job's dominant share
# (`_calculate_share` — a Python loop over resource names per job), the
# open_session job_valid gate re-validated every job, and the gang close
# re-derived every job's readiness — all O(jobs) Python per cycle.  The
# persistent columns below are patched for DIRTY jobs only (the same
# snap_epoch discipline as the tensor blocks; session-mutated rows are
# stamped always-dirty so the next open re-reads the fresh clone) and the
# three walks become numpy column ops plus an O(affected) Python tail.
# Everything degrades to the sequential control under
# KUBE_BATCH_TPU_WIRE_FAST=0 / KUBE_BATCH_TPU_INCREMENTAL=0.
# ---------------------------------------------------------------------------


class JobAggregates:
    """Persistent per-job columns, scheduling-thread only (the same
    thread model as the rest of this module)."""

    __slots__ = ("index", "uids", "clones", "epochs", "min_avail",
                 "ready", "valid", "alloc", "axis", "shares", "n",
                 "open_session_uid", "close_session_uid")

    def __init__(self):
        import numpy as np
        self.index: Dict[str, int] = {}
        self.uids: List[str] = []
        # Row validity is (epoch, CLONE IDENTITY): a session-only
        # mutation discards the pooled clone without moving truth's
        # mod_epoch, so the next session's fresh clone arrives at the
        # SAME snap_epoch — the identity check is what forces the
        # refill (and re-seeds the per-clone _drf_open_alloc cache the
        # lazy _DrfAttr materialization depends on).  Strong refs; rows
        # are bounded by the compaction rule in job_aggregates_open.
        self.clones: List[object] = []
        self.n = 0
        cap = 64
        self.epochs = np.full((cap,), -1, np.int64)
        self.min_avail = np.zeros((cap,), np.int64)
        self.ready = np.zeros((cap,), np.int64)
        self.valid = np.zeros((cap,), np.int64)
        # DRF open-allocation vectors over ``axis``; float32 so the
        # vectorized share division is the exact np.float32 operand
        # rounding api.resource.share applies (bit parity).
        self.alloc = np.zeros((cap, 2), np.float32)
        self.axis: tuple = ("cpu", "memory")
        self.shares = None
        self.open_session_uid = ""
        self.close_session_uid = ""

    def _grow(self, need: int) -> None:
        import numpy as np
        cap = len(self.epochs)
        if need <= cap:
            return
        new_cap = max(need, cap * 2)
        pad = new_cap - cap
        self.epochs = np.concatenate(
            [self.epochs, np.full((pad,), -1, np.int64)])
        for name in ("min_avail", "ready", "valid"):
            arr = getattr(self, name)
            setattr(self, name,
                    np.concatenate([arr, np.zeros((pad,), np.int64)]))
        self.alloc = np.concatenate(
            [self.alloc,
             np.zeros((pad, self.alloc.shape[1]), np.float32)])


def _drf_alloc_of(job):
    """The job clone's DRF open allocation — the exact walk
    DrfPlugin.on_session_open performs, cached on the clone under the
    same clone-identity validity token (``_drf_open_alloc``), so the
    control arm and the fast path serve byte-identical Resources."""
    from ..api import Resource, allocated_status
    cached = getattr(job, "_drf_open_alloc", None)
    if cached is not None:
        return cached
    acc = Resource.empty()
    for status, tasks in job.task_status_index.items():
        if allocated_status(status):
            for t in tasks.values():
                acc.add(t.resreq)
    try:
        job._drf_open_alloc = acc
    except AttributeError:  # lint: allow-swallow(slotted/foreign clone: the walk simply re-runs next session, which is the control behavior)
        pass
    return acc


def job_fast_enabled(ssn) -> bool:
    return (wire_fast_enabled() and incremental_enabled()
            and state_for(ssn.cache) is not None)


def _fill_job_row(agg: JobAggregates, i: int, job) -> None:
    agg.min_avail[i] = job.min_available
    agg.ready[i] = job.ready_task_num()
    agg.valid[i] = job.valid_task_num()
    res = _drf_alloc_of(job)
    row = agg.alloc[i]
    row[:] = 0.0
    for d, name in enumerate(agg.axis):
        row[d] = res.get(name)


def job_aggregates_open(ssn) -> Optional[JobAggregates]:
    """Build or dirty-patch the persistent per-job columns for this
    session's OPEN state (runs once per session; later callers get the
    cached result).  Returns None on the control arm."""
    if not job_fast_enabled(ssn):
        return None
    st = state_for(ssn.cache)
    agg = st.job_agg
    if agg is not None and len(agg.index) > 2 * max(len(ssn.jobs), 1) + 64:
        agg = None  # compaction: churn left mostly-dead rows behind
    if agg is None:
        agg = st.job_agg = JobAggregates()
    if agg.open_session_uid == ssn.uid:
        return agg
    agg.open_session_uid = ssn.uid
    agg.close_session_uid = ""
    agg._grow(len(agg.index) + len(ssn.jobs))
    mutated = getattr(ssn, "mutated_jobs", set())
    for uid, job in ssn.jobs.items():
        i = agg.index.get(uid)
        ep = (getattr(job, "snap_epoch", None)
              if uid not in mutated else None)
        if i is None:
            i = len(agg.uids)
            agg._grow(i + 1)
            agg.index[uid] = i
            agg.uids.append(uid)
            agg.clones.append(None)
            agg.n = i + 1
        elif ep is not None and agg.epochs[i] == ep \
                and agg.clones[i] is job:
            continue  # clean row: bit-unchanged clone since last fill
        _fill_job_row(agg, i, job)
        agg.epochs[i] = ep if ep is not None else -1
        agg.clones[i] = job
    # job_agg rebinds OUTSIDE the tensorize chokepoint (open-session
    # plugin path: _grow reallocations and the compaction rebuild above)
    # — re-price here, or a session that opens and then dies before any
    # tensorize (chaos faults) leaves the ledger under-counting for the
    # life of this state object.
    st._mem_refresh()
    return agg


def job_aggregates_close(ssn) -> Optional[JobAggregates]:
    """The CLOSE-state view: open columns plus a re-read of every
    session-mutated job's clone.  Mutated rows are stamped always-dirty
    (-1): a session-only mutation (e.g. pipeline) does not move truth's
    mod_epoch, so the next open must not mistake the close-state row for
    the fresh clone's state."""
    agg = job_aggregates_open(ssn)
    if agg is None:
        return None
    if agg.close_session_uid == ssn.uid:
        return agg
    agg.close_session_uid = ssn.uid
    for uid in getattr(ssn, "mutated_jobs", ()):
        i = agg.index.get(uid)
        job = ssn.jobs.get(uid)
        if i is None or job is None:
            continue
        agg.min_avail[i] = job.min_available
        agg.ready[i] = job.ready_task_num()
        agg.valid[i] = job.valid_task_num()
        agg.epochs[i] = -1
        agg.clones[i] = job
    return agg


def drf_open_shares(ssn, total_resource) -> Optional[JobAggregates]:
    """Vectorized DRF dominant shares at session open: one float32
    column division + row max over the persistent allocation matrix,
    bit-identical to the per-job ``_calculate_share`` loop because
    ``api.resource.share`` is DEFINED as the correctly-rounded float32
    division of float32-rounded operands — exactly the elementwise op
    below — and max over exact f32→f64 widenings equals the widened f32
    max.  Returns the aggregates with ``shares``/``index`` populated, or
    None on the control arm."""
    import numpy as np

    agg = job_aggregates_open(ssn)
    if agg is None:
        return None
    axis = ("cpu", "memory",
            *sorted(total_resource.scalar_resources
                    or ()))
    if axis != agg.axis or agg.alloc.shape[1] != len(axis):
        # Resource axis moved (a scalar appeared in/left the cluster
        # total): refill every live row's vector from the cached per-
        # clone Resources — O(jobs) Python, once per axis change.
        agg.axis = axis
        agg.alloc = np.zeros((len(agg.epochs), len(axis)), np.float32)
        for uid, i in agg.index.items():
            job = ssn.jobs.get(uid)
            if job is not None:
                res = _drf_alloc_of(job)
                for d, name in enumerate(axis):
                    agg.alloc[i, d] = res.get(name)
    n = agg.n
    total_vec = np.asarray([total_resource.get(name) for name in axis],
                           np.float32)
    a32 = agg.alloc[:n]
    with np.errstate(divide="ignore", invalid="ignore"):
        q = a32 / total_vec
    zero_t = total_vec == 0
    if zero_t.any():
        # share(l, 0) is 0 for l == 0 and 1 otherwise (helpers.go:47-59).
        q[:, zero_t] = np.where(a32[:, zero_t] != 0,
                                np.float32(1.0), np.float32(0.0))
    if n:
        agg.shares = np.maximum(
            q.max(axis=1), np.float32(0.0)).astype(np.float64)
    else:
        agg.shares = np.zeros((0,), np.float64)
    return agg


def job_valid_pass_uids(ssn) -> Optional[set]:
    """Job uids provably PASSING the open_session job_valid gate, or
    None when the fast path cannot decide (control arm, a non-gang
    validator registered).  Passing jobs are unobservable through the
    gate (no condition, no deletion), so skipping them is bit-parity;
    every other job still runs the real validator chain."""
    if not ssn.job_valid_fns or set(ssn.job_valid_fns) - {"gang"}:
        return None
    agg = job_aggregates_open(ssn)
    if agg is None:
        return None
    import numpy as np
    n = agg.n
    ok = np.nonzero(agg.valid[:n] >= agg.min_avail[:n])[0]
    uids = agg.uids
    return {uids[int(i)] for i in ok}


def gang_close_unready(ssn) -> Optional[list]:
    """The session's not-ready jobs for the gang close pass (ready <
    minAvailable from the close-state columns), or None on the control
    arm.  Ready jobs are skipped without a Python visit; the returned
    jobs run the exact per-job close body.  Cross-job order carries no
    observable interaction (per-job conditions, name-labeled gauges,
    monotonic counters), so aggregate row order is parity-safe."""
    agg = job_aggregates_close(ssn)
    if agg is None:
        return None
    import numpy as np
    n = agg.n
    rows = np.nonzero(agg.ready[:n] < agg.min_avail[:n])[0]
    out = []
    for i in rows:
        job = ssn.jobs.get(agg.uids[int(i)])
        if job is not None:
            out.append(job)
    return out


def finish_tensorize(plan: Optional[SessionPlan], ssn, axis,
                     node_count: int, job_count: int) -> None:
    """Close out a COMPLETED build: update the layout facts the next
    plan validates against, bump the generation, and publish the
    kind/dirty counts to metrics and the flight recorder (the
    /debug/sessions ``incremental`` surface)."""
    if plan is None:
        return
    st = plan.state
    st.build_open = False
    st.axis = tuple(axis)
    st.node_count = node_count
    st.job_count = job_count
    st.generation += 1
    st.last_kind = plan.kind
    st.last_reason = plan.reason
    st.stats[plan.kind] = st.stats.get(plan.kind, 0) + 1
    st._mem_refresh()
    metrics.set_incremental_dirty(plan.dirty_nodes, plan.dirty_jobs)
    # One count per SESSION (the scanner and the allocate action may
    # both tensorize within one cycle; the first build classifies it).
    if not getattr(ssn, "_inc_counted", False):
        try:
            ssn._inc_counted = True
        except AttributeError:
            pass
        metrics.note_incremental_session(plan.kind)
    trace.set_meta(incremental=plan.kind,
                   dirty_nodes=plan.dirty_nodes,
                   dirty_jobs=plan.dirty_jobs,
                   **({"incremental_reason": plan.reason}
                      if plan.reason else {}))
    trace.annotate(incremental=plan.kind, dirty_nodes=plan.dirty_nodes,
                   dirty_jobs=plan.dirty_jobs)
