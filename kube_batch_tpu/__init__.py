"""kube_batch_tpu: a TPU-native batch-scheduling framework.

A standalone reimplementation of the capabilities of kube-batch
(kubernetes-sigs/kube-batch, surveyed in /root/repo/SURVEY.md): gang
scheduling over PodGroup/Queue resources, multi-queue weighted fairness,
DRF, priority, preemption/reclaim/backfill, and pluggable predicates and
node scoring — with the per-session decision kernel reformulated as batched
tensor programs solved on TPU via JAX/XLA (see ``kube_batch_tpu.ops`` and the
``tpu-allocate`` action).
"""

from .version import __version__

__all__ = ["__version__"]
