"""Shared informer factory and listers.

Counterpart of the reference's generated SharedInformerFactory
(/root/reference/pkg/client/informers/externalversions/factory.go) and
listers: handler registration fan-out over the cluster-state store's watch
streams, plus read-only listers backed by the current store state.
"""

from __future__ import annotations

from typing import Callable, List, Optional

from ..apis.scheduling import v1alpha1, v1alpha2
from ..cache.cluster import Cluster


class _TypedInformer:
    """Filters a Cluster informer stream to one object type."""

    def __init__(self, informer, type_check: Callable[[object], bool]):
        self._informer = informer
        self._type_check = type_check

    def add_event_handler(self, on_add=None, on_update=None, on_delete=None):
        self._informer.add_handlers(
            on_add=on_add, on_update=on_update, on_delete=on_delete,
            filter_fn=self._type_check)


class _PodGroupLister:
    def __init__(self, cluster: Cluster, version_mod):
        self._cluster = cluster
        self._version = version_mod

    def list(self, namespace: Optional[str] = None) -> List:
        out = []
        for key, pg in self._cluster.pod_groups.items():
            if not type(pg) is self._version.PodGroup:
                continue
            if namespace and not key.startswith(f"{namespace}/"):
                continue
            out.append(pg)
        return out


class _QueueLister:
    def __init__(self, cluster: Cluster, version_mod):
        self._cluster = cluster
        self._version = version_mod

    def list(self) -> List:
        return [q for q in self._cluster.queues.values()
                if type(q) is self._version.Queue]


class SharedInformerFactory:
    def __init__(self, cluster: Cluster):
        self._cluster = cluster

    def pod_groups(self, version_mod=v1alpha1) -> _TypedInformer:
        return _TypedInformer(
            self._cluster.pod_group_informer,
            lambda pg: type(pg) is version_mod.PodGroup)

    def queues(self, version_mod=v1alpha1) -> _TypedInformer:
        return _TypedInformer(
            self._cluster.queue_informer,
            lambda q: type(q) is version_mod.Queue)

    def pods(self) -> _TypedInformer:
        return _TypedInformer(self._cluster.pod_informer, lambda p: True)

    def nodes(self) -> _TypedInformer:
        return _TypedInformer(self._cluster.node_informer, lambda n: True)

    def pod_group_lister(self, version_mod=v1alpha1) -> _PodGroupLister:
        return _PodGroupLister(self._cluster, version_mod)

    def queue_lister(self, version_mod=v1alpha1) -> _QueueLister:
        return _QueueLister(self._cluster, version_mod)
