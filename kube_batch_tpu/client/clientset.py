"""Typed clientsets for the scheduling API groups.

Counterpart of the reference's generated clients
(/root/reference/pkg/client/clientset): typed CRUD for PodGroup and Queue in
both API versions against a cluster-state store, plus fakes.  The store may
be the in-memory Cluster simulator OR an edge.client.RemoteCluster — both
expose the same verbs and mirror dicts, so the typed clients work over the
network edge unchanged (``new_for_cluster(RemoteCluster(url).start())``).
"""

from __future__ import annotations

import copy
from typing import List, Optional

from ..apis.scheduling import v1alpha1, v1alpha2
from ..cache.cluster import Cluster


class _PodGroupClient:
    """Typed PodGroup CRUD for one API version."""

    def __init__(self, cluster: Cluster, version_mod, namespace: str):
        self._cluster = cluster
        self._version = version_mod
        self._namespace = namespace

    def _check(self, pg) -> None:
        if not type(pg) is self._version.PodGroup:
            raise TypeError(
                f"expected {self._version.VERSION} PodGroup, got {type(pg)}")

    def create(self, pg):
        self._check(pg)
        pg.metadata.namespace = pg.metadata.namespace or self._namespace
        return self._cluster.create_pod_group(pg)

    def update(self, pg):
        self._check(pg)
        return self._cluster.update_pod_group(pg)

    def update_status(self, pg):
        return self.update(pg)

    def get(self, name: str):
        pg = self._cluster.pod_groups.get(f"{self._namespace}/{name}")
        if pg is None or not type(pg) is self._version.PodGroup:
            raise KeyError(f"podgroup {self._namespace}/{name} not found")
        return copy.deepcopy(pg)

    def list(self) -> List:
        return [copy.deepcopy(pg) for key, pg in
                self._cluster.pod_groups.items()
                if type(pg) is self._version.PodGroup
                and key.startswith(f"{self._namespace}/")]

    def delete(self, name: str) -> None:
        self._cluster.delete_pod_group(self._namespace, name)


class _QueueClient:
    """Typed Queue CRUD (cluster-scoped) for one API version."""

    def __init__(self, cluster: Cluster, version_mod):
        self._cluster = cluster
        self._version = version_mod

    def create(self, queue):
        if not type(queue) is self._version.Queue:
            raise TypeError(
                f"expected {self._version.VERSION} Queue, got {type(queue)}")
        return self._cluster.create_queue(queue)

    def get(self, name: str):
        q = self._cluster.queues.get(name)
        if q is None or not type(q) is self._version.Queue:
            raise KeyError(f"queue {name} not found")
        return copy.deepcopy(q)

    def list(self) -> List:
        return [copy.deepcopy(q) for q in self._cluster.queues.values()
                if type(q) is self._version.Queue]

    def delete(self, name: str) -> None:
        self._cluster.delete_queue(name)


class _VersionGroup:
    def __init__(self, cluster: Cluster, version_mod):
        self._cluster = cluster
        self._version = version_mod

    def pod_groups(self, namespace: str = "default") -> _PodGroupClient:
        return _PodGroupClient(self._cluster, self._version, namespace)

    def queues(self) -> _QueueClient:
        return _QueueClient(self._cluster, self._version)


class Clientset:
    """Typed access to both scheduling API versions (reference
    clientset/versioned.Clientset)."""

    def __init__(self, cluster: Cluster):
        self._cluster = cluster
        self.scheduling_v1alpha1 = _VersionGroup(cluster, v1alpha1)
        self.scheduling_v1alpha2 = _VersionGroup(cluster, v1alpha2)


def new_for_cluster(cluster: Cluster) -> Clientset:
    return Clientset(cluster)
