"""Typed clients for the scheduling API groups (reference pkg/client/)."""

from .clientset import Clientset, new_for_cluster
from .informers import SharedInformerFactory

__all__ = ["Clientset", "new_for_cluster", "SharedInformerFactory"]
