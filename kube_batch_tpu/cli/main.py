"""Process entry point (reference cmd/kube-batch/main.go:39)."""

from __future__ import annotations

import signal
import sys
import threading


def main(argv=None) -> int:
    from ..actions.factory import register_default_actions
    from ..plugins.factory import register_default_plugins
    from ..version import version_string
    from .options import parse_options
    from .server import ServerRuntime

    opt = parse_options(argv)
    if opt.print_version:
        print(version_string())
        return 0

    # Blank-import equivalent: register actions/plugins (main.go:32-35).
    register_default_actions()
    register_default_plugins()

    runtime = ServerRuntime(opt)
    runtime.run()

    stop = threading.Event()

    def handle(sig, frame):
        stop.set()

    signal.signal(signal.SIGINT, handle)
    signal.signal(signal.SIGTERM, handle)
    stop.wait()
    runtime.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
