"""Process runtime (L6): flags, server, metrics endpoint, leader election.

TPU-native counterpart of /root/reference/cmd/kube-batch/.
"""

from .options import ServerOption, parse_options
from .server import ServerRuntime, start_metrics_server, load_cluster_state
from .leader_election import LeaderElectionConfig, LeaderElector

__all__ = ["ServerOption", "parse_options", "ServerRuntime",
           "start_metrics_server", "load_cluster_state",
           "LeaderElectionConfig", "LeaderElector"]
