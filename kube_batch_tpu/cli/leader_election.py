"""Leader election over a shared lock object.

The reference coordinates HA standbys with a ConfigMap-lock LeaderElector
(lease 15s / renew 10s / retry 5s,
/root/reference/cmd/kube-batch/app/server.go:48-53,115-139); loss of lease
kills the scheduling loop and a standby takes over.  Two lock backends:

- ``StoreLock``: a lease object in the cluster-state store, updated via
  compare-and-swap on its resource version (the ConfigMap analog) — any
  standby anywhere that can reach the store (in-process Cluster or the
  HTTP edge) coordinates through it.
- ``FileLock``: a lock file with the same lease semantics, for
  multi-process deployments sharing a filesystem (no store required).
"""

from __future__ import annotations

import fcntl  # FileLock is Unix-only; fail at import, not silently in cas()
import json
import os
import socket
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional

DEFAULT_LEASE_DURATION = 15.0
DEFAULT_RENEW_DEADLINE = 10.0
DEFAULT_RETRY_PERIOD = 5.0

LOCK_NAME = "kube-batch-lock"


class FileLock:
    """Lock record in a file with true compare-and-swap semantics.

    A version counter is stored inside the record; ``cas`` serializes the
    re-read/compare/replace under an ``fcntl.flock`` on a sidecar file, so
    two standbys that both observed an expired lease cannot both "acquire"
    it (the loser sees the bumped version and fails).  flock is released by
    the kernel when the holder dies — a crashed process cannot wedge the
    mutex, and there is no stale-break heuristic to race on.

    CAUTION: flock coherence is per-host on common network filesystems
    (NFS with local_lock, SMB) — contenders on DIFFERENT hosts may each
    take a host-local flock and race the read/compare/replace.  FileLock
    is therefore for same-host multi-process deployments (or a
    flock-coherent shared FS); multi-host HA must use StoreLock, whose
    CAS is serialized by the store itself."""

    def __init__(self, path: str):
        self.path = path
        self._sidecar = f"{path}.mutex"

    def _read(self):
        try:
            with open(self.path) as f:
                record = json.load(f)
            return int(record.get("version", 0)), record
        except (OSError, ValueError):
            return 0, None

    def get(self):
        return self._read()

    def cas(self, record: dict, expected_version: int) -> bool:
        try:
            fd = os.open(self._sidecar, os.O_CREAT | os.O_RDWR)
        except OSError:
            return False
        try:
            try:
                fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
            except OSError:
                return False  # another contender is mid-CAS
            current_version, _ = self._read()
            if current_version != expected_version:
                return False
            record = dict(record, version=expected_version + 1)
            tmp = f"{self.path}.{os.getpid()}.tmp"
            with open(tmp, "w") as f:
                json.dump(record, f)
            os.replace(tmp, self.path)
            return True
        except OSError:
            return False
        finally:
            os.close(fd)  # releases the flock


class StoreLock:
    """Lease object in the cluster store (Cluster or RemoteCluster —
    both expose get_lease/cas_lease; over the edge the CAS rides a
    version-guarded PUT that 409s on conflict)."""

    def __init__(self, cluster, namespace: str, name: str = LOCK_NAME):
        self.cluster = cluster
        self.namespace = namespace
        self.name = name

    def get(self):
        return self.cluster.get_lease(self.namespace, self.name)

    def cas(self, record: dict, expected_version: int) -> bool:
        try:
            self.cluster.cas_lease(self.namespace, self.name, record,
                                   expected_version)
            return True
        except (ValueError, KeyError):
            return False


def cas_release(lock, identity: str,
                lease_duration: float = DEFAULT_LEASE_DURATION) -> bool:
    """CAS-clear a lease THIS identity holds so the next contender can
    acquire immediately instead of waiting out the expiry.  Returns
    False (never raises) when the lease is not ours, the CAS loses, or
    the store is unreachable — release is best-effort by design: an
    unreleased lease simply expires on schedule.  Shared by the global
    elector's embedders and the per-shard federation
    (tenancy/leases.ShardLeaseManager, doc/TENANCY.md)."""
    try:
        version, record = lock.get()
    except Exception:  # lint: allow-swallow(unreachable store: the lease will expire on schedule, which is the release fallback)
        return False
    if (record or {}).get("holderIdentity") != identity:
        return False
    released = {"holderIdentity": "", "renewTime": 0.0,
                "leaseDurationSeconds": lease_duration,
                "releasedBy": identity, "releasedAt": time.time()}
    try:
        return bool(lock.cas(released, version))
    except Exception:  # lint: allow-swallow(CAS conflict means someone already replaced the record; expiry remains the fallback)
        return False


@dataclass
class LeaderElectionConfig:
    lock_path: str = ""
    identity: str = ""
    lease_duration: float = DEFAULT_LEASE_DURATION
    renew_deadline: float = DEFAULT_RENEW_DEADLINE
    retry_period: float = DEFAULT_RETRY_PERIOD

    def __post_init__(self):
        if not self.identity:
            # hostname + uuid, like client-go's default id: pid alone
            # collides for two electors in one process, and the second
            # would mistake the first's lease for its own and self-renew.
            import uuid
            self.identity = (f"{socket.gethostname()}-{os.getpid()}-"
                             f"{uuid.uuid4().hex[:8]}")


class LeaderElector:
    """Acquire-and-renew loop (client-go leaderelection semantics) over a
    pluggable lock."""

    def __init__(self, config: LeaderElectionConfig,
                 on_started_leading: Callable[[], None],
                 on_stopped_leading: Callable[[], None],
                 lock=None):
        self.config = config
        self.lock = lock if lock is not None else FileLock(config.lock_path)
        self.on_started_leading = on_started_leading
        self.on_stopped_leading = on_stopped_leading
        self._stop = threading.Event()
        self.is_leader = False
        self.last_renew = 0.0

    def has_live_lease(self) -> bool:
        """True while this elector holds a lease it has renewed within
        renew_deadline.  Unlike ``is_leader`` (flipped by the elector
        *thread*, which may not have run yet after a long process pause),
        this is wall-clock-based: a zombie that slept past its deadline is
        fenced immediately, regardless of thread scheduling."""
        return (self.is_leader
                and time.time() - self.last_renew
                < self.config.renew_deadline)

    def try_acquire_or_renew(self) -> bool:
        try:
            version, record = self.lock.get()
        except Exception:  # lint: allow-swallow(store unreachable: cannot prove the lease, so report not-acquired and retry next tick)
            return False
        now = time.time()
        if (record is not None
                and record.get("holderIdentity") != self.config.identity):
            expires = record.get("renewTime", 0) + record.get(
                "leaseDurationSeconds", self.config.lease_duration)
            if now < expires:
                return False  # someone else holds a live lease
        new_record = {"holderIdentity": self.config.identity,
                      "renewTime": now,
                      "leaseDurationSeconds": self.config.lease_duration}
        try:
            return self.lock.cas(new_record, version)
        except Exception:  # lint: allow-swallow(CAS conflict or unreachable store both mean "did not acquire"; the elector loop retries)
            return False

    # -- loop ---------------------------------------------------------------

    def run(self) -> None:
        """Block until leadership is acquired, run the callback, then renew
        until the lease is lost (then on_stopped_leading halts the loop,
        like the reference's fatal exit path, server.go:135-137)."""
        while not self._stop.is_set():
            if self.try_acquire_or_renew():
                break
            self._stop.wait(self.config.retry_period)
        if self._stop.is_set():
            return
        self.last_renew = time.time()
        self.is_leader = True
        # client-go runs OnStartedLeading in its own goroutine
        # (leaderelection.go): a slow leader startup (cache sync at scale)
        # must not delay renewals, or the wall-clock fence would refuse the
        # new leader's first writes and one transient store hiccup could
        # abdicate it despite the continuous-failure deadline.
        threading.Thread(target=self.on_started_leading, daemon=True).start()
        # client-go renewal semantics: retry every retry_period; abdicate
        # only after renew_deadline of CONTINUOUS failure — one transient
        # store hiccup must not fail over a healthy leader.
        while not self._stop.is_set():
            self._stop.wait(self.config.retry_period)
            if self._stop.is_set():
                break
            if self.try_acquire_or_renew():
                self.last_renew = time.time()
            elif time.time() - self.last_renew > self.config.renew_deadline:
                self.is_leader = False
                self.on_stopped_leading()
                return

    def stop(self) -> None:
        self._stop.set()
