"""Leader election over a shared lock object.

The reference coordinates HA standbys with a ConfigMap-lock
LeaderElector (lease 15s / renew 10s / retry 5s,
/root/reference/cmd/kube-batch/app/server.go:48-53,115-139); loss of lease
kills the process and a standby takes over.  Here the lock object lives in
the cluster-state store's namespace — for the file-backed simulator that is
a lock file with the same lease semantics, which gives identical failover
behavior for multi-process deployments sharing a state directory.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional

DEFAULT_LEASE_DURATION = 15.0
DEFAULT_RENEW_DEADLINE = 10.0
DEFAULT_RETRY_PERIOD = 5.0


@dataclass
class LeaderElectionConfig:
    lock_path: str
    identity: str = ""
    lease_duration: float = DEFAULT_LEASE_DURATION
    renew_deadline: float = DEFAULT_RENEW_DEADLINE
    retry_period: float = DEFAULT_RETRY_PERIOD

    def __post_init__(self):
        if not self.identity:
            self.identity = f"{socket.gethostname()}-{os.getpid()}"


class LeaderElector:
    """Acquire-and-renew loop (client-go leaderelection semantics)."""

    def __init__(self, config: LeaderElectionConfig,
                 on_started_leading: Callable[[], None],
                 on_stopped_leading: Callable[[], None]):
        self.config = config
        self.on_started_leading = on_started_leading
        self.on_stopped_leading = on_stopped_leading
        self._stop = threading.Event()
        self.is_leader = False

    # -- lock record --------------------------------------------------------

    def _read_record(self) -> Optional[dict]:
        try:
            with open(self.config.lock_path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def _write_record(self) -> bool:
        record = {"holderIdentity": self.config.identity,
                  "renewTime": time.time(),
                  "leaseDurationSeconds": self.config.lease_duration}
        tmp = f"{self.config.lock_path}.{self.config.identity}.tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(record, f)
            os.replace(tmp, self.config.lock_path)
            return True
        except OSError:
            return False

    def try_acquire_or_renew(self) -> bool:
        record = self._read_record()
        now = time.time()
        if record is not None and record.get("holderIdentity") != self.config.identity:
            expires = record.get("renewTime", 0) + record.get(
                "leaseDurationSeconds", self.config.lease_duration)
            if now < expires:
                return False  # someone else holds a live lease
        return self._write_record()

    # -- loop ---------------------------------------------------------------

    def run(self) -> None:
        """Block until leadership is acquired, run the callback, then renew
        until the lease is lost (then on_stopped_leading, like the
        reference's fatal exit path)."""
        while not self._stop.is_set():
            if self.try_acquire_or_renew():
                break
            self._stop.wait(self.config.retry_period)
        if self._stop.is_set():
            return
        self.is_leader = True
        self.on_started_leading()
        while not self._stop.is_set():
            self._stop.wait(self.config.renew_deadline / 2)
            if self._stop.is_set():
                break
            if not self.try_acquire_or_renew():
                self.is_leader = False
                self.on_stopped_leading()
                return

    def stop(self) -> None:
        self._stop.set()
