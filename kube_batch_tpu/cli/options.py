"""CLI options.

Mirrors /root/reference/cmd/kube-batch/app/options/options.go:34-89 — the 11
flags (master/kubeconfig become the simulator's state-file path here),
defaults included (schedule-period 1s, default-queue "default", listen
address :8080).
"""

from __future__ import annotations

import argparse
from dataclasses import dataclass, field

DEFAULT_SCHEDULER_NAME = "kube-batch"
DEFAULT_SCHEDULE_PERIOD = 1.0
DEFAULT_QUEUE = "default"
DEFAULT_LISTEN_ADDRESS = ":8080"


@dataclass
class ServerOption:
    master: str = ""
    kubeconfig: str = ""
    scheduler_name: str = DEFAULT_SCHEDULER_NAME
    scheduler_conf: str = ""
    schedule_period: float = DEFAULT_SCHEDULE_PERIOD
    default_queue: str = DEFAULT_QUEUE
    enable_leader_election: bool = True
    lock_object_namespace: str = ""
    print_version: bool = False
    listen_address: str = DEFAULT_LISTEN_ADDRESS
    priority_class: bool = True
    # Explicit opt-in to the per-host FileLock HA backend (flock
    # coherence does not span hosts on common network filesystems; see
    # leader_election.FileLock).  Without it, a cluster edge that cannot
    # host a store lock refuses leader election at config time.
    file_lock_same_host_ok: bool = False
    # Simulator extras (no reference counterpart): cluster spec to load.
    cluster_state: str = ""
    # Compile-ahead subsystem (ops/compile_cache.py): solver buckets to
    # pre-compile at boot, and the persistent XLA cache location so those
    # compiles survive restarts and leader failover.
    warmup_buckets: str = ""
    compile_cache_dir: str = ""
    # Observability (doc/OBSERVABILITY.md): direct the XLA profiler at a
    # directory to capture a device trace around every session's solve
    # window (actions/tpu_allocate.PROFILE_ENV hook).
    jax_profile_dir: str = ""
    # Queue-shard tenancy engine + active-active replica federation
    # (kube_batch_tpu/tenancy/, doc/TENANCY.md): shard count (0 defers
    # to KUBE_BATCH_TPU_TENANCY / disabled), per-shard CAS leases in the
    # shared store instead of one global leader, and the shard lease
    # timing (renew deadline is derived as 3/5 of the duration, the
    # global elector's 15s/10s/5s ratio).
    tenancy_shards: int = 0
    replica_federation: bool = False
    shard_lease_duration: float = 5.0

    def check_option_or_die(self) -> None:
        """options.go:81-88: leader election requires a lock namespace."""
        if self.enable_leader_election and not self.lock_object_namespace:
            raise ValueError(
                "lock-object-namespace must not be nil when LeaderElection is enabled")
        if self.replica_federation:
            if self.enable_leader_election:
                raise ValueError(
                    "--replica-federation replaces --leader-elect: "
                    "per-shard leases ARE the election — enable one, "
                    "not both (doc/TENANCY.md)")
            if not self.lock_object_namespace:
                raise ValueError(
                    "lock-object-namespace must not be nil when replica "
                    "federation is enabled (the shard leases live there)")
            if self.shard_lease_duration <= 0:
                raise ValueError("shard-lease-duration must be > 0")


def add_flags(parser: argparse.ArgumentParser) -> None:
    parser.add_argument("--master", default="",
                        help="The address of the cluster state server")
    parser.add_argument("--kubeconfig", default="",
                        help="Path to a cluster connection config file")
    parser.add_argument("--scheduler-name", default=DEFAULT_SCHEDULER_NAME,
                        help="Only schedule pods with this schedulerName")
    parser.add_argument("--scheduler-conf", default="",
                        help="Path to the YAML scheduler configuration")
    parser.add_argument("--schedule-period", type=float,
                        default=DEFAULT_SCHEDULE_PERIOD,
                        help="Seconds between scheduling cycles")
    parser.add_argument("--default-queue", default=DEFAULT_QUEUE,
                        help="Queue for jobs that specify none")
    parser.add_argument("--leader-elect", action="store_true", default=False,
                        help="Enable leader election for HA deployments")
    parser.add_argument("--lock-object-namespace", default="",
                        help="Namespace of the leader-election lock object")
    parser.add_argument("--version", action="store_true", default=False,
                        help="Print version and exit")
    parser.add_argument("--listen-address", default=DEFAULT_LISTEN_ADDRESS,
                        help="Address for the /metrics endpoint")
    parser.add_argument("--priority-class", dest="priority_class",
                        action="store_true", default=True,
                        help="Enable PriorityClass-based job priority")
    parser.add_argument("--no-priority-class", dest="priority_class",
                        action="store_false",
                        help="Disable PriorityClass-based job priority")
    parser.add_argument("--leader-elect-file-lock", dest="file_lock",
                        action="store_true", default=False,
                        help="Accept the file-based election lock (flock "
                             "coherence is PER-HOST: safe only for "
                             "same-host standbys or a flock-coherent "
                             "shared filesystem)")
    parser.add_argument("--cluster-state", default="",
                        help="Path to a JSON cluster snapshot for the simulator")
    parser.add_argument("--warmup-buckets", default="",
                        help="Comma-separated TASKSxNODES[xJOBS[xQUEUES]] "
                             "shape buckets to pre-compile the solver "
                             "family for at boot (e.g. 50000x10000x2000x4),"
                             " so no live session pays a first-call XLA "
                             "compile")
    parser.add_argument("--compile-cache-dir", default="",
                        help="Directory for JAX's persistent compilation "
                             "cache; solver compiles survive process "
                             "restarts and leader failover")
    parser.add_argument("--tenancy-shards", type=int, default=0,
                        help="Queue-shard count for the tenancy engine: "
                             "per-tenant micro-sessions pipeline per "
                             "shard instead of one global cycle "
                             "(0 defers to KUBE_BATCH_TPU_TENANCY; "
                             "doc/TENANCY.md)")
    parser.add_argument("--replica-federation", action="store_true",
                        default=False,
                        help="Active-active replicas: claim queue-shards "
                             "via per-shard CAS leases in the shared "
                             "store (replaces --leader-elect; requires "
                             "--tenancy-shards and "
                             "--lock-object-namespace)")
    parser.add_argument("--shard-lease-duration", type=float, default=5.0,
                        help="Per-shard lease duration in seconds; an "
                             "orphaned shard is stolen within one "
                             "duration of its owner's death")
    parser.add_argument("--jax-profile-dir", default="",
                        help="Capture a jax.profiler trace of each "
                             "session's device solve window into this "
                             "directory (TensorBoard/Perfetto-loadable); "
                             "empty disables profiling")


def parse_options(argv=None) -> ServerOption:
    parser = argparse.ArgumentParser(prog="kube-batch-tpu")
    add_flags(parser)
    ns = parser.parse_args(argv)
    return ServerOption(
        master=ns.master, kubeconfig=ns.kubeconfig,
        scheduler_name=ns.scheduler_name, scheduler_conf=ns.scheduler_conf,
        schedule_period=ns.schedule_period, default_queue=ns.default_queue,
        enable_leader_election=ns.leader_elect,
        lock_object_namespace=ns.lock_object_namespace,
        print_version=ns.version, listen_address=ns.listen_address,
        priority_class=ns.priority_class,
        file_lock_same_host_ok=ns.file_lock,
        cluster_state=ns.cluster_state,
        warmup_buckets=ns.warmup_buckets,
        compile_cache_dir=ns.compile_cache_dir,
        jax_profile_dir=ns.jax_profile_dir,
        tenancy_shards=ns.tenancy_shards,
        replica_federation=ns.replica_federation,
        shard_lease_duration=ns.shard_lease_duration)
