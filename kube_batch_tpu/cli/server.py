"""Server runtime: scheduler startup, /metrics + /debug endpoints,
leader election.

Mirrors /root/reference/cmd/kube-batch/app/server.go:63-139 — Run() builds
the cache and scheduler, serves Prometheus metrics over HTTP, and wraps the
scheduling loop in leader election when enabled.  The flight-recorder
endpoints (doc/OBSERVABILITY.md) ride the same server:

  /debug                     index of every debug endpoint (JSON)
  /debug/sessions            recent session summaries (JSON)
  /debug/trace?session=<id>  one session as Chrome trace-event JSON
                             (open in Perfetto / chrome://tracing)
  /debug/why?job=<name>      the gating predicate/quota/gang reason for a
                             Pending job, answered from the recorder
  /debug/lineage?pod=<name>  one pod's end-to-end scheduling timeline
                             (ingest -> considered -> placed -> bind ->
                             echo), answered from the lineage ring
  /debug/tenants             per-queue fairness table (share vs
                             deserved, starvation age) from the last
                             session's proportion/drf opens
  /debug/topology            per-pool fragmentation (free nodes,
                             largest contiguous free block, frag
                             ratio) + slice placement outcomes
  /debug/memory              fleet memory ledger: per-subsystem bytes,
                             watermarks (with the session that set
                             them), process RSS, optional tracemalloc
                             top-K (KUBE_BATCH_TPU_MEMTRACE=1)
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlsplit

from ..cache import Cluster, new_scheduler_cache
from ..metrics import metrics
from ..metrics.metrics import registry
from ..scheduler import Scheduler
from ..trace import export as trace_export
from ..trace import flight_recorder
from .leader_election import (LeaderElectionConfig, LeaderElector,
                              StoreLock)
from .options import ServerOption


class _MetricsHandler(BaseHTTPRequestHandler):
    def do_GET(self):
        parts = urlsplit(self.path)
        path = parts.path
        if path == "/metrics":
            body = registry.expose().encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif path == "/healthz":
            self.send_response(200)
            self.send_header("Content-Length", "2")
            self.end_headers()
            self.wfile.write(b"ok")
        elif path == "/debug" or path.startswith("/debug/"):
            try:
                self._debug(path, parse_qs(parts.query))
            except Exception:  # a debug read must never kill the server
                metrics.note_swallowed("debug_endpoint")
                self._send_json({"error": "internal error"}, 500)
        else:
            self.send_response(404)
            self.end_headers()

    # One-line description per endpoint: the /debug index page, so
    # operators stop guessing URLs (doc/OBSERVABILITY.md).
    _DEBUG_INDEX = {
        "/debug/sessions": "recent session summaries: phases, verdicts, "
                           "evictions, degraded reasons, floors",
        "/debug/trace?session=<id|latest>": "one session as Chrome "
                           "trace-event JSON (open in ui.perfetto.dev)",
        "/debug/why?job=<[ns/]name>": "why is this job still Pending — "
                           "the gating plugin verdict + solver tally",
        "/debug/lineage?pod=<[ns/]name>": "one pod's end-to-end timeline:"
                           " ingest -> considered -> placed -> bind -> "
                           "echo, with time-to-bind",
        "/debug/tenants": "per-queue fairness: share vs deserved, "
                          "pending demand, starvation age",
        "/debug/shards": "queue-shard tenancy: shard -> owner -> queues "
                         "-> lease expiry, per-shard session counts "
                         "(doc/TENANCY.md)",
        "/debug/topology": "per-pool fragmentation: free nodes, largest "
                           "contiguous free block, frag ratio, slice "
                           "placement outcomes",
        "/debug/memory": "fleet memory ledger: per-subsystem bytes, "
                         "watermarks with owning session, process RSS, "
                         "tracemalloc top-K (KUBE_BATCH_TPU_MEMTRACE=1)",
    }

    def _debug(self, path: str, query: dict) -> None:
        """The flight-recorder read endpoints.  Read-only: everything is
        answered from recorded traces, nothing re-runs."""
        from ..metrics.tenants import tenant_table
        from ..trace import pod_lineage

        if path in ("/debug", "/debug/"):
            self._send_json({"endpoints": self._DEBUG_INDEX,
                             "tracing_enabled": _trace_enabled(),
                             "lineage": pod_lineage.summary()})
        elif path == "/debug/lineage":
            pod = (query.get("pod") or [""])[0]
            if not pod:
                self._send_json({"error": "pass ?pod=<[namespace/]name>"},
                                400)
                return
            answer = pod_lineage.lineage(pod)
            if answer is None:
                self._send_json(
                    {"pod": pod,
                     "error": "not in the lineage ring: the pod was "
                              "never ingested Pending, aged out of the "
                              "ring, or lineage is disabled "
                              "(KUBE_BATCH_TPU_LINEAGE=0)"}, 404)
                return
            self._send_json(answer)
        elif path == "/debug/tenants":
            self._send_json(tenant_table.snapshot())
        elif path == "/debug/shards":
            from ..tenancy import shard_table
            doc = shard_table.snapshot()
            doc["rebalances"] = metrics.shard_rebalance_counts()
            self._send_json(doc)
        elif path == "/debug/topology":
            from ..models.topology import topo_table
            doc = topo_table.snapshot()
            doc["slices"] = metrics.topo_slice_counts()
            self._send_json(doc)
        elif path == "/debug/memory":
            from ..metrics import memledger
            self._send_json(memledger.debug_doc())
        elif path == "/debug/sessions":
            self._send_json({"sessions": flight_recorder.summaries(),
                             "capacity": flight_recorder.capacity,
                             "evictions_total":
                                 metrics.evictions_by_action(),
                             # Mirror-memory accounting (ROADMAP item 1):
                             # retained raw-doc delta baselines per
                             # resource kind ({} for in-process caches).
                             "wire_baseline_bytes":
                                 metrics.wire_baseline_totals(),
                             "tracing_enabled":
                                 _trace_enabled()})
        elif path == "/debug/trace":
            raw = (query.get("session") or [""])[0]
            trace = None
            if raw == "latest":
                trace = flight_recorder.latest()
            elif raw.isdigit():
                trace = flight_recorder.get(int(raw))
            if trace is None:
                self._send_json(
                    {"error": "unknown session; pass ?session=<id> from "
                              "/debug/sessions (or session=latest)"}, 404)
                return
            self._send_json(trace_export.to_chrome_trace(trace))
        elif path == "/debug/why":
            job = (query.get("job") or [""])[0]
            if not job:
                self._send_json({"error": "pass ?job=<name>"}, 400)
                return
            answer = flight_recorder.why(job)
            if answer is None:
                self._send_json(
                    {"job": job,
                     "error": "no recorded verdict: the job was absent, "
                              "schedulable, or tracing is disabled "
                              "(KUBE_BATCH_TPU_TRACE=0)"}, 404)
                return
            self._send_json(answer)
        else:
            self.send_response(404)
            self.end_headers()

    def _send_json(self, obj, status: int = 200) -> None:
        body = json.dumps(obj).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):  # quiet
        pass


def _trace_enabled() -> bool:
    from ..trace import spans
    return spans.enabled()


def start_metrics_server(listen_address: str) -> ThreadingHTTPServer:
    """Serve /metrics like server.go:83-86; returns the server (its port is
    discoverable via .server_address for ':0' style binds)."""
    host, _, port = listen_address.rpartition(":")
    server = ThreadingHTTPServer((host or "0.0.0.0", int(port or 8080)),
                                 _MetricsHandler)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    return server


def stop_metrics_server(server: ThreadingHTTPServer) -> None:
    """Tear down a start_metrics_server() server: stops serve_forever
    (the serving thread exits with it) and closes the listening socket —
    shutdown() alone leaks the bound port for the life of the process."""
    server.shutdown()
    server.server_close()


def load_cluster_state(cluster: Cluster, path: str) -> None:
    """Populate the simulator from a JSON snapshot file (the standalone
    analog of pointing --master at an API server)."""
    from ..api.objects import (Container, Node, NodeSpec, NodeStatus,
                               ObjectMeta, Pod, PodSpec, PodStatus)
    from ..apis.scheduling import v1alpha1

    with open(path) as f:
        state = json.load(f)
    for n in state.get("nodes", []):
        cluster.create_node(Node(
            metadata=ObjectMeta(name=n["name"], labels=n.get("labels", {})),
            spec=NodeSpec(),
            status=NodeStatus(allocatable=n.get("allocatable", {}),
                              capacity=n.get("capacity",
                                             n.get("allocatable", {})))))
    for q in state.get("queues", []):
        cluster.create_queue(v1alpha1.Queue(
            metadata=ObjectMeta(name=q["name"]),
            spec=v1alpha1.QueueSpec(weight=q.get("weight", 1))))
    for pg in state.get("podGroups", []):
        cluster.create_pod_group(v1alpha1.PodGroup(
            metadata=ObjectMeta(name=pg["name"],
                                namespace=pg.get("namespace", "default")),
            spec=v1alpha1.PodGroupSpec(
                min_member=pg.get("minMember", 1),
                queue=pg.get("queue", "default"))))
    for p in state.get("pods", []):
        annotations = {}
        if p.get("group"):
            annotations[v1alpha1.GroupNameAnnotationKey] = p["group"]
        cluster.create_pod(Pod(
            metadata=ObjectMeta(name=p["name"],
                                namespace=p.get("namespace", "default"),
                                annotations=annotations),
            spec=PodSpec(node_name=p.get("nodeName", ""),
                         containers=[Container(requests=p.get("requests", {}))]),
            status=PodStatus(phase=p.get("phase", "Pending"))))


class ServerRuntime:
    """The running process: cluster edge + scheduler + metrics endpoint."""

    def __init__(self, opt: ServerOption, cluster: Optional[Cluster] = None,
                 lease_config: Optional[LeaderElectionConfig] = None):
        self.opt = opt
        self._lease_config = lease_config
        # Compile-ahead subsystem (ops/compile_cache.py): point JAX's
        # persistent cache at the configured directory BEFORE anything can
        # compile (so even un-warmed shapes persist across restarts), and
        # parse the warmup buckets now — a malformed flag must fail boot,
        # not the first session.  The warmup thread itself starts in run().
        self.warmup = None
        self._warmup_buckets = []
        if opt.compile_cache_dir:
            from ..ops.compile_cache import enable_persistent_cache
            enable_persistent_cache(opt.compile_cache_dir)
        if opt.warmup_buckets:
            from ..ops.compile_cache import parse_warmup_buckets
            self._warmup_buckets = parse_warmup_buckets(opt.warmup_buckets)
        if opt.jax_profile_dir:
            # The solve-window profiler hook reads PROFILE_ENV per
            # session (actions/tpu_allocate._maybe_profile): the flag is
            # just its configuration surface.
            import os
            from ..actions.tpu_allocate import PROFILE_ENV
            os.environ[PROFILE_ENV] = opt.jax_profile_dir
        # Whether the backing store is SHARED with other standbys — the
        # precondition for a store-hosted election lock.  An injected
        # cluster is shared by construction (the embedder hands the same
        # object/edge to every runtime); a --master edge is shared by the
        # server behind it; a self-built in-process Cluster is private to
        # this process, so a lease in it would only ever elect ourselves.
        self._cluster_shared = True
        if cluster is not None:
            self.cluster = cluster
        elif opt.master:
            # The network edge (reference server.go:55-60 buildConfig):
            # --master points at an edge.server.ApiServer; ingest and
            # effectors ride HTTP instead of the in-process store.
            from ..edge import RemoteCluster
            self.cluster = RemoteCluster(opt.master).start()
        else:
            self.cluster = Cluster()
            self._cluster_shared = False
        if opt.cluster_state:
            # Works against both edges: RemoteCluster exposes the same
            # create verbs over REST, so a seed file submits remotely too.
            load_cluster_state(self.cluster, opt.cluster_state)
        self.cache = new_scheduler_cache(
            self.cluster, scheduler_name=opt.scheduler_name,
            default_queue=opt.default_queue,
            priority_class_enabled=opt.priority_class)
        conf_str = None
        if opt.scheduler_conf:
            with open(opt.scheduler_conf) as f:
                conf_str = f.read()
        self.scheduler = Scheduler(self.cache, scheduler_conf=conf_str,
                                   schedule_period=opt.schedule_period)
        # Queue-shard tenancy by flag (doc/TENANCY.md): the env route
        # (KUBE_BATCH_TPU_TENANCY) already built an engine inside the
        # Scheduler; --tenancy-shards builds one here when it did not.
        if opt.tenancy_shards and self.scheduler.tenancy is None:
            from ..tenancy import ShardMap, TenancyEngine
            self.scheduler.tenancy = TenancyEngine(
                self.scheduler, ShardMap.from_env(opt.tenancy_shards))
        self.metrics_server: Optional[ThreadingHTTPServer] = None
        self.elector: Optional[LeaderElector] = None
        self.shard_leases = None  # Optional[tenancy.ShardLeaseManager]

    def run(self) -> None:
        """server.go Run(): metrics endpoint, then leader-elect or start."""
        if self.opt.listen_address:
            self.metrics_server = start_metrics_server(self.opt.listen_address)
        if self._warmup_buckets:
            # Pre-compile the solver family for the configured buckets in
            # the background: the scheduler loop starts immediately, and
            # the first live session of a warmed bucket never waits on
            # XLA.  A standby wins doubly — by the time it acquires the
            # lease its compiles are done (or already on disk).  The cfg
            # is derived from the LOADED conf (SolverConfig is a static
            # jit arg — warming the default cfg under a non-default conf
            # would compile executables no session ever hits); a conf
            # that needs the host fallback skips warmup entirely.
            from ..models.tensor_snapshot import solver_config_from_tiers
            cfg = solver_config_from_tiers(self.scheduler.tiers)
            if cfg is not None:
                from ..ops.compile_cache import SolverWarmup
                self.warmup = SolverWarmup(
                    self._warmup_buckets, cfg=cfg,
                    cache_dir=self.opt.compile_cache_dir or None).start()
        if self.opt.replica_federation:
            # Active-active federation (doc/TENANCY.md): no global
            # leader — this replica claims queue-shards via per-shard
            # CAS leases in the SHARED store and schedules exactly what
            # it owns; the shard lease fences each shard's write egress
            # the way the global write fence fences a lost leadership.
            self.opt.check_option_or_die()
            engine = self.scheduler.tenancy
            if engine is None:
                raise ValueError(
                    "--replica-federation requires the tenancy engine: "
                    "pass --tenancy-shards N (or KUBE_BATCH_TPU_TENANCY)")
            if not (self._cluster_shared
                    and hasattr(self.cluster, "cas_lease")):
                raise ValueError(
                    "replica federation needs a SHARED store for its "
                    "shard leases (point every replica at one cluster "
                    "edge via --master); a process-private store would "
                    "elect this replica onto every shard in its own "
                    "world")
            from ..tenancy import ShardLeaseManager
            duration = self.opt.shard_lease_duration
            self.shard_leases = ShardLeaseManager(
                self.cluster, self.opt.lock_object_namespace,
                engine.map.num_shards,
                lease_duration=duration,
                renew_deadline=duration * 0.6,
                retry_period=max(0.05, duration / 5.0))
            engine.attach_leases(self.shard_leases)
            # Shard-filtered ingest (doc/INGEST.md): over the HTTP edge,
            # scope the reflectors to the shards this replica owns.
            # MUST come after attach_leases — attach_shard_scope pins
            # the lease manager's load-based shed off (a filtered
            # mirror undercounts foreign load) and chains its
            # ownership-change hook.
            from ..edge import RemoteCluster, attach_shard_scope
            if isinstance(self.cluster, RemoteCluster):
                attach_shard_scope(self.cluster, engine.map,
                                   self.shard_leases)
            self.shard_leases.start()
            self.scheduler.run()
        elif self.opt.enable_leader_election:
            self.opt.check_option_or_die()
            # The HA lock lives IN THE STORE whenever the cluster edge
            # supports leases (in-process simulator or the HTTP edge) —
            # the reference's ConfigMap lock (server.go:115-139): any
            # standby pointing at the same store can take over.  The lock
            # file remains the fallback for bare shared-filesystem runs.
            if self._cluster_shared and hasattr(self.cluster, "cas_lease"):
                lock = StoreLock(self.cluster,
                                 self.opt.lock_object_namespace)
                config = self._lease_config or LeaderElectionConfig()
            else:
                # A process-private store cannot host the election lock
                # (every standby would elect itself in its own world), so
                # HA falls to the lock FILE.  But FileLock's flock CAS is
                # coherent per-host only; two standbys on different hosts
                # over NFS/SMB could dual-acquire.  Refuse at config time
                # unless the deployment explicitly accepts same-host
                # failover — by flag, or by injecting a lease_config with
                # its own lock_path (already a deliberate opt-in).
                # Reference analog: HA is always store-locked,
                # server.go:115-139.
                if (not self.opt.file_lock_same_host_ok
                        and not (self._lease_config is not None
                                 and self._lease_config.lock_path)):
                    raise ValueError(
                        "leader election needs a SHARED store for its "
                        "lock, but this runtime's store is process-"
                        "private (or has no lease support); the file-"
                        "lock fallback is safe for SAME-HOST standbys "
                        "only (flock coherence is per-host on network "
                        "filesystems).  Point every standby at one "
                        "cluster edge (--master), or pass "
                        "--leader-elect-file-lock to accept same-host-"
                        "only failover.")
                default_path = (f"{self.opt.lock_object_namespace}/"
                                f"kube-batch-lock.json")
                if self._lease_config is None:
                    config = LeaderElectionConfig(lock_path=default_path)
                elif not self._lease_config.lock_path:
                    # Timing-only injected config: fill the default on a
                    # COPY — the caller's dataclass may be shared across
                    # runtimes and must not be mutated from here.
                    import dataclasses
                    config = dataclasses.replace(self._lease_config,
                                                 lock_path=default_path)
                else:
                    config = self._lease_config
                lock = None
            self.elector = LeaderElector(
                config,
                on_started_leading=self.scheduler.run,
                on_stopped_leading=self.scheduler.stop,
                lock=lock)
            # Write fence: scheduler.stop() only signals the loop; an
            # in-flight cycle would still bind/evict after a standby took
            # the lease.  The cache refuses cluster writes the moment the
            # lease is stale — wall-clock-based (has_live_lease), so a
            # process pause past the deadline fences even before the
            # elector thread wakes (the reference fences by process exit,
            # server.go:135-137).
            self.cache.write_fence = self.elector.has_live_lease
            threading.Thread(target=self.elector.run, daemon=True).start()
        else:
            self.scheduler.run()

    def stop(self) -> None:
        if self.warmup is not None:
            # Signal between buckets; an XLA compile in flight cannot be
            # interrupted, so don't block shutdown on it (daemon thread).
            self.warmup.stop(timeout=0.5)
        if self.elector is not None:
            self.elector.stop()
        self.scheduler.stop()
        if self.shard_leases is not None:
            # AFTER the loop stops (no further egress), release every
            # owned shard so surviving replicas claim immediately
            # instead of waiting out the expiry.
            self.shard_leases.stop(release=True)
        recorder = getattr(self.cache, "event_recorder", None)
        if recorder is not None and hasattr(recorder, "stop"):
            recorder.stop()
        if self.metrics_server is not None:
            stop_metrics_server(self.metrics_server)
