"""Batched node scan for preempt/reclaim: predicates + scores over all
nodes in one device call.

The reference's preempt/reclaim walk every node per pending preemptor with
per-node predicate and prioritizer calls (preempt.go:171-254 via
util.PredicateNodes/PrioritizeNodes 16-goroutine fan-out;
reclaim.go:115-170).  This kernel vectorizes one preemptor's walk: the
session-static tensors (signature mask, score bonus, capacities) live on
device for the whole action, the dynamic node state (idle/releasing/used/
count/ports/selcnt) ships as ONE packed int32 buffer per call, and the
result is a single [N] int32 score vector — SCORE_NEG_INF marks nodes that
fail the predicate chain, so feasibility and ordering come back in one
transfer.

NOTE: unlike the allocate solver, the scan deliberately has NO resource-fit
check — preempt/reclaim predicate candidate nodes before any eviction frees
room (allocate.go's fit closure is allocate-only; preempt.go:180 uses the
plugin chain alone).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from .resources import SCORE_GRID_K
from .scoring import SCORE_NEG_INF, grid_score, shifted_caps


class ScanStatics(NamedTuple):
    """Device-resident per-session constants for the scan."""
    sig_mask: jnp.ndarray     # [S, N] bool
    sig_bonus: jnp.ndarray    # [S, N] i32
    node_alloc: jnp.ndarray   # [N, R] i32
    node_max_tasks: jnp.ndarray  # [N] i32
    node_exists: jnp.ndarray  # [N] bool
    score_shift: jnp.ndarray  # [2] i32


def _scan_body(cfg, r: int, np_pad: int, ns_pad: int, statics: ScanStatics,
               dyn: jnp.ndarray, trow: jnp.ndarray) -> jnp.ndarray:
    """The scan math, un-jitted: every term is per-node elementwise, so
    the same body serves the single-chip jit (scan_nodes) and each
    device's shard of the node axis (parallel/sharded_scan.py) with no
    cross-shard traffic."""
    used = dyn[:, :r]
    count = dyn[:, r]
    ports = dyn[:, r + 1:r + 1 + np_pad]
    selcnt = dyn[:, r + 1 + np_pad:r + 1 + np_pad + ns_pad]
    return _scan_body_cols(cfg, statics, used, count, ports, selcnt, trow,
                           r=r, np_pad=np_pad, ns_pad=ns_pad)


def _scan_body_cols(cfg, statics: ScanStatics, used, count, ports, selcnt,
                    trow: jnp.ndarray, *, r: int, np_pad: int,
                    ns_pad: int) -> jnp.ndarray:
    """The scan math over UNPACKED node columns.  The packed-``dyn`` form
    above is the host scanner's wire shape; this form lets the
    mesh-routed eviction engine feed the shipper's already-resident
    SolverInputs leaves (node_used/count/ports/selcnt) directly — zero
    node-state bytes move at dispatch, and each device scans only its
    shard (parallel/sharded_scan.evict_batch_solve_sharded).  Bool
    occupancy leaves compare identically to their int32 dyn packing
    (every predicate below tests ``> 0``)."""
    sig = trow[0]
    res = trow[1:1 + r]
    off = 1 + r
    t_ports = trow[off:off + np_pad]
    off += np_pad
    t_aff = trow[off:off + ns_pad]
    off += ns_pad
    t_anti = trow[off:off + ns_pad]
    off += ns_pad
    t_paffw = trow[off:off + ns_pad]
    off += ns_pad
    t_pantiw = trow[off:off + ns_pad]

    feasible = (statics.sig_mask[sig] & statics.node_exists
                & (count < statics.node_max_tasks))
    if cfg.has_ports:
        conflict = ((t_ports[None, :] > 0) & (ports > 0)).any(axis=-1)
        feasible = feasible & ~conflict
    if cfg.has_pod_affinity:
        have = selcnt > 0
        aff_ok = jnp.all((t_aff[None, :] == 0) | have, axis=-1)
        anti_ok = jnp.all((t_anti[None, :] == 0) | ~have, axis=-1)
        feasible = feasible & aff_ok & anti_ok

    cs, den = shifted_caps(statics.node_alloc, statics.score_shift)
    score = grid_score(res, used, statics.score_shift, cs, den, cfg.weights)
    if cfg.has_pod_affinity_score:
        wdiff = (t_paffw - t_pantiw)[None, :]
        score = score + SCORE_GRID_K * jnp.sum(wdiff * selcnt, axis=-1)
    score = score + statics.sig_bonus[sig]
    return jnp.where(feasible, score, SCORE_NEG_INF)


@functools.partial(jax.jit,
                   static_argnames=("cfg", "r", "np_pad", "ns_pad"))
def scan_nodes(cfg, r: int, np_pad: int, ns_pad: int, statics: ScanStatics,
               dyn: jnp.ndarray, trow: jnp.ndarray) -> jnp.ndarray:
    """[N] i32 scores; SCORE_NEG_INF where the predicate chain rejects.

    ``dyn`` packs the mutable node state column-wise:
        [0:r] used | [r] count | [r+1 : r+1+np_pad] ports |
        [r+1+np_pad : r+1+np_pad+ns_pad] selcnt
    (idle/releasing are irrelevant here — no fit check, and scoring reads
    used only).  ``trow`` packs the preemptor:
        [0] sig | [1:1+r] res | ports | aff | anti | match(paffw) | pantiw
    """
    return _scan_body(cfg, r, np_pad, ns_pad, statics, dyn, trow)


def choose_scan_mesh(n_nodes: int):
    """('sharded'|'xla', mesh): the eviction-scan routing gate — the
    allocate solver's node-count gate and startup-pinned knobs
    (solver.shard_knobs; the bytes-limit branch needs full SolverInputs
    and is allocate-only), so preempt/reclaim shard when allocate does."""
    from ..parallel.mesh import default_mesh
    from .solver import shard_knobs
    mesh = default_mesh()
    if mesh is not None and n_nodes % mesh.size == 0:
        knobs = shard_knobs()
        if knobs.force or n_nodes >= knobs.nodes:
            return "sharded", mesh
    return "xla", None


def best_scan_nodes(cfg, r: int, np_pad: int, ns_pad: int,
                    statics: ScanStatics, dyn, trow) -> jnp.ndarray:
    """Route one preemptor's node walk to the node-sharded scan when the
    mesh gate says the node bucket outgrew one chip."""
    from ..metrics import metrics
    choice, mesh = choose_scan_mesh(statics.node_exists.shape[0])
    metrics.note_route("scan", choice)
    if choice == "sharded":
        from ..parallel.sharded_scan import scan_nodes_sharded
        return scan_nodes_sharded(cfg, r, np_pad, ns_pad, statics, dyn,
                                  trow, mesh)
    return scan_nodes(cfg, r, np_pad, ns_pad, statics, dyn, trow)
