"""Node scoring kernels on the integer grid.

Device counterparts of plugins/nodeorder.py (reimplementing the upstream
kube-scheduler priorities the reference wraps, nodeorder.go:140-168):
least-requested, most-requested, balanced-resource-allocation, evaluated for
one task against all N nodes from the *current* used/allocatable tensors.

Scores are **integers**: utilization fractions are computed on the shared
SCORE_GRID_K grid (ops/resources.py — identical formula and values on host
and device, exact on every platform), then combined with integer weights.
A grid fraction g stands for g/K; the float formulas scale by K:

  least    = 5*(2K - gc - gm)     (was ((1-cf) + (1-mf)) * 10 / 2)
  most     = 5*(gc + gm)
  balanced = 10*K - 10*|gc - gm|

Identical integer math to the host path so placements agree bit-for-bit.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from .resources import SCORE_GRID_K

MAX_PRIORITY = 10

# Sentinel for infeasible nodes in integer score argmaxes: far below any
# real score (scores are >= 0, <= ~2**27 for sane weights).  Plain int so
# closures (e.g. the Pallas kernel) don't capture a traced constant.
SCORE_NEG_INF = -(2 ** 31) + 1


class ScoreWeights(NamedTuple):
    """Integer plugin weights (the reference reads them via GetInt,
    nodeorder.go:107-131; tensorize falls back to the host path on
    fractional weights)."""
    least_requested: int = 1
    most_requested: int = 0
    balanced_resource: int = 1


def shifted_caps(allocatable: jnp.ndarray, shift: jnp.ndarray):
    """Precompute (cs, cs_den) per cpu/mem dim for grid_score.
    allocatable: [N, R] i32; shift: [2] i32."""
    cs = [jnp.right_shift(allocatable[:, d], shift[d]) for d in range(2)]
    den = [jnp.maximum(c, 1).astype(jnp.float32) for c in cs]
    return cs, den


def grid_score(task_res: jnp.ndarray, used: jnp.ndarray, shift: jnp.ndarray,
               cs, cs_den, weights: ScoreWeights) -> jnp.ndarray:
    """Weighted-sum integer score [N] for one task over all nodes.

    THE grid-score formula: every device path (stepwise/two-level XLA,
    sharded) calls this one function so score integers cannot drift apart;
    the Pallas kernel re-implements it over its row layout (kept in sync by
    the parity suite)."""
    g = []
    for d in range(2):
        xs = jnp.minimum(
            jnp.right_shift(used[:, d] + task_res[d], shift[d]), cs[d])
        num = (xs * SCORE_GRID_K).astype(jnp.float32)
        q = (num / cs_den[d]).astype(jnp.int32)  # trunc == floor (>= 0)
        g.append(jnp.where(cs[d] == 0, SCORE_GRID_K, q))
    gc, gm = g
    score = jnp.zeros(used.shape[0], dtype=jnp.int32)
    w_least = int(weights.least_requested)
    w_most = int(weights.most_requested)
    w_bal = int(weights.balanced_resource)
    if w_least:
        score = score + w_least * 5 * (2 * SCORE_GRID_K - gc - gm)
    if w_most:
        score = score + w_most * 5 * (gc + gm)
    if w_bal:
        score = score + w_bal * (10 * SCORE_GRID_K
                                 - 10 * jnp.abs(gc - gm))
    return score


def max_weight_sum(weights: ScoreWeights) -> int:
    """Upper bound scale factor for a combined score: callers keep
    max_weight_sum * 10 * SCORE_GRID_K inside int32 (tensorize falls back
    to the host path otherwise)."""
    return (abs(int(weights.least_requested)) + abs(int(weights.most_requested))
            + abs(int(weights.balanced_resource)))


def score_nodes(task_res: jnp.ndarray, used: jnp.ndarray,
                allocatable: jnp.ndarray, shift: jnp.ndarray,
                weights: ScoreWeights) -> jnp.ndarray:
    """grid_score with caps computed on the fly (stepwise solver path)."""
    cs, den = shifted_caps(allocatable, shift)
    return grid_score(task_res, used, shift, cs, den, weights)
