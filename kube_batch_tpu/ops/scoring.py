"""Node scoring kernels.

Device counterparts of plugins/nodeorder.py (reimplementing the upstream
kube-scheduler priorities the reference wraps, nodeorder.go:140-168):
least-requested, most-requested, balanced-resource-allocation, evaluated for
one task against all N nodes from the *current* used/allocatable tensors.
Identical math to the host path so placements agree.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

MAX_PRIORITY = 10.0


class ScoreWeights(NamedTuple):
    least_requested: float = 1.0
    most_requested: float = 0.0
    balanced_resource: float = 1.0


def node_fractions(task_res: jnp.ndarray, used: jnp.ndarray,
                   allocatable: jnp.ndarray):
    """Projected cpu/mem utilization fractions if the task lands on each
    node.  task_res: [R]; used, allocatable: [N, R] -> ([N], [N])."""
    req = used + task_res[None, :]
    denom_ok = allocatable > 0
    frac = jnp.where(denom_ok,
                     jnp.minimum(req / jnp.where(denom_ok, allocatable, 1.0), 1.0),
                     1.0)
    return frac[:, 0], frac[:, 1]  # cpu, memory dims


def score_nodes(task_res: jnp.ndarray, used: jnp.ndarray,
                allocatable: jnp.ndarray, weights: ScoreWeights) -> jnp.ndarray:
    """Weighted-sum score [N] for one task over all nodes."""
    cpu_frac, mem_frac = node_fractions(task_res, used, allocatable)
    score = jnp.zeros(used.shape[0], dtype=used.dtype)
    if weights.least_requested:
        least = ((1.0 - cpu_frac) * MAX_PRIORITY
                 + (1.0 - mem_frac) * MAX_PRIORITY) / 2.0
        score = score + weights.least_requested * least
    if weights.most_requested:
        most = (cpu_frac * MAX_PRIORITY + mem_frac * MAX_PRIORITY) / 2.0
        score = score + weights.most_requested * most
    if weights.balanced_resource:
        balanced = MAX_PRIORITY - jnp.abs(cpu_frac - mem_frac) * MAX_PRIORITY
        score = score + weights.balanced_resource * balanced
    return score
