"""One-dispatch sessions: the action ladder's whole solve-family fused
into a SINGLE device program (doc/FUSED.md).

A steady micro-session still paid one device round trip per solver
family — the allocate solve (ops/solver.py), the batched eviction solve
(ops/evict_solver.py) and the topo box scan (ops/topo_solver.py) each
dispatched their own program even though all three read the SAME
resident node image and none depends on another's device output (the
sequential decision tail — victim commits, placement statements — runs
on the host against the readbacks).  This module composes the exact
per-family jitted programs inside ONE outer jit, so the session's
entire device work lands in one dispatch at the first consumer and the
host replays the decision ladder against precomputed tensors:

  * ``alloc`` leg — the allocate solve (full-bucket or candidate-row,
    single-chip / Pallas / mesh-sharded: the same routing
    ``choose_solver_mesh`` pins), packed through the SAME
    ``_pack_result_ordered`` [4, P] readback and wrapped as a standard
    ``PendingSolve`` — tpu-allocate's ``finish`` continuation consumes
    it through ``fetch_solve`` unchanged.
  * ``evict`` leg — ``evict_batch_solve``'s [K, N] profile scan + the
    victim lexsort, consumed lazily by models/scanner.py (the readback
    rides the async-dispatch window to the first ``scores()`` call).
  * ``topo`` leg — ``box_scan``'s [N, 6] origin stats for the first
    slice job, staged by actions/topo_allocate.py before the scanner
    builds so all three families share the dispatch.

Validity is generation-proved, never assumed: the alloc leg records the
shipper generation it solved at, and tpu-allocate consumes it only when
its own ship comes back CLEAN at that same generation with the same
config and the same candidate gather (byte-compared remap) — the exact
"clean ship at an unchanged generation proves byte-identical inputs"
contract the incremental solve cache already relies on
(models/shipping.py, models/incremental.py).  Anything else counts a
``kube_batch_tpu_fused_legs_total{outcome="invalidated"}`` and falls
back to the per-family dispatch — bit-parity is structural, not
probabilistic.  ``KUBE_BATCH_TPU_FUSED=0`` is the A/B control: every
consumer takes the per-family chokepoints exactly as before.

Failure degrades, never decides: a fused dispatch or readback failure
feeds the shared device breaker (chaos site ``fused.device_error``;
readback faults ``fused.slow`` / ``fused.poison``), invalidates the
resident image, and the session re-dispatches per family — then the
per-family paths' own host oracles below that (doc/CHAOS.md).
"""

from __future__ import annotations

import functools
import time
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import knobs

FUSED_ENV = knobs.FUSED.env
FUSED_SOLVE_CHOICE = "fused"

# Leg outcome vocabulary (kube_batch_tpu_fused_legs_total{outcome=}):
#   served      — the precomputed tensor answered the family's question
#   invalidated — host state moved between dispatch and consume (or the
#                 consumer's staging differed); per-family re-dispatch
#   unused      — dispatched but never consumed (e.g. the incremental
#                 cache answered first, or the session aborted)
#   failed      — the fused dispatch/readback itself errored; breaker fed


def fused_enabled() -> bool:
    return knobs.FUSED.enabled()


def storm_enabled() -> bool:
    """The storm half (doc/FUSED.md): the fused program also solves the
    post-eviction placements against the occupancy its own evict leg
    adjusts on device, so an eviction-led cycle stays at one dispatch."""
    return knobs.FUSED.enabled() and knobs.FUSED_STORM.enabled()


class _AllocLeg(NamedTuple):
    """The alloc leg's host-side capture: everything tpu-allocate must
    re-derive identically for the precomputed solve to be ITS solve."""
    inputs: object        # resident SolverInputs (the shipped image)
    cfg: object           # SolverConfig (static)
    route: str            # choose_solver_mesh choice at stage time
    mesh: object          # the mesh the route validated (or None)
    generation: int       # shipper generation the solve read
    cand_sig: object      # candidate-gather identity (None = full bucket)
    candidates: object    # the staged CandidateSet (remap for the fetch)


class FusedState:
    """Per-session fused-dispatch ledger, cached on ``ssn._fused_state``.

    One fused dispatch per session maximum: the first device-needing
    consumer stages every leg it can prove out and fires; later
    consumers either match their capture (served) or re-dispatch per
    family (invalidated, counted)."""

    __slots__ = ("dispatched", "failed", "legs", "alloc_pending",
                 "alloc_leg", "topo_request", "topo_out", "topo_sig",
                 "early_scanner", "storm")

    def __init__(self):
        self.dispatched = False
        self.failed = False
        self.legs = ()
        self.alloc_pending = None   # PendingSolve until consumed/discarded
        self.alloc_leg = None       # _AllocLeg capture
        self.topo_request = None    # (BoxInputs np, shape, sig) staging
        self.topo_out = None        # device [N, 6] stats
        self.topo_sig = None
        self.early_scanner = False  # scanner seeded before mutations ran
        self.storm = None           # _StormCapture (postevict leg)


def _storm_nbytes(cap) -> int:
    total = 0
    for a in (cap.vic_res, cap.vic_qix, cap.vic_jix, cap.vic_node):
        if a is not None:
            total += int(a.nbytes)
    if cap.dinp:
        for a in cap.dinp.values():
            total += int(a.nbytes)
    return total


# The SolverInputs fields _prove_storm compares against the fresh
# staging: the delta-replay targets (P3), the remap-compared task
# columns and the must-be-bit-equal axes (P4), and the job-block
# geometry.  Captured as numpy COPIES at dispatch time — the persistent
# staging layer rewrites the session snapshot and its buffers in place
# on the next tensorize (models/tensor_snapshot.py "Wire fast path"),
# so by-reference capture would compare the fresh state to itself.
_PROOF_FIELDS = (
    # P4: per-task columns (compared under the uid remap)
    "task_req", "task_res", "task_sig", "task_ports", "task_aff_req",
    "task_anti", "task_match", "task_paff_w", "task_panti_w",
    # P4: axes the predicted iteration cannot touch (bit-equal)
    "sig_mask", "sig_bonus", "node_idle", "node_alloc", "node_max_tasks",
    "node_exists", "node_coords", "queue_deserved", "queue_deserved_f",
    "queue_ts", "queue_uid_rank", "queue_exists", "job_queue",
    "job_minavail", "job_prio", "job_ts", "job_uid_rank", "total_res",
    "eps", "scalar_dims", "score_shift",
    # P4: job-block geometry
    "job_start", "job_count", "task_sorted",
    # P3: the mutated axes (fresh == these + modeled deltas)
    "node_releasing", "node_used", "node_count", "node_ports",
    "node_selcnt", "queue_init_alloc", "job_init_alloc",
    "job_init_ready",
)


class _StormCapture:
    """Host half of the post-eviction storm leg: the dispatch-time
    staging captured BY VALUE (uid axis, axis name lists, config, numpy
    copies of the proof-compared input arrays), the victim staging
    columns the device chose from, the device's prediction readbacks,
    and the session mutation log the serve proof replays against
    (doc/FUSED.md "Storm half").  Released at consume or at session
    close — the ledger audit pins retention.

    # mem-ledger: fused_storm
    """

    __slots__ = ("duids", "dnode_names", "djob_uids", "dqueue_ids",
                 "dres_names", "dconfig", "dinp", "route", "vic_res",
                 "vic_qix", "vic_jix", "vic_node", "uids", "meta", "sel",
                 "mutlog", "_mem_key", "__weakref__")

    def __init__(self, snap, route, vic_res, vic_qix, vic_jix, vic_node,
                 uids, meta, sel):
        self.duids = [t.uid for t in snap.tasks]  # dispatch task axis
        self.dnode_names = list(snap.node_names)
        self.djob_uids = list(snap.job_uids)
        self.dqueue_ids = list(snap.queue_ids)
        self.dres_names = list(snap.resource_names)
        self.dconfig = snap.config
        self.dinp = {name: np.array(np.asarray(getattr(snap.inputs, name)))
                     for name in _PROOF_FIELDS}
        self.route = route          # aroute the adjusted solve compiled at
        self.vic_res = vic_res      # [M, R] i32 victim resreq quanta
        self.vic_qix = vic_qix      # [M] i32 queue index (Q = absent)
        self.vic_jix = vic_jix      # [M] i32 job index (J = absent)
        self.vic_node = vic_node    # [M] i32 node row (evict-leg column)
        self.uids = list(uids)      # [m] victim uid per slot
        self.meta = meta            # device [6] i32 did,q*,j*,t*,n*,vcnt
        self.sel = sel              # device [M] bool chosen-victim mask
        self.mutlog = []            # (kind, uid, node) from Session hooks
        from ..metrics import memledger
        self._mem_key = memledger.ledger("fused_storm").track(
            self, sizer=_storm_nbytes)
        memledger.ledger("fused_storm").set(self._mem_key,
                                            _storm_nbytes(self))

    def release(self) -> None:
        self.duids = []
        self.dnode_names = self.djob_uids = self.dqueue_ids = []
        self.dres_names = []
        self.dconfig = None
        self.dinp = {}
        self.vic_res = self.vic_qix = self.vic_jix = self.vic_node = None
        self.meta = self.sel = None
        self.uids = []
        self.mutlog = []
        from ..metrics import memledger
        memledger.ledger("fused_storm").set(self._mem_key, 0)


def state_for(ssn) -> FusedState:
    st = getattr(ssn, "_fused_state", None)
    if st is None:
        st = FusedState()
        ssn._fused_state = st
    return st


def _conf_names(ssn) -> tuple:
    """The session's action ladder (scheduler stamps it at open)."""
    return tuple(getattr(ssn, "_conf_actions", ()) or ())


# ---------------------------------------------------------------------------
# The fused program: per-family jitted solvers composed inside ONE outer
# jit.  jit-of-jit inlines — the whole composition compiles to a single
# executable and the runtime enqueues ONE device program per call.
# Absent legs pass None for their traced arguments (an empty pytree) and
# are skipped at trace time via the static ``legs`` tuple.
# ---------------------------------------------------------------------------

def _postevict_adjust(inp, cfg, vic_node, vic_res, vic_queue, vic_job):
    """Predict reclaim's first committed iteration and adjust the solve
    inputs by exactly its mutations (doc/FUSED.md "Storm half").

    The prediction mirrors actions/reclaim.py against the OPEN-state
    arrays the dispatch staged: q* is the first queue surviving the PQ
    guards (exists, a pending candidate job, not Overused) in (share,
    ts, uid) order; j* is q*'s front job by the tiered job-order chain;
    t* is j*'s front task; n* is the first node ascending that passes
    the static+dynamic predicate chain AND whose other-queue residents'
    total resreq covers t*'s init request; the victims are the
    slot-order prefix of n*'s other-queue residents until the running
    sum covers (the evict loop's inclusive break).  Every delta below
    is the staged image of the host mutations those commits cause
    (NodeInfo.release_resident / add_task-Pipelined, the proportion
    event handlers, ready_task_num, the job block rebuild) — the serve
    proof in ``_prove_storm`` re-derives the same deltas on the host
    and refuses the leg on any mismatch, so a wrong prediction can only
    cost a re-dispatch, never a wrong placement.

    Returns ``(adjusted inputs, meta, chosen)`` with ``meta`` = i32
    ``[did, q*, j*, t*, n*, v_count]`` and ``chosen`` the [M] victim
    mask.  When ``did`` is 0 the adjustment is the identity and the
    solve below equals the plain fused solve bit-for-bit."""
    from .fairness import queue_shares, safe_share
    from .resources import less_equal_vec
    from .solver import _lex_argmin, dynamic_predicate_mask
    i32 = jnp.int32
    nb = inp.node_exists.shape[0]
    qb = inp.queue_exists.shape[0]
    jb = inp.job_start.shape[0]
    valid = vic_node < nb

    # q* — reclaim.py:54-61 guards in pop order.
    has_pending = jnp.zeros((qb,), bool).at[inp.job_queue].max(
        inp.job_count > 0, mode="drop")
    if cfg.has_proportion:
        overused = less_equal_vec(inp.queue_deserved, inp.queue_init_alloc,
                                  inp.eps, inp.scalar_dims)
    else:
        overused = jnp.zeros((qb,), bool)
    qmask = inp.queue_exists & has_pending & ~overused
    qkeys = []
    for name in cfg.queue_key_order:
        if name == "proportion":
            qkeys.append(queue_shares(inp.queue_init_alloc,
                                      inp.queue_deserved_f))
    qkeys.extend([inp.queue_ts, inp.queue_uid_rank])
    qstar = _lex_argmin(qmask, qkeys)

    # j* — the tiered chain of _select_job over the open-state arrays
    # (reclaim pops before anything mutates, so init IS the live state).
    jmask = (qmask.any() & (inp.job_queue == qstar) & (inp.job_count > 0)
             & (inp.job_minavail >= 0))
    jkeys = []
    for name in cfg.job_key_order:
        if name == "priority":
            jkeys.append(-inp.job_prio)
        elif name == "gang":
            ready = inp.job_init_ready >= inp.job_minavail
            jkeys.append(ready.astype(inp.job_ts.dtype))
        elif name == "drf":
            jkeys.append(jnp.max(
                safe_share(inp.job_init_alloc, inp.total_res[None, :]),
                axis=-1))
    jkeys.extend([inp.job_ts, inp.job_uid_rank])
    jstar = _lex_argmin(jmask, jkeys)
    tstar = inp.task_sorted[inp.job_start[jstar]].astype(i32)
    treq = inp.task_req[tstar]

    # n* — first node ascending passing the scanner's predicate chain
    # (models/scanner._scores_numpy feasibility) with an admissible
    # other-queue resident set whose TOTAL covers (reclaim.py:119-142).
    other = valid & (vic_queue != qstar)
    tot = jnp.zeros((nb, treq.shape[0]), i32).at[vic_node].add(
        jnp.where(other[:, None], vic_res, 0), mode="drop")
    covers = less_equal_vec(jnp.broadcast_to(treq[None, :], tot.shape),
                            tot, inp.eps, inp.scalar_dims)
    feas = (inp.sig_mask[inp.task_sig[tstar]] & inp.node_exists
            & (inp.node_count < inp.node_max_tasks))
    dyn = dynamic_predicate_mask(cfg, tstar, inp.task_ports,
                                 inp.task_aff_req, inp.task_anti,
                                 inp.node_ports, inp.node_selcnt)
    if dyn is not None:
        feas = feas & dyn
    adm = jnp.zeros((nb,), bool).at[vic_node].max(other, mode="drop")
    elig = feas & covers & adm
    did = qmask.any() & jmask.any() & elig.any()
    nstar = jnp.argmax(elig).astype(i32)

    # Victims: slot-order prefix of n*'s other-queue residents until
    # the cumulative sum covers, INCLUSIVE of the covering victim (the
    # evict loop breaks after adding, reclaim.py:144-155).
    eln = other & (vic_node == nstar)
    contrib = jnp.where(eln[:, None], vic_res, 0)
    csum = jnp.cumsum(contrib, axis=0)
    before = less_equal_vec(jnp.broadcast_to(treq[None, :], csum.shape),
                            csum - contrib, inp.eps, inp.scalar_dims)
    chosen = eln & ~before & did
    vcnt = chosen.sum().astype(i32)
    d = did.astype(i32)

    # Deltas.  Evict (release_resident): node releasing += resreq, the
    # victim queue's proportion allocation and the victim job's DRF
    # allocation / ready count shrink.  Pipeline of t* on n* (add_task
    # Pipelined + allocate event): releasing -= resreq, used += resreq,
    # count += 1, ports/selcnt gain t*'s footprint, q*'s proportion
    # allocation grows; the job block re-sorts with t* consumed.
    chv = jnp.where(chosen[:, None], vic_res, 0)
    vq = jnp.where(chosen, vic_queue, qb)   # sentinel rows drop
    vj = jnp.where(chosen, vic_job, jb)
    tres = inp.task_res[tstar] * d
    node_rel = inp.node_releasing.at[vic_node].add(chv, mode="drop")
    node_rel = node_rel.at[nstar].add(-tres)
    node_used = inp.node_used.at[nstar].add(tres)
    node_count = inp.node_count.at[nstar].add(d)
    node_ports = inp.node_ports.at[nstar].set(
        inp.node_ports[nstar] | (did & inp.task_ports[tstar]))
    node_sel = inp.node_selcnt.at[nstar].add(jnp.where(
        did, inp.task_match[tstar].astype(inp.node_selcnt.dtype), 0))
    if cfg.has_proportion:
        q_alloc = inp.queue_init_alloc.at[vq].add(-chv, mode="drop")
        q_alloc = q_alloc.at[jnp.where(did, qstar, qb)].add(
            tres, mode="drop")
    else:
        q_alloc = inp.queue_init_alloc  # stays zeros host-side too
    j_alloc = inp.job_init_alloc.at[vj].add(-chv, mode="drop")
    j_ready = inp.job_init_ready.at[vj].add(
        -chosen.astype(i32), mode="drop")
    j_start = inp.job_start.at[jnp.where(did, jstar, jb)].add(
        1, mode="drop")
    j_count = inp.job_count.at[jnp.where(did, jstar, jb)].add(
        -1, mode="drop")

    adj = inp._replace(
        node_releasing=node_rel, node_used=node_used,
        node_count=node_count, node_ports=node_ports,
        node_selcnt=node_sel, queue_init_alloc=q_alloc,
        job_init_alloc=j_alloc, job_init_ready=j_ready,
        job_start=j_start, job_count=j_count)
    meta = jnp.stack([d, qstar, jstar, tstar, nstar, vcnt]).astype(i32)
    return adj, meta, chosen


@functools.partial(jax.jit, static_argnames=(
    "legs", "acfg", "aroute", "has_cand", "amesh",
    "ecfg", "r", "np_pad", "ns_pad", "eroute", "emesh",
    "sx", "sy", "sz", "troute", "tmesh"))
def _fused_program(legs, acfg, aroute, has_cand, amesh,
                   ecfg, r, np_pad, ns_pad, eroute, emesh,
                   sx, sy, sz, troute, tmesh,
                   ainp, cand_idx, cand_valid,
                   statics, edyn, trows, vic_node, vic_rank,
                   box, pe_res, pe_queue, pe_job):
    out = {}
    if "solve" in legs:
        from .solver import (_gather_candidate_inputs, _pack_result_ordered,
                             solve_allocate)
        sinp = ainp
        if "postevict" in legs:
            # Storm half: chain the predicted first reclaim iteration's
            # occupancy update and solve against the ADJUSTED state —
            # the per-family re-dispatch this leg replaces, inside the
            # same program.  Never staged with a candidate gather.
            sinp, pe_meta, pe_sel = _postevict_adjust(
                ainp, acfg, vic_node, pe_res, pe_queue, pe_job)
            out["postevict"] = (pe_meta, pe_sel)
        if has_cand:
            if aroute == "sharded":
                from ..parallel.sharded_solver import (
                    gather_candidate_sharded, solve_allocate_sharded)
                sub = gather_candidate_sharded(ainp, cand_idx, cand_valid,
                                               amesh)
                res = solve_allocate_sharded(sub, acfg, amesh)
            else:
                sub = _gather_candidate_inputs(ainp, cand_idx, cand_valid)
                res = solve_allocate(sub, acfg)
        elif aroute == "sharded":
            from ..parallel.sharded_solver import solve_allocate_sharded
            res = solve_allocate_sharded(sinp, acfg, amesh)
        elif aroute == "pallas":
            from .pallas_solver import solve_allocate_pallas
            res = solve_allocate_pallas(sinp, acfg)
        else:
            res = solve_allocate(sinp, acfg)
        out["alloc"] = _pack_result_ordered(res.assignment, res.kind,
                                            res.order)
    if "evict" in legs:
        if eroute == "sharded":
            from ..parallel.sharded_scan import evict_batch_solve_sharded
            scores, perm = evict_batch_solve_sharded(
                ecfg, r, np_pad, ns_pad, statics, ainp.node_used,
                ainp.node_count, ainp.node_ports, ainp.node_selcnt,
                trows, vic_node, vic_rank, emesh)
        else:
            from .evict_solver import evict_batch_solve
            scores, perm = evict_batch_solve(
                ecfg, r, np_pad, ns_pad, statics, edyn, trows,
                vic_node, vic_rank)
        out["evict"] = (scores, perm)
    if "topo" in legs:
        if troute == "sharded":
            from .topo_solver import box_scan_sharded
            out["topo"] = box_scan_sharded(box, sx, sy, sz, tmesh)
        else:
            from .topo_solver import box_scan
            out["topo"] = box_scan(box, sx, sy, sz)
    return out


def fused_solve_key(legs, aroute, has_cand, cand_rows, a_shape,
                    eroute, e_shape, troute, t_shape) -> tuple:
    """Compile-cache identity of one fused executable: the static leg
    set plus each present leg's jit-relevant degrees of freedom (the
    per-family solve_key/evict_solve_key/topo_solve_key disciplines
    folded into one tuple)."""
    return (FUSED_SOLVE_CHOICE, tuple(legs), aroute, has_cand, cand_rows,
            a_shape, eroute, e_shape, troute, t_shape)


# ---------------------------------------------------------------------------
# Staging: what each leg must prove on the host before riding along.
# ---------------------------------------------------------------------------

def _cand_sig(candidates) -> object:
    """Byte identity of a candidate gather: same remap => same gathered
    program => same placements.  None means the full-bucket program."""
    if candidates is None:
        return None
    remap = candidates.remap
    return (int(candidates.count),
            None if remap is None else remap.tobytes())


def _stage_alloc(ssn, snap) -> Optional[_AllocLeg]:
    """Decide whether the allocate solve can ride the fused dispatch,
    and stage exactly what tpu-allocate's begin half would stage: the
    shipped resident image, the route, and the candidate gather.  Every
    predicate mirrors actions/tpu_allocate.execute_begin so the capture
    is the SAME dispatch that action would have issued — the consume
    check then only has to prove nothing moved in between."""
    if "tpu-allocate" not in _conf_names(ssn):
        return None
    if not knobs.PIPELINE.enabled():
        # The sequential control consumes synchronously via
        # best_solve_allocate; a pre-staged async handle would change
        # its timing topology.  Keep the control untouched.
        return None
    from ..chaos.breaker import device_breaker
    if not device_breaker().allow():
        return None
    if snap.needs_fallback or not snap.tasks:
        return None
    from ..models import incremental
    from ..models.shipping import resident_shipper
    from ..ops.solver import choose_solver_mesh
    shipper = resident_shipper(ssn.cache)
    inputs = shipper.ship(snap.inputs, snap.config)
    inc_state = (incremental.state_for(ssn.cache, create=False)
                 if incremental.incremental_enabled() else None)
    if (inc_state is not None
            and shipper.last_mode == "clean"
            and inc_state.solve_gen == shipper.generation
            and inc_state.solve_cfg == snap.config
            and inc_state.solve_result is not None):
        # The generation-keyed cache already holds this session's
        # answer; tpu-allocate will reuse it without any dispatch.
        return None
    route, mesh = choose_solver_mesh(snap.inputs)
    candidates = None
    if inc_state is not None and inc_state.last_kind == "micro":
        from .prefilter import derive_candidates
        candidates = derive_candidates(snap, route, mesh)
    return _AllocLeg(inputs=inputs, cfg=snap.config, route=route,
                     mesh=mesh, generation=shipper.generation,
                     cand_sig=_cand_sig(candidates), candidates=candidates)


def _stage_storm(ssn, scanner, node_p):
    """Host staging for the postevict leg: the victim detail columns
    (resreq quanta, queue/job snapshot indices) slot-aligned with the
    evict leg's staging and padded to its bucket, plus the per-slot
    uids the serve proof matches the committed victim order against.
    None (leg not staged; the solve ships unadjusted exactly as before)
    when the session's ladder has no reclaim walk to predict, or the
    columns can't be proven (missing snapshot, quanta overflow)."""
    if "reclaim" not in _conf_names(ssn):
        # The prediction models actions/reclaim.py specifically; a
        # preempt/backfill-only ladder would invalidate every clean
        # session against a reclaim-shaped prediction.
        return None
    snap = getattr(scanner, "snap", None)
    if snap is None or snap.needs_fallback:
        return None
    from ..models.victim_index import VictimIndex
    vindex = VictimIndex.for_session(ssn)
    qix_map = {q: i for i, q in enumerate(snap.queue_ids)}
    jix_map = {u: i for i, u in enumerate(snap.job_uids)}
    detail = vindex.victim_detail(scanner.node_index, snap.resource_names,
                                  qix_map, jix_map)
    if detail is None:
        return None
    res, qix, jix = detail
    uids = vindex.victim_tensors(scanner.node_index)[2]
    mb = int(np.asarray(node_p).shape[0])
    m = res.shape[0]
    r = int(np.asarray(snap.inputs.task_req).shape[1])
    if m > mb or res.shape[1] != r:
        return None
    qb = int(np.asarray(snap.inputs.queue_exists).shape[0])
    jb = int(np.asarray(snap.inputs.job_start).shape[0])
    res_p = np.zeros((mb, r), np.int32)
    qix_p = np.full((mb,), qb, np.int32)
    jix_p = np.full((mb,), jb, np.int32)
    if m:
        res_p[:m] = res
        # Sentinel = axis bucket: the device scatters with mode="drop",
        # so victims of axis-absent queues/jobs update nothing — their
        # host twins aren't in the solve universe either.
        qix_p[:m] = np.where(qix >= 0, qix, qb)
        jix_p[:m] = np.where(jix >= 0, jix, jb)
    return res_p, qix_p, jix_p, uids


def _chaos_consume(arr: np.ndarray) -> np.ndarray:
    """Readback fault sites for the fused legs (doc/CHAOS.md):
    ``fused.slow`` sleeps before the transfer is consumed and
    ``fused.poison`` truncates the trailing column — the shape every
    consumer validates before seeding caches.  One no-op branch when
    the chaos engine is off."""
    from ..chaos import plan as chaos_plan
    plan = chaos_plan.PLAN
    if plan is None:
        return arr
    slow = plan.fire("fused.slow")
    if slow is not None:
        time.sleep(0.01 + 0.05 * slow.magnitude)
    if plan.fire("fused.poison") and arr.ndim >= 2 and arr.shape[-1]:
        return arr[..., :-1]
    return arr


def _fail(ssn, st: FusedState, exc: Exception, families) -> None:
    """Shared degrade path: feed the breaker, invalidate the resident
    image (the fused program may have died mid-write on a real device),
    count the failure, and let every family re-dispatch (then degrade
    further to its own host oracle under the breaker)."""
    from ..chaos.breaker import device_breaker
    from ..metrics import metrics
    from ..models.shipping import resident_shipper
    from ..trace import spans as trace
    st.failed = True
    st.alloc_pending = None
    st.alloc_leg = None
    st.topo_out = None
    storm = getattr(st, "storm", None)
    if storm is not None:
        st.storm = None
        ssn._fused_mutlog = None
        storm.release()
    device_breaker().failure()
    metrics.note_device_failure("fused")
    for fam in families:
        metrics.note_fused_leg(fam, "failed")
    try:
        resident_shipper(ssn.cache).invalidate()
    except Exception:
        metrics.note_swallowed("fused_invalidate")
    trace.note_degraded(
        f"fused dispatch failed ({type(exc).__name__}); per-family "
        "re-dispatch")


# ---------------------------------------------------------------------------
# Consumers.
# ---------------------------------------------------------------------------

def take_evict(ssn, scanner, trows, node_p, rank_p):
    """The fused dispatch point, called from scanner.batch_seed with the
    eviction staging fully derived.  Stages every other leg the session
    can prove out (alloc from the scanner's own snapshot; topo if
    actions/topo_allocate.py staged a request) and fires the ONE
    program.  Returns the evict leg's device (scores, perm) — the
    scanner defers the readback to its first consumer — or None, in
    which case batch_seed dispatches per family exactly as before."""
    if not fused_enabled():
        return None
    st = state_for(ssn)
    if st.dispatched or st.failed:
        return None
    from ..metrics import metrics
    from ..ops import evict_solver
    from ..ops.compile_cache import note_solve_key
    from ..trace import spans as trace

    legs = ["evict"]
    eroute, emesh = evict_solver.choose_evict_route(scanner._resident)
    alloc = None
    try:
        alloc = _stage_alloc(ssn, scanner.snap)
    except Exception:
        metrics.note_swallowed("fused_stage_alloc")
        alloc = None
    if alloc is not None:
        legs.append("solve")
    storm = None
    if (alloc is not None and alloc.candidates is None
            and knobs.FUSED_STORM.enabled()):
        try:
            storm = _stage_storm(ssn, scanner, node_p)
        except Exception:
            metrics.note_swallowed("fused_stage_storm")
            storm = None
    if storm is not None:
        legs.append("postevict")
    topo = st.topo_request
    if topo is not None:
        legs.append("topo")
    legs = tuple(legs)

    # Resident leaves feed the sharded evict leg; the alloc leg's image
    # is the same buffer when both shipped (one shipper per cache).
    ainp = alloc.inputs if alloc is not None else scanner._resident
    if eroute == "sharded" and ainp is None:
        return None  # nothing resident to read in place; per-family path

    aroute = alloc.route if alloc is not None else "xla"
    amesh = alloc.mesh if alloc is not None else None
    has_cand = alloc is not None and alloc.candidates is not None
    cand_idx = cand_valid = None
    cand_rows = 0
    if has_cand:
        c = alloc.candidates
        cand_rows = int(c.remap.shape[0] if c.remap is not None else c.count)
        if c.sharded:
            cand_idx = jnp.asarray(c.local_idx)
            cand_valid = jnp.asarray(c.local_valid)
        else:
            cand_idx = jnp.asarray(c.idx)
            cand_valid = jnp.asarray(c.valid)

    edyn = None if eroute == "sharded" else jnp.asarray(scanner.dyn)
    pe_res = pe_queue = pe_job = None
    if eroute == "sharded":
        from jax.sharding import NamedSharding, PartitionSpec as P
        rep = NamedSharding(emesh, P())
        trows_d = jax.device_put(np.asarray(trows), rep)
        node_d = jax.device_put(np.asarray(node_p), rep)
        rank_d = jax.device_put(np.asarray(rank_p), rep)
        if storm is not None:
            pe_res = jax.device_put(storm[0], rep)
            pe_queue = jax.device_put(storm[1], rep)
            pe_job = jax.device_put(storm[2], rep)
    else:
        trows_d = jnp.asarray(trows)
        node_d = jnp.asarray(node_p)
        rank_d = jnp.asarray(rank_p)
        if storm is not None:
            pe_res = jnp.asarray(storm[0])
            pe_queue = jnp.asarray(storm[1])
            pe_job = jnp.asarray(storm[2])

    sx = sy = sz = 0
    troute, tmesh = "xla", None
    box = None
    if topo is not None:
        from .topo_solver import BoxInputs, choose_topo_route
        inp, shape, _sig = topo
        sx, sy, sz = (int(v) for v in shape)
        troute, tmesh = choose_topo_route(
            int(np.asarray(inp.coords).shape[0]))
        box = BoxInputs(*(jnp.asarray(a) for a in inp))

    key = fused_solve_key(
        legs, aroute, has_cand, cand_rows,
        (None if alloc is None
         else (int(alloc.inputs.node_idle.shape[0]), alloc.cfg)),
        eroute,
        (scanner.cfg, scanner.r, scanner.np_pad, scanner.ns_pad,
         int(np.asarray(trows).shape[0]), int(np.asarray(node_p).shape[0])),
        troute, (sx, sy, sz))

    start = time.time()
    try:
        from ..chaos import plan as chaos_plan
        plan = chaos_plan.PLAN
        if plan is not None and plan.fire("fused.device_error"):
            raise RuntimeError("chaos: fused session dispatch failed "
                               "(injected)")
        with trace.span("fused.dispatch", legs=",".join(legs)):
            out = _fused_program(
                legs, alloc.cfg if alloc is not None else None, aroute,
                has_cand, amesh, scanner.cfg, scanner.r, scanner.np_pad,
                scanner.ns_pad, eroute, emesh, sx, sy, sz, troute, tmesh,
                ainp, cand_idx, cand_valid, scanner.statics, edyn,
                trows_d, node_d, rank_d, box, pe_res, pe_queue, pe_job)
    except Exception as exc:
        _fail(ssn, st, exc, legs)
        return None

    st.dispatched = True
    st.legs = legs
    metrics.note_session_dispatch("fused")
    metrics.note_route("fused", "+".join(sorted(legs)))
    note_solve_key(key)
    metrics.set_cycle_floor("fused", time.time() - start)
    trace.annotate(fused_legs=",".join(legs))

    if alloc is not None:
        from .solver import PendingSolve, _note_dispatch
        st.alloc_leg = alloc
        st.alloc_pending = PendingSolve(
            out["alloc"],
            remap=(alloc.candidates.remap
                   if alloc.candidates is not None else None))
        _note_dispatch(+1)
        if storm is not None:
            cap = _StormCapture(
                snap=scanner.snap, route=aroute,
                vic_res=storm[0], vic_qix=storm[1], vic_jix=storm[2],
                vic_node=np.array(np.asarray(node_p)), uids=storm[3],
                meta=out["postevict"][0], sel=out["postevict"][1])
            st.storm = cap
            # Arm the session mutation log: the serve proof replays the
            # committed evict/pipeline sequence against the device's
            # predicted iteration (framework/session.py hooks).
            ssn._fused_mutlog = cap.mutlog
    if topo is not None:
        st.topo_out = out["topo"]
        st.topo_sig = topo[2]
    return out["evict"]


def consume_evict(scores, perm, kb: int, n_pad: int):
    """Host readback of the deferred evict leg as one transfer, with the
    fused chaos seams applied and the poisoned-shape check every seeded
    row depends on.  Raises on any fault — the scanner degrades exactly
    like a per-family dispatch failure."""
    packed = _chaos_consume(np.asarray(scores))
    if packed.shape != (kb, n_pad):
        raise RuntimeError(
            f"fused evict readback shape {packed.shape} != ({kb}, {n_pad})")
    return packed.astype(np.int64), np.asarray(perm)


def take_alloc(ssn, shipper, snap, route, candidates):
    """tpu-allocate's consume point.

    Quiet half: the precomputed solve is THIS session's solve iff the
    action's own ship came back CLEAN at the dispatch generation with
    the same config, route and candidate gather.

    Storm half (doc/FUSED.md): when the dispatch carried a postevict
    leg, a DIRTY ship can still serve — iff the committed mutations are
    bit-identical to the device's predicted reclaim iteration and the
    fresh staging equals the dispatch staging plus the modeled deltas
    (``_prove_storm``).  The served packed result is the adjusted solve
    remapped onto the fresh task axis; any divergence discards the leg
    and re-dispatches per-family, counted under family="postevict".

    Returns the PendingSolve (the action's finish continuation fetches
    it through the standard path) or None for the per-family dispatch."""
    st = getattr(ssn, "_fused_state", None)
    if st is None or st.alloc_pending is None:
        return None
    from ..metrics import metrics
    from .solver import discard_solve
    pending, leg = st.alloc_pending, st.alloc_leg
    st.alloc_pending = None
    st.alloc_leg = None
    storm = getattr(st, "storm", None)
    st.storm = None
    if storm is not None:
        ssn._fused_mutlog = None
    ok = (shipper.last_mode == "clean"
          and shipper.generation == leg.generation
          and snap.config == leg.cfg
          and route == leg.route
          and _cand_sig(candidates) == leg.cand_sig)
    if storm is None:
        if not ok:
            discard_solve(pending)
            metrics.note_fused_leg("solve", "invalidated")
            return None
        metrics.note_fused_leg("solve", "served")
        return pending

    from ..chaos import plan as chaos_plan
    plan = chaos_plan.PLAN
    poison = plan is not None and plan.fire("fused.postevict_poison")
    served = None
    family = "postevict"
    try:
        if ok:
            # Clean ship at the dispatch generation: nothing mutated,
            # so the leg is valid iff the device ALSO predicted a quiet
            # session — then the adjustment was the identity and the
            # packed result IS the plain fused solve (counted under the
            # plain family; the dispatch count is what the steady gate
            # pins).  A clean session with a non-identity prediction is
            # a model divergence: discard.
            meta = np.asarray(storm.meta)
            if (int(meta[0]) == 0 and int(meta[5]) == 0
                    and not storm.mutlog):
                served, family = pending, "solve"
        else:
            served = _prove_storm(storm, snap, route, candidates, pending)
    except Exception:
        metrics.note_swallowed("fused_storm_prove")
        served = None
    storm.release()
    if served is None:
        discard_solve(pending)
        metrics.note_fused_leg("postevict", "invalidated")
        return None
    if poison:
        # Chaos site fused.postevict_poison (doc/CHAOS.md): a malformed
        # served leg must die in tpu-allocate's _validate_result before
        # any apply — degrade to the per-family re-dispatch, never
        # double-evict (the victims were committed by the host walk,
        # not by this leg; the leg only places).
        from .solver import PendingSolve
        packed = np.asarray(served.packed)
        if packed.ndim >= 2 and packed.shape[-1]:
            served = PendingSolve(packed[..., :-1], remap=served.remap)
    metrics.note_fused_leg(family, "served")
    return served


def _prove_storm(storm, snap, route, candidates, pending):
    """The storm serve proof (doc/FUSED.md "Storm half"): serve ONLY
    when the host's committed mutation log bit-matches the device's
    predicted iteration (P1: victim uid sequence in slot order; P2: the
    single pipeline of t* onto n*) AND the fresh staging equals the
    dispatch staging plus the modeled deltas on every mutated axis (P3)
    with the fresh task universe exactly the dispatch universe minus t*
    (P4).  Then the device's adjusted solve IS the solve the per-family
    re-dispatch would run, and the packed result remapped onto the
    fresh task axis is served.  Returns the remapped PendingSolve or
    None (per-family re-dispatch)."""
    if route != storm.route or candidates is not None:
        return None
    dinp = storm.dinp
    if not dinp or snap.needs_fallback:
        return None
    if snap.config != storm.dconfig:
        return None
    if (list(snap.node_names) != storm.dnode_names
            or list(snap.job_uids) != storm.djob_uids
            or list(snap.queue_ids) != storm.dqueue_ids
            or list(snap.resource_names) != storm.dres_names):
        return None
    meta = np.asarray(storm.meta)
    sel = np.asarray(storm.sel).astype(bool)
    did, qstar, jstar, tstar, nstar, vcnt = (int(v) for v in meta[:6])
    if did != 1 or vcnt < 0:
        return None
    slots = np.nonzero(sel)[0]
    if slots.size != vcnt or (slots.size
                              and int(slots[-1]) >= len(storm.uids)):
        return None
    if tstar >= len(storm.duids) or nstar >= len(storm.dnode_names):
        return None

    # P1 + P2 — the committed log is EXACTLY the predicted iteration.
    log = list(storm.mutlog)
    if len(log) != vcnt + 1:
        return None
    for i in range(vcnt):
        kind, uid, _node = log[i]
        if kind != "evict" or uid != storm.uids[int(slots[i])]:
            return None
    kind, uid, node = log[-1]
    if (kind != "pipeline" or uid != storm.duids[tstar]
            or node != storm.dnode_names[nstar]):
        return None

    finp = snap.inputs
    npa = np.asarray

    # P4 — fresh task universe == dispatch minus t*, per-job order kept.
    if len(snap.tasks) != len(storm.duids) - 1:
        return None
    drow = {uid: i for i, uid in enumerate(storm.duids)}
    remap = np.empty(len(snap.tasks), np.int64)
    for f, t in enumerate(snap.tasks):
        dr = drow.get(t.uid)
        if dr is None or dr == tstar:
            return None
        remap[f] = dr
    fstart, fcount = npa(finp.job_start), npa(finp.job_count)
    dstart, dcount = dinp["job_start"], dinp["job_count"]
    if fstart.shape != dstart.shape or jstar >= dcount.shape[0]:
        return None
    adjc = np.zeros_like(dcount)
    adjc[jstar] = 1
    if not np.array_equal(fcount, dcount - adjc):
        return None
    fsorted, dsorted = npa(finp.task_sorted), dinp["task_sorted"]
    if int(dsorted[int(dstart[jstar])]) != tstar:
        return None
    jobs = np.nonzero(fcount > 0)[0]
    reps = fcount[jobs].astype(np.int64)
    total = int(reps.sum())
    if total != len(snap.tasks):
        return None
    if total:
        jrep = np.repeat(jobs, reps)
        within = (np.arange(total, dtype=np.int64)
                  - np.repeat(np.cumsum(reps) - reps, reps))
        fpos = fstart[jrep].astype(np.int64) + within
        dpos = (dstart[jrep].astype(np.int64)
                + (jrep == jstar).astype(np.int64) + within)
        frows = fsorted[fpos]
        if frows.size and int(frows.max()) >= remap.shape[0]:
            return None
        if not np.array_equal(remap[frows], dsorted[dpos]):
            return None

    # P4 — per-task columns equal under the uid remap; sig tables and
    # every axis the iteration cannot touch bit-equal.
    rows = np.arange(len(snap.tasks), dtype=np.int64)
    for name in ("task_req", "task_res", "task_sig", "task_ports",
                 "task_aff_req", "task_anti", "task_match",
                 "task_paff_w", "task_panti_w"):
        fa, da = npa(getattr(finp, name)), dinp[name]
        if fa.shape[1:] != da.shape[1:] or fa.shape[0] < len(snap.tasks):
            return None
        if not np.array_equal(fa[rows], da[remap]):
            return None
    for name in ("sig_mask", "sig_bonus", "node_idle", "node_alloc",
                 "node_max_tasks", "node_exists", "node_coords",
                 "queue_deserved", "queue_deserved_f", "queue_ts",
                 "queue_uid_rank", "queue_exists", "job_queue",
                 "job_minavail", "job_prio", "job_ts", "job_uid_rank",
                 "total_res", "eps", "scalar_dims", "score_shift"):
        fa, da = npa(getattr(finp, name)), dinp[name]
        if fa.shape != da.shape or not np.array_equal(fa, da):
            return None

    # P3 — fresh mutated axes == dispatch + modeled deltas (int64
    # intermediates; int32 staging can't overflow them).
    i64 = np.int64
    tres = dinp["task_res"][tstar].astype(i64)
    vres = storm.vic_res[slots].astype(i64)
    vnode = storm.vic_node[slots].astype(i64)
    if slots.size and not np.all(vnode == nstar):
        return None
    exp = dinp["node_releasing"].astype(i64)
    np.add.at(exp, vnode, vres)
    exp[nstar] -= tres
    if not np.array_equal(npa(finp.node_releasing).astype(i64), exp):
        return None
    exp = dinp["node_used"].astype(i64)
    exp[nstar] += tres
    if not np.array_equal(npa(finp.node_used).astype(i64), exp):
        return None
    exp = dinp["node_count"].astype(i64)
    exp[nstar] += 1
    if not np.array_equal(npa(finp.node_count).astype(i64), exp):
        return None
    expp = dinp["node_ports"].copy()
    expp[nstar] = expp[nstar] | dinp["task_ports"][tstar]
    if not np.array_equal(npa(finp.node_ports), expp):
        return None
    exp = dinp["node_selcnt"].astype(i64)
    exp[nstar] += dinp["task_match"][tstar].astype(i64)
    if not np.array_equal(npa(finp.node_selcnt).astype(i64), exp):
        return None
    qb = dinp["queue_init_alloc"].shape[0]
    jb = dinp["job_init_alloc"].shape[0]
    if qstar >= qb:
        return None
    if snap.config.has_proportion:
        exp = dinp["queue_init_alloc"].astype(i64)
        vq = storm.vic_qix[slots].astype(i64)
        keep = vq < qb
        np.subtract.at(exp, vq[keep], vres[keep])
        exp[qstar] += tres
        if not np.array_equal(npa(finp.queue_init_alloc).astype(i64),
                              exp):
            return None
    elif not np.array_equal(npa(finp.queue_init_alloc),
                            dinp["queue_init_alloc"]):
        return None
    vj = storm.vic_jix[slots].astype(i64)
    keepj = vj < jb
    exp = dinp["job_init_alloc"].astype(i64)
    np.subtract.at(exp, vj[keepj], vres[keepj])
    if not np.array_equal(npa(finp.job_init_alloc).astype(i64), exp):
        return None
    exp = dinp["job_init_ready"].astype(i64)
    np.subtract.at(exp, vj[keepj], 1)
    if not np.array_equal(npa(finp.job_init_ready).astype(i64), exp):
        return None

    # Serve: remap the packed adjusted solve onto the fresh task axis.
    # Fresh real row f held dispatch row remap[f]; extras (BestEffort)
    # and padding rows stay unplaced, exactly as a fresh solve leaves
    # them.  The perm rebuild is _pack_result_ordered's argsort over
    # the same (placed, order) keys, so the fetch path decodes the
    # served leg exactly like a per-family readback.
    from .solver import PendingSolve
    packed = np.asarray(pending.packed)
    if packed.ndim != 2 or packed.shape[0] != 4:
        return None
    if remap.size and int(remap.max()) >= packed.shape[1]:
        return None
    pf = int(npa(finp.task_req).shape[0])
    a_f = np.zeros((pf,), np.int32)
    k_f = np.zeros((pf,), np.int32)
    o_f = np.zeros((pf,), np.int32)
    a_f[rows] = packed[0][remap]
    k_f[rows] = packed[1][remap]
    o_f[rows] = packed[2][remap]
    if int((packed[1] > 0).sum()) != int((k_f > 0).sum()):
        return None  # the device placed a row outside the fresh universe
    key = np.where(k_f > 0, o_f.astype(np.int64),
                   np.iinfo(np.int32).max)
    perm_f = np.argsort(key, kind="stable").astype(np.int32)
    out = np.ascontiguousarray(np.stack([a_f, k_f, o_f, perm_f]))
    return PendingSolve(out, remap=None)


def take_topo(ssn, inp, shape, n: int):
    """actions/topo_allocate's chokepoint, wired around dispatch_box_scan.

    First call in a session STAGES the scan and — when the conf carries
    an eviction action — triggers the shared scanner build so the fused
    dispatch serves all three families from one program.  Returns the
    host [n, 6] stats when the staged leg matches this exact request
    (same arrays, same shape), else None for the per-family dispatch."""
    if not fused_enabled():
        return None
    st = state_for(ssn)
    if st.failed:
        return None
    from ..metrics import metrics
    sig = (tuple(int(v) for v in shape),
           b"".join(np.ascontiguousarray(a).tobytes() for a in inp))
    if not st.dispatched and st.topo_request is None:
        st.topo_request = (inp, tuple(int(v) for v in shape), sig)
        names = _conf_names(ssn)
        if {"reclaim", "preempt", "backfill"} & set(names):
            from ..models.scanner import batch_evict_enabled, \
                maybe_shared_scanner
            if batch_evict_enabled():
                st.early_scanner = True
                try:
                    sc = maybe_shared_scanner(ssn)  # batch_seed -> take_evict
                    if sc is not None:
                        # Seeded BEFORE this session's mutating actions:
                        # refresh drops the victim ranking on the first
                        # mutation so the walk replays the exact queue.
                        sc._fused_early = True
                except Exception:
                    metrics.note_swallowed("fused_topo_scanner")
        if not st.dispatched:
            st.topo_request = None  # nothing fused it; per-family path
            return None
    if not st.dispatched or st.topo_out is None:
        return None
    if sig != st.topo_sig:
        metrics.note_fused_leg("topo", "invalidated")
        return None
    try:
        stats = _chaos_consume(np.asarray(st.topo_out))
        if stats.ndim != 2 or stats.shape[1] != 6 or stats.shape[0] < n:
            raise RuntimeError(
                f"fused topo readback shape {stats.shape} (need >= "
                f"({n}, 6))")
    except Exception as exc:
        _fail(ssn, st, exc, ("topo",))
        return None
    metrics.note_fused_leg("topo", "served")
    return stats[:n]


def flush_deferred(ssn) -> None:
    """Flush commit sinks the action-commit scope deferred into the
    fused dispatch window (framework/commit.py): tpu-allocate's finish
    calls this FIRST — before fetching the device result — so the
    cluster egress overlaps the device wait and evict events still
    precede the session's binds on every path (served, invalidated,
    fallback).  close_session's finalize is the safety net when the
    consume never ran."""
    sinks = getattr(ssn, "_deferred_flush", None)
    if not sinks:
        return
    ssn._deferred_flush = []
    for sink in sinks:
        sink.flush()


def finalize_session(ssn) -> None:
    """Ledger hygiene at session close/abandon: flush any commit sinks
    still deferred into a dispatch window nobody reached, release the
    storm capture, and retire an unconsumed alloc leg's in-flight
    dispatch handle (incremental cache answered first, fallback path,
    stale abort)."""
    flush_deferred(ssn)
    st = getattr(ssn, "_fused_state", None)
    if st is None:
        return
    storm = getattr(st, "storm", None)
    if storm is not None:
        st.storm = None
        ssn._fused_mutlog = None
        storm.release()
    if st.alloc_pending is None:
        return
    from ..metrics import metrics
    from .solver import discard_solve
    pending, st.alloc_pending, st.alloc_leg = st.alloc_pending, None, None
    discard_solve(pending)
    metrics.note_fused_leg("solve", "unused")
