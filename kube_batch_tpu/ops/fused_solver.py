"""One-dispatch sessions: the action ladder's whole solve-family fused
into a SINGLE device program (doc/FUSED.md).

A steady micro-session still paid one device round trip per solver
family — the allocate solve (ops/solver.py), the batched eviction solve
(ops/evict_solver.py) and the topo box scan (ops/topo_solver.py) each
dispatched their own program even though all three read the SAME
resident node image and none depends on another's device output (the
sequential decision tail — victim commits, placement statements — runs
on the host against the readbacks).  This module composes the exact
per-family jitted programs inside ONE outer jit, so the session's
entire device work lands in one dispatch at the first consumer and the
host replays the decision ladder against precomputed tensors:

  * ``alloc`` leg — the allocate solve (full-bucket or candidate-row,
    single-chip / Pallas / mesh-sharded: the same routing
    ``choose_solver_mesh`` pins), packed through the SAME
    ``_pack_result_ordered`` [4, P] readback and wrapped as a standard
    ``PendingSolve`` — tpu-allocate's ``finish`` continuation consumes
    it through ``fetch_solve`` unchanged.
  * ``evict`` leg — ``evict_batch_solve``'s [K, N] profile scan + the
    victim lexsort, consumed lazily by models/scanner.py (the readback
    rides the async-dispatch window to the first ``scores()`` call).
  * ``topo`` leg — ``box_scan``'s [N, 6] origin stats for the first
    slice job, staged by actions/topo_allocate.py before the scanner
    builds so all three families share the dispatch.

Validity is generation-proved, never assumed: the alloc leg records the
shipper generation it solved at, and tpu-allocate consumes it only when
its own ship comes back CLEAN at that same generation with the same
config and the same candidate gather (byte-compared remap) — the exact
"clean ship at an unchanged generation proves byte-identical inputs"
contract the incremental solve cache already relies on
(models/shipping.py, models/incremental.py).  Anything else counts a
``kube_batch_tpu_fused_legs_total{outcome="invalidated"}`` and falls
back to the per-family dispatch — bit-parity is structural, not
probabilistic.  ``KUBE_BATCH_TPU_FUSED=0`` is the A/B control: every
consumer takes the per-family chokepoints exactly as before.

Failure degrades, never decides: a fused dispatch or readback failure
feeds the shared device breaker (chaos site ``fused.device_error``;
readback faults ``fused.slow`` / ``fused.poison``), invalidates the
resident image, and the session re-dispatches per family — then the
per-family paths' own host oracles below that (doc/CHAOS.md).
"""

from __future__ import annotations

import functools
import time
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import knobs

FUSED_ENV = knobs.FUSED.env
FUSED_SOLVE_CHOICE = "fused"

# Leg outcome vocabulary (kube_batch_tpu_fused_legs_total{outcome=}):
#   served      — the precomputed tensor answered the family's question
#   invalidated — host state moved between dispatch and consume (or the
#                 consumer's staging differed); per-family re-dispatch
#   unused      — dispatched but never consumed (e.g. the incremental
#                 cache answered first, or the session aborted)
#   failed      — the fused dispatch/readback itself errored; breaker fed


def fused_enabled() -> bool:
    return knobs.FUSED.enabled()


class _AllocLeg(NamedTuple):
    """The alloc leg's host-side capture: everything tpu-allocate must
    re-derive identically for the precomputed solve to be ITS solve."""
    inputs: object        # resident SolverInputs (the shipped image)
    cfg: object           # SolverConfig (static)
    route: str            # choose_solver_mesh choice at stage time
    mesh: object          # the mesh the route validated (or None)
    generation: int       # shipper generation the solve read
    cand_sig: object      # candidate-gather identity (None = full bucket)
    candidates: object    # the staged CandidateSet (remap for the fetch)


class FusedState:
    """Per-session fused-dispatch ledger, cached on ``ssn._fused_state``.

    One fused dispatch per session maximum: the first device-needing
    consumer stages every leg it can prove out and fires; later
    consumers either match their capture (served) or re-dispatch per
    family (invalidated, counted)."""

    __slots__ = ("dispatched", "failed", "legs", "alloc_pending",
                 "alloc_leg", "topo_request", "topo_out", "topo_sig",
                 "early_scanner")

    def __init__(self):
        self.dispatched = False
        self.failed = False
        self.legs = ()
        self.alloc_pending = None   # PendingSolve until consumed/discarded
        self.alloc_leg = None       # _AllocLeg capture
        self.topo_request = None    # (BoxInputs np, shape, sig) staging
        self.topo_out = None        # device [N, 6] stats
        self.topo_sig = None
        self.early_scanner = False  # scanner seeded before mutations ran


def state_for(ssn) -> FusedState:
    st = getattr(ssn, "_fused_state", None)
    if st is None:
        st = FusedState()
        ssn._fused_state = st
    return st


def _conf_names(ssn) -> tuple:
    """The session's action ladder (scheduler stamps it at open)."""
    return tuple(getattr(ssn, "_conf_actions", ()) or ())


# ---------------------------------------------------------------------------
# The fused program: per-family jitted solvers composed inside ONE outer
# jit.  jit-of-jit inlines — the whole composition compiles to a single
# executable and the runtime enqueues ONE device program per call.
# Absent legs pass None for their traced arguments (an empty pytree) and
# are skipped at trace time via the static ``legs`` tuple.
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=(
    "legs", "acfg", "aroute", "has_cand", "amesh",
    "ecfg", "r", "np_pad", "ns_pad", "eroute", "emesh",
    "sx", "sy", "sz", "troute", "tmesh"))
def _fused_program(legs, acfg, aroute, has_cand, amesh,
                   ecfg, r, np_pad, ns_pad, eroute, emesh,
                   sx, sy, sz, troute, tmesh,
                   ainp, cand_idx, cand_valid,
                   statics, edyn, trows, vic_node, vic_rank,
                   box):
    out = {}
    if "solve" in legs:
        from .solver import (_gather_candidate_inputs, _pack_result_ordered,
                             solve_allocate)
        if has_cand:
            if aroute == "sharded":
                from ..parallel.sharded_solver import (
                    gather_candidate_sharded, solve_allocate_sharded)
                sub = gather_candidate_sharded(ainp, cand_idx, cand_valid,
                                               amesh)
                res = solve_allocate_sharded(sub, acfg, amesh)
            else:
                sub = _gather_candidate_inputs(ainp, cand_idx, cand_valid)
                res = solve_allocate(sub, acfg)
        elif aroute == "sharded":
            from ..parallel.sharded_solver import solve_allocate_sharded
            res = solve_allocate_sharded(ainp, acfg, amesh)
        elif aroute == "pallas":
            from .pallas_solver import solve_allocate_pallas
            res = solve_allocate_pallas(ainp, acfg)
        else:
            res = solve_allocate(ainp, acfg)
        out["alloc"] = _pack_result_ordered(res.assignment, res.kind,
                                            res.order)
    if "evict" in legs:
        if eroute == "sharded":
            from ..parallel.sharded_scan import evict_batch_solve_sharded
            scores, perm = evict_batch_solve_sharded(
                ecfg, r, np_pad, ns_pad, statics, ainp.node_used,
                ainp.node_count, ainp.node_ports, ainp.node_selcnt,
                trows, vic_node, vic_rank, emesh)
        else:
            from .evict_solver import evict_batch_solve
            scores, perm = evict_batch_solve(
                ecfg, r, np_pad, ns_pad, statics, edyn, trows,
                vic_node, vic_rank)
        out["evict"] = (scores, perm)
    if "topo" in legs:
        if troute == "sharded":
            from .topo_solver import box_scan_sharded
            out["topo"] = box_scan_sharded(box, sx, sy, sz, tmesh)
        else:
            from .topo_solver import box_scan
            out["topo"] = box_scan(box, sx, sy, sz)
    return out


def fused_solve_key(legs, aroute, has_cand, cand_rows, a_shape,
                    eroute, e_shape, troute, t_shape) -> tuple:
    """Compile-cache identity of one fused executable: the static leg
    set plus each present leg's jit-relevant degrees of freedom (the
    per-family solve_key/evict_solve_key/topo_solve_key disciplines
    folded into one tuple)."""
    return (FUSED_SOLVE_CHOICE, tuple(legs), aroute, has_cand, cand_rows,
            a_shape, eroute, e_shape, troute, t_shape)


# ---------------------------------------------------------------------------
# Staging: what each leg must prove on the host before riding along.
# ---------------------------------------------------------------------------

def _cand_sig(candidates) -> object:
    """Byte identity of a candidate gather: same remap => same gathered
    program => same placements.  None means the full-bucket program."""
    if candidates is None:
        return None
    remap = candidates.remap
    return (int(candidates.count),
            None if remap is None else remap.tobytes())


def _stage_alloc(ssn, snap) -> Optional[_AllocLeg]:
    """Decide whether the allocate solve can ride the fused dispatch,
    and stage exactly what tpu-allocate's begin half would stage: the
    shipped resident image, the route, and the candidate gather.  Every
    predicate mirrors actions/tpu_allocate.execute_begin so the capture
    is the SAME dispatch that action would have issued — the consume
    check then only has to prove nothing moved in between."""
    if "tpu-allocate" not in _conf_names(ssn):
        return None
    if not knobs.PIPELINE.enabled():
        # The sequential control consumes synchronously via
        # best_solve_allocate; a pre-staged async handle would change
        # its timing topology.  Keep the control untouched.
        return None
    from ..chaos.breaker import device_breaker
    if not device_breaker().allow():
        return None
    if snap.needs_fallback or not snap.tasks:
        return None
    from ..models import incremental
    from ..models.shipping import resident_shipper
    from ..ops.solver import choose_solver_mesh
    shipper = resident_shipper(ssn.cache)
    inputs = shipper.ship(snap.inputs, snap.config)
    inc_state = (incremental.state_for(ssn.cache, create=False)
                 if incremental.incremental_enabled() else None)
    if (inc_state is not None
            and shipper.last_mode == "clean"
            and inc_state.solve_gen == shipper.generation
            and inc_state.solve_cfg == snap.config
            and inc_state.solve_result is not None):
        # The generation-keyed cache already holds this session's
        # answer; tpu-allocate will reuse it without any dispatch.
        return None
    route, mesh = choose_solver_mesh(snap.inputs)
    candidates = None
    if inc_state is not None and inc_state.last_kind == "micro":
        from .prefilter import derive_candidates
        candidates = derive_candidates(snap, route, mesh)
    return _AllocLeg(inputs=inputs, cfg=snap.config, route=route,
                     mesh=mesh, generation=shipper.generation,
                     cand_sig=_cand_sig(candidates), candidates=candidates)


def _chaos_consume(arr: np.ndarray) -> np.ndarray:
    """Readback fault sites for the fused legs (doc/CHAOS.md):
    ``fused.slow`` sleeps before the transfer is consumed and
    ``fused.poison`` truncates the trailing column — the shape every
    consumer validates before seeding caches.  One no-op branch when
    the chaos engine is off."""
    from ..chaos import plan as chaos_plan
    plan = chaos_plan.PLAN
    if plan is None:
        return arr
    slow = plan.fire("fused.slow")
    if slow is not None:
        time.sleep(0.01 + 0.05 * slow.magnitude)
    if plan.fire("fused.poison") and arr.ndim >= 2 and arr.shape[-1]:
        return arr[..., :-1]
    return arr


def _fail(ssn, st: FusedState, exc: Exception, families) -> None:
    """Shared degrade path: feed the breaker, invalidate the resident
    image (the fused program may have died mid-write on a real device),
    count the failure, and let every family re-dispatch (then degrade
    further to its own host oracle under the breaker)."""
    from ..chaos.breaker import device_breaker
    from ..metrics import metrics
    from ..models.shipping import resident_shipper
    from ..trace import spans as trace
    st.failed = True
    st.alloc_pending = None
    st.alloc_leg = None
    st.topo_out = None
    device_breaker().failure()
    metrics.note_device_failure("fused")
    for fam in families:
        metrics.note_fused_leg(fam, "failed")
    try:
        resident_shipper(ssn.cache).invalidate()
    except Exception:
        metrics.note_swallowed("fused_invalidate")
    trace.note_degraded(
        f"fused dispatch failed ({type(exc).__name__}); per-family "
        "re-dispatch")


# ---------------------------------------------------------------------------
# Consumers.
# ---------------------------------------------------------------------------

def take_evict(ssn, scanner, trows, node_p, rank_p):
    """The fused dispatch point, called from scanner.batch_seed with the
    eviction staging fully derived.  Stages every other leg the session
    can prove out (alloc from the scanner's own snapshot; topo if
    actions/topo_allocate.py staged a request) and fires the ONE
    program.  Returns the evict leg's device (scores, perm) — the
    scanner defers the readback to its first consumer — or None, in
    which case batch_seed dispatches per family exactly as before."""
    if not fused_enabled():
        return None
    st = state_for(ssn)
    if st.dispatched or st.failed:
        return None
    from ..metrics import metrics
    from ..ops import evict_solver
    from ..ops.compile_cache import note_solve_key
    from ..trace import spans as trace

    legs = ["evict"]
    eroute, emesh = evict_solver.choose_evict_route(scanner._resident)
    alloc = None
    try:
        alloc = _stage_alloc(ssn, scanner.snap)
    except Exception:
        metrics.note_swallowed("fused_stage_alloc")
        alloc = None
    if alloc is not None:
        legs.append("solve")
    topo = st.topo_request
    if topo is not None:
        legs.append("topo")
    legs = tuple(legs)

    # Resident leaves feed the sharded evict leg; the alloc leg's image
    # is the same buffer when both shipped (one shipper per cache).
    ainp = alloc.inputs if alloc is not None else scanner._resident
    if eroute == "sharded" and ainp is None:
        return None  # nothing resident to read in place; per-family path

    aroute = alloc.route if alloc is not None else "xla"
    amesh = alloc.mesh if alloc is not None else None
    has_cand = alloc is not None and alloc.candidates is not None
    cand_idx = cand_valid = None
    cand_rows = 0
    if has_cand:
        c = alloc.candidates
        cand_rows = int(c.remap.shape[0] if c.remap is not None else c.count)
        if c.sharded:
            cand_idx = jnp.asarray(c.local_idx)
            cand_valid = jnp.asarray(c.local_valid)
        else:
            cand_idx = jnp.asarray(c.idx)
            cand_valid = jnp.asarray(c.valid)

    edyn = None if eroute == "sharded" else jnp.asarray(scanner.dyn)
    if eroute == "sharded":
        from jax.sharding import NamedSharding, PartitionSpec as P
        rep = NamedSharding(emesh, P())
        trows_d = jax.device_put(np.asarray(trows), rep)
        node_d = jax.device_put(np.asarray(node_p), rep)
        rank_d = jax.device_put(np.asarray(rank_p), rep)
    else:
        trows_d = jnp.asarray(trows)
        node_d = jnp.asarray(node_p)
        rank_d = jnp.asarray(rank_p)

    sx = sy = sz = 0
    troute, tmesh = "xla", None
    box = None
    if topo is not None:
        from .topo_solver import BoxInputs, choose_topo_route
        inp, shape, _sig = topo
        sx, sy, sz = (int(v) for v in shape)
        troute, tmesh = choose_topo_route(
            int(np.asarray(inp.coords).shape[0]))
        box = BoxInputs(*(jnp.asarray(a) for a in inp))

    key = fused_solve_key(
        legs, aroute, has_cand, cand_rows,
        (None if alloc is None
         else (int(alloc.inputs.node_idle.shape[0]), alloc.cfg)),
        eroute,
        (scanner.cfg, scanner.r, scanner.np_pad, scanner.ns_pad,
         int(np.asarray(trows).shape[0]), int(np.asarray(node_p).shape[0])),
        troute, (sx, sy, sz))

    start = time.time()
    try:
        from ..chaos import plan as chaos_plan
        plan = chaos_plan.PLAN
        if plan is not None and plan.fire("fused.device_error"):
            raise RuntimeError("chaos: fused session dispatch failed "
                               "(injected)")
        with trace.span("fused.dispatch", legs=",".join(legs)):
            out = _fused_program(
                legs, alloc.cfg if alloc is not None else None, aroute,
                has_cand, amesh, scanner.cfg, scanner.r, scanner.np_pad,
                scanner.ns_pad, eroute, emesh, sx, sy, sz, troute, tmesh,
                ainp, cand_idx, cand_valid, scanner.statics, edyn,
                trows_d, node_d, rank_d, box)
    except Exception as exc:
        _fail(ssn, st, exc, legs)
        return None

    st.dispatched = True
    st.legs = legs
    metrics.note_session_dispatch("fused")
    metrics.note_route("fused", "+".join(sorted(legs)))
    note_solve_key(key)
    metrics.set_cycle_floor("fused", time.time() - start)
    trace.annotate(fused_legs=",".join(legs))

    if alloc is not None:
        from .solver import PendingSolve, _note_dispatch
        st.alloc_leg = alloc
        st.alloc_pending = PendingSolve(
            out["alloc"],
            remap=(alloc.candidates.remap
                   if alloc.candidates is not None else None))
        _note_dispatch(+1)
    if topo is not None:
        st.topo_out = out["topo"]
        st.topo_sig = topo[2]
    return out["evict"]


def consume_evict(scores, perm, kb: int, n_pad: int):
    """Host readback of the deferred evict leg as one transfer, with the
    fused chaos seams applied and the poisoned-shape check every seeded
    row depends on.  Raises on any fault — the scanner degrades exactly
    like a per-family dispatch failure."""
    packed = _chaos_consume(np.asarray(scores))
    if packed.shape != (kb, n_pad):
        raise RuntimeError(
            f"fused evict readback shape {packed.shape} != ({kb}, {n_pad})")
    return packed.astype(np.int64), np.asarray(perm)


def take_alloc(ssn, shipper, snap, route, candidates):
    """tpu-allocate's consume point: the precomputed solve is THIS
    session's solve iff the action's own ship came back CLEAN at the
    dispatch generation with the same config, route and candidate
    gather.  Returns the PendingSolve (the action's finish continuation
    fetches it through the standard path) or None for the per-family
    dispatch."""
    st = getattr(ssn, "_fused_state", None)
    if st is None or st.alloc_pending is None:
        return None
    from ..metrics import metrics
    from .solver import discard_solve
    pending, leg = st.alloc_pending, st.alloc_leg
    st.alloc_pending = None
    st.alloc_leg = None
    ok = (shipper.last_mode == "clean"
          and shipper.generation == leg.generation
          and snap.config == leg.cfg
          and route == leg.route
          and _cand_sig(candidates) == leg.cand_sig)
    if not ok:
        discard_solve(pending)
        metrics.note_fused_leg("solve", "invalidated")
        return None
    metrics.note_fused_leg("solve", "served")
    return pending


def take_topo(ssn, inp, shape, n: int):
    """actions/topo_allocate's chokepoint, wired around dispatch_box_scan.

    First call in a session STAGES the scan and — when the conf carries
    an eviction action — triggers the shared scanner build so the fused
    dispatch serves all three families from one program.  Returns the
    host [n, 6] stats when the staged leg matches this exact request
    (same arrays, same shape), else None for the per-family dispatch."""
    if not fused_enabled():
        return None
    st = state_for(ssn)
    if st.failed:
        return None
    from ..metrics import metrics
    sig = (tuple(int(v) for v in shape),
           b"".join(np.ascontiguousarray(a).tobytes() for a in inp))
    if not st.dispatched and st.topo_request is None:
        st.topo_request = (inp, tuple(int(v) for v in shape), sig)
        names = _conf_names(ssn)
        if {"reclaim", "preempt", "backfill"} & set(names):
            from ..models.scanner import batch_evict_enabled, \
                maybe_shared_scanner
            if batch_evict_enabled():
                st.early_scanner = True
                try:
                    sc = maybe_shared_scanner(ssn)  # batch_seed -> take_evict
                    if sc is not None:
                        # Seeded BEFORE this session's mutating actions:
                        # refresh drops the victim ranking on the first
                        # mutation so the walk replays the exact queue.
                        sc._fused_early = True
                except Exception:
                    metrics.note_swallowed("fused_topo_scanner")
        if not st.dispatched:
            st.topo_request = None  # nothing fused it; per-family path
            return None
    if not st.dispatched or st.topo_out is None:
        return None
    if sig != st.topo_sig:
        metrics.note_fused_leg("topo", "invalidated")
        return None
    try:
        stats = _chaos_consume(np.asarray(st.topo_out))
        if stats.ndim != 2 or stats.shape[1] != 6 or stats.shape[0] < n:
            raise RuntimeError(
                f"fused topo readback shape {stats.shape} (need >= "
                f"({n}, 6))")
    except Exception as exc:
        _fail(ssn, st, exc, ("topo",))
        return None
    metrics.note_fused_leg("topo", "served")
    return stats[:n]


def finalize_session(ssn) -> None:
    """Ledger hygiene at session close/abandon: an alloc leg nobody
    consumed (incremental cache answered first, fallback path, stale
    abort) still holds an in-flight dispatch handle — retire it."""
    st = getattr(ssn, "_fused_state", None)
    if st is None or st.alloc_pending is None:
        return
    from ..metrics import metrics
    from .solver import discard_solve
    pending, st.alloc_pending, st.alloc_leg = st.alloc_pending, None, None
    discard_solve(pending)
    metrics.note_fused_leg("solve", "unused")
