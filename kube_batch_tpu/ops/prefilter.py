"""Candidate-row solve prefilter: the [C << N] allocate program.

The last solver-side per-cycle floor (ROADMAP item #2, doc/INCREMENTAL.md
"Killing the per-cycle floors"): even a micro session's solve scans every
[N] node row per placement, so a 0.1% churn cycle at 50k x 10k still pays
the full-cluster device wait.  This module derives, on host and per
session, a PROVABLY sufficient candidate node set C from the staged start
tensors; the dispatch then gathers only those rows out of the resident
buffer into a bucketed [C]-node program and the readback scatters the
assignment back into full-node indices — bit-identical placements at a
per-placement cost of O(C) instead of O(N).

## Why the candidate set is exact (not a heuristic)

Fix the session-start tensors.  During the allocate solve:

* a node's ``idle``/``releasing`` only DECREASE and its ``count`` only
  INCREASES — and only when a task is placed on it ("touched");
* ``sig_mask``/``node_exists``/``node_alloc``/``sig_bonus`` never change;
* an UNTOUCHED node's feasibility for a task profile and its score are
  therefore constant, equal to their session-start values.

At every placement step the argmax winner is either (a) a previously
touched node, or (b) the (score desc, node-index asc)-best start-feasible
untouched node.  At most ``T = p_real`` placements happen, so at most T
nodes are ever touched, and the winner-from-untouched at any step lies
within the first ``T+1`` start-feasible nodes of its profile's start
ranking.  Inductively every winner — hence every touched node — lies in

    C = union over distinct pending profiles (sig, req, res) of the
        first min(T+1, all) start-feasible nodes in
        (start score desc, node index asc) order,

evaluated with the device's exact integer formulas (the same grid-score
ints the host scanner mirrors, models/scanner._scores_numpy).  Ties are
safe because candidate rows are gathered in ascending node order, so
"first max" over the gathered program equals "first max" over the full
one restricted to C — and no node outside C can attain the max.

Dynamic predicates (host ports, pod (anti-)affinity) make untouched-node
scores task-placement-dependent only through occupancy tensors that also
change exclusively on touch — but the required-affinity mask can GROW
feasibility, so rather than ranking under those features the prefilter
simply stands down when any of them is active (they are rare; the full
program is the unconditional fallback and the parity control).

The prefilter keys off the resident buffer's generation contract: it is
consulted only on the dispatch path (a byte-clean ship reuses the cached
solve without any program at all, doc/INCREMENTAL.md), and the readback
is remapped and stored in the SAME generation-keyed solve cache, so a
later clean cycle reuses the full-space result regardless of which
program produced it.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from .. import knobs
from .compile_cache import bucket
from .resources import EPS_QUANTA, SCORE_GRID_K

# Escape hatch for A/B measurement and field debugging: =0 always runs
# the full-node-bucket program (placement-identical by construction).
CANDIDATE_SOLVE_ENV = knobs.CANDIDATE_SOLVE.env
# Above this many distinct pending (sig, req, res) profiles the host
# ranking pass costs more than the device scan it would save.
_MAX_PROFILES = 64


def candidate_solve_enabled() -> bool:
    return knobs.CANDIDATE_SOLVE.enabled()


class CandidateSet:
    """One session's candidate-row gather plan.

    ``remap`` maps every gathered program row back to its full-space node
    row — the scatter applied to the readback's assignment column.  For
    the mesh route the gather happens per shard (each device takes its
    own rows of the resident buffer), so the plan carries device-local
    index/valid matrices shaped [n_dev, L]."""

    __slots__ = ("count", "remap", "idx", "valid", "local_idx",
                 "local_valid", "sharded")

    def __init__(self, count, remap, idx=None, valid=None,
                 local_idx=None, local_valid=None):
        self.count = count          # real candidate rows (pre-padding)
        self.remap = remap          # np [C_pad] int32 full node rows
        self.idx = idx              # single-chip: np [C_pad] int32
        self.valid = valid          # single-chip: np [C_pad] bool
        self.local_idx = local_idx      # sharded: np [n_dev, L] int32
        self.local_valid = local_valid  # sharded: np [n_dev, L] bool
        self.sharded = local_idx is not None


def _fit_rows(req: np.ndarray, mat: np.ndarray) -> np.ndarray:
    """[N] bool epsilon LessEqual of one task request against [N, R]
    state — the numpy mirror of ops.solver._unrolled_le (same EPS_QUANTA
    semantics per dimension, scalar dims >= 2 skipped when the request
    is epsilon-low).  Exactness-load-bearing (like the sibling mirror in
    models/scanner._scores_numpy): a drift from the device math would
    silently mis-rank candidates, so
    tests/test_cycle_floors.py::test_prefilter_host_mirrors_equal_device_math
    pins value identity — change them together."""
    r = mat.shape[1]
    ok = None
    for i in range(r):
        l = int(req[i])
        m = mat[:, i].astype(np.int64)
        oki = (l < m) | (np.abs(l - m) < EPS_QUANTA)
        if i >= 2:
            oki = oki | (l <= EPS_QUANTA)
        ok = oki if ok is None else ok & oki
    return ok


def _grid_score_rows(res: np.ndarray, used: np.ndarray, alloc: np.ndarray,
                     shift: np.ndarray, weights) -> np.ndarray:
    """[N] int64 start scores — the exact integer math of
    ops/scoring.grid_score (same ints as the device and the host
    scanner's _scores_numpy: grid floor divisions + weighted sums).
    Pinned against the device kernel by
    test_prefilter_host_mirrors_equal_device_math — change together."""
    g = []
    for d in range(2):
        cs = alloc[:, d].astype(np.int64) >> int(shift[d])
        xs = np.minimum((used[:, d].astype(np.int64) + int(res[d]))
                        >> int(shift[d]), cs)
        q = np.where(cs > 0, (xs * SCORE_GRID_K) // np.maximum(cs, 1),
                     SCORE_GRID_K)
        g.append(q)
    gc, gm = g
    score = np.zeros(used.shape[0], np.int64)
    if weights.least_requested:
        score += int(weights.least_requested) * 5 * (
            2 * SCORE_GRID_K - gc - gm)
    if weights.most_requested:
        score += int(weights.most_requested) * 5 * (gc + gm)
    if weights.balanced_resource:
        score += int(weights.balanced_resource) * (
            10 * SCORE_GRID_K - 10 * np.abs(gc - gm))
    return score


def derive_candidates(snap, route: str, mesh=None) -> Optional["CandidateSet"]:
    """The session's candidate set, or None when the full program should
    run (feature gated off, dynamic predicates active, too many
    profiles, or C's bucket is not strictly smaller than the node
    bucket — no win to be had)."""
    if not candidate_solve_enabled():
        return None
    cfg = snap.config
    if cfg.has_ports or cfg.has_pod_affinity or cfg.has_pod_affinity_score:
        return None  # dynamic occupancy terms: see module docstring
    p_real = len(snap.tasks)
    if p_real == 0:
        return None
    inp = snap.inputs
    n_pad = int(np.asarray(inp.node_idle).shape[0])

    task_sig = np.asarray(inp.task_sig)[:p_real].astype(np.int64)
    task_req = np.asarray(inp.task_req)[:p_real].astype(np.int64)
    task_res = np.asarray(inp.task_res)[:p_real].astype(np.int64)
    profiles = np.unique(
        np.concatenate([task_sig[:, None], task_req, task_res], axis=1),
        axis=0)
    if profiles.shape[0] > _MAX_PROFILES:
        return None

    idle = np.asarray(inp.node_idle)
    releasing = np.asarray(inp.node_releasing)
    used = np.asarray(inp.node_used)
    alloc = np.asarray(inp.node_alloc)
    count = np.asarray(inp.node_count).astype(np.int64)
    maxt = np.asarray(inp.node_max_tasks).astype(np.int64)
    exists = np.asarray(inp.node_exists)
    sig_mask = np.asarray(inp.sig_mask)
    sig_bonus = np.asarray(inp.sig_bonus).astype(np.int64)
    shift = np.asarray(inp.score_shift)
    r = task_req.shape[1]

    top_k = p_real + 1  # T+1: at most p_real placements can touch nodes
    static_ok = exists & (count < maxt)
    members = []
    for row in profiles:
        sig = int(row[0])
        req = row[1:1 + r]
        res = row[1 + r:]
        feasible = (sig_mask[sig] & static_ok
                    & (_fit_rows(req, idle) | _fit_rows(req, releasing)))
        feas_idx = np.nonzero(feasible)[0]
        if feas_idx.size == 0:
            continue
        if feas_idx.size > top_k:
            score = (_grid_score_rows(res, used[feas_idx], alloc[feas_idx],
                                      shift, cfg.weights)
                     + sig_bonus[sig][feas_idx])
            # (score desc, node index asc): lexsort's last key is
            # primary; feas_idx is already ascending so equal scores
            # keep index order.
            order = np.lexsort((feas_idx, -score))[:top_k]
            feas_idx = feas_idx[order]
        members.append(feas_idx)
    if not members:
        return None  # nothing placeable: the full program retires fast
    cand = np.unique(np.concatenate(members)).astype(np.int32)

    if route == "sharded" and mesh is not None:
        n_dev = int(mesh.size)
        n_local = n_pad // n_dev
        shard_of = cand // n_local
        per_shard = [cand[shard_of == s] - s * n_local
                     for s in range(n_dev)]
        l_pad = bucket(max(max(len(p) for p in per_shard), 1))
        if n_dev * l_pad >= n_pad:
            return None
        local_idx = np.zeros((n_dev, l_pad), np.int32)
        local_valid = np.zeros((n_dev, l_pad), bool)
        remap = np.zeros((n_dev * l_pad,), np.int32)
        for s, rows in enumerate(per_shard):
            k = len(rows)
            local_idx[s, :k] = rows
            local_valid[s, :k] = True
            remap[s * l_pad:s * l_pad + k] = rows + s * n_local
        return CandidateSet(int(cand.size), remap,
                            local_idx=local_idx, local_valid=local_valid)

    c_pad = bucket(int(cand.size))
    if c_pad >= n_pad:
        return None
    idx = np.full((c_pad,), int(cand[-1]), np.int32)
    idx[:cand.size] = cand
    valid = np.zeros((c_pad,), bool)
    valid[:cand.size] = True
    remap = idx.copy()
    return CandidateSet(int(cand.size), remap, idx=idx, valid=valid)
