"""Compile-ahead subsystem: bucketed AOT warmup + persistent XLA cache.

The cold-session killer (BENCH_r05: 12.6 s of a 13.6 s cold session is
the solver family's first-call XLA compile) is structural: every solver
entry point is a bare ``jax.jit``, so any new (shape-bucket, cfg)
signature pays a multi-second compile *inside a live scheduling
session*.  This module keeps that compile out of the session loop the
same way the reference keeps one-time setup out of its per-session path
(scheduler.go:88) and production JAX serving stacks solve cold start —
ahead-of-time lowering plus persisted executables:

1. **Bucket ladder** (``bucket`` / ``bucket_shapes``): the geometric
   padded-shape ladder every tensorized axis rounds up to (tasks, nodes,
   jobs, queues — models/tensor_snapshot.py pads with it at tensorize
   time), so session-to-session shape drift lands on ONE executable
   instead of recompiling.  Lives here because the ladder *is* the
   compile-cache key space; tensor_snapshot re-exports it.
2. **Startup warmup** (``SolverWarmup`` / ``warm_bucket``): at server
   boot (cli/server.py ``--warmup-buckets``), pre-build zero-valued
   inputs at the configured buckets, ship them through the real packed
   transfer (warming shipping's per-layout unpack program too), and
   execute every applicable member of the solver family —
   two-level XLA, stepwise oracle, Pallas on TPU, node-sharded on a
   mesh — in a background thread.  Executing the jitted entry point
   (rather than only ``.lower().compile()``) both populates the
   in-process jit cache the live path actually hits and writes the
   persistent cache; the run itself is ~free because warmup inputs have
   no active queues, so the solve loop exits after the first predicate.
3. **Persistence** (``enable_persistent_cache``): JAX's persistent
   compilation cache (``--compile-cache-dir``), thresholds dropped to
   zero so every solver executable is written; compiles then survive
   process restarts and leader failover.  A version/cfg-keyed manifest
   records what was warmed so the next boot (and bench.py) can
   attribute cold-vs-warm.
4. **Observability**: every routed solve is keyed (``solve_key``) and
   counted as a compile-cache hit or miss (metrics.py
   ``compile_cache_{hits,misses}_total``), warmup exposes an inflight
   gauge, and tensorize reports per-axis bucket pad waste.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Iterable, List, NamedTuple, Optional, Sequence

# NOTE: no jax / numpy / models imports at module level — this module is
# imported from the solver chokepoint and from tensor_snapshot, and must
# stay cycle-free and cheap to import.


# ---------------------------------------------------------------------------
# 1. Bucket ladder
# ---------------------------------------------------------------------------

def bucket(n: int, minimum: int = 8) -> int:
    """Next padded-shape bucket (compilation-cache friendly).

    Powers of two up to 1024; quarter steps within each octave above
    (1.0/1.25/1.5/1.75 x 2^k).  Worst-case padding drops from 2x to
    1.25x — at kubemark scale that is 37% less node-major device state
    (10000 -> 10240 instead of 16384) — while the compile-shape count
    stays bounded (four shapes per octave).  Every bucket above 1024 is
    a multiple of 256, keeping TPU lane alignment and mesh-shard
    divisibility (N % n_devices == 0) intact."""
    b = minimum
    while b < n:
        b *= 2
    if b <= 1024:
        return b
    half = b // 2
    for frac in (1.25, 1.5, 1.75):
        cand = int(half * frac)
        if n <= cand:
            return cand
    return b


class BucketSpec(NamedTuple):
    """Requested (unbucketed) axis sizes of one warmup target."""
    tasks: int
    nodes: int
    jobs: int
    queues: int

    def padded(self) -> "BucketSpec":
        return BucketSpec(bucket(max(self.tasks, 1)),
                          bucket(max(self.nodes, 1)),
                          bucket(max(self.jobs, 1)),
                          bucket(max(self.queues, 1)))


def bucket_shapes(tasks: int, nodes: int, jobs: int,
                  queues: int) -> BucketSpec:
    """The padded bucket every tensorized session of these sizes lands on."""
    return BucketSpec(tasks, nodes, jobs, queues).padded()


def parse_warmup_buckets(spec: str) -> List[BucketSpec]:
    """Parse the ``--warmup-buckets`` flag: comma/semicolon-separated
    ``TASKSxNODES[xJOBS[xQUEUES]]`` entries (e.g. ``50000x10000x2000x4``).
    Omitted jobs default to tasks/25 (the bench-scale task:job ratio);
    omitted queues default to 4.  Malformed entries raise ValueError at
    config time — a bad flag must fail boot, not the first session."""
    out: List[BucketSpec] = []
    for entry in spec.replace(";", ",").split(","):
        entry = entry.strip()
        if not entry:
            continue
        parts = entry.lower().split("x")
        if len(parts) < 2 or len(parts) > 4:
            raise ValueError(
                f"warmup bucket {entry!r}: want TASKSxNODES[xJOBS[xQUEUES]]")
        try:
            nums = [int(p) for p in parts]
        except ValueError as exc:
            raise ValueError(f"warmup bucket {entry!r}: {exc}") from None
        if any(v <= 0 for v in nums):
            raise ValueError(f"warmup bucket {entry!r}: sizes must be > 0")
        tasks, nodes = nums[0], nums[1]
        jobs = nums[2] if len(nums) > 2 else max(1, tasks // 25)
        queues = nums[3] if len(nums) > 3 else 4
        out.append(BucketSpec(tasks, nodes, jobs, queues))
    return out


# ---------------------------------------------------------------------------
# 3. Persistent compilation cache + manifest
# ---------------------------------------------------------------------------

_MANIFEST_NAME = "kube_batch_tpu_warmup_manifest.json"
_cache_dir: Optional[str] = None   # guarded-by: _cache_lock
_cache_lock = threading.Lock()


def persistent_cache_dir() -> Optional[str]:
    return _cache_dir


def enable_persistent_cache(cache_dir: str) -> Optional[str]:
    """Point JAX's persistent compilation cache at ``cache_dir`` with the
    write thresholds dropped to zero (every solver executable persists,
    CPU included), so compiles survive process restarts and leader
    failover.  Returns the directory, or None when this JAX build has no
    persistent cache (the subsystem then degrades to in-process warmup
    only).  Must run before the first compile to cover it."""
    global _cache_dir
    import jax

    cache_dir = os.path.abspath(cache_dir)
    os.makedirs(cache_dir, exist_ok=True)
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    except Exception:  # lint: allow-swallow(jax builds without these config keys degrade to in-process warmup; None tells the caller)
        return None
    try:
        # JAX memoizes its cache-enabled decision at the first compile;
        # if anything compiled before this call (an eager op is enough),
        # the new dir would be silently ignored without a reset.
        from jax._src import compilation_cache as _cc
        _cc.reset_cache()
    except Exception:  # lint: allow-swallow(private-API reset is an optimization; without it only pre-enable compiles miss the cache)
        pass
    with _cache_lock:
        _cache_dir = cache_dir
    return cache_dir


def _version_key() -> dict:
    """Executable identity: a manifest entry is only trustworthy for the
    exact (jax, repo, backend) that produced it — XLA's own cache keys
    change across any of these, so a mismatched manifest is reset."""
    import jax

    from ..version import __version__
    try:
        backend = jax.default_backend()
    except Exception:  # lint: allow-swallow(backend probe at manifest-read time; "unknown" just voids manifest trust)
        backend = "unknown"
    return {"jax": jax.__version__, "kube_batch_tpu": __version__,
            "backend": backend}


def _manifest_path(cache_dir: str) -> str:
    return os.path.join(cache_dir, _MANIFEST_NAME)


def read_manifest(cache_dir: str) -> dict:
    """The warmup manifest for this version key, or an empty one (missing
    file, unreadable file, or a version mismatch all reset it)."""
    empty = {"version": _version_key(), "warmed": {}}
    try:
        with open(_manifest_path(cache_dir)) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return empty
    if not isinstance(doc, dict) or doc.get("version") != empty["version"]:
        return empty
    if not isinstance(doc.get("warmed"), dict):
        return empty
    return doc


def record_warmed(cache_dir: str, entries: dict) -> None:
    """Merge ``entries`` ({key_str: {...}}) into the manifest atomically
    (temp file + rename: concurrent standbys warming the same dir may
    lose each other's merge but can never corrupt the document)."""
    doc = read_manifest(cache_dir)
    doc["warmed"].update(entries)
    tmp = _manifest_path(cache_dir) + f".tmp.{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
        os.replace(tmp, _manifest_path(cache_dir))
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass


# ---------------------------------------------------------------------------
# 4. Hit/miss registry (the solver chokepoint reports here)
# ---------------------------------------------------------------------------

_seen_lock = threading.Lock()
_seen: set = set()  # guarded-by: _seen_lock

#: Flat per-solve-key estimate (a ~10-slot tuple of ints/strs + the set
#: slot).  Hook and auditor share it, so audit_mem_ledgers checks hook
#: coverage, not estimate quality (doc/OBSERVABILITY.md "Memory ledger").
_KEY_EST = 160


def _seen_actual_nbytes(seen: set) -> int:
    with _seen_lock:
        return len(seen) * _KEY_EST


def _track_seen():
    from ..metrics import memledger
    with _seen_lock:  # registration keys off the set's identity
        return memledger.ledger("compile_cache").track(
            _seen, sizer=_seen_actual_nbytes)


_mem_seen = _track_seen()


def _mem_seen_add(n: int) -> None:
    from ..metrics import memledger
    memledger.ledger("compile_cache").add(_mem_seen, n)


def solve_key(choice: str, inp, cfg) -> tuple:
    """In-process identity of one compiled solver executable: routing
    choice + every jit-cache-relevant degree of freedom — the padded
    axis shapes (P/N/J/Q/R and the port/selector/signature pads), the
    float key dtype, and the static cfg.  Two solves with equal keys hit
    one executable; a new key is a fresh XLA compile."""
    return (choice,
            tuple(inp.task_req.shape),      # (P, R)
            tuple(inp.node_idle.shape),     # (N, R)
            inp.job_start.shape[0],         # J
            inp.queue_deserved.shape[0],    # Q
            inp.task_ports.shape[1],        # NP pad
            inp.task_aff_req.shape[1],      # NS pad
            inp.sig_mask.shape[0],          # S
            str(inp.job_ts.dtype),          # float key dtype (x64 or not)
            cfg)


def note_solve(choice: str, inp, cfg) -> bool:
    """Record one routed solve; returns True on a compile-cache hit (the
    signature was warmed or already solved in-process).  O(1): a tuple
    of ints + one set probe per session."""
    from ..metrics import metrics

    key = solve_key(choice, inp, cfg)
    with _seen_lock:
        hit = key in _seen
        _seen.add(key)
    if not hit:
        _mem_seen_add(_KEY_EST)
    metrics.note_compile_cache(hit)
    return hit


def note_solve_key(key: tuple) -> bool:
    """note_solve for callers that build their own executable identity
    (the batched eviction dispatch, ops/evict_solver.evict_solve_key):
    same seen-set, same hit/miss counters."""
    from ..metrics import metrics

    with _seen_lock:
        hit = key in _seen
        _seen.add(key)
    if not hit:
        _mem_seen_add(_KEY_EST)
    metrics.note_compile_cache(hit)
    return hit


def note_warmed(key: tuple) -> None:
    """Mark a signature as compiled (warmup path) WITHOUT counting it as
    a live hit or miss — warmup is setup, not traffic."""
    with _seen_lock:
        added = key not in _seen
        _seen.add(key)
    if added:
        _mem_seen_add(_KEY_EST)


def reset_seen() -> None:
    """Test hook: forget every in-process signature."""
    from ..metrics import memledger
    with _seen_lock:
        _seen.clear()
    memledger.ledger("compile_cache").set(_mem_seen, 0)


# ---------------------------------------------------------------------------
# 2. Warmup inputs + the warmup run
# ---------------------------------------------------------------------------

def make_bucket_inputs(spec: BucketSpec, r: int = 2, np_pad: int = 8,
                       ns_pad: int = 8, n_sigs: int = 1):
    """Zero-valued, numpy-staged SolverInputs at ``spec``'s padded bucket,
    leaf-for-leaf aval-identical (shape AND dtype) to what tensorize_session
    emits for a featureless session of those sizes — so the executable
    compiled here is the one live sessions of this bucket reuse.  All
    queues are non-existent, so executing the solve is O(1): the loop
    predicate fails on the first check."""
    import numpy as np
    import jax.numpy as jnp

    from .resources import EPS_QUANTA
    from .solver import SolverInputs

    p, n, j, q = spec.padded()
    r = max(r, 2)
    np_dtype = (np.float64 if jnp.asarray(np.float64(1.0)).dtype
                == jnp.float64 else np.float32)

    def f(*shape):
        return np.zeros(shape, np_dtype)

    def i(*shape):
        return np.zeros(shape, np.int32)

    def b(*shape):
        return np.zeros(shape, bool)

    return SolverInputs(
        task_req=i(p, r), task_res=i(p, r), task_sig=i(p),
        task_sorted=np.arange(p, dtype=np.int32),
        task_ports=b(p, np_pad), task_aff_req=b(p, ns_pad),
        task_anti=b(p, ns_pad), task_match=b(p, ns_pad),
        task_paff_w=i(p, ns_pad), task_panti_w=i(p, ns_pad),
        job_start=i(j), job_count=i(j), job_queue=i(j),
        job_minavail=np.full((j,), -1, np.int32),
        job_prio=f(j), job_ts=f(j), job_uid_rank=f(j),
        job_init_ready=i(j), job_init_alloc=i(j, r),
        queue_deserved=i(q, r), queue_deserved_f=f(q, r),
        queue_init_alloc=i(q, r), queue_ts=f(q), queue_uid_rank=f(q),
        queue_exists=b(q),
        node_idle=i(n, r), node_releasing=i(n, r), node_used=i(n, r),
        node_alloc=i(n, r), node_count=i(n), node_max_tasks=i(n),
        node_exists=b(n), node_ports=b(n, np_pad),
        node_selcnt=i(n, ns_pad),
        sig_mask=b(max(n_sigs, 1), n), sig_bonus=i(max(n_sigs, 1), n),
        total_res=f(r),
        eps=np.full((r,), EPS_QUANTA, dtype=np.int32),
        scalar_dims=np.asarray([False, False] + [True] * (r - 2)),
        score_shift=i(2),
        node_coords=np.full((n, 8), -1, np.int32))


class WarmupRecord(NamedTuple):
    spec: BucketSpec
    solver: str
    key: tuple
    compile_ms: float
    error: Optional[str] = None


def _resolve_family(family: Sequence[str], inp) -> List[str]:
    """Expand ``family`` names to the concrete solvers to warm for this
    bucket.  ``auto`` = whatever best_solve_allocate would route this
    shape to (exactly the executable a live session of this bucket
    needs); explicit names add the rest of the family where the backend
    supports them."""
    import jax

    from ..parallel.mesh import default_mesh
    from .solver import choose_solver_mesh

    out: List[str] = []
    for name in family:
        if name == "auto":
            out.append(choose_solver_mesh(inp)[0])
        elif name == "pallas":
            if jax.default_backend() == "tpu":
                out.append("pallas")
        elif name == "sharded":
            mesh = default_mesh()
            if mesh is not None and inp.node_idle.shape[0] % mesh.size == 0:
                out.append("sharded")
        elif name in ("xla", "two-level", "stepwise"):
            out.append("xla" if name == "two-level" else name)
        else:
            raise ValueError(f"unknown warmup solver {name!r}")
    deduped: List[str] = []
    for name in out:
        if name not in deduped:
            deduped.append(name)
    return deduped


def warm_bucket(spec: BucketSpec, cfg=None, family: Sequence[str] = ("auto",),
                r: int = 2) -> List[WarmupRecord]:
    """Compile (and persist, when the cache dir is enabled) the solver
    family for one bucket by executing each member on zero-valued inputs
    shipped through the real packed-transfer path — which also warms
    shipping's per-layout unpack program.  Returns one record per
    solver; a member's failure is recorded, not raised (warmup must
    never take down boot)."""
    from ..models.shipping import ship_inputs
    from .solver import fetch_result, solve_allocate, solve_allocate_stepwise

    if cfg is None:
        from .solver import SolverConfig
        cfg = SolverConfig()
    inp_np = make_bucket_inputs(spec, r=r)
    names = _resolve_family(family, inp_np)
    records: List[WarmupRecord] = []
    inp = ship_inputs(inp_np)
    resident = None
    if "sharded" in names:
        # Live sessions reach the sharded solve through the shipper's
        # MESH-RESIDENT layout, and input shardings are part of the jit
        # cache key — warming on single-device leaves would compile an
        # executable the live path never hits.  Ship through a throwaway
        # resident shipper (compiling the sharded pack/unpack programs
        # too), then delta-ship one dirtied row so the per-shard donated
        # scatter is compiled ahead as well (doc/SHARDING.md).
        from ..models.shipping import DeviceResidentShipper
        try:
            warm_shipper = DeviceResidentShipper()
            warm_shipper.ship(inp_np, cfg)
            dirty = inp_np._replace(node_count=inp_np.node_count.copy())
            dirty.node_count[0] += 1
            warm_shipper.ship(dirty, cfg)
            resident = warm_shipper.ship(inp_np, cfg)
        except Exception:  # lint: allow-swallow(warmup must never take down boot; the sharded member below records its own failure)
            resident = None
    for name in names:
        key = solve_key(name, inp_np, cfg)
        start = time.perf_counter()
        try:
            if name == "xla":
                result = solve_allocate(inp, cfg)
            elif name == "stepwise":
                result = solve_allocate_stepwise(inp, cfg)
            elif name == "pallas":
                from .pallas_solver import solve_allocate_pallas
                result = solve_allocate_pallas(inp, cfg)
            elif name == "sharded":
                from ..parallel.mesh import default_mesh
                from ..parallel.sharded_solver import solve_allocate_sharded
                result = solve_allocate_sharded(
                    inp if resident is None else resident, cfg,
                    default_mesh())
            else:  # pragma: no cover - _resolve_family guards
                raise ValueError(name)
            fetch_result(result)  # forces completion + warms the pack jit
        except Exception as exc:  # lint: allow-swallow(warmup must never take down boot; failure is recorded in WarmupRecord.error)
            records.append(WarmupRecord(
                spec, name, key,
                round((time.perf_counter() - start) * 1e3, 1),
                f"{type(exc).__name__}: {exc}"))
            continue
        note_warmed(key)
        records.append(WarmupRecord(
            spec, name, key,
            round((time.perf_counter() - start) * 1e3, 1)))
    records.append(_warm_evict_batch(spec, cfg, inp_np, inp,
                                     resident=resident))
    records.append(_warm_candidate(spec, cfg, inp, resident=resident))
    from ..models.topology import topology_enabled
    if topology_enabled():
        records.append(_warm_topo(spec))
    from .fused_solver import fused_enabled
    if fused_enabled():
        records.append(_warm_fused(spec, cfg, inp_np, inp,
                                   resident=resident))
    return records


def _warm_fused(spec: BucketSpec, cfg, inp_np, inp,
                resident=None) -> WarmupRecord:
    """Warm the fused one-dispatch session program (ops/fused_solver.py)
    at this bucket: the allocate solve plus the batched eviction scan
    (plus the storm half's post-eviction adjustment when FUSED_STORM is
    on, plus the topo box scan when topology is enabled) composed
    inside ONE jit is a DIFFERENT executable from its warmed members,
    so the first fused session would otherwise pay the composition's
    XLA compile live.  Routed as the live dispatch would be:
    mesh-sharded legs when the warm shipper produced a resident image,
    the pinned single-chip route otherwise.  Other leg subsets compile
    on first use (each is strictly smaller than this one)."""
    import numpy as np
    import jax.numpy as jnp

    from .. import knobs
    from ..models.topology import topology_enabled
    from .evict_solver import choose_evict_route
    from .fused_solver import _fused_program, fused_solve_key
    from .scan import ScanStatics
    from .solver import choose_solver_mesh

    r = inp_np.task_req.shape[1]
    np_pad = inp_np.task_ports.shape[1]
    ns_pad = inp_np.task_aff_req.shape[1]
    n_pad = inp_np.node_idle.shape[0]
    kb = bucket(1)
    mb = bucket(max(spec.tasks, 1))
    legs = ["evict", "solve"]
    if knobs.FUSED_STORM.enabled():
        # The eviction-heavy storm variant (doc/FUSED.md "Storm half")
        # is the executable a reclaim ladder dispatches.
        legs.append("postevict")
    if topology_enabled():
        legs.append("topo")
    legs = tuple(legs)
    eroute, emesh = choose_evict_route(resident)
    if resident is not None:
        from ..parallel.mesh import default_mesh
        aroute, amesh = "sharded", default_mesh()
    else:
        aroute, amesh = choose_solver_mesh(inp_np)
        if aroute == "sharded":
            aroute, amesh = "xla", None
    sx, sy, sz = (2, 2, 2) if "topo" in legs else (0, 0, 0)
    key = fused_solve_key(legs, aroute, False, 0, (n_pad, cfg), eroute,
                          (cfg, r, np_pad, ns_pad, kb, mb), "xla",
                          (sx, sy, sz))
    start = time.perf_counter()
    try:
        src = resident if resident is not None else inp
        statics = ScanStatics(
            sig_mask=jnp.asarray(src.sig_mask),
            sig_bonus=jnp.asarray(src.sig_bonus),
            node_alloc=jnp.asarray(src.node_alloc),
            node_max_tasks=jnp.asarray(src.node_max_tasks),
            node_exists=jnp.asarray(src.node_exists),
            score_shift=jnp.asarray(src.score_shift))
        trows = np.zeros((kb, 1 + r + np_pad + 4 * ns_pad), np.int32)
        vic_node = np.full((mb,), n_pad, np.int32)
        vic_rank = np.full((mb,), mb, np.int32)
        pe_res = pe_queue = pe_job = None
        if "postevict" in legs:
            # All-sentinel staging (no victims): the adjustment traces
            # through the same scatter/solve graph as a live storm.
            qb = int(np.asarray(src.queue_exists).shape[0])
            jb = int(np.asarray(src.job_start).shape[0])
            pe_res = np.zeros((mb, r), np.int32)
            pe_queue = np.full((mb,), qb, np.int32)
            pe_job = np.full((mb,), jb, np.int32)
        if eroute == "sharded":
            import jax
            from jax.sharding import NamedSharding, PartitionSpec as P
            rep = NamedSharding(emesh, P())
            trows_d = jax.device_put(trows, rep)
            node_d = jax.device_put(vic_node, rep)
            rank_d = jax.device_put(vic_rank, rep)
            edyn = None
            if pe_res is not None:
                pe_res = jax.device_put(pe_res, rep)
                pe_queue = jax.device_put(pe_queue, rep)
                pe_job = jax.device_put(pe_job, rep)
        else:
            trows_d = jnp.asarray(trows)
            node_d = jnp.asarray(vic_node)
            rank_d = jnp.asarray(vic_rank)
            edyn = jnp.asarray(np.concatenate(
                [np.asarray(inp_np.node_used),
                 np.asarray(inp_np.node_count)[:, None],
                 np.asarray(inp_np.node_ports).astype(np.int32),
                 np.asarray(inp_np.node_selcnt)],
                axis=1).astype(np.int32))
            if pe_res is not None:
                pe_res = jnp.asarray(pe_res)
                pe_queue = jnp.asarray(pe_queue)
                pe_job = jnp.asarray(pe_job)
        box = None
        troute, tmesh = "xla", None
        if "topo" in legs:
            from . import topo_solver as ts
            box = ts.BoxInputs(
                coords=jnp.asarray(np.full((n_pad, 8), -1, np.int32)),
                free=jnp.zeros((n_pad,), bool),
                evictable=jnp.zeros((n_pad,), bool),
                vic_cnt=jnp.zeros((n_pad,), jnp.int32),
                vic_cost=jnp.zeros((n_pad,), jnp.int32))
        out = _fused_program(
            legs, cfg, aroute, False, amesh, cfg, r, np_pad, ns_pad,
            eroute, emesh, sx, sy, sz, troute, tmesh,
            src, None, None, statics, edyn, trows_d, node_d, rank_d, box,
            pe_res, pe_queue, pe_job)
        np.asarray(out["alloc"])
        np.asarray(out["evict"][0])
        if "postevict" in legs:
            np.asarray(out["postevict"][0])
        if "topo" in legs:
            np.asarray(out["topo"])
    except Exception as exc:  # lint: allow-swallow(warmup must never take down boot; failure is recorded in WarmupRecord.error)
        return WarmupRecord(
            spec, "fused", key,
            round((time.perf_counter() - start) * 1e3, 1),
            f"{type(exc).__name__}: {exc}")
    note_warmed(key)
    return WarmupRecord(
        spec, "fused", key,
        round((time.perf_counter() - start) * 1e3, 1))


def _warm_topo(spec: BucketSpec) -> WarmupRecord:
    """Warm the batched slice box scan (ops/topo_solver.py) at this
    node bucket for the documented default slice shape, through the
    same dispatch chokepoint the live topo-allocate action uses — so
    the first slice session never pays its XLA compile live.  Other
    shapes compile on first use (the scan is small).  Skipped entirely
    when KUBE_BATCH_TPU_TOPOLOGY=0 (warm_bucket gates the append):
    flat deployments pay nothing for a kernel they can never
    dispatch."""
    import numpy as np

    from ..ops import topo_solver as ts

    # The default slice shape every in-repo gate exercises (bench-topo,
    # the frag_pressure scenario, tests/test_topology.py).
    shape = (2, 2, 2)
    n_pad = bucket(max(spec.nodes, 1))
    route, _mesh = ts.choose_topo_route(n_pad)
    key = ts.topo_solve_key(route, n_pad, shape)
    start = time.perf_counter()
    try:
        inp = ts.BoxInputs(
            coords=np.full((n_pad, 8), -1, np.int32),
            free=np.zeros((n_pad,), bool),
            evictable=np.zeros((n_pad,), bool),
            vic_cnt=np.zeros((n_pad,), np.int32),
            vic_cost=np.zeros((n_pad,), np.int32))
        ts.dispatch_box_scan(inp, shape)
    except Exception as exc:  # lint: allow-swallow(warmup must never take down boot; failure is recorded in WarmupRecord.error)
        return WarmupRecord(
            spec, "topo_box", key,
            round((time.perf_counter() - start) * 1e3, 1),
            f"{type(exc).__name__}: {exc}")
    note_warmed(key)
    return WarmupRecord(
        spec, "topo_box", key,
        round((time.perf_counter() - start) * 1e3, 1))


def _warm_candidate(spec: BucketSpec, cfg, inp,
                    resident=None) -> WarmupRecord:
    """Warm the candidate-row gather+solve (ops/prefilter.py) at the
    smallest candidate bucket — where micro churn cycles land — so the
    first prefiltered session never pays its XLA compile live.  When the
    warm shipper produced a mesh-resident image, the PER-SHARD gather and
    the sharded solve at the candidate bucket are warmed through the same
    entry points the live dispatch uses (doc/SHARDING.md)."""
    import numpy as np
    import jax.numpy as jnp

    from .solver import (_gather_candidate_inputs, fetch_result,
                         solve_allocate)

    cb = bucket(1)
    key: tuple = ("candidate", spec, cb)
    start = time.perf_counter()
    try:
        if resident is not None:
            from ..parallel.mesh import default_mesh
            from ..parallel.sharded_solver import (gather_candidate_sharded,
                                                   solve_allocate_sharded)
            mesh = default_mesh()
            local = np.zeros((mesh.size, cb), np.int32)
            valid = np.zeros((mesh.size, cb), bool)
            sub = gather_candidate_sharded(resident, jnp.asarray(local),
                                           jnp.asarray(valid), mesh)
            key = solve_key("sharded", sub, cfg)
            result = solve_allocate_sharded(sub, cfg, mesh)
        else:
            idx = np.zeros((cb,), np.int32)
            valid = np.zeros((cb,), bool)
            sub = _gather_candidate_inputs(inp, jnp.asarray(idx),
                                           jnp.asarray(valid))
            key = solve_key("xla", sub, cfg)
            result = solve_allocate(sub, cfg)
        fetch_result(result)
    except Exception as exc:  # lint: allow-swallow(warmup must never take down boot; failure is recorded in WarmupRecord.error)
        return WarmupRecord(
            spec, "candidate", key,
            round((time.perf_counter() - start) * 1e3, 1),
            f"{type(exc).__name__}: {exc}")
    note_warmed(key)
    return WarmupRecord(
        spec, "candidate", key,
        round((time.perf_counter() - start) * 1e3, 1))


def _warm_evict_batch(spec: BucketSpec, cfg, inp_np, inp,
                      resident=None) -> WarmupRecord:
    """Warm the batched eviction kernel (ops/evict_solver.py) at this
    bucket: the storm path's single dispatch should never pay its XLA
    compile inside a live session either.  Warmed at the smallest
    profile bucket (storms interleave a handful of preemptor profiles)
    and the node/victim buckets this spec implies.  When ``resident``
    (the warm shipper's mesh-sharded SolverInputs) is present, the
    MESH-ROUTED engine is warmed through the same dispatch chokepoint
    the live scanner uses, so the first sharded evict solve is never a
    live compile (doc/SHARDING.md)."""
    import numpy as np
    import jax.numpy as jnp

    from .evict_solver import (choose_evict_route, evict_batch_solve,
                               evict_solve_key)
    from .scan import ScanStatics

    r = inp_np.task_req.shape[1]
    np_pad = inp_np.task_ports.shape[1]
    ns_pad = inp_np.task_aff_req.shape[1]
    n_pad = inp_np.node_idle.shape[0]
    kb = bucket(1)
    mb = bucket(max(spec.tasks, 1))
    route, _mesh = choose_evict_route(resident)
    key = evict_solve_key(cfg, r, np_pad, ns_pad, n_pad, kb, mb,
                          int(inp_np.sig_mask.shape[0]), route=route)
    start = time.perf_counter()
    try:
        src = resident if resident is not None else inp
        statics = ScanStatics(
            sig_mask=jnp.asarray(src.sig_mask),
            sig_bonus=jnp.asarray(src.sig_bonus),
            node_alloc=jnp.asarray(src.node_alloc),
            node_max_tasks=jnp.asarray(src.node_max_tasks),
            node_exists=jnp.asarray(src.node_exists),
            score_shift=jnp.asarray(src.score_shift))
        trows = np.zeros((kb, 1 + r + np_pad + 4 * ns_pad), np.int32)
        vic_node = np.full((mb,), n_pad, np.int32)
        vic_rank = np.full((mb,), mb, np.int32)
        if route == "sharded":
            # Direct call (not the dispatch chokepoint): warmup is
            # setup, not traffic — it must not count routes, feed the
            # breaker, or hit a chaos site.
            import jax
            from jax.sharding import NamedSharding, PartitionSpec as P

            from ..parallel.sharded_scan import evict_batch_solve_sharded
            rep = NamedSharding(_mesh, P())
            scores, perm = evict_batch_solve_sharded(
                cfg, r, np_pad, ns_pad, statics, resident.node_used,
                resident.node_count, resident.node_ports,
                resident.node_selcnt, jax.device_put(trows, rep),
                jax.device_put(vic_node, rep),
                jax.device_put(vic_rank, rep), _mesh)
        else:
            dyn = np.concatenate(
                [np.asarray(inp_np.node_used),
                 np.asarray(inp_np.node_count)[:, None],
                 np.asarray(inp_np.node_ports).astype(np.int32),
                 np.asarray(inp_np.node_selcnt)], axis=1).astype(np.int32)
            scores, perm = evict_batch_solve(
                cfg, r, np_pad, ns_pad, statics, jnp.asarray(dyn),
                jnp.asarray(trows), jnp.asarray(vic_node),
                jnp.asarray(vic_rank))
        np.asarray(scores)
        np.asarray(perm)
    except Exception as exc:  # lint: allow-swallow(warmup must never take down boot; failure is recorded in WarmupRecord.error)
        return WarmupRecord(
            spec, "evict_batch", key,
            round((time.perf_counter() - start) * 1e3, 1),
            f"{type(exc).__name__}: {exc}")
    note_warmed(key)
    return WarmupRecord(
        spec, "evict_batch", key,
        round((time.perf_counter() - start) * 1e3, 1))


class SolverWarmup:
    """Background startup warmup: compile the solver family for each
    configured bucket off the scheduler thread, so the first live
    session of a warmed bucket never waits on XLA.

    ``start`` is idempotent (one thread per instance, ever), ``stop``
    signals between buckets — an XLA compile in flight cannot be
    interrupted, so the thread is a daemon and stop() bounds its own
    wait instead of the process exit."""

    def __init__(self, buckets: Iterable[BucketSpec], cfg=None,
                 family: Sequence[str] = ("auto",),
                 cache_dir: Optional[str] = None):
        self.buckets = list(buckets)
        self._cfg = cfg
        self._family = tuple(family)
        self._cache_dir = cache_dir
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None  # guarded-by: _lock
        self.records: List[WarmupRecord] = []
        self.errors: List[str] = []

    def start(self) -> "SolverWarmup":
        with self._lock:
            if self._thread is not None:
                return self
            self._thread = threading.Thread(
                target=self._run, name="solver-warmup", daemon=True)
            self._thread.start()
        return self

    def _run(self) -> None:
        from ..metrics import metrics

        manifest: dict = {}
        try:
            for k, spec in enumerate(self.buckets):
                if self._stop.is_set():
                    break
                metrics.set_compile_inflight(len(self.buckets) - k)
                try:
                    records = warm_bucket(spec, cfg=self._cfg,
                                          family=self._family)
                except Exception as exc:  # noqa: BLE001 - never kill boot
                    self.errors.append(f"{spec}: {type(exc).__name__}: {exc}")
                    continue
                self.records.extend(records)
                for rec in records:
                    if rec.error:
                        self.errors.append(
                            f"{rec.spec}/{rec.solver}: {rec.error}")
                    else:
                        manifest[repr(rec.key)] = {
                            "spec": list(rec.spec),
                            "solver": rec.solver,
                            "compile_ms": rec.compile_ms,
                        }
        finally:
            metrics.set_compile_inflight(0)
            if self._cache_dir and manifest:
                record_warmed(self._cache_dir, manifest)

    def join(self, timeout: Optional[float] = None) -> None:
        t = self._thread
        if t is not None:
            t.join(timeout)

    def stop(self, timeout: float = 0.0) -> None:
        self._stop.set()
        self.join(timeout)

    @property
    def done(self) -> bool:
        t = self._thread
        return t is not None and not t.is_alive()
