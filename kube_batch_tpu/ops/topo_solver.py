"""Batched slice-shape feasibility: every candidate origin's contiguous-
block question answered in ONE device dispatch.

A PodGroup requesting a slice shape ``(sx, sy, sz)`` needs an
axis-aligned sub-box of the torus — ``prod(shape)`` nodes at coordinates
``origin + [0..sx) x [0..sy) x [0..sz)`` (mod the pod's torus dims) —
that are all placeable.  The host formulation walks N origins x vol box
offsets; this module vectorizes the whole question as a pairwise
membership scan over the int32 coordinate rows (models/topology.py's
``node_coords`` leaf layout): one jitted program returns, per origin,

  * ``complete``       — the box has all prod(shape) member nodes
                         (wrapped self-overlap can never fake this: a
                         torus axis shorter than the request covers
                         fewer distinct positions, so the count falls
                         short — doc/TOPOLOGY.md),
  * ``free_cnt``       — members currently free,
  * ``blocked``        — members neither free nor evictable (a box with
                         blocked > 0 can never become this slice),
  * ``vic_cnt`` / ``vic_cost`` — the defrag evictor's cost row: how many
                         victims (and their priority sum) clearing the
                         box would evict,
  * ``boundary_free``  — free nodes OUTSIDE the box torus-adjacent to
                         it: the fragmentation-aware placement key
                         (fewer free neighbors = tighter packing =
                         larger contiguous blocks preserved elsewhere).

``box_scan_seq`` is the pure-numpy per-origin sequential oracle — a
structurally different implementation computing the same exact integers
(pinned by tests/test_topology.py); ``KUBE_BATCH_TPU_TOPO_BATCH=0``
routes every live scan through it.  ``dispatch_box_scan`` is the routing
chokepoint: compile-cache keyed (``topo_solve_key`` + ``note_solve_key``,
warmed by compile_cache.warm_bucket), counted in
``kube_batch_solver_route_total{family="topo"}``, and sharded over the
origin axis of the device mesh under the same startup-pinned gates the
allocate/evict engines use (ops/solver.shard_knobs).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

TOPO_SOLVE_CHOICE = "topo_box"

# Stats column layout (shared by the batched kernel and the oracle).
COL_COMPLETE = 0
COL_FREE = 1
COL_BLOCKED = 2
COL_VCNT = 3
COL_VCOST = 4
COL_BOUNDARY = 5
N_COLS = 6


class BoxInputs(NamedTuple):
    """One scan's staged arrays ([N] over the padded node bucket)."""
    coords: jnp.ndarray     # [N, 8] i32 (models/topology.COORD_WIDTH)
    free: jnp.ndarray       # [N] bool: placeable now (empty + fits + preds)
    evictable: jnp.ndarray  # [N] bool: clearable for this preemptor
    vic_cnt: jnp.ndarray    # [N] i32 victims resident on the node
    vic_cost: jnp.ndarray   # [N] i32 victim priority sum on the node


def _box_body(coords, free, evictable, vic_cnt, vic_cost, origins,
              sx: int, sy: int, sz: int):
    """The box scan over an ``origins`` row block ([L, 8] — the whole
    bucket single-chip, one shard's rows on the mesh).  All int32
    elementwise/matmul math; every term is exact."""
    valid = coords[:, 0] >= 0
    o_valid = origins[:, 0] >= 0
    pod = coords[:, 0]
    xyz = coords[:, 2:5]
    dims = jnp.maximum(coords[:, 5:8], 1)

    o_pod = origins[:, 0]
    o_xyz = origins[:, 2:5]
    o_dims = jnp.maximum(origins[:, 5:8], 1)

    # Pairwise torus offsets of every node j relative to every origin o,
    # modulo the ORIGIN's pod dims (same pod => same dims).
    d = jnp.mod(xyz[None, :, :] - o_xyz[:, None, :], o_dims[:, None, :])
    member = (o_valid[:, None] & valid[None, :]
              & (pod[None, :] == o_pod[:, None])
              & (d[:, :, 0] < sx) & (d[:, :, 1] < sy) & (d[:, :, 2] < sz))
    m32 = member.astype(jnp.int32)

    vol = sx * sy * sz
    cnt = m32.sum(axis=1)
    complete = (o_valid & (cnt == vol)).astype(jnp.int32)
    free32 = free.astype(jnp.int32)
    free_cnt = (m32 * free32[None, :]).sum(axis=1)
    blocked = (m32 * (~free & ~evictable & valid)[None, :]
               .astype(jnp.int32)).sum(axis=1)
    vcnt = (m32 * vic_cnt[None, :]).sum(axis=1)
    vcost = (m32 * vic_cost[None, :]).sum(axis=1)

    # Torus adjacency of every (j, k) node pair: same pod, exactly one
    # axis one step apart (mod dims), the rest equal.
    dd = jnp.mod(xyz[None, :, :] - xyz[:, None, :], dims[:, None, :])
    step = ((dd == 1) | (dd == (dims[:, None, :] - 1))) \
        & (dims[:, None, :] > 1)
    same = dd == 0
    one_step = ((step[:, :, 0] & same[:, :, 1] & same[:, :, 2])
                | (same[:, :, 0] & step[:, :, 1] & same[:, :, 2])
                | (same[:, :, 0] & same[:, :, 1] & step[:, :, 2]))
    adj = (valid[:, None] & valid[None, :]
           & (pod[:, None] == pod[None, :]) & one_step
           & ~(same[:, :, 0] & same[:, :, 1] & same[:, :, 2]))
    touch = (m32 @ adj.astype(jnp.int32)) > 0
    boundary_free = (touch & ~member & free[None, :]) \
        .astype(jnp.int32).sum(axis=1)

    return jnp.stack([complete, free_cnt, blocked, vcnt, vcost,
                      boundary_free], axis=1)


@functools.partial(jax.jit, static_argnames=("sx", "sy", "sz"))
def box_scan(inp: BoxInputs, sx: int, sy: int, sz: int) -> jnp.ndarray:
    """[N, 6] i32 per-origin stats; every node row is a candidate
    origin."""
    return _box_body(inp.coords, inp.free, inp.evictable, inp.vic_cnt,
                     inp.vic_cost, inp.coords, sx, sy, sz)


@functools.partial(jax.jit, static_argnames=("sx", "sy", "sz", "mesh"))
def box_scan_sharded(inp: BoxInputs, sx: int, sy: int, sz: int,
                     mesh) -> jnp.ndarray:
    """Origin-axis sharded scan: each device answers its own origin rows
    against the replicated coordinate table — no cross-device traffic,
    rows identical to the single-chip program."""
    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import NODE_AXIS, shard_map_kwargs

    def local(origins, coords, free, evictable, vic_cnt, vic_cost):
        return _box_body(coords, free, evictable, vic_cnt, vic_cost,
                         origins, sx, sy, sz)

    fn = shard_map(
        local, mesh=mesh,
        in_specs=(P(NODE_AXIS, None), P(None, None), P(None), P(None),
                  P(None), P(None)),
        out_specs=P(NODE_AXIS, None), **shard_map_kwargs())
    return fn(inp.coords, inp.coords, inp.free, inp.evictable,
              inp.vic_cnt, inp.vic_cost)


def box_scan_seq(view, free, evictable, vic_cnt, vic_cost,
                 shape) -> np.ndarray:
    """The sequential oracle: per-origin Python walk over box offsets
    through the view's coordinate index — the reference formulation the
    batched kernel must match bit-for-bit.  [N, 6] i32 over the view's
    (unpadded) node rows."""
    sx, sy, sz = shape
    vol = sx * sy * sz
    n = len(view.node_names)
    out = np.zeros((n, N_COLS), np.int32)
    nbrs = view.neighbors()
    for o in range(n):
        if not view.valid[o]:
            continue
        pod, _r, x, y, z, dx, dy, dz = (int(v) for v in view.coords[o])
        members = []
        for ox in range(sx):
            for oy in range(sy):
                for oz in range(sz):
                    j = view._index.get(
                        (pod, (x + ox) % dx, (y + oy) % dy, (z + oz) % dz))
                    if j is not None:
                        members.append(j)
        members = set(members)
        cnt = len(members)
        out[o, COL_COMPLETE] = 1 if cnt == vol else 0
        boundary = set()
        for j in members:
            if free[j]:
                out[o, COL_FREE] += 1
            elif not evictable[j]:
                out[o, COL_BLOCKED] += 1
            out[o, COL_VCNT] += int(vic_cnt[j])
            out[o, COL_VCOST] += int(vic_cost[j])
            for k in nbrs[j]:
                if k not in members and free[k]:
                    boundary.add(k)
        out[o, COL_BOUNDARY] = len(boundary)
    return out


def choose_topo_route(n_pad: int):
    """('sharded'|'xla', mesh): the topo scan's mesh gate — the
    allocate/evict engines' node-count gate and startup-pinned knobs
    (ops/solver.shard_knobs), so slice scans shard when the solvers
    do."""
    from ..parallel.mesh import default_mesh
    from .solver import shard_knobs
    mesh = default_mesh()
    if mesh is not None and n_pad % mesh.size == 0:
        knobs = shard_knobs()
        if knobs.force or n_pad >= knobs.nodes:
            return "sharded", mesh
    return "xla", None


def topo_solve_key(route: str, n_pad: int, shape) -> tuple:
    """Compile-cache identity of one box-scan executable (the
    evict_solve_key discipline): route + padded node bucket + the static
    slice shape."""
    return (TOPO_SOLVE_CHOICE, route, n_pad, tuple(shape))


def dispatch_box_scan(inp: BoxInputs, shape) -> np.ndarray:
    """Route and run one batched box scan, returning host [N, 6] i32.
    The one production chokepoint: route counters, compile-cache
    hit/miss accounting, and the mesh gate all live here."""
    from ..metrics import metrics
    from ..trace import spans as trace
    from .compile_cache import note_solve_key

    sx, sy, sz = (int(v) for v in shape)
    n_pad = int(np.asarray(inp.coords).shape[0])
    route, mesh = choose_topo_route(n_pad)
    metrics.note_route("topo", route)
    metrics.note_session_dispatch("topo")
    trace.annotate(route=route, mesh_devices=mesh.size if mesh else 1)
    note_solve_key(topo_solve_key(route, n_pad, (sx, sy, sz)))
    staged = BoxInputs(*(jnp.asarray(a) for a in inp))
    if route == "sharded":
        return np.asarray(box_scan_sharded(staged, sx, sy, sz, mesh))
    return np.asarray(box_scan(staged, sx, sy, sz))
