"""Batched eviction solve: every preemptor's node walk in ONE dispatch.

The paper's design says the preempt/reclaim/backfill actions "reuse the
same feasibility tensor" as tpu-allocate, but ops/scan.py only batched
the per-NODE axis: models/scanner.py still issued one device call (or one
numpy pass) per preemptor.  BENCH_r05 prices that loop: preempt is the
most expensive action at 1281.5 ms/cycle.  This module batches the
per-PREEMPTOR axis too — ``batch_scan_nodes`` vmaps the exact scan body
over a ``[K, L]`` request tensor (K distinct preemptor profiles, L the
packed trow layout ops/scan.py documents) so the whole session's
eviction feasibility + scoring lands in one ``[K, N]`` tensor from one
device dispatch, and ``evict_batch_solve`` fuses the device-side
victim-candidate ranking (per-node Running residents ordered by the
host's victim-order key, shipped as exact int32 rank columns) into the
same dispatch.

Eviction itself stays inherently sequential — each commit changes state
for the next preemptor — so the host actions consume these rows
optimistically and recompute only dirty rows (models/scanner.py's
edit-log patch path).  Bit-parity contract: ``_scan_body`` is the SAME
function the per-preemptor device scan jits, and the numpy mirror
(``DeviceNodeScanner._scores_numpy``) computes the same integers, so a
batched row equals the sequential engines exactly (pinned by
tests/test_evict_batch.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .scan import ScanStatics, _scan_body

# The batched-profile axis is bucketed like every other tensor axis so
# the kernel compiles once per (K, M) bucket pair, not once per storm
# shape; the warmup (compile_cache.warm_bucket) pre-builds the smallest
# bucket, which covers the common few-profile storm.
EVICT_SOLVE_CHOICE = "evict_batch"


def _batch_body(cfg, r: int, np_pad: int, ns_pad: int,
                statics: ScanStatics, dyn: jnp.ndarray,
                trows: jnp.ndarray) -> jnp.ndarray:
    """[K, N] i32 scores: _scan_body vmapped over the profile axis.  The
    scan math is per-node elementwise, so the vmap is a pure batching of
    identical per-row programs — row k equals scan_nodes(.., trows[k])
    bit for bit."""
    return jax.vmap(
        lambda trow: _scan_body(cfg, r, np_pad, ns_pad, statics, dyn, trow)
    )(trows)


@functools.partial(jax.jit,
                   static_argnames=("cfg", "r", "np_pad", "ns_pad"))
def batch_scan_nodes(cfg, r: int, np_pad: int, ns_pad: int,
                     statics: ScanStatics, dyn: jnp.ndarray,
                     trows: jnp.ndarray) -> jnp.ndarray:
    """One dispatch answering EVERY preemptor profile's candidate-node
    question; SCORE_NEG_INF marks predicate-rejected nodes, exactly like
    ops/scan.scan_nodes per row."""
    return _batch_body(cfg, r, np_pad, ns_pad, statics, dyn, trows)


@functools.partial(jax.jit,
                   static_argnames=("cfg", "r", "np_pad", "ns_pad"))
def evict_batch_solve(cfg, r: int, np_pad: int, ns_pad: int,
                      statics: ScanStatics, dyn: jnp.ndarray,
                      trows: jnp.ndarray, vic_node: jnp.ndarray,
                      vic_rank: jnp.ndarray):
    """The session's whole eviction pre-solve as ONE device program:

    * ``[K, N]`` feasibility+score rows for all K preemptor profiles
      (the vmapped scan), and
    * the victim-candidate permutation: ``vic_node`` ([M] i32 node row
      of each Running resident) and ``vic_rank`` ([M] i32, the resident's
      position in the host's victim-order key — reversed task order:
      priority ascending, creation-time descending, uid descending —
      staged as exact integer ranks so float-precision never reorders a
      tie) sorted to (node ascending, victim order) in one lexsort.

    Padding contract: trow padding rows are all-zero (their output rows
    are ignored); victim padding carries node = N (sorts after every
    real node) and rank = M (after every real resident).
    """
    scores = _batch_body(cfg, r, np_pad, ns_pad, statics, dyn, trows)
    perm = jnp.lexsort((vic_rank, vic_node))
    return scores, perm


def dispatch_evict_batch_solve(cfg, r: int, np_pad: int, ns_pad: int,
                               statics: ScanStatics, dyn: jnp.ndarray,
                               trows: jnp.ndarray, vic_node: jnp.ndarray,
                               vic_rank: jnp.ndarray):
    """Host-side dispatch chokepoint for the jitted batched eviction
    solve — the seam the chaos engine injects device faults into
    (doc/CHAOS.md site ``evict_solve.device_error``; the branch cannot
    live inside the jitted program).  A no-op single branch when the
    chaos engine is off.  The scanner degrades a failure here to
    per-profile host scoring and feeds the device breaker
    (models/scanner.py batch_seed)."""
    from ..chaos import plan as chaos_plan
    plan = chaos_plan.PLAN
    if plan is not None and plan.fire("evict_solve.device_error"):
        raise RuntimeError(
            "chaos: batched eviction solve failed (injected)")
    return evict_batch_solve(cfg, r, np_pad, ns_pad, statics, dyn, trows,
                             vic_node, vic_rank)


def evict_solve_key(cfg, r: int, np_pad: int, ns_pad: int, n_pad: int,
                    k_pad: int, m_pad: int, s_real: int) -> tuple:
    """Compile-cache identity of one batched eviction executable — the
    jit-relevant degrees of freedom (static args + every traced shape),
    in the same spirit as compile_cache.solve_key for the allocate
    family."""
    return (EVICT_SOLVE_CHOICE, r, np_pad, ns_pad, n_pad, k_pad, m_pad,
            s_real, cfg)
