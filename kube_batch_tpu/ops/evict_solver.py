"""Batched eviction solve: every preemptor's node walk in ONE dispatch.

The paper's design says the preempt/reclaim/backfill actions "reuse the
same feasibility tensor" as tpu-allocate, but ops/scan.py only batched
the per-NODE axis: models/scanner.py still issued one device call (or one
numpy pass) per preemptor.  BENCH_r05 prices that loop: preempt is the
most expensive action at 1281.5 ms/cycle.  This module batches the
per-PREEMPTOR axis too — ``batch_scan_nodes`` vmaps the exact scan body
over a ``[K, L]`` request tensor (K distinct preemptor profiles, L the
packed trow layout ops/scan.py documents) so the whole session's
eviction feasibility + scoring lands in one ``[K, N]`` tensor from one
device dispatch, and ``evict_batch_solve`` fuses the device-side
victim-candidate ranking (per-node Running residents ordered by the
host's victim-order key, shipped as exact int32 rank columns) into the
same dispatch.

Eviction itself stays inherently sequential — each commit changes state
for the next preemptor — so the host actions consume these rows
optimistically and recompute only dirty rows (models/scanner.py's
edit-log patch path).  Bit-parity contract: ``_scan_body`` is the SAME
function the per-preemptor device scan jits, and the numpy mirror
(``DeviceNodeScanner._scores_numpy``) computes the same integers, so a
batched row equals the sequential engines exactly (pinned by
tests/test_evict_batch.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .scan import ScanStatics, _scan_body

# The batched-profile axis is bucketed like every other tensor axis so
# the kernel compiles once per (K, M) bucket pair, not once per storm
# shape; the warmup (compile_cache.warm_bucket) pre-builds the smallest
# bucket, which covers the common few-profile storm.
EVICT_SOLVE_CHOICE = "evict_batch"


def _batch_body(cfg, r: int, np_pad: int, ns_pad: int,
                statics: ScanStatics, dyn: jnp.ndarray,
                trows: jnp.ndarray) -> jnp.ndarray:
    """[K, N] i32 scores: _scan_body vmapped over the profile axis.  The
    scan math is per-node elementwise, so the vmap is a pure batching of
    identical per-row programs — row k equals scan_nodes(.., trows[k])
    bit for bit."""
    return jax.vmap(
        lambda trow: _scan_body(cfg, r, np_pad, ns_pad, statics, dyn, trow)
    )(trows)


@functools.partial(jax.jit,
                   static_argnames=("cfg", "r", "np_pad", "ns_pad"))
def batch_scan_nodes(cfg, r: int, np_pad: int, ns_pad: int,
                     statics: ScanStatics, dyn: jnp.ndarray,
                     trows: jnp.ndarray) -> jnp.ndarray:
    """One dispatch answering EVERY preemptor profile's candidate-node
    question; SCORE_NEG_INF marks predicate-rejected nodes, exactly like
    ops/scan.scan_nodes per row."""
    return _batch_body(cfg, r, np_pad, ns_pad, statics, dyn, trows)


@functools.partial(jax.jit,
                   static_argnames=("cfg", "r", "np_pad", "ns_pad"))
def evict_batch_solve(cfg, r: int, np_pad: int, ns_pad: int,
                      statics: ScanStatics, dyn: jnp.ndarray,
                      trows: jnp.ndarray, vic_node: jnp.ndarray,
                      vic_rank: jnp.ndarray):
    """The session's whole eviction pre-solve as ONE device program:

    * ``[K, N]`` feasibility+score rows for all K preemptor profiles
      (the vmapped scan), and
    * the victim-candidate permutation: ``vic_node`` ([M] i32 node row
      of each Running resident) and ``vic_rank`` ([M] i32, the resident's
      position in the host's victim-order key — reversed task order:
      priority ascending, creation-time descending, uid descending —
      staged as exact integer ranks so float-precision never reorders a
      tie) sorted to (node ascending, victim order) in one lexsort.

    Padding contract: trow padding rows are all-zero (their output rows
    are ignored); victim padding carries node = N (sorts after every
    real node) and rank = M (after every real resident).
    """
    scores = _batch_body(cfg, r, np_pad, ns_pad, statics, dyn, trows)
    perm = jnp.lexsort((vic_rank, vic_node))
    return scores, perm


def choose_evict_route(resident=None):
    """('sharded'|'xla', mesh): the eviction engine's mesh gate.

    Derived from the RESIDENT BUFFER'S OWN SHARDING, not re-gated: the
    shipper already routed its layout through ``choose_solver_mesh``
    (models/shipping.py), and the sharded dispatch reads those leaves in
    place — so following the leaves is self-consistent by construction
    (a bytes-gate-only shard, which the node-count scan gate alone would
    miss, still routes the eviction solve to the mesh).  Without a
    resident buffer there is nothing sharded to read: single-chip."""
    if resident is None:
        return "xla", None
    sharding = getattr(resident.node_used, "sharding", None)
    spec = getattr(sharding, "spec", None)
    mesh = getattr(sharding, "mesh", None)
    if (mesh is not None and getattr(mesh, "size", 1) > 1
            and spec is not None and len(spec) > 0
            and spec[0] is not None):
        return "sharded", mesh
    return "xla", None


def dispatch_evict_batch_solve(cfg, r: int, np_pad: int, ns_pad: int,
                               statics: ScanStatics, dyn: jnp.ndarray,
                               trows: jnp.ndarray, vic_node: jnp.ndarray,
                               vic_rank: jnp.ndarray, resident=None):
    """Host-side dispatch chokepoint for the jitted batched eviction
    solve — the seam the chaos engine injects device faults into
    (doc/CHAOS.md site ``evict_solve.device_error``; the branch cannot
    live inside the jitted program), and the eviction engine's mesh
    routing point (doc/SHARDING.md): when the node bucket crosses the
    shared shard gate AND ``resident`` (the shipper's device-resident
    SolverInputs) is attached, the solve runs node-sharded over the mesh
    reading the resident leaves in place — ``dyn`` then ships nothing.
    A no-op single branch when the chaos engine is off.  The scanner
    degrades a failure here to per-profile host scoring and feeds the
    device breaker (models/scanner.py batch_seed)."""
    from ..chaos import plan as chaos_plan
    from ..metrics import metrics
    plan = chaos_plan.PLAN
    if plan is not None and plan.fire("evict_solve.device_error"):
        raise RuntimeError(
            "chaos: batched eviction solve failed (injected)")
    choice, mesh = choose_evict_route(resident)
    metrics.note_route("evict", choice)
    metrics.note_session_dispatch("evict")
    from ..trace import spans as trace
    trace.annotate(route=choice, mesh_devices=mesh.size if mesh else 1)
    if choice == "sharded":
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..parallel.sharded_scan import evict_batch_solve_sharded
        # Profile rows and victim metadata are O(preemptors)/O(residents)
        # small and replicated; committing them to the mesh up front keeps
        # the dispatch free of mixed-device inputs.
        rep = NamedSharding(mesh, P())
        return evict_batch_solve_sharded(
            cfg, r, np_pad, ns_pad, statics, resident.node_used,
            resident.node_count, resident.node_ports,
            resident.node_selcnt, jax.device_put(trows, rep),
            jax.device_put(vic_node, rep), jax.device_put(vic_rank, rep),
            mesh)
    return evict_batch_solve(cfg, r, np_pad, ns_pad, statics, dyn, trows,
                             vic_node, vic_rank)


def evict_solve_key(cfg, r: int, np_pad: int, ns_pad: int, n_pad: int,
                    k_pad: int, m_pad: int, s_real: int,
                    route: str = "xla") -> tuple:
    """Compile-cache identity of one batched eviction executable — the
    jit-relevant degrees of freedom (static args + every traced shape,
    plus the routing choice: the sharded and single-chip engines are
    distinct executables), in the same spirit as compile_cache.solve_key
    for the allocate family."""
    return (EVICT_SOLVE_CHOICE, route, r, np_pad, ns_pad, n_pad, k_pad,
            m_pad, s_real, cfg)
