"""Device kernels (JAX/XLA): resource comparisons, fairness, scoring, and
the batched allocate solver.

This package is the TPU-side of the architecture mandated by BASELINE.json's
north star: the reference's per-session scheduling math re-expressed as
tensor programs.  Import is lazy where possible so host-only deployments
don't pay for jax.
"""

from . import fairness, resources, scoring, solver  # noqa: F401

__all__ = ["fairness", "resources", "scoring", "solver"]
