"""Vectorized resource-comparison semantics in integer milli-units.

The epsilon-tolerant comparisons of the host Resource algebra
(api/resource.py, mirroring reference resource_info.go:239-311) expressed
over a fixed resource axis R = [milli-cpu, memory, scalar...].

Device tensors hold **int32 fixed-point quanta** rather than floats: the
host's float64 values are scaled by a power-of-two quantum per dimension
(cpu: 1 milli-CPU, memory: 1 MiB = 2**20 bytes, scalars: 1 milli-unit) and
rounded to integers at tensorization.  This makes every add/subtract in the
solver loop *exact* — no f32 drift at 50k-task accumulations, where memory
in bytes overflows f32's 24-bit mantissa — and turns every epsilon into
exactly 10 quanta (minMilliCPU=10 / minMemory=10MiB=10 quanta /
minScalar=10, resource_info.go:68-70), so fit decisions match the host's
float64 math without jax_enable_x64 for quantities that are whole
multiples of the quantum (the practical case).  Sub-quantum quantities
round with <= 0.5-quantum error, so an epsilon compare whose true margin
lies within half a quantum of the 10-quantum boundary can flip vs the
host's exact bytes — a documented deviation, bounded by 1/20 of the
epsilon itself.  Power-of-two scaling keeps ratios (DRF shares, scoring
fractions) bit-identical to the unscaled ratios for quantum multiples.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# One quantum per dimension kind; all epsilons become EPS_QUANTA.
CPU_QUANTUM = 1.0                 # milli-CPU
MEMORY_QUANTUM = float(2 ** 20)   # bytes per quantum (1 MiB)
SCALAR_QUANTUM = 1.0              # milli-units
EPS_QUANTA = 10                   # 10 milli / 10 MiB / 10 milli-scalar


# --- integer grid scoring ---------------------------------------------------
# Node-scoring fractions (used/allocatable) are computed on a fixed integer
# grid so host (Python ints) and device (int32 + an exactness-proven f32
# floor-division) produce IDENTICAL score integers on every platform —
# float scores near-tie differently in f32 vs f64 and broke placement
# parity.  Formula, identical on both sides:
#
#   cs = cap >> shift            (shift normalizes the largest cap < 2**10)
#   xs = min((used + res) >> shift, cs)       (the min(frac, 1) clip)
#   frac_grid = SCORE_GRID_K                  if cs == 0
#             = (xs * SCORE_GRID_K) // cs     otherwise
#
# Exactness of the device's  floor(f32(xs*K) / f32(cs)):  numerator
# <= 2**10 * 2**12 = 2**22 is f32-exact, division is correctly rounded, and
# for a <= 2**22 the quotient error (<= a/b * 2**-24 < 2**-2/b) is smaller
# than the 1/b gap to the nearest integer, so the floor never flips.
# Grid resolution is 1/1024 of capacity — coarser than the reference's f64
# scores, but any within-grid coalescing lands in the reference's own
# random-among-max tie envelope (scheduler_helper.go:188-208).
SCORE_GRID_K = 1 << 12
_SCORE_CAP_LIMIT = 1 << 10


def score_shift_for(max_cap_quanta: int) -> int:
    """Per-dimension shift normalizing the largest capacity below 2**10."""
    s = 0
    while (int(max_cap_quanta) >> s) >= _SCORE_CAP_LIMIT:
        s += 1
    return s


def grid_fraction_int(x: int, cap: int, shift: int) -> int:
    """Host-side grid fraction (exact Python ints); see formula above."""
    cs = int(cap) >> shift
    if cs == 0:
        return SCORE_GRID_K
    xs = min(int(x) >> shift, cs)
    return (xs * SCORE_GRID_K) // cs


def quantum_for_dim(i: int) -> float:
    return (CPU_QUANTUM, MEMORY_QUANTUM)[i] if i < 2 else SCALAR_QUANTUM


def quantize_value(value: float, dim: int) -> int:
    """Host-side: one float64 quantity -> integer quanta."""
    return int(round(value / quantum_for_dim(dim)))


def scale_columns(arr: np.ndarray) -> np.ndarray:
    """Host-side: [..., R] float64 resource array -> float quanta, exactly
    scaled but NOT rounded (power-of-two division is exact in binary
    floating point)."""
    out = arr / MEMORY_QUANTUM
    out[..., 0] = arr[..., 0] / CPU_QUANTUM
    if arr.shape[-1] > 2:
        out[..., 2:] = arr[..., 2:] / SCALAR_QUANTUM
    return out


def quantize_columns(arr: np.ndarray) -> np.ndarray:
    """Host-side: [..., R] float64 resource array -> int64 quanta (callers
    range-check before narrowing to int32)."""
    return np.rint(scale_columns(arr)).astype(np.int64)


def eps_vector(r: int, dtype=jnp.int32) -> jnp.ndarray:
    """Per-dimension epsilon in quanta: 10 everywhere by construction."""
    return jnp.full((max(r, 2),), EPS_QUANTA, dtype=dtype)


def scalar_dims_mask(r: int) -> jnp.ndarray:
    """[R] bool marking scalar-resource dims (index >= 2)."""
    return jnp.asarray([False, False] + [True] * (max(r, 2) - 2))


EPS_VEC_FN = eps_vector


def less_equal_vec(l: jnp.ndarray, r: jnp.ndarray, eps: jnp.ndarray,
                   scalar_dims: jnp.ndarray) -> jnp.ndarray:
    """Epsilon-tolerant Resource.LessEqual reduced over the last axis.

    Per dim: l < r or |l-r| < eps; scalar dims with l <= eps are skipped
    (the host path skips low/absent scalars, resource_info.go:293-296).
    Exact on int32 quanta; also valid on float inputs.
    """
    ok = (l < r) | (jnp.abs(l - r) < eps)
    skip = scalar_dims & (l <= eps)
    return jnp.all(ok | skip, axis=-1)


def less_vec(l: jnp.ndarray, r: jnp.ndarray, eps: jnp.ndarray,
             scalar_dims: jnp.ndarray) -> jnp.ndarray:
    """Strict Resource.Less over the last axis.

    Per dim strictly less; for scalar dims the reference's absent-scalar
    asymmetry (resource_info.go:247-262) maps to: a scalar dim with l <= eps
    counts as less only when r's dim exceeds eps.
    """
    strict = l < r
    trivial = scalar_dims & (l <= eps) & (r > eps)
    return jnp.all(strict | trivial, axis=-1)


def is_empty_vec(v: jnp.ndarray, eps: jnp.ndarray) -> jnp.ndarray:
    """Resource.IsEmpty: every dim below its epsilon."""
    return jnp.all(v < eps, axis=-1)
