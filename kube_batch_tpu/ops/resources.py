"""Vectorized resource-comparison semantics.

The epsilon-tolerant comparisons of the host Resource algebra
(api/resource.py, mirroring reference resource_info.go:239-311) expressed
over a fixed resource axis R = [milli-cpu, memory-bytes, scalar...].
All device tensors use this layout; the epsilon vector is
[10, 10MiB, 10, 10, ...].
"""

from __future__ import annotations

import jax.numpy as jnp

from ..api.resource import MIN_MEMORY, MIN_MILLI_CPU, MIN_MILLI_SCALAR


def eps_vector(r: int, dtype=jnp.float32) -> jnp.ndarray:
    """Per-dimension epsilon: [minMilliCPU, minMemory, minScalar...]."""
    eps = [MIN_MILLI_CPU, MIN_MEMORY] + [MIN_MILLI_SCALAR] * (max(r, 2) - 2)
    return jnp.asarray(eps, dtype=dtype)


def scalar_dims_mask(r: int) -> jnp.ndarray:
    """[R] bool marking scalar-resource dims (index >= 2)."""
    return jnp.asarray([False, False] + [True] * (max(r, 2) - 2))


EPS_VEC_FN = eps_vector


def less_equal_vec(l: jnp.ndarray, r: jnp.ndarray, eps: jnp.ndarray,
                   scalar_dims: jnp.ndarray) -> jnp.ndarray:
    """Epsilon-tolerant Resource.LessEqual reduced over the last axis.

    Per dim: l < r or |l-r| < eps; scalar dims with l <= eps are skipped
    (the host path skips low/absent scalars, resource_info.go:293-296).
    """
    ok = (l < r) | (jnp.abs(l - r) < eps)
    skip = scalar_dims & (l <= eps)
    return jnp.all(ok | skip, axis=-1)


def less_vec(l: jnp.ndarray, r: jnp.ndarray, eps: jnp.ndarray,
             scalar_dims: jnp.ndarray) -> jnp.ndarray:
    """Strict Resource.Less over the last axis.

    Per dim strictly less; for scalar dims the reference's absent-scalar
    asymmetry (resource_info.go:247-262) maps to: a scalar dim with l <= eps
    counts as less only when r's dim exceeds eps.
    """
    strict = l < r
    trivial = scalar_dims & (l <= eps) & (r > eps)
    return jnp.all(strict | trivial, axis=-1)


def is_empty_vec(v: jnp.ndarray, eps: jnp.ndarray) -> jnp.ndarray:
    """Resource.IsEmpty: every dim below its epsilon."""
    return jnp.all(v < eps, axis=-1)
