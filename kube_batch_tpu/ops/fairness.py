"""Fairness math on device: DRF shares and proportion water-filling.

Device counterparts of plugins/drf.py (dominant share = max over resources of
allocated/total, reference drf.go:161-171) and plugins/proportion.py (the
iterative ``deserved`` water-fill, reference proportion.go:101-154) — the
fixed-point loop becomes a ``lax.while_loop`` over [Q, R] tensors.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .resources import EPS_VEC_FN, is_empty_vec, less_vec, scalar_dims_mask


def safe_share(alloc: jnp.ndarray, total: jnp.ndarray) -> jnp.ndarray:
    """share() semantics per element: x/0 -> 1 (0/0 -> 0)
    (reference api/helpers/helpers.go:47-59).  Accepts int32 quanta (the
    solver's exact fixed-point state).

    The division is ALWAYS float32 of float32-cast operands, matching
    api.resource.share on the host bit-for-bit (see its docstring): a
    share near-tie must resolve identically on the host plugins and on
    every device engine in both x64 modes, or job/queue order — and with
    it placements — diverges (fuzz seed 1088)."""
    f32 = jnp.float32
    alloc = alloc.astype(f32)
    total = total.astype(f32)
    zero_total = total == 0
    return jnp.where(zero_total,
                     jnp.where(alloc == 0, f32(0.0), f32(1.0)),
                     alloc / jnp.where(zero_total, f32(1), total))


def drf_shares(job_alloc: jnp.ndarray, total: jnp.ndarray) -> jnp.ndarray:
    """[J, R] allocated, [R] total -> [J] dominant shares."""
    return jnp.max(safe_share(job_alloc, total[None, :]), axis=-1)


def queue_shares(queue_alloc: jnp.ndarray, deserved: jnp.ndarray) -> jnp.ndarray:
    """[Q, R] allocated, [Q, R] deserved -> [Q] shares (proportion.go:239-251)."""
    return jnp.max(safe_share(queue_alloc, deserved), axis=-1)


def proportion_deserved(total: jnp.ndarray, weight: jnp.ndarray,
                        request: jnp.ndarray, active: jnp.ndarray,
                        max_iters: int = 64):
    """Weighted max-min water-filling of deserved resources.

    total: [R]; weight: [Q]; request: [Q, R]; active: [Q] bool (queues that
    have jobs this session).  Returns deserved [Q, R].

    Mirrors proportion.go:101-154: each round splits ``remaining`` by weight
    among unmet queues, caps a queue at its request (then it is 'met' and its
    surplus returns to the pool), and stops when remaining is epsilon-empty
    or every queue is met.  Inputs may be int32 quanta; the fill itself is
    float (weight splits are fractional) and the result is returned as float
    quanta — callers round before feeding the int compare paths.
    """
    fdt = jnp.promote_types(total.dtype, jnp.float32)
    total = total.astype(fdt)
    weight = weight.astype(fdt)
    request = request.astype(fdt)
    eps = EPS_VEC_FN(total.shape[-1], fdt)
    scalar_dims = scalar_dims_mask(total.shape[-1])
    q = weight.shape[0]

    def cond(state):
        deserved, remaining, met, it = state
        total_weight = jnp.sum(jnp.where(active & ~met, weight, 0.0))
        return (it < max_iters) & (total_weight > 0) \
            & ~is_empty_vec(remaining, eps)

    def body(state):
        deserved, remaining, met, it = state
        live = active & ~met
        total_weight = jnp.sum(jnp.where(live, weight, 0.0))
        frac = jnp.where(live, weight, 0.0) / jnp.maximum(total_weight, 1e-30)
        proposed = deserved + frac[:, None] * remaining[None, :]
        # Queue met when request < proposed (strict Resource.Less).
        newly_met = live & less_vec(request, proposed, eps, scalar_dims)
        capped = jnp.where(newly_met[:, None], jnp.minimum(proposed, request),
                           proposed)
        new_deserved = jnp.where(live[:, None], capped, deserved)
        # remaining -= (new - old) summed over live queues, matching the
        # increased/decreased bookkeeping in proportion.go:138-147.
        delta = jnp.sum(jnp.where(live[:, None], new_deserved - deserved, 0.0),
                        axis=0)
        return new_deserved, remaining - delta, met | newly_met, it + 1

    deserved0 = jnp.zeros_like(request)
    met0 = jnp.zeros((q,), dtype=bool)
    deserved, _, _, _ = jax.lax.while_loop(
        cond, body, (deserved0, total.astype(request.dtype) * 0 + total,
                     met0, jnp.int32(0)))
    return deserved
