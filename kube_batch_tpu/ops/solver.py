"""The batched allocate solver: kube-batch's session loop as one XLA program.

This is the TPU-native reformulation demanded by the north star
(BASELINE.json): the reference's allocate action (allocate.go:43-195) — queue
PQ / job PQ / task PQ with DRF+proportion shares recomputed after every
single placement — becomes a ``lax.while_loop`` state machine over dense
tensors that runs entirely on device:

  * queue/job selection = lexicographic masked argmin over [Q]/[J] key
    vectors (replacing the priority queues);
  * predicates = boolean [N] feasibility vectors from epsilon-correct
    resource fit + a precomputed [S, N] static-predicate mask indexed by
    task signature (replacing the 16-goroutine fan-out,
    scheduler_helper.go:63-86);
  * scoring = the nodeorder kernel over current [N, R] state;
  * fairness = DRF / proportion share updates as segment additions.

One loop iteration performs exactly one reference-loop event (a task
placement, or a job/queue retiring from rotation), so the device trace
reproduces the host path's order-dependent outcome placement-for-placement.
Ties are broken deterministically (first index in sorted-name node order /
first max score), matching utils/scheduler_helper.py.

The state layout is chosen for SPMD sharding: all [N, ...] tensors shard
over the node axis of a device mesh (parallel/sharded.py); job/queue state
is replicated and updated identically on every device.
"""

from __future__ import annotations

import functools
import threading
from typing import NamedTuple

import jax
import jax.numpy as jnp

from .. import knobs
from ..chaos import plan as chaos_plan
from .fairness import queue_shares, safe_share
from .resources import less_equal_vec
from .scoring import (SCORE_NEG_INF, ScoreWeights, grid_score, score_nodes,
                      shifted_caps)

# Placements unrolled per inner-loop iteration: device loop iterations carry
# a fixed dispatch overhead (~tens of µs on some TPU runtimes), so the drain
# loop executes UNROLL guarded placements per iteration to amortize it.
UNROLL = 8


class SolverInputs(NamedTuple):
    """Static per-session tensors (see models/tensor_snapshot.py).

    Resource tensors ([.., R]) are **int32 fixed-point quanta**
    (ops/resources.py: milli-cpu / MiB / milli-scalar), so solver-loop
    accounting is exact integer math; ts/prio/rank keys and total_res are
    float.
    """
    # tasks (P = padded candidate count)
    task_req: jnp.ndarray       # [P, R] i32 launch requirement (init_resreq)
    task_res: jnp.ndarray       # [P, R] i32 steady requirement (resreq)
    task_sig: jnp.ndarray       # [P] i32 index into sig_mask
    task_sorted: jnp.ndarray    # [P] i32 task ids in (job, task-order) order
    # dynamic predicates (all-zero unless cfg.has_ports/has_pod_affinity)
    task_ports: jnp.ndarray     # [P, NP] bool: task uses host-port key
    task_aff_req: jnp.ndarray   # [P, NS] bool: requires selector matched
    task_anti: jnp.ndarray      # [P, NS] bool: forbids selector matched
    task_match: jnp.ndarray     # [P, NS] bool: task's labels match selector
    task_paff_w: jnp.ndarray    # [P, NS] i32 preferred-affinity weights
    task_panti_w: jnp.ndarray   # [P, NS] i32 preferred-anti weights
    # jobs (J)
    job_start: jnp.ndarray      # [J] i32 offset into task_sorted
    job_count: jnp.ndarray      # [J] i32 number of candidate tasks
    job_queue: jnp.ndarray      # [J] i32 queue index
    job_minavail: jnp.ndarray   # [J] i32
    job_prio: jnp.ndarray       # [J] f  PriorityClass value
    job_ts: jnp.ndarray         # [J] f  creation timestamp
    job_uid_rank: jnp.ndarray   # [J] f  rank of UID (tie-break)
    job_init_ready: jnp.ndarray  # [J] i32 ready_task_num at session open
    job_init_alloc: jnp.ndarray  # [J, R] allocated at session open (drf)
    # queues (Q)
    queue_deserved: jnp.ndarray  # [Q, R] i32 water-fill (overused compare)
    queue_deserved_f: jnp.ndarray  # [Q, R] float, UNrounded scaled quanta:
                                 # deserved is inherently fractional (weight
                                 # splits), and rounding it flips near-tied
                                 # share orderings.  The alloc numerator is
                                 # still integer quanta, so share ratios are
                                 # host-exact for quantum-multiple requests
                                 # and within one quantum otherwise
    queue_init_alloc: jnp.ndarray  # [Q, R]
    queue_ts: jnp.ndarray       # [Q] f
    queue_uid_rank: jnp.ndarray  # [Q] f
    queue_exists: jnp.ndarray   # [Q] bool (padding rows False)
    # nodes (N)
    node_idle: jnp.ndarray      # [N, R]
    node_releasing: jnp.ndarray  # [N, R]
    node_used: jnp.ndarray      # [N, R]
    node_alloc: jnp.ndarray     # [N, R] allocatable (scoring denominator)
    node_count: jnp.ndarray     # [N] i32 resident task count
    node_max_tasks: jnp.ndarray  # [N] i32 pod-count cap
    node_exists: jnp.ndarray    # [N] bool (padding rows False)
    node_ports: jnp.ndarray     # [N, NP] bool: host-port key in use
    node_selcnt: jnp.ndarray    # [N, NS] i32: resident tasks matching sel
    sig_mask: jnp.ndarray       # [S, N] bool static predicate mask
    sig_bonus: jnp.ndarray      # [S, N] i32 static score bonus (preferred
                                # node affinity, grid-scaled and weighted)
    # cluster
    total_res: jnp.ndarray      # [R] sum of allocatable (drf denominator)
    eps: jnp.ndarray            # [R] epsilon vector
    scalar_dims: jnp.ndarray    # [R] bool
    score_shift: jnp.ndarray    # [2] i32 grid shifts for cpu/mem scoring
    # topology (models/topology.py): [N, 8] i32 pod/rack/x/y/z + the
    # owning pod's torus dims; -1 rows = no coordinates (flat node).
    # Inert to the allocate solve (no program reads it), and the box
    # scan (ops/topo_solver.py) currently stages its own origin-sharded
    # copy per dispatch — the leaf exists so the RESIDENT layout never
    # flips when the topology subsystem engages (layout stability is
    # the delta-ship/generation contract) and mesh-resident topology
    # consumers can bind to it without a reshape.  All-(-1) on flat
    # clusters: one full ship, then zero delta bytes.
    node_coords: jnp.ndarray    # [N, 8] i32


class SolverConfig(NamedTuple):
    """Static plugin/tier structure baked into the compiled program.

    ``job_key_order``/``queue_key_order`` list the order-contributing plugins
    in tier order (session_plugins.go evaluates order fns tier by tier, first
    non-zero wins), so the lexicographic device keys reproduce the exact
    tiered chain of the loaded conf.
    """
    job_key_order: tuple = ("priority", "gang", "drf")
    queue_key_order: tuple = ("proportion",)
    has_gang: bool = True          # gang registers JobReady
    has_proportion: bool = True    # proportion registers Overused
    has_ports: bool = False        # any candidate uses host ports
    has_pod_affinity: bool = False  # any candidate uses pod (anti-)affinity
    has_pod_affinity_score: bool = False  # preferred pod-affinity scoring
    weights: ScoreWeights = ScoreWeights()


class SolverState(NamedTuple):
    idle: jnp.ndarray           # [N, R]
    releasing: jnp.ndarray      # [N, R]
    used: jnp.ndarray           # [N, R]
    count: jnp.ndarray          # [N] i32
    ports: jnp.ndarray          # [N, NP] bool host-port occupancy
    selcnt: jnp.ndarray         # [N, NS] i32 selector match counts
    job_ptr: jnp.ndarray        # [J] i32 next task offset
    job_active: jnp.ndarray     # [J] bool still in rotation
    job_ready_cnt: jnp.ndarray  # [J] i32 dynamic ready_task_num
    job_alloc: jnp.ndarray      # [J, R] dynamic drf allocation
    queue_alloc: jnp.ndarray    # [Q, R]
    queue_active: jnp.ndarray   # [Q] bool
    locked_job: jnp.ndarray     # scalar i32, -1 when none
    assignment: jnp.ndarray     # [P] i32 node index or -1
    kind: jnp.ndarray           # [P] i32 0=none 1=allocate 2=pipeline
    order: jnp.ndarray          # [P] i32 step at which placed
    step: jnp.ndarray           # scalar i32


def _lex_argmin(mask: jnp.ndarray, keys) -> jnp.ndarray:
    """Index of the masked lexicographic minimum; assumes mask.any()."""
    for k in keys:
        kv = jnp.where(mask, k, jnp.inf)
        mask = mask & (kv == jnp.min(kv))
    return jnp.argmax(mask).astype(jnp.int32)


def _select_queue(inp: SolverInputs, st: SolverState, cfg: SolverConfig):
    """Pop the front queue (allocate.go:90-95): min share (proportion), then
    creation time, then UID."""
    keys = []
    for name in cfg.queue_key_order:
        if name == "proportion":
            keys.append(queue_shares(st.queue_alloc, inp.queue_deserved_f))
    keys.extend([inp.queue_ts, inp.queue_uid_rank])
    return _lex_argmin(st.queue_active, keys)


def _queue_overused(inp: SolverInputs, st: SolverState, q, cfg: SolverConfig):
    if not cfg.has_proportion:
        return jnp.bool_(False)
    return less_equal_vec(inp.queue_deserved[q], st.queue_alloc[q], inp.eps,
                          inp.scalar_dims)


def _select_job(inp: SolverInputs, st: SolverState, q, cfg: SolverConfig):
    """Pop the front job of queue q: tiered JobOrderFn chain — priority desc,
    gang not-ready first, DRF share asc, then creation time / UID
    (session_plugins.go:247-271 with the default tier layout)."""
    mask = st.job_active & (inp.job_queue == q)
    keys = []
    for name in cfg.job_key_order:
        if name == "priority":
            keys.append(-inp.job_prio)
        elif name == "gang":
            ready = (st.job_ready_cnt >= inp.job_minavail)
            keys.append(ready.astype(inp.job_ts.dtype))
        elif name == "drf":
            keys.append(jnp.max(
                safe_share(st.job_alloc, inp.total_res[None, :]), axis=-1))
    keys.extend([inp.job_ts, inp.job_uid_rank])
    return _lex_argmin(mask, keys), mask


def dynamic_predicate_mask(cfg: SolverConfig, t, task_ports, task_aff_req,
                           task_anti, ports, selcnt):
    """[N] bool: host-port conflicts (predicates.go:174) and required
    inter-pod (anti-)affinity at hostname topology (predicates.go:249-262),
    evaluated against the in-loop occupancy state (the reference re-reads
    its session-view PodLister the same way).  Returns None when neither
    feature is active (masks compile away)."""
    ok = None
    if cfg.has_ports:
        conflict = (task_ports[t][None, :] & ports).any(axis=-1)
        ok = ~conflict
    if cfg.has_pod_affinity:
        have = selcnt > 0
        aff_ok = jnp.all(~task_aff_req[t][None, :] | have, axis=-1)
        anti_ok = jnp.all(~task_anti[t][None, :] | ~have, axis=-1)
        both = aff_ok & anti_ok
        ok = both if ok is None else (ok & both)
    return ok


def interpod_score_term(cfg: SolverConfig, t, task_paff_w, task_panti_w,
                        selcnt):
    """[N] i32 InterPodAffinity priority term (nodeorder.go:107-131 analog;
    see plugins/nodeorder.interpod_affinity_score): grid-scaled sum of
    preferred term weights times selector match counts.  None when the
    feature is inactive."""
    from .resources import SCORE_GRID_K
    if not cfg.has_pod_affinity_score:
        return None
    wdiff = (task_paff_w[t] - task_panti_w[t])[None, :]
    return SCORE_GRID_K * jnp.sum(wdiff * selcnt, axis=-1)


def _needs_selcnt(cfg: SolverConfig) -> bool:
    return cfg.has_pod_affinity or cfg.has_pod_affinity_score


def _job_ready(inp: SolverInputs, st: SolverState, j, cfg: SolverConfig):
    """ssn.JobReady: gang's ready_task_num >= minAvailable; True when gang is
    absent (session_plugins.go:184-203)."""
    if not cfg.has_gang:
        return jnp.bool_(True)
    return st.job_ready_cnt[j] >= inp.job_minavail[j]


def solver_step(inp: SolverInputs, cfg: SolverConfig,
                st: SolverState) -> SolverState:
    """One reference-loop event (see module docstring)."""
    have_locked = st.locked_job >= 0

    # ---- queue + job selection (skipped while a job is locked) -----------
    q_sel = _select_queue(inp, st, cfg)
    overused = _queue_overused(inp, st, q_sel, cfg)
    j_sel, job_mask = _select_job(inp, st, q_sel, cfg)
    queue_has_job = job_mask.any()
    # Queue retires from rotation when overused or jobless (allocate.go:95-108
    # `continue` without re-push).
    retire_queue = ~have_locked & (overused | ~queue_has_job)

    j = jnp.where(have_locked, st.locked_job, j_sel)
    act = ~retire_queue  # this iteration processes a task of job j
    jq = inp.job_queue[j]

    # ---- task of job j ----------------------------------------------------
    ptr = st.job_ptr[j]
    exhausted = ptr >= inp.job_count[j]
    t = inp.task_sorted[jnp.clip(inp.job_start[j] + ptr, 0,
                                 inp.task_sorted.shape[0] - 1)]

    req = inp.task_req[t]
    res = inp.task_res[t]

    fit_idle = less_equal_vec(req[None, :], st.idle, inp.eps, inp.scalar_dims)
    fit_rel = less_equal_vec(req[None, :], st.releasing, inp.eps,
                             inp.scalar_dims)
    feasible = (inp.sig_mask[inp.task_sig[t]] & inp.node_exists
                & (st.count < inp.node_max_tasks) & (fit_idle | fit_rel))
    dyn = dynamic_predicate_mask(cfg, t, inp.task_ports, inp.task_aff_req,
                                 inp.task_anti, st.ports, st.selcnt)
    if dyn is not None:
        feasible = feasible & dyn
    any_feasible = feasible.any()

    placing = act & ~exhausted & any_feasible

    score = score_nodes(res, st.used, inp.node_alloc, inp.score_shift,
                        cfg.weights)
    pa = interpod_score_term(cfg, t, inp.task_paff_w, inp.task_panti_w,
                             st.selcnt)
    if pa is not None:
        score = score + pa
    score = score + inp.sig_bonus[inp.task_sig[t]]
    score = jnp.where(feasible, score, SCORE_NEG_INF)
    # first max = deterministic tie-break
    n = jnp.argmax(score).astype(jnp.int32)

    alloc_ok = placing & fit_idle[n]
    pipe_ok = placing & ~fit_idle[n] & fit_rel[n]
    placed = alloc_ok | pipe_ok

    # ---- state updates (exact integer quanta) -----------------------------
    dres = jnp.where(placed, res, 0)
    idle = st.idle.at[n].add(jnp.where(alloc_ok, -dres, 0))
    releasing = st.releasing.at[n].add(jnp.where(pipe_ok, -dres, 0))
    used = st.used.at[n].add(dres)
    count = st.count.at[n].add(placed.astype(st.count.dtype))
    ports = st.ports
    if cfg.has_ports:
        ports = ports.at[n].set(
            ports[n] | (placed & inp.task_ports[t]))
    selcnt = st.selcnt
    if _needs_selcnt(cfg):
        selcnt = selcnt.at[n].add(
            jnp.where(placed, inp.task_match[t].astype(selcnt.dtype), 0))

    # Event handlers fire for both allocate and pipeline (session.go:269-275):
    # DRF job share and proportion queue share grow by resreq.
    job_alloc = st.job_alloc.at[j].add(dres)
    queue_alloc = st.queue_alloc.at[jq].add(dres)
    job_ready_cnt = st.job_ready_cnt.at[j].add(alloc_ok.astype(jnp.int32))

    consumed = act & ~exhausted & any_feasible  # task consumed even if placed on neither (can't happen; kept for clarity)
    job_ptr = st.job_ptr.at[j].add(consumed.astype(jnp.int32))

    assignment = st.assignment.at[t].set(
        jnp.where(placed, n, st.assignment[t]))
    kind = st.kind.at[t].set(
        jnp.where(alloc_ok, 1, jnp.where(pipe_ok, 2, st.kind[t])))
    order = st.order.at[t].set(
        jnp.where(placed, st.step, st.order[t]))

    # ---- rotation bookkeeping ---------------------------------------------
    st2 = st._replace(job_ready_cnt=job_ready_cnt)
    now_ready = _job_ready(inp, st2, j, cfg)
    remaining = job_ptr[j] < inp.job_count[j]

    # Job leaves rotation on: exhausted-at-pop, predicate-dead-end
    # (allocate.go:146-150 break), or task loop ending without a re-push
    # (ready with tasks remaining is the only re-push, allocate.go:185-188).
    job_dies = act & (exhausted | (~any_feasible)
                      | (~remaining))
    job_active = st.job_active.at[j].set(
        jnp.where(job_dies, False, st.job_active[j]))

    # Lock semantics: keep draining this job's tasks until it turns ready or
    # dies (the inner `for !tasks.Empty()` loop).
    stay_locked = act & placed & ~now_ready & remaining
    locked_job = jnp.where(stay_locked, j, -1)

    queue_active = st.queue_active.at[q_sel].set(
        jnp.where(retire_queue, False, st.queue_active[q_sel]))

    return SolverState(
        idle=idle, releasing=releasing, used=used, count=count,
        ports=ports, selcnt=selcnt,
        job_ptr=job_ptr, job_active=job_active,
        job_ready_cnt=job_ready_cnt, job_alloc=job_alloc,
        queue_alloc=queue_alloc, queue_active=queue_active,
        locked_job=locked_job, assignment=assignment, kind=kind,
        order=order, step=st.step + 1)


def initial_state(inp: SolverInputs) -> SolverState:
    p = inp.task_req.shape[0]
    j = inp.job_start.shape[0]
    q = inp.queue_deserved.shape[0]
    # Jobs enter rotation when their queue exists (allocate.go:52-65 pushes
    # every job whose queue is found, even with zero pending tasks).
    job_active = inp.queue_exists[inp.job_queue] & (inp.job_minavail >= 0)
    # Queues enter rotation when any job references them.
    queue_active = jnp.zeros((q,), dtype=bool).at[inp.job_queue].set(
        True) & inp.queue_exists
    return SolverState(
        idle=inp.node_idle, releasing=inp.node_releasing, used=inp.node_used,
        count=inp.node_count,
        ports=inp.node_ports, selcnt=inp.node_selcnt,
        job_ptr=jnp.zeros((j,), jnp.int32), job_active=job_active,
        job_ready_cnt=inp.job_init_ready, job_alloc=inp.job_init_alloc,
        queue_alloc=inp.queue_init_alloc, queue_active=queue_active,
        locked_job=jnp.int32(-1),
        assignment=jnp.full((p,), -1, jnp.int32),
        kind=jnp.zeros((p,), jnp.int32),
        order=jnp.full((p,), -1, jnp.int32),
        step=jnp.int32(0))


@functools.partial(jax.jit, static_argnames=("cfg",))
def solve_allocate_stepwise(inp: SolverInputs, cfg: SolverConfig) -> SolverState:
    """Single-level reference solver: one loop iteration per event.  Kept as
    the readable specification and cross-validation oracle for the optimized
    two-level solver below."""
    st = initial_state(inp)

    def cond(st: SolverState):
        return st.queue_active.any() | (st.locked_job >= 0)

    return jax.lax.while_loop(cond, lambda s: solver_step(inp, cfg, s), st)


class SolveResult(NamedTuple):
    assignment: jnp.ndarray  # [P] i32 node index or -1
    kind: jnp.ndarray        # [P] i32 0=none 1=allocate 2=pipeline
    order: jnp.ndarray       # [P] i32 placement sequence number
    step: jnp.ndarray        # scalar i32 total placements


@jax.jit
def _pack_result(assignment, kind, order):
    return jnp.stack([assignment, kind, order])


def _chaos_fetch(packed):
    """Readback fault sites (doc/CHAOS.md): a slow device (``solve.slow``
    sleeps before the transfer is consumed) and a poisoned readback
    (``solve.poison`` truncates a column, the shape every consumer must
    validate before applying).  One no-op branch when chaos is off."""
    plan = chaos_plan.PLAN
    if plan is None:
        return packed, None
    slow = plan.fire("solve.slow")
    if slow is not None:
        import time
        time.sleep(0.01 + 0.05 * slow.magnitude)
    if plan.fire("solve.poison") and packed.shape[-1]:
        return packed[:, :-1], slow
    return packed, slow


def fetch_result(result: "SolveResult"):
    """Device->host readback of (assignment, kind, order) as ONE transfer:
    the TPU tunnel charges fixed latency per transfer, so three np.asarray
    calls cost 3x (models/shipping.py is the mirror-image on the way in)."""
    import numpy as np

    from ..trace import spans as trace
    with trace.span("solver.fetch"):
        packed = np.asarray(_pack_result(result.assignment, result.kind,
                                         result.order))
    packed, _ = _chaos_fetch(packed)
    return packed[0], packed[1], packed[2]


@jax.jit
def _pack_result_ordered(assignment, kind, order):
    """[4, P] packed readback with the placement permutation computed ON
    DEVICE: row 3 sorts task ids by placement step (unplaced rows pushed
    to the tail via an int32-max key), so the host-side
    ``argsort(order[placed])`` the apply phase needs rides the async solve
    instead of serializing after the fetch.  Placed steps are unique, so
    the sort equals the host's stable argsort exactly."""
    key = jnp.where(kind > 0, order, jnp.iinfo(jnp.int32).max)
    perm = jnp.argsort(key).astype(jnp.int32)
    return jnp.stack([assignment, kind, order, perm])


class PendingSolve(NamedTuple):
    """An in-flight solve: the packed result tensor has been DISPATCHED
    (device executing asynchronously) but not fetched.  The action runs
    its host-overlappable apply preparation between ``dispatch_solve``
    and ``fetch_solve`` — the input-pipeline overlap the pipelined
    session engine is built on (doc/PIPELINE.md).  ``remap`` is set by
    the candidate-row dispatch (ops/prefilter.py): the packed assignment
    column then holds candidate-LOCAL rows and fetch_solve scatters them
    back into full-space node indices.

    Handles are INDEPENDENT: the concurrent shard pipeline
    (doc/TENANCY.md "Concurrent micro-sessions") keeps several
    outstanding at once — each owns its own packed result buffer (and
    each shard its own resident SolverInputs, models/shipping.py), so
    dispatch order imposes nothing on fetch order.  Every dispatched
    handle must end in exactly one ``fetch_solve`` or ``discard_solve``;
    the ``kube_batch_tpu_solver_inflight`` gauge audits the ledger."""
    packed: jnp.ndarray  # [4, P]: assignment / kind / order / placed-perm
    remap: object = None  # np [C_pad] int32 full node row per program row


# In-flight dispatch ledger (process-wide): dispatched-but-not-consumed
# PendingSolve handles.  A plain guarded int — dispatch/fetch run on the
# scheduler loop thread, but tests and multi-replica soaks drive several
# engines per process.
_inflight_lock = threading.Lock()
_inflight = 0  # guarded-by: _inflight_lock


def _note_dispatch(delta: int) -> None:
    global _inflight
    from ..metrics import metrics
    with _inflight_lock:
        _inflight = max(0, _inflight + delta)
        metrics.set_solver_inflight(_inflight)


def solver_inflight() -> int:
    """Outstanding dispatch handles (tests + /metrics)."""
    with _inflight_lock:
        return _inflight


def discard_solve(pending: PendingSolve) -> None:
    """Abandon a dispatched solve without reading it back: the device
    work completes (or completed) on its own and the buffer is dropped —
    the fetch-and-discard half of the pipeline's conflict/drain paths.
    The resident input image is NOT invalidated here: the ship that fed
    this dispatch completed, so it remains the correct delta baseline
    (callers that cannot prove that — stop() on a wedged loop — pair the
    discard with DeviceResidentShipper.invalidate)."""
    if pending is not None:
        _note_dispatch(-1)


@jax.jit
def _gather_candidate_inputs(inp: SolverInputs, idx: jnp.ndarray,
                             valid: jnp.ndarray) -> SolverInputs:
    """Rebucket the node axis to the candidate rows (ascending full-space
    order, so first-max tie-breaks survive the gather): node-major leaves
    take rows out of the RESIDENT buffer on device, [S, N] leaves take
    columns, and padding rows are masked out through node_exists (their
    data repeats the last real candidate, so downstream math stays
    well-defined).  Everything replicated (tasks/jobs/queues/cluster,
    including total_res and score_shift — the DRF denominator and score
    grid stay full-cluster) passes through untouched."""
    def take(a):
        return jnp.take(a, idx, axis=0)

    return inp._replace(
        node_idle=take(inp.node_idle),
        node_releasing=take(inp.node_releasing),
        node_used=take(inp.node_used),
        node_alloc=take(inp.node_alloc),
        node_count=take(inp.node_count),
        node_max_tasks=take(inp.node_max_tasks),
        node_exists=take(inp.node_exists) & valid,
        node_ports=take(inp.node_ports),
        node_selcnt=take(inp.node_selcnt),
        sig_mask=jnp.take(inp.sig_mask, idx, axis=1),
        sig_bonus=jnp.take(inp.sig_bonus, idx, axis=1))


def _solve_candidates(inp: SolverInputs, cfg: SolverConfig,
                      candidates) -> SolveResult:
    """Dispatch the candidate-row program: gather [C] rows from the
    resident buffer (per shard on the mesh route — the gather follows
    ``choose_solver_mesh`` exactly like the shipper, so candidate rows
    never leave their owning device) and run the standard solver on the
    smaller bucket.  Placement-identical to the full program by the
    prefilter's exactness argument (ops/prefilter.py) and pinned by the
    oracle suite (tests/test_cycle_floors.py)."""
    choice, mesh = choose_solver_mesh(inp)
    # Same chaos chokepoint as best_solve_allocate: the candidate path is
    # still a device dispatch and must feed the breaker under injection.
    plan = chaos_plan.PLAN
    if plan is not None and plan.fire("solve.device_error"):
        raise RuntimeError("chaos: device solve dispatch failed (injected)")
    from ..metrics import metrics
    from ..trace import spans as trace
    from .compile_cache import note_solve
    if choice == "sharded":
        from ..parallel.sharded_solver import (gather_candidate_sharded,
                                               solve_allocate_sharded)
        sub = gather_candidate_sharded(
            inp, jnp.asarray(candidates.local_idx),
            jnp.asarray(candidates.local_valid), mesh)
        metrics.note_route("allocate", "sharded")
        trace.annotate(route="sharded", mesh_devices=mesh.size,
                       candidate_rows=candidates.count)
        note_solve("sharded", sub, cfg)
        return solve_allocate_sharded(sub, cfg, mesh)
    # Single chip: the gathered program runs the two-level XLA solve on
    # every backend (the Pallas kernel keeps the full-bucket layout; all
    # family members are placement-identical by the parity suite).
    sub = _gather_candidate_inputs(inp, jnp.asarray(candidates.idx),
                                   jnp.asarray(candidates.valid))
    metrics.note_route("allocate", "xla")
    trace.annotate(route="xla", mesh_devices=1,
                   candidate_rows=candidates.count)
    note_solve("xla", sub, cfg)
    return solve_allocate(sub, cfg)


def dispatch_solve(inp: SolverInputs, cfg: SolverConfig,
                   candidates=None) -> PendingSolve:
    """Route and dispatch the solve without blocking on its result.  All
    solver family members dispatch asynchronously (JAX async dispatch on
    every backend), so this returns as soon as the programs are enqueued.
    ``candidates`` (ops/prefilter.CandidateSet) narrows the node axis to
    the prefiltered rows; the fetch remaps the result to full space."""
    from ..trace import spans as trace
    with trace.span("solver.dispatch"):
        if candidates is not None:
            result = _solve_candidates(inp, cfg, candidates)
            pending = PendingSolve(
                _pack_result_ordered(result.assignment, result.kind,
                                     result.order),
                remap=candidates.remap)
        else:
            result = best_solve_allocate(inp, cfg)
            pending = PendingSolve(_pack_result_ordered(
                result.assignment, result.kind, result.order))
    from ..metrics import metrics
    metrics.note_session_dispatch("solve")
    _note_dispatch(+1)
    return pending


def fetch_solve(pending: PendingSolve):
    """Block on and read back a dispatched solve as ONE transfer.

    Returns (assignment, kind, order, ordered) where ``ordered`` is the
    placed task ids in placement order — the device-computed equivalent of
    ``placed[np.argsort(order[placed], kind="stable")]``.  A candidate-row
    solve's assignment column is scattered back to full-space node rows
    here (unplaced rows keep -1), so consumers never see program-local
    indices."""
    import numpy as np

    from ..trace import spans as trace
    try:
        with trace.span("solver.fetch"):
            packed = np.asarray(pending.packed)
    finally:
        # Consumed either way: a fetch that raises (dead tunnel) still
        # retires the handle from the in-flight ledger.
        _note_dispatch(-1)
    packed, _ = _chaos_fetch(packed)
    assignment, kind, order, perm = packed
    if pending.remap is not None:
        remap = pending.remap
        local = np.clip(assignment, 0, len(remap) - 1)
        assignment = np.where(kind > 0, remap[local], assignment)
    n_placed = int(np.count_nonzero(kind > 0))
    return assignment, kind, order, perm[:n_placed]


# When to shard the solve over the mesh.  MEASUREMENT-DERIVED
# (doc/SHARD_BENCH.json, tools/shard_bench.py --sweep): the single-chip
# solve's per-node marginal cost is ~0.51 ns per placement step (TPU
# v5e, node axis 2.5k-41k sweep), so sharding over K=8 chips saves
# ~0.51ns * N * 7/8 per placement and costs one packed pmax + one
# packed pmin on ICI (~2-10 us for the pair).  Break-even lands between
# ~4.5k nodes (2 us collectives) and ~22.5k (10 us); the default gate
# sits mid-conservative at 16384.  A bytes cap still triggers sharding
# when node-major state would pressure one chip's HBM regardless of
# latency.  Overridable for ops tuning; FORCE_SHARD for tests/drills.
SHARD_NODES_ENV = knobs.SHARD_NODES.env
SHARD_BYTES_ENV = knobs.SHARD_BYTES.env
FORCE_SHARD_ENV = knobs.FORCE_SHARD.env
DEFAULT_SHARD_NODES = knobs.SHARD_NODES.default
DEFAULT_SHARD_BYTES = knobs.SHARD_BYTES.default


def _node_state_bytes(inp: SolverInputs) -> int:
    """Approximate node-major working set: the only state that scales with
    the cluster's node count (everything else is replicated)."""
    n = inp.node_idle.shape[0]
    r = inp.node_idle.shape[1]
    per_node = (4 * r * 4                       # idle/releasing/used/alloc
                + inp.sig_mask.shape[0]          # static mask rows (bool)
                + inp.task_ports.shape[1]        # port occupancy (bool)
                + 4 * inp.task_aff_req.shape[1]  # selector counts (i32)
                + 16)                            # count/cap/exists/cs rows
    return n * per_node


class ShardKnobs(NamedTuple):
    """The routing gates, resolved from the environment ONCE (like the
    trace kill switch): ``choose_solver_mesh`` sits on every solve AND
    every shipper call, and the eviction scan gate re-reads the same
    knobs — per-call ``os.environ`` probes plus a silent-int parse meant
    a malformed value was swallowed invisibly on every session forever.
    A bad value now warns loudly exactly once and pins the default."""
    nodes: int = DEFAULT_SHARD_NODES
    bytes: int = DEFAULT_SHARD_BYTES
    force: bool = False


_SHARD_KNOBS = None  # resolved lazily once; refresh_shard_knobs re-reads


def _resolve_shard_knobs() -> ShardKnobs:
    return ShardKnobs(
        nodes=knobs.SHARD_NODES.value(),
        bytes=knobs.SHARD_BYTES.value(),
        force=knobs.FORCE_SHARD.enabled())


def shard_knobs() -> ShardKnobs:
    """The pinned routing knobs (resolved at first use, startup-stable)."""
    global _SHARD_KNOBS
    if _SHARD_KNOBS is None:
        _SHARD_KNOBS = _resolve_shard_knobs()
    return _SHARD_KNOBS


def refresh_shard_knobs() -> ShardKnobs:
    """Re-resolve the knobs from the current environment — the deliberate
    ops/test hook (bench A/B arms toggle FORCE_SHARD in-process).  The
    production loop never calls this: routing stays startup-pinned."""
    global _SHARD_KNOBS
    _SHARD_KNOBS = None
    return shard_knobs()


def choose_solver_mesh(inp: SolverInputs):
    """('sharded'|'pallas'|'xla', mesh) — one production chokepoint, chosen
    by shape and the startup-pinned knobs (SURVEY.md §7 stage 7:
    pjit-shard [P, N] when it outgrows one chip).  The returned mesh is
    the one the precondition validated (non-None, node bucket divisible).
    The DeviceResidentShipper routes its resident-buffer layout through
    this same chokepoint, so the bytes land pre-sharded exactly where the
    solve will read them (doc/SHARDING.md)."""
    from ..parallel.mesh import default_mesh
    mesh = default_mesh()
    if mesh is not None and inp.node_idle.shape[0] % mesh.size == 0:
        knobs = shard_knobs()
        if knobs.force \
                or inp.node_idle.shape[0] >= knobs.nodes \
                or _node_state_bytes(inp) > knobs.bytes:
            return "sharded", mesh
    if jax.default_backend() == "tpu":
        return "pallas", None
    return "xla", None


def choose_solver(inp: SolverInputs) -> str:
    return choose_solver_mesh(inp)[0]


def best_solve_allocate(inp: SolverInputs, cfg: SolverConfig) -> SolveResult:
    """Pick the fastest correct solver for the current shape and backend:
    the node-sharded mesh solve when the node bucket outgrows one chip, the
    single-kernel Pallas solve on TPU (ops/pallas_solver.py), the two-level
    XLA solve elsewhere.  All are placement-identical (parity suite)."""
    choice, mesh = choose_solver_mesh(inp)
    # Chaos site: the device dispatch chokepoint every solver family
    # member routes through (doc/CHAOS.md site ``solve.device_error``);
    # a no-op single branch when the chaos engine is off.
    plan = chaos_plan.PLAN
    if plan is not None and plan.fire("solve.device_error"):
        raise RuntimeError("chaos: device solve dispatch failed (injected)")
    from ..metrics import metrics
    metrics.note_route("allocate", choice)
    from ..trace import spans as trace
    trace.annotate(route=choice, mesh_devices=mesh.size if mesh else 1)
    from .compile_cache import note_solve
    note_solve(choice, inp, cfg)  # compile-cache hit/miss observability
    if choice == "sharded":
        from ..parallel.sharded_solver import solve_allocate_sharded
        return solve_allocate_sharded(inp, cfg, mesh)
    if choice == "pallas":
        from .pallas_solver import solve_allocate_pallas
        return solve_allocate_pallas(inp, cfg)
    return solve_allocate(inp, cfg)


def _unrolled_le(req, mat, r):
    """Epsilon LessEqual of a task vector against [N, R] state, unrolled over
    the static resource axis so XLA sees one elementwise chain instead of a
    reduction (less_equal_vec semantics, resource_info.go:279-311).  In
    quantized units every dimension's epsilon is EPS_QUANTA; scalar dims
    (>= 2) are skipped when the request is epsilon-low."""
    from .resources import EPS_QUANTA
    ok = None
    for i in range(r):
        l, m = req[i], mat[:, i]
        oki = (l < m) | (jnp.abs(l - m) < EPS_QUANTA)
        if i >= 2:
            oki = oki | (l <= EPS_QUANTA)
        ok = oki if ok is None else (ok & oki)
    return ok


@functools.partial(jax.jit, static_argnames=("cfg",))
def solve_allocate(inp: SolverInputs, cfg: SolverConfig) -> SolveResult:
    """Optimized two-level solver with identical placement semantics.

    Outer loop = one iteration per queue-pop event (selection, overused
    gating, rotation bookkeeping — the expensive lexicographic argmins).
    Inner ``lax.while_loop`` = one iteration per task placement of the
    locked job, with a minimal body: the reference's inner task loop
    (allocate.go:125-193) never re-reads queue/job order or shares, so the
    DRF/proportion allocation updates are deferred to the pop boundary —
    outcome-identical because shares are only consulted during selection.

    Validated against solve_allocate_stepwise and the host path by the
    parity suite.
    """
    r = inp.task_req.shape[1]
    p = inp.task_req.shape[0]

    # Precompute scoring constants: shifted capacities for the integer grid
    # (ops/scoring.py — identical score integers to the host path).
    cs2, cs2_den = shifted_caps(inp.node_alloc, inp.score_shift)
    neg_inf = SCORE_NEG_INF

    def score_fn(res, used):
        return grid_score(res, used, inp.score_shift, cs2, cs2_den,
                          cfg.weights)

    def drain_job(j, carry):
        """Inner loop: place tasks of job j until the reference's task loop
        would break.  Returns (carry', survive)."""
        (idle, releasing, used, count, ports, selcnt, out_node, out_kind,
         out_order, job_ptr, job_ready_cnt, step) = carry
        start = inp.job_start[j]
        count_j = inp.job_count[j]
        minavail = inp.job_minavail[j]

        def inner_cond(ic):
            return ~ic[0]

        def place_once(ic):
            """One placement of the reference inner task loop; a no-op once
            the done flag is set (lets UNROLL placements share one loop
            iteration's dispatch overhead)."""
            (done, survive, idle, releasing, used, count, ports, selcnt,
             out_node, out_kind, out_order, ptr, ready_cnt, dstep, dres) = ic
            exhausted = ptr >= count_j
            t = inp.task_sorted[jnp.clip(start + ptr, 0, p - 1)]
            req = inp.task_req[t]
            res = inp.task_res[t]

            fit_idle = _unrolled_le(req, idle, r)
            fit_rel = _unrolled_le(req, releasing, r)
            feasible = (inp.sig_mask[inp.task_sig[t]] & inp.node_exists
                        & (count < inp.node_max_tasks) & (fit_idle | fit_rel))
            dyn = dynamic_predicate_mask(cfg, t, inp.task_ports,
                                         inp.task_aff_req, inp.task_anti,
                                         ports, selcnt)
            if dyn is not None:
                feasible = feasible & dyn

            score = score_fn(res, used)
            pa = interpod_score_term(cfg, t, inp.task_paff_w,
                                     inp.task_panti_w, selcnt)
            if pa is not None:
                score = score + pa
            score = score + inp.sig_bonus[inp.task_sig[t]]
            score = jnp.where(feasible, score, neg_inf)
            nsel = jnp.argmax(score).astype(jnp.int32)
            feasible_any = score[nsel] > neg_inf

            placing = ~done & ~exhausted & feasible_any
            alloc_ok = placing & fit_idle[nsel]
            pipe_ok = placing & ~fit_idle[nsel] & fit_rel[nsel]
            placed = alloc_ok | pipe_ok

            fres = jnp.where(placed, res, 0)
            idle = idle.at[nsel].add(jnp.where(alloc_ok, -fres, 0))
            releasing = releasing.at[nsel].add(jnp.where(pipe_ok, -fres, 0))
            used = used.at[nsel].add(fres)
            count = count.at[nsel].add(placed.astype(count.dtype))
            if cfg.has_ports:
                ports = ports.at[nsel].set(
                    ports[nsel] | (placed & inp.task_ports[t]))
            if _needs_selcnt(cfg):
                selcnt = selcnt.at[nsel].add(
                    jnp.where(placed, inp.task_match[t].astype(selcnt.dtype),
                              0))

            out_node = out_node.at[t].set(jnp.where(placed, nsel, out_node[t]))
            out_kind = out_kind.at[t].set(
                jnp.where(alloc_ok, 1, jnp.where(pipe_ok, 2, out_kind[t])))
            out_order = out_order.at[t].set(
                jnp.where(placed, dstep, out_order[t]))

            ptr = ptr + placed.astype(jnp.int32)
            ready_cnt = ready_cnt + alloc_ok.astype(jnp.int32)
            dstep = dstep + placed.astype(jnp.int32)
            dres = dres + fres

            if cfg.has_gang:
                ready = ready_cnt >= minavail
            else:
                ready = jnp.bool_(True)
            remaining = ptr < count_j
            new_done = exhausted | ~feasible_any | ready | ~remaining
            new_survive = ~exhausted & feasible_any & ready & remaining
            return (done | new_done,
                    jnp.where(done, survive, new_survive),
                    idle, releasing, used, count, ports, selcnt,
                    out_node, out_kind, out_order, ptr, ready_cnt, dstep, dres)

        def inner_body(ic):
            for _ in range(UNROLL):
                ic = place_once(ic)
            return ic

        init = (jnp.bool_(False), jnp.bool_(False), idle, releasing, used,
                count, ports, selcnt, out_node, out_kind, out_order,
                job_ptr[j], job_ready_cnt[j], step,
                jnp.zeros((r,), inp.task_res.dtype))
        (done, survive, idle, releasing, used, count, ports, selcnt,
         out_node, out_kind, out_order, ptr, ready_cnt, step,
         dres) = jax.lax.while_loop(inner_cond, inner_body, init)

        job_ptr = job_ptr.at[j].set(ptr)
        job_ready_cnt = job_ready_cnt.at[j].set(ready_cnt)
        carry = (idle, releasing, used, count, ports, selcnt, out_node,
                 out_kind, out_order, job_ptr, job_ready_cnt, step)
        return carry, survive, dres

    def outer_cond(oc):
        return oc[0].any()

    def outer_body(oc):
        (queue_active, job_active, job_alloc, queue_alloc, idle, releasing,
         used, count, ports, selcnt, out_node, out_kind, out_order, job_ptr,
         job_ready_cnt, step) = oc

        # -- queue selection (allocate.go:90-108) ---------------------------
        qkeys = []
        for name in cfg.queue_key_order:
            if name == "proportion":
                qkeys.append(queue_shares(queue_alloc,
                                          inp.queue_deserved_f))
        qkeys.extend([inp.queue_ts, inp.queue_uid_rank])
        q = _lex_argmin(queue_active, qkeys)

        if cfg.has_proportion:
            overused = less_equal_vec(inp.queue_deserved[q], queue_alloc[q],
                                      inp.eps, inp.scalar_dims)
        else:
            overused = jnp.bool_(False)

        jmask = job_active & (inp.job_queue == q)
        jkeys = []
        for name in cfg.job_key_order:
            if name == "priority":
                jkeys.append(-inp.job_prio)
            elif name == "gang":
                jkeys.append((job_ready_cnt >= inp.job_minavail)
                             .astype(inp.job_ts.dtype))
            elif name == "drf":
                jkeys.append(jnp.max(
                    safe_share(job_alloc, inp.total_res[None, :]), axis=-1))
        jkeys.extend([inp.job_ts, inp.job_uid_rank])
        j = _lex_argmin(jmask, jkeys)
        queue_has_job = jmask.any()
        retire_queue = overused | ~queue_has_job

        # -- drain the popped job ------------------------------------------
        carry = (idle, releasing, used, count, ports, selcnt, out_node,
                 out_kind, out_order, job_ptr, job_ready_cnt, step)

        def do_drain(args):
            carry, j = args
            new_carry, survive, dres = drain_job(j, carry)
            return new_carry, survive, dres

        def skip_drain(args):
            carry, _ = args
            return carry, jnp.bool_(False), jnp.zeros((r,), inp.task_res.dtype)

        carry, survive, dres = jax.lax.cond(
            retire_queue, skip_drain, do_drain, (carry, j))
        (idle, releasing, used, count, ports, selcnt, out_node, out_kind,
         out_order, job_ptr, job_ready_cnt, step) = carry

        processed = ~retire_queue
        # Deferred fairness events: one segment-add per pop boundary.
        job_alloc = job_alloc.at[j].add(jnp.where(processed, dres, 0))
        queue_alloc = queue_alloc.at[q].add(jnp.where(processed, dres, 0))
        job_active = job_active.at[j].set(
            jnp.where(processed, survive, job_active[j]))
        queue_active = queue_active.at[q].set(
            jnp.where(retire_queue, False, queue_active[q]))

        return (queue_active, job_active, job_alloc, queue_alloc, idle,
                releasing, used, count, ports, selcnt, out_node, out_kind,
                out_order, job_ptr, job_ready_cnt, step)

    jdim = inp.job_start.shape[0]
    qdim = inp.queue_deserved.shape[0]
    job_active0 = inp.queue_exists[inp.job_queue] & (inp.job_minavail >= 0)
    queue_active0 = jnp.zeros((qdim,), bool).at[inp.job_queue].set(
        True) & inp.queue_exists
    init = (queue_active0, job_active0, inp.job_init_alloc,
            inp.queue_init_alloc, inp.node_idle, inp.node_releasing,
            inp.node_used, inp.node_count, inp.node_ports, inp.node_selcnt,
            jnp.full((p,), -1, jnp.int32), jnp.zeros((p,), jnp.int32),
            jnp.full((p,), -1, jnp.int32),
            jnp.zeros((jdim,), jnp.int32), inp.job_init_ready, jnp.int32(0))
    final = jax.lax.while_loop(outer_cond, outer_body, init)
    return SolveResult(assignment=final[10], kind=final[11], order=final[12],
                       step=final[15])
