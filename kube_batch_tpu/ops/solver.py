"""The batched allocate solver: kube-batch's session loop as one XLA program.

This is the TPU-native reformulation demanded by the north star
(BASELINE.json): the reference's allocate action (allocate.go:43-195) — queue
PQ / job PQ / task PQ with DRF+proportion shares recomputed after every
single placement — becomes a ``lax.while_loop`` state machine over dense
tensors that runs entirely on device:

  * queue/job selection = lexicographic masked argmin over [Q]/[J] key
    vectors (replacing the priority queues);
  * predicates = boolean [N] feasibility vectors from epsilon-correct
    resource fit + a precomputed [S, N] static-predicate mask indexed by
    task signature (replacing the 16-goroutine fan-out,
    scheduler_helper.go:63-86);
  * scoring = the nodeorder kernel over current [N, R] state;
  * fairness = DRF / proportion share updates as segment additions.

One loop iteration performs exactly one reference-loop event (a task
placement, or a job/queue retiring from rotation), so the device trace
reproduces the host path's order-dependent outcome placement-for-placement.
Ties are broken deterministically (first index in sorted-name node order /
first max score), matching utils/scheduler_helper.py.

The state layout is chosen for SPMD sharding: all [N, ...] tensors shard
over the node axis of a device mesh (parallel/sharded.py); job/queue state
is replicated and updated identically on every device.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from .fairness import queue_shares, safe_share
from .resources import less_equal_vec
from .scoring import ScoreWeights, score_nodes

NEG_INF = -jnp.inf


class SolverInputs(NamedTuple):
    """Static per-session tensors (see models/tensor_snapshot.py)."""
    # tasks (P = padded candidate count)
    task_req: jnp.ndarray       # [P, R] launch requirement (init_resreq)
    task_res: jnp.ndarray       # [P, R] steady requirement (resreq)
    task_sig: jnp.ndarray       # [P] i32 index into sig_mask
    task_sorted: jnp.ndarray    # [P] i32 task ids in (job, task-order) order
    # jobs (J)
    job_start: jnp.ndarray      # [J] i32 offset into task_sorted
    job_count: jnp.ndarray      # [J] i32 number of candidate tasks
    job_queue: jnp.ndarray      # [J] i32 queue index
    job_minavail: jnp.ndarray   # [J] i32
    job_prio: jnp.ndarray       # [J] f  PriorityClass value
    job_ts: jnp.ndarray         # [J] f  creation timestamp
    job_uid_rank: jnp.ndarray   # [J] f  rank of UID (tie-break)
    job_init_ready: jnp.ndarray  # [J] i32 ready_task_num at session open
    job_init_alloc: jnp.ndarray  # [J, R] allocated at session open (drf)
    # queues (Q)
    queue_deserved: jnp.ndarray  # [Q, R] proportion water-fill result
    queue_init_alloc: jnp.ndarray  # [Q, R]
    queue_ts: jnp.ndarray       # [Q] f
    queue_uid_rank: jnp.ndarray  # [Q] f
    queue_exists: jnp.ndarray   # [Q] bool (padding rows False)
    # nodes (N)
    node_idle: jnp.ndarray      # [N, R]
    node_releasing: jnp.ndarray  # [N, R]
    node_used: jnp.ndarray      # [N, R]
    node_alloc: jnp.ndarray     # [N, R] allocatable (scoring denominator)
    node_count: jnp.ndarray     # [N] i32 resident task count
    node_max_tasks: jnp.ndarray  # [N] i32 pod-count cap
    node_exists: jnp.ndarray    # [N] bool (padding rows False)
    sig_mask: jnp.ndarray       # [S, N] bool static predicate mask
    # cluster
    total_res: jnp.ndarray      # [R] sum of allocatable (drf denominator)
    eps: jnp.ndarray            # [R] epsilon vector
    scalar_dims: jnp.ndarray    # [R] bool


class SolverConfig(NamedTuple):
    """Static plugin/tier structure baked into the compiled program.

    ``job_key_order``/``queue_key_order`` list the order-contributing plugins
    in tier order (session_plugins.go evaluates order fns tier by tier, first
    non-zero wins), so the lexicographic device keys reproduce the exact
    tiered chain of the loaded conf.
    """
    job_key_order: tuple = ("priority", "gang", "drf")
    queue_key_order: tuple = ("proportion",)
    has_gang: bool = True          # gang registers JobReady
    has_proportion: bool = True    # proportion registers Overused
    weights: ScoreWeights = ScoreWeights()


class SolverState(NamedTuple):
    idle: jnp.ndarray           # [N, R]
    releasing: jnp.ndarray      # [N, R]
    used: jnp.ndarray           # [N, R]
    count: jnp.ndarray          # [N] i32
    job_ptr: jnp.ndarray        # [J] i32 next task offset
    job_active: jnp.ndarray     # [J] bool still in rotation
    job_ready_cnt: jnp.ndarray  # [J] i32 dynamic ready_task_num
    job_alloc: jnp.ndarray      # [J, R] dynamic drf allocation
    queue_alloc: jnp.ndarray    # [Q, R]
    queue_active: jnp.ndarray   # [Q] bool
    locked_job: jnp.ndarray     # scalar i32, -1 when none
    assignment: jnp.ndarray     # [P] i32 node index or -1
    kind: jnp.ndarray           # [P] i32 0=none 1=allocate 2=pipeline
    order: jnp.ndarray          # [P] i32 step at which placed
    step: jnp.ndarray           # scalar i32


def _lex_argmin(mask: jnp.ndarray, keys) -> jnp.ndarray:
    """Index of the masked lexicographic minimum; assumes mask.any()."""
    for k in keys:
        kv = jnp.where(mask, k, jnp.inf)
        mask = mask & (kv == jnp.min(kv))
    return jnp.argmax(mask).astype(jnp.int32)


def _select_queue(inp: SolverInputs, st: SolverState, cfg: SolverConfig):
    """Pop the front queue (allocate.go:90-95): min share (proportion), then
    creation time, then UID."""
    keys = []
    for name in cfg.queue_key_order:
        if name == "proportion":
            keys.append(queue_shares(st.queue_alloc, inp.queue_deserved))
    keys.extend([inp.queue_ts, inp.queue_uid_rank])
    return _lex_argmin(st.queue_active, keys)


def _queue_overused(inp: SolverInputs, st: SolverState, q, cfg: SolverConfig):
    if not cfg.has_proportion:
        return jnp.bool_(False)
    return less_equal_vec(inp.queue_deserved[q], st.queue_alloc[q], inp.eps,
                          inp.scalar_dims)


def _select_job(inp: SolverInputs, st: SolverState, q, cfg: SolverConfig):
    """Pop the front job of queue q: tiered JobOrderFn chain — priority desc,
    gang not-ready first, DRF share asc, then creation time / UID
    (session_plugins.go:247-271 with the default tier layout)."""
    mask = st.job_active & (inp.job_queue == q)
    keys = []
    for name in cfg.job_key_order:
        if name == "priority":
            keys.append(-inp.job_prio)
        elif name == "gang":
            ready = (st.job_ready_cnt >= inp.job_minavail)
            keys.append(ready.astype(inp.job_ts.dtype))
        elif name == "drf":
            keys.append(jnp.max(
                safe_share(st.job_alloc, inp.total_res[None, :]), axis=-1))
    keys.extend([inp.job_ts, inp.job_uid_rank])
    return _lex_argmin(mask, keys), mask


def _job_ready(inp: SolverInputs, st: SolverState, j, cfg: SolverConfig):
    """ssn.JobReady: gang's ready_task_num >= minAvailable; True when gang is
    absent (session_plugins.go:184-203)."""
    if not cfg.has_gang:
        return jnp.bool_(True)
    return st.job_ready_cnt[j] >= inp.job_minavail[j]


def solver_step(inp: SolverInputs, cfg: SolverConfig,
                st: SolverState) -> SolverState:
    """One reference-loop event (see module docstring)."""
    have_locked = st.locked_job >= 0

    # ---- queue + job selection (skipped while a job is locked) -----------
    q_sel = _select_queue(inp, st, cfg)
    overused = _queue_overused(inp, st, q_sel, cfg)
    j_sel, job_mask = _select_job(inp, st, q_sel, cfg)
    queue_has_job = job_mask.any()
    # Queue retires from rotation when overused or jobless (allocate.go:95-108
    # `continue` without re-push).
    retire_queue = ~have_locked & (overused | ~queue_has_job)

    j = jnp.where(have_locked, st.locked_job, j_sel)
    act = ~retire_queue  # this iteration processes a task of job j
    jq = inp.job_queue[j]

    # ---- task of job j ----------------------------------------------------
    ptr = st.job_ptr[j]
    exhausted = ptr >= inp.job_count[j]
    t = inp.task_sorted[jnp.clip(inp.job_start[j] + ptr, 0,
                                 inp.task_sorted.shape[0] - 1)]

    req = inp.task_req[t]
    res = inp.task_res[t]

    fit_idle = less_equal_vec(req[None, :], st.idle, inp.eps, inp.scalar_dims)
    fit_rel = less_equal_vec(req[None, :], st.releasing, inp.eps,
                             inp.scalar_dims)
    feasible = (inp.sig_mask[inp.task_sig[t]] & inp.node_exists
                & (st.count < inp.node_max_tasks) & (fit_idle | fit_rel))
    any_feasible = feasible.any()

    placing = act & ~exhausted & any_feasible

    score = score_nodes(res, st.used, inp.node_alloc, cfg.weights)
    score = jnp.where(feasible, score, NEG_INF)
    # first max = deterministic tie-break
    n = jnp.argmax(score).astype(jnp.int32)

    alloc_ok = placing & fit_idle[n]
    pipe_ok = placing & ~fit_idle[n] & fit_rel[n]
    placed = alloc_ok | pipe_ok

    # ---- state updates ----------------------------------------------------
    dres = jnp.where(placed, 1.0, 0.0).astype(res.dtype) * res
    idle = st.idle.at[n].add(jnp.where(alloc_ok, -dres, 0.0))
    releasing = st.releasing.at[n].add(jnp.where(pipe_ok, -dres, 0.0))
    used = st.used.at[n].add(dres)
    count = st.count.at[n].add(placed.astype(st.count.dtype))

    # Event handlers fire for both allocate and pipeline (session.go:269-275):
    # DRF job share and proportion queue share grow by resreq.
    job_alloc = st.job_alloc.at[j].add(dres)
    queue_alloc = st.queue_alloc.at[jq].add(dres)
    job_ready_cnt = st.job_ready_cnt.at[j].add(alloc_ok.astype(jnp.int32))

    consumed = act & ~exhausted & any_feasible  # task consumed even if placed on neither (can't happen; kept for clarity)
    job_ptr = st.job_ptr.at[j].add(consumed.astype(jnp.int32))

    assignment = st.assignment.at[t].set(
        jnp.where(placed, n, st.assignment[t]))
    kind = st.kind.at[t].set(
        jnp.where(alloc_ok, 1, jnp.where(pipe_ok, 2, st.kind[t])))
    order = st.order.at[t].set(
        jnp.where(placed, st.step, st.order[t]))

    # ---- rotation bookkeeping ---------------------------------------------
    st2 = st._replace(job_ready_cnt=job_ready_cnt)
    now_ready = _job_ready(inp, st2, j, cfg)
    remaining = job_ptr[j] < inp.job_count[j]

    # Job leaves rotation on: exhausted-at-pop, predicate-dead-end
    # (allocate.go:146-150 break), or task loop ending without a re-push
    # (ready with tasks remaining is the only re-push, allocate.go:185-188).
    job_dies = act & (exhausted | (~any_feasible)
                      | (~remaining))
    job_active = st.job_active.at[j].set(
        jnp.where(job_dies, False, st.job_active[j]))

    # Lock semantics: keep draining this job's tasks until it turns ready or
    # dies (the inner `for !tasks.Empty()` loop).
    stay_locked = act & placed & ~now_ready & remaining
    locked_job = jnp.where(stay_locked, j, -1)

    queue_active = st.queue_active.at[q_sel].set(
        jnp.where(retire_queue, False, st.queue_active[q_sel]))

    return SolverState(
        idle=idle, releasing=releasing, used=used, count=count,
        job_ptr=job_ptr, job_active=job_active,
        job_ready_cnt=job_ready_cnt, job_alloc=job_alloc,
        queue_alloc=queue_alloc, queue_active=queue_active,
        locked_job=locked_job, assignment=assignment, kind=kind,
        order=order, step=st.step + 1)


def initial_state(inp: SolverInputs) -> SolverState:
    p = inp.task_req.shape[0]
    j = inp.job_start.shape[0]
    q = inp.queue_deserved.shape[0]
    # Jobs enter rotation when their queue exists (allocate.go:52-65 pushes
    # every job whose queue is found, even with zero pending tasks).
    job_active = inp.queue_exists[inp.job_queue] & (inp.job_minavail >= 0)
    # Queues enter rotation when any job references them.
    queue_active = jnp.zeros((q,), dtype=bool).at[inp.job_queue].set(
        True) & inp.queue_exists
    return SolverState(
        idle=inp.node_idle, releasing=inp.node_releasing, used=inp.node_used,
        count=inp.node_count,
        job_ptr=jnp.zeros((j,), jnp.int32), job_active=job_active,
        job_ready_cnt=inp.job_init_ready, job_alloc=inp.job_init_alloc,
        queue_alloc=inp.queue_init_alloc, queue_active=queue_active,
        locked_job=jnp.int32(-1),
        assignment=jnp.full((p,), -1, jnp.int32),
        kind=jnp.zeros((p,), jnp.int32),
        order=jnp.full((p,), -1, jnp.int32),
        step=jnp.int32(0))


@functools.partial(jax.jit, static_argnames=("cfg",))
def solve_allocate(inp: SolverInputs, cfg: SolverConfig) -> SolverState:
    """Run the session's allocate loop to completion on device."""
    st = initial_state(inp)

    def cond(st: SolverState):
        return st.queue_active.any() | (st.locked_job >= 0)

    return jax.lax.while_loop(cond, lambda s: solver_step(inp, cfg, s), st)
