"""Pallas TPU kernels for the allocate solver.

Two levels of kernelization over ops/solver.py's two-level XLA solver:

* ``solve_allocate_pallas``: the whole session solve — queue/job selection,
  fairness shares, and every placement — as ONE Pallas kernel.  Device loop
  iterations in XLA cost ~35µs each in kernel dispatch on TPU runtimes; in
  a single kernel a placement costs only its actual VPU work (a dozen
  vector ops over [rows, N] node state resident in VMEM), and a queue/job
  pop costs vector ops over [1, J]/[1, Q] rows.

State layout (rows padded to sublane multiples of 8):

  node_int [3R+3 -> pad8, N] i32: idle[0:R], releasing[R:2R], used[2R:3R],
      count, pod cap, exists flag — ALL resource state is int32 quanta
      (ops/resources.py), so every add/subtract and epsilon compare in the
      loop is exact integer math (f32 rows would drift past 2**24).
  node_cs  [2 -> 8, N] i32: shift-normalized cpu/mem capacities for the
      integer-grid scorer (ops/scoring.py; shifts ride scal_ref SMEM).
  job_sta  [8, J] float: start, count, queue, minavail, priority, ts,
      uid_rank (ints here stay < 2**24, exact in f32)
  job_dyn  [R+3 -> pad8, J] i32: drf alloc rows, ptr, ready_cnt, active
  que_des  [R -> pad8, Q] i32: proportion deserved (exact for the
      epsilon-overused compare)
  que_sta  [3+R -> pad8, Q] float: ts, uid_rank, exists, then UNrounded
      deserved rows (share denominators; the int que_des rows serve the
      epsilon overused compare)
  que_dyn  [R+1 -> pad8, Q] i32: alloc rows, active

Placement updates are rank-1 (delta-column ⊗ one-hot) adds.  Ties break
first-in-order everywhere (Mosaic's argmax picks the LAST max, so argmax is
implemented as max + min-index-where-equal).  Shares/scores convert the
exact ints to float only at the division.

Semantics match ops/solver.solve_allocate placement-for-placement;
cross-validated by tests/test_pallas_solver.py (interpreter mode) and on
real TPU by bench.py's parity assert.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .fairness import safe_share
from .resources import EPS_QUANTA, SCORE_GRID_K
from .scoring import SCORE_NEG_INF
from .solver import SolveResult, SolverConfig, SolverInputs


def _pad8(x: int) -> int:
    return ((x + 7) // 8) * 8


def _solve_kernel(r: int, np_pad: int, ns_pad: int, cfg: SolverConfig,
                  scal_ref, total_ref, task_ref, sig_ref, sig_mask_ref,
                  sig_bonus_ref, nint_in, ncs_ref, out_in, jdyn_in, qdyn_in,
                  nport_in, nsel_in, jsta_ref, qsta_ref, qdes_ref,
                  nint_ref, out_ref, jdyn_ref, qdyn_ref, nport_ref,
                  nsel_ref, scal_out_ref):
    """One kernel = one full session solve.  scal_ref (SMEM [1,8] i32):
    [0]=P, [2]=cpu grid shift, [3]=mem grid shift.  total_ref (SMEM [1,R]
    float): cluster totals (DRF denominator).  The *_in refs are aliased
    input views of the corresponding output refs."""
    n = nint_ref.shape[1]
    jdim = jsta_ref.shape[1]
    qdim = qsta_ref.shape[1]
    dtype = jsta_ref.dtype            # float dtype for keys/scores
    inf = jnp.asarray(jnp.inf, dtype)
    neg_inf = -inf

    col_n = jax.lax.broadcasted_iota(jnp.int32, (1, n), 1)
    col_j = jax.lax.broadcasted_iota(jnp.int32, (1, jdim), 1)
    col_q = jax.lax.broadcasted_iota(jnp.int32, (1, qdim), 1)

    # node_int row indices
    IDLE, REL, USED = 0, r, 2 * r
    CNT, CAP, EXISTS = 3 * r, 3 * r + 1, 3 * r + 2
    # node_cs rows: shifted cpu/mem capacities
    CS = 0
    # task_ref column offsets: [req 0:r][res r:2r][ports][aff][anti][match]
    PORTS_OFF = 2 * r
    AFF_OFF = PORTS_OFF + np_pad
    ANTI_OFF = AFF_OFF + ns_pad
    MATCH_OFF = ANTI_OFF + ns_pad
    PAFFW_OFF = MATCH_OFF + ns_pad
    PANTIW_OFF = PAFFW_OFF + ns_pad
    # job_sta rows
    JSTART, JCOUNT, JQUEUE, JMIN, JPRIO, JTS, JUID = 0, 1, 2, 3, 4, 5, 6
    # job_dyn rows: [0:r] alloc, then ptr, ready, active
    JPTR, JREADY, JACT = r, r + 1, r + 2
    # que_sta rows: ts, uid_rank, exists, then float deserved rows
    QTS, QUID = 0, 1
    QDESF = 3
    # que_dyn rows: [0:r] alloc, active
    QACT = r

    w_least = int(cfg.weights.least_requested)
    w_most = int(cfg.weights.most_requested)
    w_bal = int(cfg.weights.balanced_resource)
    neg_score = SCORE_NEG_INF

    def scalar_at(row, hot):
        """Extract row value at the one-hot lane (float rows)."""
        return jnp.sum(jnp.where(hot, row, 0.0))

    def scalar_at_i(row, hot):
        """Extract row value at the one-hot lane (int rows).  The sum
        dtype is pinned: under jax_enable_x64 an unpinned integer sum
        widens to int64, which poisons the while-loop carries and the
        int32 ref writebacks."""
        return jnp.sum(jnp.where(hot, row, 0), dtype=jnp.int32)

    def lex_first(mask, keys):
        m = mask
        for k in keys:
            kv = jnp.where(m, k, inf)
            m = m & (kv == jnp.min(kv))
        return m

    def queue_share_row():
        """[1, Q] proportion shares: max_r safe_share(alloc_r, deserved_r)
        over the UNrounded float deserved rows (the int rows serve only the
        epsilon overused compare; rounding would flip near-tied shares).
        The ONE share implementation (ops.fairness.safe_share — float32 of
        float32 operands on every engine) runs on values loaded from the
        refs, so near-tie ordering matches the host and the XLA paths
        exactly."""
        share = jnp.zeros((1, qdim), jnp.float32)
        for i in range(r):
            share = jnp.maximum(share, safe_share(
                qdyn_ref[i:i + 1, :], qsta_ref[QDESF + i:QDESF + i + 1, :]))
        return share.astype(dtype)

    def drf_share_row():
        share = jnp.zeros((1, jdim), jnp.float32)
        for i in range(r):
            share = jnp.maximum(share, safe_share(jdyn_ref[i:i + 1, :],
                                                  total_ref[0, i]))
        return share.astype(dtype)

    def outer_body(carry):
        _, step = carry

        # ---- queue pop (allocate.go:90-108) -------------------------------
        q_active = qdyn_ref[QACT:QACT + 1, :] > 0
        qkeys = []
        for name in cfg.queue_key_order:
            if name == "proportion":
                qkeys.append(queue_share_row())
        qkeys.append(qsta_ref[QTS:QTS + 1, :])
        qkeys.append(qsta_ref[QUID:QUID + 1, :])
        qmask = lex_first(q_active, qkeys)
        q = jnp.min(jnp.where(qmask, col_q, qdim)).astype(jnp.int32)
        qhot = col_q == q

        if cfg.has_proportion:
            ou = jnp.bool_(True)
            for i in range(r):
                des = scalar_at_i(qdes_ref[i:i + 1, :], qhot)
                alc = scalar_at_i(qdyn_ref[i:i + 1, :], qhot)
                oki = (des < alc) | (jnp.abs(des - alc) < EPS_QUANTA)
                if i >= 2:
                    oki = oki | (des <= EPS_QUANTA)
                ou = ou & oki
            overused = ou
        else:
            overused = jnp.bool_(False)

        # ---- job pop (tiered JobOrderFn chain) ----------------------------
        jq = jsta_ref[JQUEUE:JQUEUE + 1, :]
        j_active = (jdyn_ref[JACT:JACT + 1, :] > 0) \
            & (jq == q.astype(dtype))
        jkeys = []
        for name in cfg.job_key_order:
            if name == "priority":
                jkeys.append(-jsta_ref[JPRIO:JPRIO + 1, :])
            elif name == "gang":
                ready_row = (jdyn_ref[JREADY:JREADY + 1, :].astype(dtype)
                             >= jsta_ref[JMIN:JMIN + 1, :])
                jkeys.append(ready_row.astype(dtype))
            elif name == "drf":
                jkeys.append(drf_share_row())
        jkeys.append(jsta_ref[JTS:JTS + 1, :])
        jkeys.append(jsta_ref[JUID:JUID + 1, :])
        jmask = lex_first(j_active, jkeys)
        j = jnp.min(jnp.where(jmask, col_j, jdim)).astype(jnp.int32)
        jhot = col_j == j
        has_job = j < jdim

        retire = overused | ~has_job

        start = scalar_at(jsta_ref[JSTART:JSTART + 1, :], jhot).astype(jnp.int32)
        count_j = jnp.where(retire, 0,
                            scalar_at(jsta_ref[JCOUNT:JCOUNT + 1, :], jhot)
                            ).astype(jnp.int32)
        minavail = scalar_at(jsta_ref[JMIN:JMIN + 1, :], jhot).astype(jnp.int32)
        ptr0 = scalar_at_i(jdyn_ref[JPTR:JPTR + 1, :], jhot)
        ready0 = scalar_at_i(jdyn_ref[JREADY:JREADY + 1, :], jhot)

        # ---- drain the popped job (allocate.go:125-193) -------------------
        def drain_body(ic):
            done, survive, ptr, ready_cnt, dstep, dres = ic
            exhausted = ptr >= count_j
            t = jnp.clip(start + ptr, 0, task_ref.shape[0] - 1)
            req = [task_ref[t, i] for i in range(r)]
            res = [task_ref[t, r + i] for i in range(r)]
            sig = sig_ref[t, 0]

            fit_idle = None
            fit_rel = None
            for i in range(r):
                mi = nint_ref[IDLE + i:IDLE + i + 1, :]
                mr = nint_ref[REL + i:REL + i + 1, :]
                oki = (req[i] < mi) | (jnp.abs(req[i] - mi) < EPS_QUANTA)
                okr = (req[i] < mr) | (jnp.abs(req[i] - mr) < EPS_QUANTA)
                if i >= 2:
                    low = req[i] <= EPS_QUANTA
                    oki = oki | low
                    okr = okr | low
                fit_idle = oki if fit_idle is None else (fit_idle & oki)
                fit_rel = okr if fit_rel is None else (fit_rel & okr)

            sig_row = sig_mask_ref[pl.ds(sig, 1), :] > 0.5
            cap_ok = nint_ref[CNT:CNT + 1, :] < nint_ref[CAP:CAP + 1, :]
            exists = nint_ref[EXISTS:EXISTS + 1, :] > 0
            feasible = sig_row & exists & cap_ok & (fit_idle | fit_rel)
            # Dynamic predicates from occupancy rows (predicates.go:174,
            # :249-262); padded rows are all-zero no-ops.
            if cfg.has_ports:
                conflict = jnp.zeros((1, n), bool)
                for i in range(np_pad):
                    tp = task_ref[t, PORTS_OFF + i]
                    conflict = conflict | ((tp > 0)
                                           & (nport_ref[i:i + 1, :] > 0))
                feasible = feasible & ~conflict
            if cfg.has_pod_affinity:
                # Boolean algebra only: Mosaic can't legalize select on i1
                # vectors, so (need ? have : True) becomes (~need | have).
                aff_ok = jnp.ones((1, n), bool)
                for s in range(ns_pad):
                    have = nsel_ref[s:s + 1, :] > 0
                    need = task_ref[t, AFF_OFF + s] > 0
                    forbid = task_ref[t, ANTI_OFF + s] > 0
                    aff_ok = aff_ok & (~need | have) & (~forbid | ~have)
                feasible = feasible & aff_ok

            # Integer grid scoring (ops/scoring.py): exact ints, identical
            # to host and XLA paths on every platform.
            g = []
            for d in range(2):
                s = scal_ref[0, 2 + d]
                cs = ncs_ref[CS + d:CS + d + 1, :]
                used_d = nint_ref[USED + d:USED + d + 1, :]
                xs = jnp.minimum(
                    jax.lax.shift_right_logical(used_d + res[d], s), cs)
                q = ((xs * SCORE_GRID_K).astype(dtype)
                     / jnp.maximum(cs, 1).astype(dtype)).astype(jnp.int32)
                g.append(jnp.where(cs == 0, SCORE_GRID_K, q))
            gc, gm = g
            score = jnp.zeros((1, n), jnp.int32)
            if w_least:
                score = score + w_least * 5 * (2 * SCORE_GRID_K - gc - gm)
            if w_most:
                score = score + w_most * 5 * (gc + gm)
            if w_bal:
                score = score + w_bal * (10 * SCORE_GRID_K
                                         - 10 * jnp.abs(gc - gm))
            if cfg.has_pod_affinity_score:
                # InterPodAffinity priority (nodeorder.go:107-131 analog).
                for s in range(ns_pad):
                    wd = task_ref[t, PAFFW_OFF + s] \
                        - task_ref[t, PANTIW_OFF + s]
                    score = score + SCORE_GRID_K * wd * nsel_ref[s:s + 1, :]
            score = score + sig_bonus_ref[pl.ds(sig, 1), :]
            score = jnp.where(feasible, score, neg_score)

            best = jnp.max(score)
            nsel = jnp.min(jnp.where(score == best, col_n, n)).astype(jnp.int32)
            feasible_any = best > neg_score
            onehot = col_n == nsel
            pick = lambda v: jnp.sum(
                jnp.where(onehot, v.astype(jnp.int32), 0)) > 0
            fit_idle_n = pick(fit_idle)
            fit_rel_n = pick(fit_rel)

            placing = ~done & ~exhausted & feasible_any
            alloc_ok = placing & fit_idle_n
            pipe_ok = placing & ~fit_idle_n & fit_rel_n
            placed = alloc_ok | pipe_ok

            ai = alloc_ok.astype(jnp.int32)
            pi = pipe_ok.astype(jnp.int32)
            pli = placed.astype(jnp.int32)
            # Rank-1 integer update over the dynamic rows only (idle,
            # releasing, used, count); the static rows below never change.
            ndyn = 3 * r + 1
            delta_col = [(-ai * res[i]) for i in range(r)] \
                + [(-pi * res[i]) for i in range(r)] \
                + [(pli * res[i]) for i in range(r)] + [pli]
            delta = jnp.stack(delta_col).reshape(ndyn, 1)
            nint_ref[0:ndyn, :] = nint_ref[0:ndyn, :] \
                + delta * onehot.astype(jnp.int32)

            row = jnp.stack([jnp.where(placed, nsel, -1),
                             jnp.where(alloc_ok, 1,
                                       jnp.where(pipe_ok, 2, 0)),
                             jnp.where(placed, dstep, -1),
                             jnp.int32(0)]).reshape(1, 4)

            @pl.when(placed)
            def _():
                out_ref[pl.ds(t, 1), :] = row

            if cfg.has_ports:
                for i in range(np_pad):
                    tp = task_ref[t, PORTS_OFF + i]
                    nport_ref[i:i + 1, :] = nport_ref[i:i + 1, :] \
                        | (onehot.astype(jnp.int32) * (pli * tp))
            if cfg.has_pod_affinity or cfg.has_pod_affinity_score:
                for s in range(ns_pad):
                    m = task_ref[t, MATCH_OFF + s]
                    nsel_ref[s:s + 1, :] = nsel_ref[s:s + 1, :] \
                        + onehot.astype(jnp.int32) * (pli * m)

            ptr = ptr + pli
            ready_cnt = ready_cnt + ai
            dstep = dstep + pli
            dres = dres + pli * jnp.stack(res).reshape(1, r)

            if cfg.has_gang:
                ready = ready_cnt >= minavail
            else:
                ready = jnp.bool_(True)
            remaining = ptr < count_j
            new_done = exhausted | ~feasible_any | ready | ~remaining
            new_survive = ~exhausted & feasible_any & ready & remaining
            return (done | new_done, jnp.where(done, survive, new_survive),
                    ptr, ready_cnt, dstep, dres)

        init = (jnp.bool_(False), jnp.bool_(False), ptr0, ready0, step,
                jnp.zeros((1, r), jnp.int32))
        done, survive, ptr, ready_cnt, step, dres = jax.lax.while_loop(
            lambda c: ~c[0], drain_body, init)

        # ---- writeback + rotation (allocate.go:185-193) -------------------
        proc_i = (~retire).astype(jnp.int32)
        jhot_i = jhot.astype(jnp.int32) * proc_i
        qhot_i = qhot.astype(jnp.int32) * proc_i
        for i in range(r):
            jdyn_ref[i:i + 1, :] = jdyn_ref[i:i + 1, :] + dres[0, i] * jhot_i
            qdyn_ref[i:i + 1, :] = qdyn_ref[i:i + 1, :] + dres[0, i] * qhot_i
        jdyn_ref[JPTR:JPTR + 1, :] = jnp.where(
            jhot_i > 0, ptr, jdyn_ref[JPTR:JPTR + 1, :])
        jdyn_ref[JREADY:JREADY + 1, :] = jnp.where(
            jhot_i > 0, ready_cnt, jdyn_ref[JREADY:JREADY + 1, :])
        jdyn_ref[JACT:JACT + 1, :] = jnp.where(
            jhot_i > 0, jnp.where(survive, 1, 0),
            jdyn_ref[JACT:JACT + 1, :])
        qdyn_ref[QACT:QACT + 1, :] = jnp.where(
            (qhot & retire), 0, qdyn_ref[QACT:QACT + 1, :])

        any_active = jnp.max(qdyn_ref[QACT:QACT + 1, :]) > 0
        return any_active, step

    any0 = jnp.max(qdyn_in[QACT:QACT + 1, :]) > 0
    _, total_steps = jax.lax.while_loop(
        lambda c: c[0], outer_body, (any0, scal_ref[0, 1]))
    scal_out_ref[0, 0] = total_steps


def _build_buffers(inp: SolverInputs):
    r = inp.task_req.shape[1]
    n = inp.node_idle.shape[0]
    fdt = inp.job_ts.dtype
    ni_rows = _pad8(3 * r + 3)

    i32 = lambda x: x.astype(jnp.int32)
    cs2 = jnp.stack(
        [jnp.right_shift(i32(inp.node_alloc[:, d]), inp.score_shift[d])
         for d in range(2)], axis=0)
    node_int = jnp.concatenate(
        [i32(inp.node_idle).T, i32(inp.node_releasing).T, i32(inp.node_used).T,
         i32(inp.node_count)[None, :], i32(inp.node_max_tasks)[None, :],
         i32(inp.node_exists)[None, :]], axis=0)
    node_int = jnp.concatenate(
        [node_int, jnp.zeros((ni_rows - node_int.shape[0], n), jnp.int32)],
        axis=0)
    node_cs = jnp.concatenate(
        [cs2, jnp.zeros((8 - 2, n), jnp.int32)], axis=0)

    f = lambda x: x.astype(fdt)[None, :]
    jdim = inp.job_start.shape[0]
    job_active0 = (inp.queue_exists[inp.job_queue]
                   & (inp.job_minavail >= 0)).astype(jnp.int32)
    jsta = jnp.concatenate([
        f(inp.job_start), f(inp.job_count), f(inp.job_queue),
        f(inp.job_minavail), f(inp.job_prio), f(inp.job_ts),
        f(inp.job_uid_rank), jnp.zeros((1, jdim), fdt)], axis=0)
    jd_rows = _pad8(r + 3)
    jdyn = jnp.concatenate([
        i32(inp.job_init_alloc).T,
        jnp.zeros((1, jdim), jnp.int32),  # ptr
        i32(inp.job_init_ready)[None, :],
        job_active0[None, :],
        jnp.zeros((jd_rows - r - 3, jdim), jnp.int32)], axis=0)

    qdim = inp.queue_deserved.shape[0]
    queue_active0 = (jnp.zeros((qdim,), bool).at[inp.job_queue].set(True)
                     & inp.queue_exists).astype(jnp.int32)
    qdes = jnp.concatenate(
        [i32(inp.queue_deserved).T,
         jnp.zeros((_pad8(r) - r, qdim), jnp.int32)], axis=0)
    qs_rows = _pad8(3 + r)
    qsta = jnp.concatenate([
        f(inp.queue_ts), f(inp.queue_uid_rank), f(inp.queue_exists),
        inp.queue_deserved_f.T.astype(fdt),
        jnp.zeros((qs_rows - 3 - r, qdim), fdt)], axis=0)
    qd_rows = _pad8(r + 1)
    qdyn = jnp.concatenate([
        i32(inp.queue_init_alloc).T,
        queue_active0[None, :],
        jnp.zeros((qd_rows - r - 1, qdim), jnp.int32)], axis=0)
    return node_int, node_cs, jsta, jdyn, qdes, qsta, qdyn


@functools.partial(jax.jit, static_argnames=("cfg", "interpret"))
def solve_allocate_pallas(inp: SolverInputs, cfg: SolverConfig,
                          interpret: bool = False) -> SolveResult:
    """Full-session solve as a single Pallas kernel launch."""
    r = inp.task_req.shape[1]
    p = inp.task_req.shape[0]
    fdt = inp.job_ts.dtype

    i32c = lambda x: x.astype(jnp.int32)
    task_data = jnp.concatenate(
        [i32c(inp.task_req), i32c(inp.task_res), i32c(inp.task_ports),
         i32c(inp.task_aff_req), i32c(inp.task_anti), i32c(inp.task_match),
         i32c(inp.task_paff_w), i32c(inp.task_panti_w)],
        axis=1)
    np_pad = inp.task_ports.shape[1]
    ns_pad = inp.task_aff_req.shape[1]
    # bucket() widths are powers of two >= 8, already sublane-aligned.
    assert np_pad % 8 == 0 and ns_pad % 8 == 0
    nport = i32c(inp.node_ports).T
    nsel = i32c(inp.node_selcnt).T
    task_sig2 = inp.task_sig[:, None]
    sig_mask_f = inp.sig_mask.astype(fdt)
    sig_bonus = inp.sig_bonus.astype(jnp.int32)
    (node_int, node_cs, jsta, jdyn, qdes, qsta,
     qdyn) = _build_buffers(inp)
    out_buf0 = jnp.concatenate(
        [jnp.full((p, 1), -1, jnp.int32), jnp.zeros((p, 1), jnp.int32),
         jnp.full((p, 1), -1, jnp.int32), jnp.zeros((p, 1), jnp.int32)],
        axis=1)
    scal = jnp.concatenate(
        [jnp.asarray([p, 0], jnp.int32), inp.score_shift.astype(jnp.int32),
         jnp.zeros((4,), jnp.int32)])[None, :]
    total = inp.total_res.astype(fdt)[None, :]

    kernel = functools.partial(_solve_kernel, r, np_pad, ns_pad, cfg)
    ni_rows, n = node_int.shape
    vmem = pl.BlockSpec(memory_space=pltpu.VMEM)
    smem = pl.BlockSpec(memory_space=pltpu.SMEM)
    outs = pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct((ni_rows, n), jnp.int32),
                   jax.ShapeDtypeStruct((p, 4), jnp.int32),
                   jax.ShapeDtypeStruct(jdyn.shape, jnp.int32),
                   jax.ShapeDtypeStruct(qdyn.shape, jnp.int32),
                   jax.ShapeDtypeStruct(nport.shape, jnp.int32),
                   jax.ShapeDtypeStruct(nsel.shape, jnp.int32),
                   jax.ShapeDtypeStruct((1, 8), jnp.int32)),
        in_specs=[smem, smem] + [vmem] * 14,
        out_specs=(vmem, vmem, vmem, vmem, vmem, vmem, smem),
        input_output_aliases={6: 0, 8: 1, 9: 2, 10: 3, 11: 4, 12: 5},
        interpret=interpret,
    )(scal, total, task_data, task_sig2, sig_mask_f, sig_bonus,
      node_int, node_cs, out_buf0, jdyn, qdyn, nport, nsel,
      jsta, qsta, qdes)

    out = outs[1]
    return SolveResult(assignment=out[:, 0], kind=out[:, 1],
                       order=out[:, 2], step=outs[6][0, 0])
