"""Pallas TPU kernels for the allocate solver.

Two levels of kernelization over ops/solver.py's two-level XLA solver:

* ``solve_allocate_pallas``: the whole session solve — queue/job selection,
  fairness shares, and every placement — as ONE Pallas kernel.  Device loop
  iterations in XLA cost ~35µs each in kernel dispatch on TPU runtimes; in
  a single kernel a placement costs only its actual VPU work (a dozen
  vector ops over [rows, N] node state resident in VMEM), and a queue/job
  pop costs vector ops over [1, J]/[1, Q] rows.

State layout (all float rows, padded to sublane multiples of 8):

  node_buf [NROWS, N]: idle[0:R], releasing[R:2R], used[2R:3R], count,
      pod cap, exists flag, 1/alloc(cpu,mem), alloc==0 flags(cpu,mem)
  job_sta  [8, J]: start, count, queue, minavail, priority, ts, uid_rank
  job_dyn  [R+3 -> 8, J]: drf alloc rows, ptr, ready_cnt, active
  que_sta  [R+3 -> 8, Q]: deserved rows, ts, uid_rank, exists
  que_dyn  [R+1 -> 8, Q]: alloc rows, active

Placement updates are rank-1 (delta-column ⊗ one-hot) adds.  Ties break
first-in-order everywhere (Mosaic's argmax picks the LAST max, so argmax is
implemented as max + min-index-where-equal).

Semantics match ops/solver.solve_allocate placement-for-placement;
cross-validated by tests/test_pallas_solver.py (interpreter mode) and on
real TPU by bench.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..api.resource import MIN_MEMORY, MIN_MILLI_CPU, MIN_MILLI_SCALAR
from .solver import SolveResult, SolverConfig, SolverInputs


def _pad8(x: int) -> int:
    return ((x + 7) // 8) * 8


def _eps_for_dim(i: int) -> float:
    return (MIN_MILLI_CPU, MIN_MEMORY)[i] if i < 2 else MIN_MILLI_SCALAR


def _first_min_index(mask, values, col_ids, size):
    """Index of the first masked minimum (lexicographic building block)."""
    kv = jnp.where(mask, values, jnp.inf)
    m = mask & (kv == jnp.min(kv))
    return m


def _solve_kernel(r: int, cfg: SolverConfig,
                  scal_ref, total_ref, task_ref, sig_ref, sig_mask_ref,
                  node_in, out_in, jdyn_in, qdyn_in, jsta_ref, qsta_ref,
                  node_ref, out_ref, jdyn_ref, qdyn_ref, scal_out_ref):
    """One kernel = one full session solve.  scal_ref (SMEM [1,8] i32):
    [0]=P.  total_ref (SMEM [1,R] float): cluster totals (DRF denominator).
    The *_in refs are aliased input views of the corresponding output refs."""
    n = node_ref.shape[1]
    jdim = jsta_ref.shape[1]
    qdim = qsta_ref.shape[1]
    nrows = node_ref.shape[0]
    dtype = node_ref.dtype
    inf = jnp.asarray(jnp.inf, dtype)
    neg_inf = -inf

    col_n = jax.lax.broadcasted_iota(jnp.int32, (1, n), 1)
    col_j = jax.lax.broadcasted_iota(jnp.int32, (1, jdim), 1)
    col_q = jax.lax.broadcasted_iota(jnp.int32, (1, qdim), 1)

    # node_buf row indices
    IDLE, REL, USED = 0, r, 2 * r
    CNT, CAP, EXISTS = 3 * r, 3 * r + 1, 3 * r + 2
    INV, ZERO = 3 * r + 3, 3 * r + 5
    # job_sta rows
    JSTART, JCOUNT, JQUEUE, JMIN, JPRIO, JTS, JUID = 0, 1, 2, 3, 4, 5, 6
    # job_dyn rows: [0:r] alloc, then ptr, ready, active
    JPTR, JREADY, JACT = r, r + 1, r + 2
    # que_sta rows: [0:r] deserved, ts, uid, exists
    QTS, QUID = r, r + 1
    # que_dyn rows: [0:r] alloc, active
    QACT = r

    w_least = float(cfg.weights.least_requested)
    w_most = float(cfg.weights.most_requested)
    w_bal = float(cfg.weights.balanced_resource)

    def scalar_at(row, hot):
        """Extract row value at the one-hot lane."""
        return jnp.sum(jnp.where(hot, row, 0.0))

    def lex_first(mask, keys, col_ids):
        m = mask
        for k in keys:
            kv = jnp.where(m, k, inf)
            m = m & (kv == jnp.min(kv))
        return m

    def queue_share_row():
        """[1, Q] proportion shares: max_r safe_share(alloc_r, deserved_r)."""
        share = jnp.zeros((1, qdim), dtype)
        for i in range(r):
            alloc = qdyn_ref[i:i + 1, :]
            des = qsta_ref[i:i + 1, :]
            s = jnp.where(des == 0, jnp.where(alloc == 0, 0.0, 1.0),
                          alloc / jnp.where(des == 0, 1.0, des))
            share = jnp.maximum(share, s)
        return share

    def drf_share_row():
        share = jnp.zeros((1, jdim), dtype)
        for i in range(r):
            alloc = jdyn_ref[i:i + 1, :]
            t = total_ref[0, i]
            s = jnp.where(t == 0, jnp.where(alloc == 0, 0.0, 1.0),
                          alloc / jnp.where(t == 0, 1.0, t))
            share = jnp.maximum(share, s)
        return share

    def outer_body(carry):
        _, step = carry

        # ---- queue pop (allocate.go:90-108) -------------------------------
        q_active = qdyn_ref[QACT:QACT + 1, :] > 0.5
        qkeys = []
        for name in cfg.queue_key_order:
            if name == "proportion":
                qkeys.append(queue_share_row())
        qkeys.append(qsta_ref[QTS:QTS + 1, :])
        qkeys.append(qsta_ref[QUID:QUID + 1, :])
        qmask = lex_first(q_active, qkeys, col_q)
        q = jnp.min(jnp.where(qmask, col_q, qdim)).astype(jnp.int32)
        qhot = col_q == q

        if cfg.has_proportion:
            ou = jnp.bool_(True)
            for i in range(r):
                e = _eps_for_dim(i)
                des = scalar_at(qsta_ref[i:i + 1, :], qhot)
                alc = scalar_at(qdyn_ref[i:i + 1, :], qhot)
                oki = (des < alc) | (jnp.abs(des - alc) < e)
                if i >= 2:
                    oki = oki | (des <= e)
                ou = ou & oki
            overused = ou
        else:
            overused = jnp.bool_(False)

        # ---- job pop (tiered JobOrderFn chain) ----------------------------
        jq = jsta_ref[JQUEUE:JQUEUE + 1, :]
        j_active = (jdyn_ref[JACT:JACT + 1, :] > 0.5) \
            & (jq == q.astype(dtype))
        jkeys = []
        for name in cfg.job_key_order:
            if name == "priority":
                jkeys.append(-jsta_ref[JPRIO:JPRIO + 1, :])
            elif name == "gang":
                ready_row = (jdyn_ref[JREADY:JREADY + 1, :]
                             >= jsta_ref[JMIN:JMIN + 1, :])
                jkeys.append(ready_row.astype(dtype))
            elif name == "drf":
                jkeys.append(drf_share_row())
        jkeys.append(jsta_ref[JTS:JTS + 1, :])
        jkeys.append(jsta_ref[JUID:JUID + 1, :])
        jmask = lex_first(j_active, jkeys, col_j)
        j = jnp.min(jnp.where(jmask, col_j, jdim)).astype(jnp.int32)
        jhot = col_j == j
        has_job = j < jdim

        retire = overused | ~has_job

        start = scalar_at(jsta_ref[JSTART:JSTART + 1, :], jhot).astype(jnp.int32)
        count_j = jnp.where(retire, 0,
                            scalar_at(jsta_ref[JCOUNT:JCOUNT + 1, :], jhot)
                            ).astype(jnp.int32)
        minavail = scalar_at(jsta_ref[JMIN:JMIN + 1, :], jhot).astype(jnp.int32)
        ptr0 = scalar_at(jdyn_ref[JPTR:JPTR + 1, :], jhot).astype(jnp.int32)
        ready0 = scalar_at(jdyn_ref[JREADY:JREADY + 1, :], jhot).astype(jnp.int32)

        # ---- drain the popped job (allocate.go:125-193) -------------------
        def drain_body(ic):
            done, survive, ptr, ready_cnt, dstep, dres = ic
            exhausted = ptr >= count_j
            t = jnp.clip(start + ptr, 0, task_ref.shape[0] - 1)
            req = [task_ref[t, i] for i in range(r)]
            res = [task_ref[t, r + i] for i in range(r)]
            sig = sig_ref[t, 0]

            fit_idle = None
            fit_rel = None
            for i in range(r):
                e = _eps_for_dim(i)
                mi = node_ref[IDLE + i:IDLE + i + 1, :]
                mr = node_ref[REL + i:REL + i + 1, :]
                oki = (req[i] < mi) | (jnp.abs(req[i] - mi) < e)
                okr = (req[i] < mr) | (jnp.abs(req[i] - mr) < e)
                if i >= 2:
                    low = req[i] <= e
                    oki = oki | low
                    okr = okr | low
                fit_idle = oki if fit_idle is None else (fit_idle & oki)
                fit_rel = okr if fit_rel is None else (fit_rel & okr)

            sig_row = sig_mask_ref[pl.ds(sig, 1), :] > 0.5
            cap_ok = node_ref[CNT:CNT + 1, :] < node_ref[CAP:CAP + 1, :]
            exists = node_ref[EXISTS:EXISTS + 1, :] > 0.5
            feasible = sig_row & exists & cap_ok & (fit_idle | fit_rel)

            used_cm = node_ref[USED:USED + 2, :]
            inv = node_ref[INV:INV + 2, :]
            zero = node_ref[ZERO:ZERO + 2, :] > 0.5
            res_cm = jnp.concatenate(
                [jnp.full((1, n), res[0], dtype),
                 jnp.full((1, n), res[1], dtype)], axis=0)
            frac = jnp.where(zero, 1.0,
                             jnp.minimum((used_cm + res_cm) * inv, 1.0))
            cpu_frac, mem_frac = frac[0:1, :], frac[1:2, :]
            score = jnp.zeros((1, n), dtype)
            if w_least:
                score = score + w_least * 5.0 * ((1.0 - cpu_frac)
                                                 + (1.0 - mem_frac))
            if w_most:
                score = score + w_most * 5.0 * (cpu_frac + mem_frac)
            if w_bal:
                score = score + w_bal * (10.0 - jnp.abs(cpu_frac - mem_frac)
                                         * 10.0)
            score = jnp.where(feasible, score, neg_inf)

            best = jnp.max(score)
            nsel = jnp.min(jnp.where(score == best, col_n, n)).astype(jnp.int32)
            feasible_any = best > neg_inf
            onehot = col_n == nsel
            pick = lambda v: jnp.sum(
                jnp.where(onehot, v.astype(dtype), 0.0)) > 0.5
            fit_idle_n = pick(fit_idle)
            fit_rel_n = pick(fit_rel)

            placing = ~done & ~exhausted & feasible_any
            alloc_ok = placing & fit_idle_n
            pipe_ok = placing & ~fit_idle_n & fit_rel_n
            placed = alloc_ok | pipe_ok

            af = jnp.where(alloc_ok, 1.0, 0.0).astype(dtype)
            pf = jnp.where(pipe_ok, 1.0, 0.0).astype(dtype)
            plf = jnp.where(placed, 1.0, 0.0).astype(dtype)
            # Rank-1 update over the dynamic rows only (idle, releasing,
            # used, count); the static rows below never change.
            ndyn = 3 * r + 1
            delta_col = [(-af * res[i]) for i in range(r)] \
                + [(-pf * res[i]) for i in range(r)] \
                + [(plf * res[i]) for i in range(r)] + [plf]
            delta = jnp.stack(delta_col).reshape(ndyn, 1)
            node_ref[0:ndyn, :] = node_ref[0:ndyn, :] \
                + delta * onehot.astype(dtype)

            row = jnp.stack([jnp.where(placed, nsel, -1),
                             jnp.where(alloc_ok, 1,
                                       jnp.where(pipe_ok, 2, 0)),
                             jnp.where(placed, dstep, -1),
                             jnp.int32(0)]).reshape(1, 4)

            @pl.when(placed)
            def _():
                out_ref[pl.ds(t, 1), :] = row

            ptr = ptr + placed.astype(jnp.int32)
            ready_cnt = ready_cnt + alloc_ok.astype(jnp.int32)
            dstep = dstep + placed.astype(jnp.int32)
            dres = dres + plf * jnp.stack(res).reshape(1, r)

            if cfg.has_gang:
                ready = ready_cnt >= minavail
            else:
                ready = jnp.bool_(True)
            remaining = ptr < count_j
            new_done = exhausted | ~feasible_any | ready | ~remaining
            new_survive = ~exhausted & feasible_any & ready & remaining
            return (done | new_done, jnp.where(done, survive, new_survive),
                    ptr, ready_cnt, dstep, dres)

        init = (jnp.bool_(False), jnp.bool_(False), ptr0, ready0, step,
                jnp.zeros((1, r), dtype))
        done, survive, ptr, ready_cnt, step, dres = jax.lax.while_loop(
            lambda c: ~c[0], drain_body, init)

        # ---- writeback + rotation (allocate.go:185-193) -------------------
        processed = (~retire).astype(dtype)
        jhot_f = jhot.astype(dtype) * processed
        qhot_f = qhot.astype(dtype)
        for i in range(r):
            jdyn_ref[i:i + 1, :] = jdyn_ref[i:i + 1, :] + dres[0, i] * jhot_f
            qdyn_ref[i:i + 1, :] = qdyn_ref[i:i + 1, :] \
                + dres[0, i] * qhot_f * processed
        jdyn_ref[JPTR:JPTR + 1, :] = jnp.where(
            jhot_f > 0.5, ptr.astype(dtype), jdyn_ref[JPTR:JPTR + 1, :])
        jdyn_ref[JREADY:JREADY + 1, :] = jnp.where(
            jhot_f > 0.5, ready_cnt.astype(dtype),
            jdyn_ref[JREADY:JREADY + 1, :])
        jdyn_ref[JACT:JACT + 1, :] = jnp.where(
            jhot_f > 0.5, jnp.where(survive, 1.0, 0.0).astype(dtype),
            jdyn_ref[JACT:JACT + 1, :])
        qdyn_ref[QACT:QACT + 1, :] = jnp.where(
            (qhot & retire), 0.0, qdyn_ref[QACT:QACT + 1, :])

        any_active = jnp.max(qdyn_ref[QACT:QACT + 1, :]) > 0.5
        return any_active, step

    any0 = jnp.max(qdyn_in[QACT:QACT + 1, :]) > 0.5
    _, total_steps = jax.lax.while_loop(
        lambda c: c[0], outer_body, (any0, scal_ref[0, 1]))
    scal_out_ref[0, 0] = total_steps


def _build_buffers(inp: SolverInputs):
    r = inp.task_req.shape[1]
    n = inp.node_idle.shape[0]
    dtype = inp.task_req.dtype
    nrows = _pad8(3 * r + 7)

    alloc2 = inp.node_alloc[:, :2]
    inv2 = jnp.where(alloc2 > 0, 1.0 / jnp.where(alloc2 > 0, alloc2, 1.0), 0.0)
    zero2 = (alloc2 <= 0).astype(dtype)
    parts = [inp.node_idle.T, inp.node_releasing.T, inp.node_used.T,
             inp.node_count.astype(dtype)[None, :],
             inp.node_max_tasks.astype(dtype)[None, :],
             inp.node_exists.astype(dtype)[None, :],
             inv2.T, zero2.T]
    node_buf = jnp.concatenate(parts, axis=0)
    node_buf = jnp.concatenate(
        [node_buf, jnp.zeros((nrows - node_buf.shape[0], n), dtype)], axis=0)

    f = lambda x: x.astype(dtype)[None, :]
    job_active0 = (inp.queue_exists[inp.job_queue]
                   & (inp.job_minavail >= 0)).astype(dtype)
    jsta = jnp.concatenate([
        f(inp.job_start), f(inp.job_count), f(inp.job_queue),
        f(inp.job_minavail), f(inp.job_prio), f(inp.job_ts),
        f(inp.job_uid_rank), jnp.zeros((1, inp.job_start.shape[0]), dtype)],
        axis=0)
    jd_rows = _pad8(r + 3)
    jdyn = jnp.concatenate([
        inp.job_init_alloc.T.astype(dtype),
        jnp.zeros((1, inp.job_start.shape[0]), dtype),  # ptr
        f(inp.job_init_ready),
        job_active0[None, :],
        jnp.zeros((jd_rows - r - 3, inp.job_start.shape[0]), dtype)], axis=0)

    qdim = inp.queue_deserved.shape[0]
    queue_active0 = (jnp.zeros((qdim,), bool).at[inp.job_queue].set(True)
                     & inp.queue_exists).astype(dtype)
    qs_rows = _pad8(r + 3)
    qsta = jnp.concatenate([
        inp.queue_deserved.T.astype(dtype),
        f(inp.queue_ts), f(inp.queue_uid_rank),
        f(inp.queue_exists),
        jnp.zeros((qs_rows - r - 3, qdim), dtype)], axis=0)
    qd_rows = _pad8(r + 1)
    qdyn = jnp.concatenate([
        inp.queue_init_alloc.T.astype(dtype),
        queue_active0[None, :],
        jnp.zeros((qd_rows - r - 1, qdim), dtype)], axis=0)
    return node_buf, jsta, jdyn, qsta, qdyn


@functools.partial(jax.jit, static_argnames=("cfg", "interpret"))
def solve_allocate_pallas(inp: SolverInputs, cfg: SolverConfig,
                          interpret: bool = False) -> SolveResult:
    """Full-session solve as a single Pallas kernel launch."""
    r = inp.task_req.shape[1]
    p = inp.task_req.shape[0]
    dtype = inp.task_req.dtype

    task_data = jnp.concatenate([inp.task_req, inp.task_res], axis=1)
    task_sig2 = inp.task_sig[:, None]
    sig_mask_f = inp.sig_mask.astype(dtype)
    node_buf, jsta, jdyn, qsta, qdyn = _build_buffers(inp)
    out_buf0 = jnp.concatenate(
        [jnp.full((p, 1), -1, jnp.int32), jnp.zeros((p, 1), jnp.int32),
         jnp.full((p, 1), -1, jnp.int32), jnp.zeros((p, 1), jnp.int32)],
        axis=1)
    scal = jnp.array([[p, 0, 0, 0, 0, 0, 0, 0]], jnp.int32)
    total = inp.total_res.astype(dtype)[None, :]

    kernel = functools.partial(_solve_kernel, r, cfg)
    nrows, n = node_buf.shape
    outs = pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct((nrows, n), dtype),
                   jax.ShapeDtypeStruct((p, 4), jnp.int32),
                   jax.ShapeDtypeStruct(jdyn.shape, dtype),
                   jax.ShapeDtypeStruct(qdyn.shape, dtype),
                   jax.ShapeDtypeStruct((1, 8), jnp.int32)),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec(memory_space=pltpu.VMEM),
                  pl.BlockSpec(memory_space=pltpu.VMEM),
                  pl.BlockSpec(memory_space=pltpu.VMEM),
                  pl.BlockSpec(memory_space=pltpu.VMEM),
                  pl.BlockSpec(memory_space=pltpu.VMEM),
                  pl.BlockSpec(memory_space=pltpu.VMEM),
                  pl.BlockSpec(memory_space=pltpu.VMEM),
                  pl.BlockSpec(memory_space=pltpu.VMEM),
                  pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=(pl.BlockSpec(memory_space=pltpu.VMEM),
                   pl.BlockSpec(memory_space=pltpu.VMEM),
                   pl.BlockSpec(memory_space=pltpu.VMEM),
                   pl.BlockSpec(memory_space=pltpu.VMEM),
                   pl.BlockSpec(memory_space=pltpu.SMEM)),
        input_output_aliases={5: 0, 6: 1, 7: 2, 8: 3},
        interpret=interpret,
    )(scal, total, task_data, task_sig2, sig_mask_f,
      node_buf, out_buf0, jdyn, qdyn, jsta, qsta)

    out = outs[1]
    return SolveResult(assignment=out[:, 0], kind=out[:, 1],
                       order=out[:, 2], step=outs[4][0, 0])
