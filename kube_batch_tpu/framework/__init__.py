"""Scheduling framework (L3): session lifecycle, plugin/action registries,
tiered decision combinators, and the Statement transaction.

TPU-native counterpart of /root/reference/pkg/scheduler/framework/.
"""

from .arguments import Arguments
from .events import Event, EventHandler
from .interface import Action, Plugin
from .registry import (register_action, get_action, list_actions,
                       register_plugin_builder, get_plugin_builder,
                       cleanup_plugin_builders)
from .session import Session, open_session, close_session, job_status
from .statement import Statement

__all__ = [
    "Arguments", "Event", "EventHandler", "Action", "Plugin",
    "register_action", "get_action", "list_actions",
    "register_plugin_builder", "get_plugin_builder",
    "cleanup_plugin_builders",
    "Session", "open_session", "close_session", "job_status", "Statement",
]
