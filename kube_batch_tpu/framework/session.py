"""Session: snapshot-backed working state for one scheduling cycle.

Mirrors /root/reference/pkg/scheduler/framework/session.go (lifecycle,
Allocate/Pipeline/Evict/dispatch) and session_plugins.go (the tiered decision
combinators: victim-intersection with first-decisive-tier for Preemptable/
Reclaimable, veto-AND for JobReady/JobPipelined/JobValid/Overused,
first-nonzero comparison chains for the order functions, all-tiers AND for
predicates, concatenation for node-order functions).
"""

from __future__ import annotations

import time
import uuid
from typing import Callable, Dict, List, Optional

from ..api import (ClusterInfo, FitError, JobInfo, NodeInfo, QueueInfo,
                   TaskInfo, TaskStatus, ValidateResult, allocated_status,
                   pod_key)
from ..api.node_info import lazy_insert
from ..api.pod_group_info import (PodGroupCondition, PodGroupPending,
                                  PodGroupRunning, PodGroupUnknown,
                                  PodGroupUnschedulableType)
from ..chaos import plan as chaos_plan
from ..metrics import memledger, metrics
from ..native import apply_placements as native_apply
from ..trace import spans as trace
from ..trace.lineage import lineage as pod_lineage
from ..utils.priority_queue import PriorityQueue, SortedDrainQueue
from .events import AllocateBatch, Event, EventHandler
from .interface import Plugin


class Session:
    """One scheduling cycle's working state + plugin callback registries
    (session.go:37-61)."""

    def __init__(self, cache):
        self.uid: str = str(uuid.uuid4())
        self.cache = cache
        # Queue-shard scope (doc/TENANCY.md): the owning shard when this
        # session runs over a tenancy ShardView, else None (the global
        # engine).  Plugins use it to publish shard-SCOPED fairness rows
        # (metrics/tenants.py) instead of wholesale table replaces.
        self.shard = getattr(cache, "shard", None)

        self.jobs: Dict[str, JobInfo] = {}
        self.nodes: Dict[str, NodeInfo] = {}
        self.queues: Dict[str, QueueInfo] = {}
        self.tiers: List[Tier] = []

        # Clones this session has mutated: their pooled copies must not be
        # reused by the next snapshot, and tensorization must not serve
        # cached blocks for them (cache.py snapshot / tensor_snapshot.py).
        # The delta-shipping layer (models/shipping.py) relies on these
        # being complete: a mutation that bypasses _dirty_job/_dirty_node
        # would leave the next cycle staging stale rows.
        self.mutated_jobs: set = set()
        self.mutated_nodes: set = set()

        # Cross-action pre-scan results: a pipelined action computes
        # snapshot-derived facts during its device-wait window and later
        # actions consume them instead of re-walking the session (e.g.
        # tpu-allocate answers backfill's BestEffort discovery from the
        # tensorizer's rows).  Entries are valid for this session only.
        self.prescan: Dict[str, object] = {}

        self.plugins: Dict[str, Plugin] = {}
        self.event_handlers: List[EventHandler] = []
        self.job_order_fns: Dict[str, Callable] = {}
        self.queue_order_fns: Dict[str, Callable] = {}
        self.task_order_fns: Dict[str, Callable] = {}
        # Optional static-key forms of task_order_fns: key_fn(task) must
        # sort ascending exactly like the cmp fn.  When EVERY enabled
        # task-order plugin registers one, task_sort_key() lets the
        # actions replace O(n)-scan comparator queues with sorted drains
        # (task keys are immutable within a session).
        self.task_order_key_fns: Dict[str, Callable] = {}
        self.predicate_fns: Dict[str, Callable] = {}
        self.preemptable_fns: Dict[str, Callable] = {}
        self.reclaimable_fns: Dict[str, Callable] = {}
        self.overused_fns: Dict[str, Callable] = {}
        self.job_ready_fns: Dict[str, Callable] = {}
        self.job_pipelined_fns: Dict[str, Callable] = {}
        self.job_valid_fns: Dict[str, Callable] = {}
        self.node_order_fns: Dict[str, List] = {}

        # Batched commit (framework/commit.py): the active per-action
        # effect sink, installed by ``action_commit`` for the duration
        # of one eviction action's execute.  None = the sequential
        # per-task effector path (the KUBE_BATCH_TPU_BATCH_COMMIT=0
        # control, and every action that never evicts).
        self._commit_sink = None
        # Shard-pipeline de-alias hook (tenancy/pipeline.py): called with
        # an iterable of node names BEFORE the first session mutation of
        # each node, so in-flight successor sessions sharing pooled
        # clones can take private copies before the object changes.
        # None outside a pipelined retire (zero overhead: one attribute
        # read per first-touch)  — doc/TENANCY.md "Concurrent
        # micro-sessions".
        self._dirty_node_hook = None
        # Shard-pipeline conflict fence (set by tpu-allocate's begin
        # half): (node_names, feasible_mask) naming the nodes whose state
        # this session's outcome can depend on, or _pipeline_reads_all
        # when the footprint is unbounded (fallback/backfill/volumes) —
        # the pipeline reruns this session when a predecessor mutates
        # inside the footprint.
        self._pipeline_fence = None
        self._pipeline_reads_all = False
        # True only for sessions opened by the shard pipeline's begin
        # half (Scheduler.begin_shard_session): fence derivation is
        # skipped everywhere else, so the sequential control keeps its
        # exact per-session work profile.
        self._pipeline_active = False
        # Set by the pipeline when ANY predecessor committed mutations
        # after this session's snapshot: a retire half that then needs
        # the unbounded host fallback must abort for the sequential
        # rerun instead of reading stale state (StaleSessionAbort).
        self._pipeline_stale = False
        # Per-session commit/apply floor accumulators (published as
        # ``cycle_floor_ms{floor="commit"|"apply"}`` at close): the
        # effect-side wall time — sequential per-task effector calls or
        # batched flushes for commit; the placement apply phase for
        # apply — so storm regressions are attributable in the bench
        # gate (doc/EVICTION.md "Batched commit").
        self._floor_commit = 0.0
        self._floor_apply = 0.0

        # Lazily resolved tier-walk chains for the order comparators:
        # heap-heavy actions (a preemption storm pushes/pops thousands
        # of jobs and tasks) call these per comparison, and the
        # tier x plugin x dict-lookup walk per call dominated them.
        # Registrations are fixed once open_session returns, so the
        # first call freezes the chain.
        self._job_order_chain: Optional[List[Callable]] = None
        self._task_order_chain: Optional[List[Callable]] = None
        self._task_key_fn = False  # False = uncomputed, None = unavailable

    # ------------------------------------------------------------------
    # registration (session_plugins.go:25-77)

    def add_job_order_fn(self, name, fn):
        self.job_order_fns[name] = fn

    def add_queue_order_fn(self, name, fn):
        self.queue_order_fns[name] = fn

    def add_task_order_fn(self, name, fn):
        self.task_order_fns[name] = fn

    def add_task_order_key_fn(self, name, key_fn):
        self.task_order_key_fns[name] = key_fn

    def add_predicate_fn(self, name, fn):
        self.predicate_fns[name] = fn

    def add_preemptable_fn(self, name, fn):
        self.preemptable_fns[name] = fn

    def add_reclaimable_fn(self, name, fn):
        self.reclaimable_fns[name] = fn

    def add_overused_fn(self, name, fn):
        self.overused_fns[name] = fn

    def add_job_ready_fn(self, name, fn):
        self.job_ready_fns[name] = fn

    def add_job_pipelined_fn(self, name, fn):
        self.job_pipelined_fns[name] = fn

    def add_job_valid_fn(self, name, fn):
        self.job_valid_fns[name] = fn

    def add_node_order_fns(self, name, prioritizers):
        """prioritizers: list of (weight, NodeOrderFn)."""
        self.node_order_fns[name] = prioritizers

    def add_event_handler(self, handler: EventHandler):
        self.event_handlers.append(handler)

    # ------------------------------------------------------------------
    # tiered combinators (session_plugins.go:80-369)

    def _victims(self, fns: Dict[str, Callable], flag_attr: str,
                 claimer: TaskInfo, claimees: List[TaskInfo]) -> List[TaskInfo]:
        """Within a tier victims are intersected across plugins; the first
        tier whose intersection is non-None decides (go:80-162; note Go's
        nil-vs-empty distinction: a tier whose plugins all return nil defers
        to the next tier, an empty-but-initialized result decides 'none')."""
        victims: Optional[List[TaskInfo]] = None
        for tier in self.tiers:
            for plugin in tier.plugins:
                if not getattr(plugin, flag_attr):
                    continue
                fn = fns.get(plugin.name)
                if fn is None:
                    continue
                candidates = fn(claimer, claimees)
                if victims is None:
                    victims = candidates if candidates is not None else []
                else:
                    cand_uids = {c.uid for c in (candidates or [])}
                    victims = [v for v in victims if v.uid in cand_uids]
            if victims is not None:
                return victims
        return victims or []

    def preemptable(self, preemptor: TaskInfo, preemptees: List[TaskInfo]):
        return self._victims(self.preemptable_fns, "enabled_preemptable",
                             preemptor, preemptees)

    def reclaimable(self, reclaimer: TaskInfo, reclaimees: List[TaskInfo]):
        return self._victims(self.reclaimable_fns, "enabled_reclaimable",
                             reclaimer, reclaimees)

    def overused(self, queue: QueueInfo) -> bool:
        """Any plugin saying overused wins (go:165-181; note: not gated by an
        enable flag in the reference either)."""
        for tier in self.tiers:
            for plugin in tier.plugins:
                fn = self.overused_fns.get(plugin.name)
                if fn is not None and fn(queue):
                    return True
        return False

    def job_ready(self, job: JobInfo) -> bool:
        for tier in self.tiers:
            for plugin in tier.plugins:
                if not plugin.enabled_job_ready:
                    continue
                fn = self.job_ready_fns.get(plugin.name)
                if fn is not None and not fn(job):
                    return False
        return True

    def job_pipelined(self, job: JobInfo) -> bool:
        for tier in self.tiers:
            for plugin in tier.plugins:
                if not plugin.enabled_job_pipelined:
                    continue
                fn = self.job_pipelined_fns.get(plugin.name)
                if fn is not None and not fn(job):
                    return False
        return True

    def job_valid(self, job: JobInfo) -> Optional[ValidateResult]:
        """First failing validator wins (go:228-244; not flag-gated)."""
        for tier in self.tiers:
            for plugin in tier.plugins:
                fn = self.job_valid_fns.get(plugin.name)
                if fn is None:
                    continue
                vr = fn(job)
                if vr is not None and not vr.pass_:
                    return vr
        return None

    def job_order_fn(self, l: JobInfo, r: JobInfo) -> bool:
        """First non-zero comparison wins; fallback creation-time then UID
        (go:247-271)."""
        chain = self._job_order_chain
        if chain is None:
            chain = self._job_order_chain = [
                fn for tier in self.tiers for plugin in tier.plugins
                if plugin.enabled_job_order
                and (fn := self.job_order_fns.get(plugin.name)) is not None]
        for fn in chain:
            j = fn(l, r)
            if j != 0:
                return j < 0
        if l.creation_timestamp == r.creation_timestamp:
            return l.uid < r.uid
        return l.creation_timestamp < r.creation_timestamp

    def queue_order_fn(self, l: QueueInfo, r: QueueInfo) -> bool:
        for tier in self.tiers:
            for plugin in tier.plugins:
                if not plugin.enabled_queue_order:
                    continue
                fn = self.queue_order_fns.get(plugin.name)
                if fn is None:
                    continue
                j = fn(l, r)
                if j != 0:
                    return j < 0
        lt = l.queue.metadata.creation_timestamp
        rt = r.queue.metadata.creation_timestamp
        if lt == rt:
            return l.uid < r.uid
        return lt < rt

    def task_compare_fns(self, l: TaskInfo, r: TaskInfo) -> int:
        chain = self._task_order_chain
        if chain is None:
            chain = self._task_order_chain = [
                fn for tier in self.tiers for plugin in tier.plugins
                if plugin.enabled_task_order
                and (fn := self.task_order_fns.get(plugin.name)) is not None]
        for fn in chain:
            j = fn(l, r)
            if j != 0:
                return j
        return 0

    def task_order_fn(self, l: TaskInfo, r: TaskInfo) -> bool:
        res = self.task_compare_fns(l, r)
        if res != 0:
            return res < 0
        lt = l.pod.metadata.creation_timestamp
        rt = r.pod.metadata.creation_timestamp
        if lt == rt:
            return l.uid < r.uid
        return lt < rt

    def task_sort_key(self) -> Optional[Callable]:
        """Static ascending sort key equivalent to task_order_fn, or None
        when some enabled task-order plugin has no key form.  Task keys
        are immutable within a session (the cmp chain reads only
        priority/timestamps/uid-class fields), so a one-time sort equals
        the comparator queue's live re-evaluation exactly — including
        the creation-time/UID total-order fallback."""
        if self._task_key_fn is not False:
            return self._task_key_fn
        key_fns = []
        for tier in self.tiers:
            for plugin in tier.plugins:
                if (plugin.enabled_task_order
                        and plugin.name in self.task_order_fns):
                    kf = self.task_order_key_fns.get(plugin.name)
                    if kf is None:
                        self._task_key_fn = None
                        return None
                    key_fns.append(kf)
        if len(key_fns) == 1:
            k0 = key_fns[0]

            def key(t, _k0=k0):
                return (_k0(t), t.pod.metadata.creation_timestamp, t.uid)
        else:
            def key(t, _ks=tuple(key_fns)):
                return (*[k(t) for k in _ks],
                        t.pod.metadata.creation_timestamp, t.uid)
        self._task_key_fn = key
        return key

    def task_queue(self, items=()):
        """Queue over tasks in task_order_fn order.  A one-sort drain
        when every enabled task-order plugin registered a static key
        form (task keys are immutable within a session), else the live
        comparator queue — identical pop order either way."""
        key = self.task_sort_key()
        if key is not None:
            return SortedDrainQueue(key, items)
        q = PriorityQueue(self.task_order_fn)
        for t in items:
            q.push(t)
        return q

    def victims_queue(self, victims):
        """Victims in REVERSED task order — lowest priority evicted
        first (preempt.go:213-218)."""
        key = self.task_sort_key()
        if key is not None:
            return SortedDrainQueue(key, victims, reverse=True)
        q = PriorityQueue(lambda l, r: not self.task_order_fn(l, r))
        for v in victims:
            q.push(v)
        return q

    def predicate_fn(self, task: TaskInfo, node: NodeInfo) -> None:
        """All enabled predicates across all tiers must pass (go:334-351).
        Raises FitError on the first rejection."""
        for tier in self.tiers:
            for plugin in tier.plugins:
                if not plugin.enabled_predicate:
                    continue
                fn = self.predicate_fns.get(plugin.name)
                if fn is not None:
                    fn(task, node)

    def node_prioritizers(self) -> List:
        """Concatenate enabled (weight, fn) prioritizers (go:354-369)."""
        configs: List = []
        for tier in self.tiers:
            for plugin in tier.plugins:
                if not plugin.enabled_node_order:
                    continue
                prioritizers = self.node_order_fns.get(plugin.name)
                if prioritizers:
                    configs.extend(prioritizers)
        return configs

    # ------------------------------------------------------------------
    # decisions (session.go:186-345)

    def statement(self):
        from .statement import Statement
        return Statement(self)

    def _dirty_job(self, uid: str) -> None:
        """Record that this session mutated job ``uid``'s clone (and evict
        it from the cache's snapshot pool).  Every session-side mutation
        path MUST route through here or _dirty_node — a missed call means
        the next cycle schedules on a stale clone."""
        if uid not in self.mutated_jobs:
            self.mutated_jobs.add(uid)
            discard = getattr(self.cache, "discard_pooled_job", None)
            if discard is not None:
                discard(uid)

    def _dirty_node(self, name: str) -> None:
        if name not in self.mutated_nodes:
            hook = self._dirty_node_hook
            if hook is not None:
                # Every mutation path dirties BEFORE touching the clone
                # (the contract above), so the pipeline's de-alias guard
                # always runs while the object is still bit-identical to
                # its snapshot.  Batch walks that mutate before their
                # settle-phase dirty marks pre-declare via
                # _predeclare_nodes instead.
                hook((name,))
            self.mutated_nodes.add(name)
            discard = getattr(self.cache, "discard_pooled_node", None)
            if discard is not None:
                discard(name)

    def _predeclare_nodes(self, names) -> None:
        """Announce the node set a batch walk is about to mutate (the
        native/columnar apply writes node clones before its settle-phase
        _dirty_node calls): gives the shard pipeline's de-alias guard its
        before-the-mutation window.  No-op outside a pipelined retire."""
        hook = self._dirty_node_hook
        if hook is not None:
            hook(names)

    def _fire_allocate(self, task: TaskInfo):
        for eh in self.event_handlers:
            if eh.allocate_func is not None:
                eh.allocate_func(Event(task))

    def _fire_deallocate(self, task: TaskInfo):
        for eh in self.event_handlers:
            if eh.deallocate_func is not None:
                eh.deallocate_func(Event(task))

    def pipeline(self, task: TaskInfo, hostname: str) -> None:
        """Session-only assignment onto releasing resources (session.go:194-232)."""
        job = self.jobs.get(task.job)
        if job is None:
            raise KeyError(f"failed to find job {task.job} when pipelining")
        self._dirty_job(task.job)
        job.update_task_status(task, TaskStatus.Pipelined)
        node = self.nodes.get(hostname)
        if node is None:
            raise KeyError(f"failed to find node {hostname}")
        self._dirty_node(hostname)
        node.add_task(task)
        self._fire_allocate(task)
        log = getattr(self, "_fused_mutlog", None)
        if log is not None:
            log.append(("pipeline", task.uid, hostname))

    def allocate(self, task: TaskInfo, hostname: str) -> None:
        """Assign idle resources; dispatch the whole gang once JobReady
        (session.go:235-288)."""
        if task.pod.spec.volumes:
            # Volume-less pods skip the binder round-trip (the gate all
            # placement paths share: batch_apply applies the same one, so
            # batch and sequential end states stay identical).
            self.cache.allocate_volumes(task, hostname)
        job = self.jobs.get(task.job)
        if job is None:
            raise KeyError(f"failed to find job {task.job}")
        self._dirty_job(task.job)
        job.update_task_status(task, TaskStatus.Allocated)
        node = self.nodes.get(hostname)
        if node is None:
            raise KeyError(f"failed to find node {hostname}")
        self._dirty_node(hostname)
        node.add_task(task)
        self._fire_allocate(task)
        log = getattr(self, "_fused_mutlog", None)
        if log is not None:
            log.append(("allocate", task.uid, hostname))

        if self.job_ready(job):
            # Gang barrier: dispatch every Allocated task of the job at once.
            for t in list(job.task_status_index.get(TaskStatus.Allocated, {}).values()):
                self.dispatch(t)

    def dispatch(self, task: TaskInfo) -> None:
        """Bind to the cluster (session.go:290-314)."""
        if task.pod.spec.volumes:  # same gate as allocate()/batch_apply
            self.cache.bind_volumes(task)
        self.cache.bind(task, task.node_name)
        job = self.jobs.get(task.job)
        if job is None:
            raise KeyError(f"failed to find job {task.job}")
        self._dirty_job(task.job)
        job.move_task_status(task, TaskStatus.Binding)
        metrics.observe_task_schedule_latency(
            time.time() - task.pod.metadata.creation_timestamp)

    def _fire_allocate_batch(self, batch) -> None:
        for eh in self.event_handlers:
            if eh.batch_allocate_func is not None:
                eh.batch_allocate_func(batch)
            elif eh.allocate_func is not None:
                for t in batch.tasks:
                    eh.allocate_func(Event(t))

    def _apply_sequential(self, placements) -> None:
        """Exact per-task replay (the pre-batch apply path): used when the
        batch feasibility pre-check trips, so infeasible placements are
        rejected individually exactly as allocate()/pipeline() would."""
        for task, hostname, kind in placements:
            try:
                if kind == 1:
                    self.allocate(task, hostname)
                else:
                    self.pipeline(task, hostname)
            except (KeyError, ValueError):
                # Mirror the reference's log-and-continue on bind errors
                # (allocate.go:162-166); cache resync repairs divergence.
                continue

    def batch_apply(self, placements, agg=None) -> None:
        """Apply a solved placement sequence in bulk.

        ``placements``: iterable of (task, hostname, kind) with kind
        1=allocate, 2=pipeline, in solve order.  Final state is identical
        to calling allocate()/pipeline() per task in that order: status
        moves, node accounting, and plugin event state are all linear in
        the placed tasks, and the gang dispatch barrier depends only on
        final readiness (ready_task_num never decreases while allocating),
        so per-node/per-job aggregation commutes (f64 sums may reassociate;
        the <=1e-10 relative drift is far inside every epsilon).

        ``agg``: optional BatchAggregates precomputed from the solver's own
        arrays (models/tensor_snapshot.build_apply_aggregates); with it the
        per-task loop is only index moves + node-clone inserts."""
        from ..api.resource import Resource

        placements = list(placements)
        # Feasibility pre-check: the sequential path rejects a placement
        # whose request exceeds idle beyond epsilon (node_info.go AddTask)
        # and the action skips it.  Summed aggregates can't reproduce that
        # per-task skip, so if any node's total looks overdrawn (solver bug
        # or stale snapshot), replay the whole batch through the exact
        # per-task path instead.  With agg the sums already exist
        # (vectorized); without it, build them once and reuse below.
        if agg is not None:
            check_alloc, check_pipe = agg.node_alloc, agg.node_pipe
        else:
            check_alloc, check_pipe = {}, {}
            for task, hostname, kind in placements:
                accs = check_alloc if kind == 1 else check_pipe
                acc = accs.get(hostname)
                if acc is None:
                    acc = accs[hostname] = Resource.empty()
                acc.add(task.resreq)
        for accs, pool in ((check_alloc, "idle"), (check_pipe, "releasing")):
            for hostname, acc in accs.items():
                node = self.nodes.get(hostname)
                if node is not None and not acc.less_equal(
                        getattr(node, pool)):
                    self._apply_sequential(placements)
                    return

        if self._dirty_node_hook is not None:
            self._predeclare_nodes({h for _t, h, _k in placements})
        node_alloc: dict = check_alloc
        node_pipe: dict = check_pipe
        touched_jobs: dict = {}
        applied: List[TaskInfo] = []
        skipped = []
        jobs_get = self.jobs.get
        nodes_get = self.nodes.get
        allocate_volumes = self.cache.allocate_volumes
        applied_append = applied.append
        allocated_st, pipelined_st = TaskStatus.Allocated, TaskStatus.Pipelined
        # With agg, status-index moves are deferred and batched per job
        # (same end state: index moves commute within the batch); the
        # whole-bucket case — every Pending task of a job allocated, the
        # norm for gang jobs — moves the bucket dict wholesale instead of
        # one pop+insert per task.  The per-placement pass itself runs in
        # C when the native extension built (kube_batch_tpu/native).
        alloc_moves: dict = {}
        pipe_moves: dict = {}
        if agg is not None and native_apply is not None:
            (applied, skipped, touched_jobs, alloc_moves,
             pipe_moves) = native_apply(self.jobs, self.nodes, placements,
                                        allocate_volumes)
        else:
            for task, hostname, kind in placements:
                job = jobs_get(task.job)
                node = nodes_get(hostname)
                if job is None or node is None:
                    skipped.append((task, hostname, kind))
                    continue
                key = pod_key(task.pod)  # f"{namespace}/{name}", cached
                if key in node.tasks:  # add_task would raise; log-and-skip
                    skipped.append((task, hostname, kind))
                    continue
                if kind == 1:
                    if task.pod.spec.volumes:
                        # Volume-less pods skip the binder round-trip:
                        # every VolumeBinder is a no-op without claims,
                        # and 50k no-op calls cost ~30 ms per cycle.
                        try:
                            allocate_volumes(task, hostname)
                        except (KeyError, ValueError):
                            # e.g. a missing PVC: skip this placement
                            # exactly as the sequential path's per-task
                            # catch would.
                            skipped.append((task, hostname, kind))
                            continue
                    if agg is None:
                        job.move_task_status(task, allocated_st)
                    else:
                        alloc_moves.setdefault(task.job, []).append(task)
                else:
                    if agg is None:
                        job.move_task_status(task, pipelined_st)
                    else:
                        pipe_moves.setdefault(task.job, []).append(task)
                task.node_name = node.name
                lazy_insert(node.tasks, key, task)
                touched_jobs[task.job] = job
                applied_append(task)

        self._settle_batch(node_alloc, node_pipe, touched_jobs, applied,
                           skipped, agg, alloc_moves, pipe_moves)

    def _settle_batch(self, node_alloc, node_pipe, touched_jobs, applied,
                      skipped, agg, alloc_moves, pipe_moves) -> None:
        """The result-independent back half of a batch apply, shared by
        the placement-tuple path (batch_apply) and the columnar path
        (batch_apply_solved): deferred status-index moves, dirty marks,
        lineage, skip settlement, per-node/per-job accounting, the
        plugin batch event, and the gang dispatch barrier — in exactly
        the order the tuple path always ran them."""
        if alloc_moves or pipe_moves:
            allocated_st, pipelined_st = (TaskStatus.Allocated,
                                          TaskStatus.Pipelined)
            for uid, job in touched_jobs.items():
                to_alloc = alloc_moves.get(uid, ())
                to_pipe = pipe_moves.get(uid, ())
                index = job.task_status_index
                pend = index.get(TaskStatus.Pending)
                if (to_alloc and not to_pipe and pend is not None
                        and len(to_alloc) == len(pend)
                        and all(pend.get(t.uid) is t for t in to_alloc)):
                    # Whole-bucket move: Pending becomes Allocated.
                    del index[TaskStatus.Pending]
                    for t in pend.values():
                        t.status = allocated_st
                    existing = index.get(allocated_st)
                    if existing:
                        existing.update(pend)
                    else:
                        index[allocated_st] = pend
                    job._ready_num = None  # bypassed move_task_index
                else:
                    for t in to_alloc:
                        job.move_task_index(t, allocated_st)
                    for t in to_pipe:
                        job.move_task_index(t, pipelined_st)

        for uid in touched_jobs:
            self._dirty_job(uid)
        for accs in (node_alloc, node_pipe):
            for hostname in accs:
                self._dirty_node(hostname)

        # Pod lineage: one bulk "placed" record for the whole batch (the
        # cycle context set by tpu-allocate names the action/route).
        # Untracked pods are skipped inside; O(applied) key builds only
        # while lineage is enabled.
        if applied and pod_lineage.cfg().enabled:
            pod_lineage.note_placed([pod_key(t.pod) for t in applied],
                                    session=trace.current_session_id())

        # Remove contributions of skipped placements so the (pre)computed
        # sums describe exactly what was applied.
        for task, hostname, kind in skipped:
            if kind == 1 and hostname in node_alloc:
                node_alloc[hostname].sub_lenient(task.resreq)
            elif hostname in node_pipe:
                node_pipe[hostname].sub_lenient(task.resreq)
            if agg is not None:
                if task.job in agg.job_alloc and kind == 1:
                    agg.job_alloc[task.job].sub_lenient(task.resreq)
                if agg.job_sums and task.job in agg.job_sums:
                    agg.job_sums[task.job].sub_lenient(task.resreq)
                if agg.node_quanta and hostname in agg.node_quanta:
                    from ..ops.resources import quantize_value
                    qc, qm = agg.node_quanta[hostname]
                    agg.node_quanta[hostname] = (
                        qc - quantize_value(task.resreq.milli_cpu, 0),
                        qm - quantize_value(task.resreq.memory, 1))

        if agg is not None:
            # Settle job.allocated with one aggregate per job (only
            # Allocated counts: Pipelined is not an allocated status).
            for uid, res in agg.job_alloc.items():
                job = self.jobs.get(uid)
                if job is not None:
                    job.allocated.add(res)

        # Node accounting, one vector op per touched node (node_info.go
        # AddTask semantics summed; sub_lenient reproduces the sequential
        # path's epsilon-tolerant end state).
        for hostname, acc in node_alloc.items():
            node = self.nodes.get(hostname)
            if node is not None:
                node.idle.sub_lenient(acc)
                node.used.add(acc)
        for hostname, acc in node_pipe.items():
            node = self.nodes.get(hostname)
            if node is not None:
                node.releasing.sub_lenient(acc)
                node.used.add(acc)

        self._fire_allocate_batch(AllocateBatch(
            tasks=applied,
            job_sums=None if agg is None else agg.job_sums,
            node_quanta=None if agg is None else agg.node_quanta))

        # Gang barrier: dispatch every Allocated task of each now-ready job
        # (session.go:277-285; end state matches the interleaved loop).
        # Bulk form of dispatch(): Allocated and Binding are both
        # allocated_status, so job.allocated is invariant and the whole
        # status bucket moves at once; binds and latency metrics batch.
        now = time.time()
        dispatching: List[TaskInfo] = []
        for job in touched_jobs.values():
            if not self.job_ready(job):
                continue
            moving = job.task_status_index.pop(TaskStatus.Allocated, None)
            if not moving:
                continue
            # Allocated -> Binding keeps ready_task_num invariant (both
            # are allocated statuses), but reset the memo anyway: this
            # path bypasses move_task_index.
            job._ready_num = None
            binding = job.task_status_index[TaskStatus.Binding]
            moving_items = list(moving.items())
            if not any(t.pod.spec.volumes for t in moving.values()):
                # Volume-free fast path: no bind_volumes call can raise,
                # so the whole bucket moves in bulk.
                for t in moving.values():
                    t.status = TaskStatus.Binding
                binding.update(moving)
                dispatching.extend(moving.values())
                continue
            for i, (uid, t) in enumerate(moving_items):
                try:
                    if t.pod.spec.volumes:  # no-op (and raise-free) without
                        self.cache.bind_volumes(t)
                except (KeyError, ValueError):
                    # Sequential-path semantics: dispatch() propagates the
                    # error out of allocate(), so this and the job's
                    # remaining Allocated tasks stay Allocated this cycle
                    # (session.go:290-314 error return; allocate.go:164
                    # logs and moves on).  Already-dispatched tasks keep
                    # their Binding status, as in the interleaved loop.
                    alloc = job.task_status_index[TaskStatus.Allocated]
                    for ruid, rt in moving_items[i:]:
                        alloc[ruid] = rt
                    break
                t.status = TaskStatus.Binding
                binding[uid] = t
                dispatching.append(t)
        if dispatching:
            self.cache.bind_batch(dispatching)
            metrics.observe_task_schedule_latencies(
                [now - t.pod.metadata.creation_timestamp
                 for t in dispatching])

    def batch_apply_solved(self, tasks_arr, node_names_arr, assignment,
                           kind, ordered, jobix, job_uids, agg) -> None:
        """Columnar apply of a device solve: the same end state as
        ``batch_apply`` over (task, hostname, kind) tuples, fed directly
        from the solver's arrays and the staged index->TaskInfo table —
        no per-placement tuple materialization, no per-placement
        job/node dict resolution, and the status-index move lists
        grouped by numpy instead of per-task setdefault/append.

        Bit parity with the tuple path (pinned by the pipeline/churn/
        commit parity gates): the per-placement walk runs in solve
        order, ``touched_jobs`` keeps first-touch order (the gang
        dispatch barrier iterates it — bind order depends on it), and
        the per-job move lists keep placement order via stable sorts
        (status-index dict order feeds the bind batch).

        ``tasks_arr``: [P_real+] object ndarray (index -> TaskInfo);
        ``node_names_arr``: [N] object ndarray of node names;
        ``assignment``/``kind``: [P] result vectors; ``ordered``:
        placed rows in placement order; ``jobix``: [P_real] task -> job
        index; ``job_uids``: job index -> uid; ``agg``:
        BatchAggregates (required — the pre-check and accounting read
        it)."""
        import numpy as np

        sel = ordered
        n_idx = assignment[sel]

        # Feasibility pre-check, identical to batch_apply: an overdrawn
        # node total means the solver and session disagree — replay the
        # whole batch through the exact per-task path.
        for accs, pool in ((agg.node_alloc, "idle"),
                           (agg.node_pipe, "releasing")):
            for hostname, acc in accs.items():
                node = self.nodes.get(hostname)
                if node is not None and not acc.less_equal(
                        getattr(node, pool)):
                    self._apply_sequential(
                        list(zip(tasks_arr[sel].tolist(),
                                 node_names_arr[n_idx].tolist(),
                                 kind[sel].tolist())))
                    return

        if self._dirty_node_hook is not None:
            self._predeclare_nodes(set(node_names_arr[n_idx].tolist()))

        # Native columns walk: the same C per-placement pass the tuple
        # path runs (kube_batch_tpu/native), fed three parallel lists —
        # no per-placement tuple packing.  Returns exactly the settle
        # inputs, with touched_jobs/moves in first-touch placement
        # order by dict-insertion construction.
        if native_apply is not None:
            (applied, skipped, touched_jobs, alloc_moves,
             pipe_moves) = native_apply(
                self.jobs, self.nodes,
                (tasks_arr[sel].tolist(), node_names_arr[n_idx].tolist(),
                 kind[sel].tolist()),
                self.cache.allocate_volumes)
            self._settle_batch(agg.node_alloc, agg.node_pipe,
                               touched_jobs, applied, skipped, agg,
                               alloc_moves, pipe_moves)
            return

        # Python columnar fallback: object fan-out resolves each unique
        # node/job once, then numpy takes; the per-task loop keeps only
        # the work that is inherently per object.
        node_objs = np.empty(len(node_names_arr), dtype=object)
        node_objs[:] = [self.nodes.get(n)
                        for n in node_names_arr.tolist()]
        job_objs = np.empty(len(job_uids), dtype=object)
        job_objs[:] = [self.jobs.get(u) for u in job_uids]

        t_col = tasks_arr[sel]
        k_list = kind[sel].tolist()
        node_col = node_objs[n_idx]
        job_col = job_objs[jobix[sel]]

        applied: List[TaskInfo] = []
        applied_append = applied.append
        skip_pos: List[int] = []
        allocate_volumes = self.cache.allocate_volumes
        pos = 0
        for task, node, job, k in zip(t_col, node_col, job_col, k_list):
            if job is None or node is None:
                skip_pos.append(pos)
                pos += 1
                continue
            key = pod_key(task.pod)
            ntasks = node.tasks
            if key in ntasks:  # add_task would raise; log-and-skip
                skip_pos.append(pos)
                pos += 1
                continue
            if k == 1 and task.pod.spec.volumes:
                try:
                    allocate_volumes(task, node.name)
                except (KeyError, ValueError):
                    skip_pos.append(pos)
                    pos += 1
                    continue
            task.node_name = node.name
            lazy_insert(ntasks, key, task)
            applied_append(task)
            pos += 1

        # Applied rows + numpy grouping for the deferred status moves.
        if skip_pos:
            mask = np.ones(sel.shape[0], dtype=bool)
            mask[skip_pos] = False
            applied_sel = sel[mask]
            skipped = [(t_col[i], node_names_arr[int(n_idx[i])], k_list[i])
                       for i in skip_pos]
        else:
            applied_sel = sel
            skipped = []

        jseq = jobix[applied_sel]
        # touched_jobs in FIRST-TOUCH order (np.unique sorts by job
        # index; argsort of the first-occurrence positions restores the
        # placement-order first touch the tuple path records).
        uniq, first = np.unique(jseq, return_index=True)
        touch_order = uniq[np.argsort(first, kind="stable")].tolist()
        touched_jobs = {job_uids[i]: job_objs[i] for i in touch_order}

        alloc_moves: dict = {}
        pipe_moves: dict = {}
        k_arr = kind[applied_sel]
        for kk, moves in ((1, alloc_moves), (2, pipe_moves)):
            rows = applied_sel[k_arr == kk]
            if not rows.size:
                continue
            jr = jobix[rows]
            o = np.argsort(jr, kind="stable")  # placement order per job
            rows_sorted = rows[o]
            jr_sorted = jr[o]
            groups, starts = np.unique(jr_sorted, return_index=True)
            bounds = np.append(starts, rows_sorted.shape[0])
            for gi, j in enumerate(groups.tolist()):
                moves[job_uids[j]] = tasks_arr[
                    rows_sorted[bounds[gi]:bounds[gi + 1]]].tolist()

        self._settle_batch(agg.node_alloc, agg.node_pipe, touched_jobs,
                           applied, skipped, agg, alloc_moves, pipe_moves)

    def evict(self, reclaimee: TaskInfo, reason: str) -> None:
        """Evict through the cache, then mirror in-session (session.go:317-345).

        Batched commit (framework/commit.py): with the action's
        CommitSink active, the session mirror applies immediately (the
        rest of the walk depends on it) and the cluster effect defers
        to the action's single flush — same mirror, same decision
        order, one egress.  The sequential body below is the
        KUBE_BATCH_TPU_BATCH_COMMIT=0 control."""
        # The ``commit`` floor times exactly the CLUSTER-EFFECT side
        # (the machinery the batched flush replaces): the per-task
        # cache.evict round-trip here, or the sink flush.  The session
        # mirror below is identical work in both arms and deliberately
        # outside the floor.
        sink = self._commit_sink
        if sink is None:
            start = time.perf_counter()
            self.cache.evict(reclaimee, reason)
            metrics.note_eviction(reason)  # "reclaim" on the direct path
            trace.note_evict(reason)
            self._floor_commit += time.perf_counter() - start
        job = self.jobs.get(reclaimee.job)
        if job is None:
            if sink is not None:
                # The sequential path has already egressed by the time
                # it discovers the missing job: keep the effect (the
                # flush will evict) and surface the same error.
                sink.add_evict(reclaimee, reason)
            log = getattr(self, "_fused_mutlog", None)
            if log is not None:
                # Cluster effect without the session mirror: no storm
                # leg can model this — a kind the proof never matches.
                log.append(("evict_error", reclaimee.uid,
                            reclaimee.node_name))
            raise KeyError(f"failed to find job {reclaimee.job}")
        # Fused Releasing transition (ROADMAP 5a): the session-clone twin
        # of the truth mirror's evict_many fast path — one status-index
        # move plus a releasing add per victim instead of the
        # delete/re-add Resource churn and the node-side remove/clone/add
        # round trip, with the same dict-order side effects (both tasks
        # dicts end with the victim at the END, exactly as the slow pair
        # leaves them).
        self._dirty_job(reclaimee.job)
        job.release_task(reclaimee)
        node = self.nodes.get(reclaimee.node_name)
        if node is not None:
            self._dirty_node(reclaimee.node_name)
            node.release_resident(reclaimee)
        self._fire_deallocate(reclaimee)
        if sink is not None:
            sink.add_evict(reclaimee, reason)
        log = getattr(self, "_fused_mutlog", None)
        if log is not None:
            log.append(("evict", reclaimee.uid, reclaimee.node_name))

    def update_job_condition(self, job_info: JobInfo, cond: PodGroupCondition):
        """Upsert a PodGroup condition by type (session.go:348-369)."""
        job = self.jobs.get(job_info.uid)
        if job is None:
            raise KeyError(f"failed to find job {job_info.namespace}/{job_info.name}")
        self._dirty_job(job.uid)
        if cond.type == PodGroupUnschedulableType and cond.status == "True":
            # Every unschedulable verdict (job_valid gate at open, gang's
            # close pass) flows through here: record it in the session
            # trace so /debug/why answers from the flight recorder.
            # Namespace-qualified: job names are only unique per
            # namespace, and a bare-name key would let ns-b/train
            # clobber ns-a/train's reason.
            trace.note_verdict(f"{job.namespace}/{job.name}",
                               cond.reason, cond.message)
        conditions = job.pod_group.status.conditions
        for i, c in enumerate(conditions):
            if c.type == cond.type:
                conditions[i] = cond
                return
        conditions.append(cond)


# ----------------------------------------------------------------------
# lifecycle (framework.go:30-63, session.go:63-184)

def open_session(cache, tiers: List[Tier],
                 plugin_builders=None) -> Session:
    from .registry import get_plugin_builder

    ssn = Session(cache)
    # Memory-ledger baseline for the session's mem_delta trace
    # annotation (close_session; doc/OBSERVABILITY.md "Memory ledger").
    ssn._mem_open = memledger.totals()
    with trace.span("snapshot"):
        # Chaos site: a session-open snapshot failure is the whole cycle
        # dying at its first step — the loop must swallow it and back off
        # (doc/CHAOS.md site ``session.snapshot``; no-op branch when the
        # chaos engine is off).
        plan = chaos_plan.PLAN
        if plan is not None and plan.fire("session.snapshot"):
            raise RuntimeError("chaos: session snapshot failed (injected)")
        snap_start = time.perf_counter()
        snapshot: ClusterInfo = cache.snapshot()
        metrics.set_cycle_floor("snapshot",
                                time.perf_counter() - snap_start)
    # Wire-decode floor: the wall time reflector threads spent decoding
    # watch frames since the last session — attributed to the cycle that
    # absorbs the churn (0 for in-process caches; the wire A/B reads it).
    metrics.set_cycle_floor("decode", metrics.take_decode_seconds())
    # Pod-lineage session ledger: this open is the "first consider" for
    # every pod ingested since the previous one (trace/lineage.py).
    pod_lineage.note_session_open()
    ssn.jobs = snapshot.jobs
    ssn.nodes = snapshot.nodes
    ssn.queues = snapshot.queues
    ssn.tiers = tiers

    # Instantiate plugins and open them on the session.
    for tier in tiers:
        for option in tier.plugins:
            if option.name in ssn.plugins:
                continue
            builder = (plugin_builders or {}).get(option.name) \
                if plugin_builders else None
            if builder is None:
                builder = get_plugin_builder(option.name)
            if builder is None:
                raise KeyError(f"failed to get plugin {option.name}")
            plugin = builder(option.arguments)
            ssn.plugins[plugin.name()] = plugin

    for plugin in ssn.plugins.values():
        start = time.time()
        with trace.span("plugin." + plugin.name(), on="open"):
            plugin.on_session_open(ssn)
        metrics.observe_plugin_latency(plugin.name(), "OnSessionOpen",
                                       time.time() - start)

    # Gate invalid jobs (gang minAvailable) out of the session, recording the
    # unschedulable condition (session.go:89-108).
    #
    # Wire fast path: jobs provably passing (valid >= minAvailable from
    # the persistent per-job columns, the only check the stock gang
    # validator performs) skip the validator chain — a passing job is
    # unobservable through this gate, so the skip is bit-parity
    # (models/incremental.job_valid_pass_uids; None = control arm or a
    # non-stock validator registered, full walk below).
    from ..models.incremental import job_valid_pass_uids
    fast_pass = job_valid_pass_uids(ssn)
    for job in list(ssn.jobs.values()):
        if fast_pass is not None and job.uid in fast_pass:
            continue
        vr = ssn.job_valid(job)
        if vr is not None and not vr.pass_:
            if job.pod_group is not None:
                cond = PodGroupCondition(
                    type=PodGroupUnschedulableType, status="True",
                    transition_id=ssn.uid, last_transition_time=time.time(),
                    reason=vr.reason, message=vr.message)
                ssn.update_job_condition(job, cond)
                try:
                    ssn.cache.update_job_status(job)
                except Exception:
                    # A failed PodGroup status write must not abort the
                    # session open; countable instead of invisible.
                    metrics.note_swallowed("job_status_update")
            del ssn.jobs[job.uid]

    return ssn


def _close_one_job(ssn: Session, job: JobInfo) -> bool:
    """One job's close-out — the exact per-job body of the reference
    walk (session.go:119-144).  Returns True when the outcome was
    provably SILENT: nothing was pushed, no event was appended, no pod
    condition was written, AND (because the clone is bit-unchanged until
    it re-enters the dirty set) re-running it next cycle would be just as
    silent — the license for the incremental close to skip it."""
    if job.pod_group is None:
        ssn.cache.record_job_status_event(job)
        return _close_is_silent(job)
    status = job.pod_group.status
    phase, running, failed, succeeded = _derive_job_status(ssn, job)
    if (job.uid in ssn.mutated_jobs
            or (status.phase, status.running, status.failed,
                status.succeeded) != (phase, running, failed,
                                      succeeded)):
        # The session touched the job (placements, conditions) or the
        # derived status moved: push it.  mutated_jobs matters for
        # condition-only changes (e.g. gang Unschedulable), which the
        # phase/count compare cannot see.
        ssn._dirty_job(job.uid)
        status.phase = phase
        status.running = running
        status.failed = failed
        status.succeeded = succeeded
        try:
            ssn.cache.update_job_status(job)
        except Exception:
            # Same policy as open_session's discard path: the close
            # must finish; the failure is counted.
            metrics.note_swallowed("job_status_update")
        return False  # pushed (and the echo re-dirties it anyway)
    ssn.cache.record_job_status_event(job)
    return _close_is_silent(job)


def _close_is_silent(job: JobInfo) -> bool:
    """Whether record_job_status_event(job) observably did anything:
    mirrors its guards exactly — a non-shadow Pending/Unknown PodGroup
    (or a PDB job with Pending tasks) appends an Unschedulable event, and
    any Allocated/Pending task gets a pod condition + FailedScheduling
    event.  A True verdict is stable for an unchanged clone, so the
    incremental close may skip the job until it re-enters a dirty set."""
    from ..cache.shadow import shadow_pod_group
    pg = job.pod_group
    if not shadow_pod_group(pg):
        if pg is not None and pg.status.phase in (PodGroupUnknown,
                                                  PodGroupPending):
            return False
        if job.pdb is not None and \
                job.task_status_index.get(TaskStatus.Pending):
            return False
    if job.task_status_index.get(TaskStatus.Allocated) \
            or job.task_status_index.get(TaskStatus.Pending):
        return False
    return True


def close_session(ssn: Session) -> None:
    # Fused-dispatch ledger hygiene (ops/fused_solver.py): an alloc leg
    # nobody consumed still holds an in-flight handle — retire it before
    # the inflight gauge audit.
    from ..ops import fused_solver
    fused_solver.finalize_session(ssn)
    # plugin_close floor: the gang not-ready walk dominates this loop at
    # scale; the vectorized form (plugins/gang.py) must actually kill it
    # — the bench gate watches this number (doc/INCREMENTAL.md).
    plugin_close_start = time.perf_counter()
    for plugin in ssn.plugins.values():
        start = time.time()
        with trace.span("plugin." + plugin.name(), on="close"):
            plugin.on_session_close(ssn)
        metrics.observe_plugin_latency(plugin.name(), "OnSessionClose",
                                       time.time() - start)
    metrics.set_cycle_floor("plugin_close",
                            time.perf_counter() - plugin_close_start)

    # PodGroup status writeback (session.go:119-144).  The status write is
    # gated on an actual change: a no-op UpdatePodGroup would differ from
    # the derived state by nothing, and skipping it keeps pristine job
    # clones reusable by the snapshot pool (events and pod conditions are
    # still recorded every cycle, as the reference does).
    #
    # Incremental close (doc/INCREMENTAL.md "floors"): after an
    # incremental snapshot, only the session's touched jobs, the freshly
    # re-cloned ones, and the jobs whose last close was not provably
    # silent are walked — every skipped job is bit-unchanged since a
    # close that observably did nothing, so the event stream, condition
    # writes, and status pushes are identical to the full walk (the
    # churn parity gate pins it).  Candidates run in truth (seq) order so
    # multi-job event interleaving matches the control exactly.
    from ..models import incremental
    close_start = time.perf_counter()
    plan = None
    if incremental.incremental_enabled():
        close_plan = getattr(ssn.cache, "close_plan", None)
        if close_plan is not None:
            plan = close_plan()
    walked = 0
    if plan is None:
        active = set()
        for job in ssn.jobs.values():
            walked += 1
            if not _close_one_job(ssn, job):
                active.add(job.uid)
        if incremental.incremental_enabled():
            note = getattr(ssn.cache, "note_close_results", None)
            if note is not None:
                note(active)
    else:
        old_active, recloned, seqmap = plan
        process = old_active | recloned | set(ssn.mutated_jobs)
        active = set(old_active)
        tail = float("inf")
        for uid in sorted(process, key=lambda u: seqmap.get(u, tail)):
            job = ssn.jobs.get(uid)
            if job is None:
                active.discard(uid)
                continue
            walked += 1
            if _close_one_job(ssn, job):
                active.discard(uid)
            else:
                active.add(uid)
        ssn.cache.note_close_results(active)
    metrics.set_close_objects_walked(walked)
    metrics.set_cycle_floor("close", time.perf_counter() - close_start)

    # Commit/apply floors (doc/EVICTION.md "Batched commit"): the
    # session's accumulated effect-side wall time — what the eviction
    # actions paid committing effects to the cluster (batched flushes
    # or the sequential per-task control) and what tpu-allocate paid
    # applying placements.  Published every session so the bench gate
    # and the commit A/B can attribute storm regressions.
    metrics.set_cycle_floor("commit", ssn._floor_commit)
    metrics.set_cycle_floor("apply", ssn._floor_apply)

    # Publish the cycle's mutation footprint: the dirty-set sizes that
    # bound the next cycle's incremental staging and delta ship.  The
    # incremental session state accumulates the same footprint as the
    # churn the NEXT cycle's plan reports (models/incremental.py).
    metrics.set_session_mutations(len(ssn.mutated_jobs),
                                  len(ssn.mutated_nodes))
    from ..models import incremental
    incremental.note_session_mutations(ssn.cache, len(ssn.mutated_jobs),
                                       len(ssn.mutated_nodes))

    # Per-session memory footprint: which ledgers this session grew or
    # shrank, annotated onto the trace ("which session peaked the stage
    # buffers" is then a /debug/sessions read, not a bisection).
    mem_open = getattr(ssn, "_mem_open", None)
    if mem_open is not None:
        mem_delta = {name: nbytes - mem_open.get(name, 0)
                     for name, nbytes in memledger.totals().items()
                     if nbytes != mem_open.get(name, 0)}
        if mem_delta:
            trace.set_meta(mem_delta=mem_delta)

    ssn.jobs = {}
    ssn.nodes = {}
    ssn.queues = {}
    ssn.plugins = {}
    ssn.event_handlers = []
    ssn.prescan = {}


def _derive_job_status(ssn: Session, job_info: JobInfo):
    """(phase, running, failed, succeeded) from session state, without
    mutating (session.go:146-184)."""
    status = job_info.pod_group.status
    unschedulable = any(
        c.type == PodGroupUnschedulableType and c.status == "True"
        and c.transition_id == ssn.uid
        for c in status.conditions)

    if job_info.task_status_index.get(TaskStatus.Running) and unschedulable:
        phase = PodGroupUnknown
    else:
        allocated = 0
        for st, tasks in job_info.task_status_index.items():
            if allocated_status(st):
                allocated += len(tasks)
        if allocated >= job_info.pod_group.spec.min_member:
            phase = PodGroupRunning
        else:
            phase = PodGroupPending
    return (phase,
            len(job_info.task_status_index.get(TaskStatus.Running, {})),
            len(job_info.task_status_index.get(TaskStatus.Failed, {})),
            len(job_info.task_status_index.get(TaskStatus.Succeeded, {})))


def job_status(ssn: Session, job_info: JobInfo):
    """Derive and apply the PodGroup phase (session.go:146-184)."""
    status = job_info.pod_group.status
    (status.phase, status.running, status.failed,
     status.succeeded) = _derive_job_status(ssn, job_info)
    return status
