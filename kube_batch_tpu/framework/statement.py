"""Statement: session-level transaction for speculative preemption.

Mirrors /root/reference/pkg/scheduler/framework/statement.go: Evict/Pipeline
apply session-side effects immediately and log operations; Commit replays
evictions to the cluster; Discard rolls back in reverse order.
"""

from __future__ import annotations

from typing import List, Tuple

from ..api import TaskInfo, TaskStatus
from .events import Event


def unevict_session(ssn, reclaimee: TaskInfo) -> None:
    """Restore one evicted task's session state (the rollback side of a
    session eviction): status back to Running, node accounting, the
    deallocate event reversed, and the session-shared VictimIndex
    counted back in.  Shared by Statement rollback (discard and
    commit-failure) and the batched commit flush's degradation path
    (framework/commit.py) so every restore runs the same altitude."""
    job = ssn.jobs.get(reclaimee.job)
    if job is not None:
        ssn._dirty_job(reclaimee.job)
        job.update_task_status(reclaimee, TaskStatus.Running)
    node = ssn.nodes.get(reclaimee.node_name)
    if node is not None:
        ssn._dirty_node(reclaimee.node_name)
        node.update_task(reclaimee)
    ssn._fire_allocate(reclaimee)
    # Count the restored Running resident back into the session-shared
    # VictimIndex (the evicting action counted it out at evict time).
    # Living here covers every rollback path — discard, commit-failure,
    # and the batched flush's degradation — at one altitude; an
    # under-counted index would let later preemptors skip nodes holding
    # victims.
    idx = getattr(ssn, "_victim_index", None)
    if idx is not None and job is not None:
        idx.on_restore(reclaimee.node_name, job.queue, reclaimee.job)


class Statement:

    def __init__(self, ssn):
        self.ssn = ssn
        self.operations: List[Tuple[str, tuple]] = []

    # -- forward ops --------------------------------------------------------

    def evict(self, reclaimee: TaskInfo, reason: str) -> None:
        """Session-side eviction, logged for commit/rollback (go:36-76).

        The mirror transition is the fused Releasing fast path
        (JobInfo.release_task + NodeInfo.release_resident, ROADMAP 5a):
        the eviction decision walk calls this once per victim, and the
        old update_task_status + node.update_task pair paid a
        delete/re-add Resource round trip and a fresh task clone per
        call.  End state — including both tasks dicts' iteration order —
        is identical to the slow pair (pinned by the evict/commit parity
        gates)."""
        job = self.ssn.jobs.get(reclaimee.job)
        if job is not None:
            self.ssn._dirty_job(reclaimee.job)
            job.release_task(reclaimee)
        node = self.ssn.nodes.get(reclaimee.node_name)
        if node is not None:
            self.ssn._dirty_node(reclaimee.node_name)
            node.release_resident(reclaimee)
        self.ssn._fire_deallocate(reclaimee)
        self.operations.append(("evict", (reclaimee, reason)))

    def pipeline(self, task: TaskInfo, hostname: str) -> None:
        """Session-side pipeline, logged for rollback (go:113-155)."""
        job = self.ssn.jobs.get(task.job)
        if job is not None:
            self.ssn._dirty_job(task.job)
            job.update_task_status(task, TaskStatus.Pipelined)
        node = self.ssn.nodes.get(hostname)
        if node is not None:
            self.ssn._dirty_node(hostname)
            node.add_task(task)
        self.ssn._fire_allocate(task)
        self.operations.append(("pipeline", (task, hostname)))

    # -- rollback helpers ---------------------------------------------------
    # (rollback targets were dirtied by the forward op; a rollback restores
    # scheduling state but not bit-identical dict order, so the clones stay
    # out of the snapshot pool for this cycle)

    def _unevict(self, reclaimee: TaskInfo) -> None:
        unevict_session(self.ssn, reclaimee)

    def _unpipeline(self, task: TaskInfo) -> None:
        job = self.ssn.jobs.get(task.job)
        if job is not None:
            self.ssn._dirty_job(task.job)
            job.update_task_status(task, TaskStatus.Pending)
        node = self.ssn.nodes.get(task.node_name)
        if node is not None:
            self.ssn._dirty_node(task.node_name)
            node.remove_task(task)
        task.node_name = ""
        self.ssn._fire_deallocate(task)

    # -- transaction outcomes ----------------------------------------------

    def discard(self) -> None:
        """Roll back all logged operations in reverse (go:196-207)."""
        for name, args in reversed(self.operations):
            if name == "evict":
                self._unevict(args[0])
            elif name == "pipeline":
                self._unpipeline(args[0])
        self.operations.clear()

    def commit(self) -> None:
        """Replay evictions against the cluster; pipelines stay session-only
        (go:210-220).

        Batched commit (framework/commit.py): with the action's
        CommitSink active, the committed evictions hand off to the
        per-action accumulator instead of egressing here — the sink's
        single flush replays them in this exact order, so the victim
        sequence and event stream equal the sequential loop below (the
        KUBE_BATCH_TPU_BATCH_COMMIT=0 control)."""
        import time

        from ..metrics import metrics
        from ..trace import spans as trace
        sink = getattr(self.ssn, "_commit_sink", None)
        if sink is not None:
            for name, args in self.operations:
                if name == "evict":
                    sink.add_evict(args[0], args[1])
            self.operations.clear()
            return
        start = time.perf_counter()
        for name, args in self.operations:
            if name == "evict":
                reclaimee, reason = args
                try:
                    self.ssn.cache.evict(reclaimee, reason)
                except Exception:  # lint: allow-swallow(commit continues past one failed evict; _unevict restores session state and cache.evict queued the resync)
                    self._unevict(reclaimee)  # also restores VictimIndex
                else:
                    # Per-action eviction attribution (the reason string
                    # IS the deciding action: "preempt" here).
                    metrics.note_eviction(reason)
                    trace.note_evict(reason)
        self.operations.clear()
        self.ssn._floor_commit += time.perf_counter() - start
