"""Typed access over per-plugin string arguments.

Mirrors /root/reference/pkg/scheduler/framework/arguments.go:28-76.
"""

from __future__ import annotations

from typing import Dict, Optional


class Arguments(dict):
    """``map[string]string`` plugin arguments with typed getters."""

    def get_int(self, key: str, default: Optional[int] = None) -> Optional[int]:
        value = self.get(key)
        if value is None or value == "":
            return default
        try:
            return int(value)
        except (TypeError, ValueError):
            return default

    def get_float(self, key: str, default: Optional[float] = None) -> Optional[float]:
        value = self.get(key)
        if value is None or value == "":
            return default
        try:
            return float(value)
        except (TypeError, ValueError):
            return default

    def get_bool(self, key: str, default: bool = False) -> bool:
        value = self.get(key)
        if value is None or value == "":
            return default
        return str(value).strip().lower() in ("1", "t", "true", "y", "yes")
