"""Batched statement commit: the per-action effect flush.

The effect side of a session used to be a Python for-loop per task:
``Statement.commit`` (preempt) and the direct ``Session.evict`` path
(reclaim) each drove one ``cache.evict`` round-trip per victim — one
mutex acquisition, one effector call, one event append, one lineage
note, per task.  After the batched eviction solve (doc/EVICTION.md)
this commit machinery was the last sequential wall of a preemption
storm (~1.0-1.5 s of a 50k x 10k cycle).

This module accumulates an action's cluster-side effects in decision
order and flushes them as ONE fused cache update plus ONE bulk egress
call per action (``SchedulerCache.evict_many``): one mutex acquisition
for the whole truth mirror, one events extend, one lineage batch, one
victim-index-consistent restore path for failures.

Ordering contract (the bit-parity the tests pin): effects flush in the
exact order the action decided them, and no other cache event can
interleave within an action (binds egress at the gang-dispatch barrier
inside ``batch_apply``, session-only pipelines never egress), so the
cache event stream, the evictor's victim sequence, and the lineage
sample order are identical to the sequential control —
``KUBE_BATCH_TPU_BATCH_COMMIT=0``.  The concurrent shard pipeline
(tenancy/pipeline.py) extends the same contract ACROSS shards: actions
— and therefore their sinks' flushes — run only in a micro-session's
retire half, and retire halves execute in deterministic shard order, so
per-shard flush sequences never interleave no matter how many shard
dispatches are in flight (doc/TENANCY.md "Concurrent micro-sessions").

Failure contract (doc/CHAOS.md site ``commit.flush_error``): an effect
the bulk egress could not land is re-driven once through the per-task
sequential path (counted as a degraded flush); if that also fails, the
session state is restored exactly as the sequential path's per-task
failure handling would — ``unevict_session`` — so no effect is ever
dropped or double-applied.  Ambiguous outcomes are never re-driven
(the resync machinery owns them, cache/interface.py).
"""

from __future__ import annotations

import contextlib
import time
from typing import List, Tuple

from .. import knobs

BATCH_COMMIT_ENV = knobs.BATCH_COMMIT.env


def batch_commit_enabled() -> bool:
    return knobs.BATCH_COMMIT.enabled()


class CommitSink:
    """One action's deferred cluster-effect accumulator, installed on
    the session as ``ssn._commit_sink`` for the action's lifetime
    (``action_commit`` below).  ``Statement.commit`` and the sink-aware
    ``Session.evict`` append here instead of calling the effector; the
    flush at action exit is the single egress."""

    __slots__ = ("ssn", "action", "evicts")

    def __init__(self, ssn, action: str):
        self.ssn = ssn
        self.action = action
        self.evicts: List[Tuple[object, str]] = []  # (task, reason)

    def add_evict(self, task, reason: str) -> None:
        self.evicts.append((task, reason))

    def _restore(self, task) -> None:
        """Best-effort session restore of one failed effect.  A restore
        can itself fail when the victim's released room was already
        consumed by a later pipeline (the same arithmetic dead end the
        sequential commit-failure path has); the already-queued resync
        owns the repair either way, so the flush must not die here and
        take the remaining restores with it."""
        from ..metrics import metrics
        from .statement import unevict_session
        try:
            unevict_session(self.ssn, task)
        except Exception:  # lint: allow-swallow(restore is best-effort: the failed effect's resync is already queued and the next snapshot rebuilds from truth; counted, not fatal)
            metrics.note_swallowed("commit_unevict")

    def flush(self) -> None:
        """One fused cache update + one bulk egress for everything the
        action committed.  Leaves the sink empty (an action may flush
        more than once only if it re-enters; the context manager
        flushes exactly once at exit)."""
        if not self.evicts:
            return
        from ..cache.interface import AmbiguousOutcomeError
        from ..metrics import metrics
        from ..trace import spans as trace

        ssn = self.ssn
        pairs = self.evicts
        self.evicts = []
        start = time.perf_counter()
        with trace.span("commit.flush", action=self.action,
                        batch=len(pairs)):
            failures = ssn.cache.evict_many(pairs)
            landed_counts: dict = {}
            for task, reason in pairs:
                landed_counts[reason] = landed_counts.get(reason, 0) + 1
            if failures:
                # Degrade the remainder to the per-task sequential path:
                # a failed bulk egress must not drop an effect (the
                # retry) nor double-apply one (only non-landed effects
                # are re-driven; evict_many already mirrored the landed
                # prefix).  Ambiguous outcomes are never re-driven —
                # evict_many queued their resync.
                for task, reason, exc in failures:
                    landed_counts[reason] -= 1
                    if isinstance(exc, AmbiguousOutcomeError):
                        self._restore(task)
                        continue
                    try:
                        ssn.cache.evict(task, reason)
                    except Exception:  # lint: allow-swallow(sequential-path semantics: a victim whose evict fails is restored and skipped; cache.evict queued the resync)
                        self._restore(task)
                    else:
                        landed_counts[reason] += 1
        for reason, count in landed_counts.items():
            metrics.note_evictions(reason, count)
            trace.note_evicts(reason, count)
        trace.counter(f"commit.flush.{self.action}", len(pairs))
        metrics.note_commit_flush(
            self.action, "degraded" if failures else "batched", len(pairs))
        ssn._floor_commit += time.perf_counter() - start


def _defer_to_dispatch_window(ssn, action: str) -> bool:
    """Whether this action's sink flush rides the fused dispatch window
    (doc/FUSED.md "Storm half"): a fused program with a live alloc leg
    is in flight and tpu-allocate still runs LATER in this session's
    ladder — its finish flushes the deferred sink right before touching
    the device result, so the cluster egress overlaps the device wait
    and an eviction-heavy cycle converges to one dispatch + one fused
    flush.  Event order is preserved by construction: the evicts still
    flush before the session's binds (batch_apply egresses binds after
    finish starts), and the sequential control
    (KUBE_BATCH_TPU_BATCH_COMMIT=0) never builds a sink at all."""
    from .. import knobs as _knobs
    if not (_knobs.FUSED.enabled() and _knobs.FUSED_STORM.enabled()):
        return False
    st = getattr(ssn, "_fused_state", None)
    if st is None or not st.dispatched or st.failed:
        return False
    if st.alloc_pending is None:
        # No alloc leg in flight: tpu-allocate may early-out without a
        # finish continuation, and a later action could bind before the
        # close-time safety flush — keep the at-exit flush.
        return False
    names = tuple(getattr(ssn, "_conf_actions", ()) or ())
    if action not in names or "tpu-allocate" not in names:
        return False
    return names.index(action) < names.index("tpu-allocate")


@contextlib.contextmanager
def action_commit(ssn, action: str):
    """Install a CommitSink on ``ssn`` for the duration of one action's
    execute, flushing at exit (including the exception path — effects
    already mirrored into the session MUST reach the cluster, or truth
    and session diverge until resync).  A no-op handing back the outer
    sink when one is already active (nested actions accumulate into
    their caller's flush), and a no-op entirely under the sequential
    control arm.

    Storm half (ops/fused_solver.flush_deferred): when a fused dispatch
    with a live alloc leg is in flight and tpu-allocate runs later in
    the ladder, the at-exit flush defers into that action's device-wait
    window instead — same sink, same effect order, one fused flush."""
    if not batch_commit_enabled():
        yield None
        return
    existing = getattr(ssn, "_commit_sink", None)
    if existing is not None:
        yield existing
        return
    sink = CommitSink(ssn, action)
    ssn._commit_sink = sink
    try:
        yield sink
    finally:
        ssn._commit_sink = None
        if sink.evicts and _defer_to_dispatch_window(ssn, action):
            deferred = getattr(ssn, "_deferred_flush", None)
            if deferred is None:
                deferred = []
                ssn._deferred_flush = deferred
            deferred.append(sink)
        else:
            sink.flush()
