"""Global action/plugin registries.

Mirrors /root/reference/pkg/scheduler/framework/plugins.go:26-88 (mutex-guarded
maps; plugin builders are ``Arguments -> Plugin`` factories).
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional

from .arguments import Arguments
from .interface import Action, Plugin

PluginBuilder = Callable[[Arguments], Plugin]

_lock = threading.Lock()
_plugin_builders: Dict[str, PluginBuilder] = {}  # guarded-by: _lock
_actions: Dict[str, Action] = {}                 # guarded-by: _lock


def register_plugin_builder(name: str, builder: PluginBuilder) -> None:
    with _lock:
        _plugin_builders[name] = builder


def get_plugin_builder(name: str) -> Optional[PluginBuilder]:
    with _lock:
        return _plugin_builders.get(name)


def cleanup_plugin_builders() -> None:
    with _lock:
        _plugin_builders.clear()


def register_action(action: Action) -> None:
    with _lock:
        _actions[action.name()] = action


def get_action(name: str) -> Optional[Action]:
    with _lock:
        return _actions.get(name)


def list_actions() -> Dict[str, Action]:
    with _lock:
        return dict(_actions)
