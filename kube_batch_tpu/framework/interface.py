"""Action and Plugin interfaces (reference framework/interface.go:19-42)."""

from __future__ import annotations

import abc


class Action(abc.ABC):
    """A scheduling policy step executed once per session."""

    @abc.abstractmethod
    def name(self) -> str: ...

    def initialize(self) -> None: ...

    @abc.abstractmethod
    def execute(self, ssn) -> None: ...

    def uninitialize(self) -> None: ...


class Plugin(abc.ABC):
    """An extension hooked into Session callback registries."""

    @abc.abstractmethod
    def name(self) -> str: ...

    @abc.abstractmethod
    def on_session_open(self, ssn) -> None: ...

    def on_session_close(self, ssn) -> None: ...
