"""Session events (reference framework/event.go:20-31)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional

from ..api.job_info import TaskInfo


@dataclass
class Event:
    task: TaskInfo


@dataclass
class EventHandler:
    """Allocate/Deallocate callbacks plugins register to keep incremental
    state (DRF shares, proportion allocations) in sync with decisions."""
    allocate_func: Optional[Callable[[Event], None]] = None
    deallocate_func: Optional[Callable[[Event], None]] = None
