"""Session events (reference framework/event.go:20-31)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional

from ..api.job_info import TaskInfo


@dataclass
class Event:
    task: TaskInfo


@dataclass
class AllocateBatch:
    """Argument to EventHandler.batch_allocate_func.

    ``tasks`` is always set (placement order).  When the caller has
    vectorized aggregates (the tpu-allocate apply path), ``job_sums`` maps
    job uid -> Resource summed over the batch and ``node_quanta`` maps node
    name -> (cpu, mem) int grid quanta summed over the batch, letting
    plugins skip per-task work; both are None on the generic path."""
    tasks: list
    job_sums: Optional[dict] = None
    node_quanta: Optional[dict] = None


@dataclass
class EventHandler:
    """Allocate/Deallocate callbacks plugins register to keep incremental
    state (DRF shares, proportion allocations) in sync with decisions.

    ``batch_allocate_func`` is an optional bulk form taking an
    AllocateBatch: plugin state updates are linear in the placed tasks, so
    a batch apply (Session.batch_apply) lets plugins aggregate per job/
    queue/node instead of paying one callback per task.  When absent, the
    batch path falls back to per-task allocate_func calls."""
    allocate_func: Optional[Callable[[Event], None]] = None
    deallocate_func: Optional[Callable[[Event], None]] = None
    batch_allocate_func: Optional[Callable[["AllocateBatch"], None]] = None
