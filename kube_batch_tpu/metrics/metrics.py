"""Scheduler metrics: a small dependency-free Prometheus-style registry.

Keeps the reference's collector set and names
(/root/reference/pkg/scheduler/metrics/metrics.go:27-121, subsystem
``kube_batch``): e2e/plugin/action/task latency histograms,
schedule_attempts_total, preemption victims/attempts, unschedule task/job
counts, job_retry_counts.  Exposition-format text is served by
``kube_batch_tpu.cli.server``.
"""

from __future__ import annotations

import logging
import threading
from collections import defaultdict
from typing import Dict, List, Tuple

from .. import knobs

SUBSYSTEM = "kube_batch"

log = logging.getLogger(__name__)

# ----------------------------------------------------------------------
# Label-cardinality bound (doc/OBSERVABILITY.md "SLO metrics"): metrics
# labeled by USER-INFLUENCED names (queue / namespace) cap their distinct
# series; past the cap, new label values collapse into one ``other``
# series and the rerouted observations count in
# ``kube_batch_metric_series_dropped_total{metric}`` — a namespace storm
# can no longer grow the Prometheus scrape without bound.  The cap env
# is validated like ops/solver.shard_knobs: a malformed value warns
# loudly exactly once and pins the default.

SERIES_CAP_ENV = knobs.METRIC_SERIES_CAP.env
DEFAULT_SERIES_CAP = knobs.METRIC_SERIES_CAP.default

_series_lock = threading.Lock()
_series_seen: Dict[str, set] = {}       # guarded-by: _series_lock
_series_cap = None                      # guarded-by: _series_lock
OTHER_LABEL = "other"


def _resolve_series_cap() -> int:
    return knobs.METRIC_SERIES_CAP.value()


def refresh_series_cap() -> int:
    """Re-resolve the series cap from the current environment — the
    deliberate test hook (mirror of ops.solver.refresh_shard_knobs).
    Forgets which label values were already admitted."""
    global _series_cap
    with _series_lock:
        _series_cap = None
        _series_seen.clear()
    return series_cap()


def series_cap() -> int:
    global _series_cap
    with _series_lock:
        if _series_cap is None:
            _series_cap = _resolve_series_cap()
        return _series_cap


def bounded_label(metric: str, value: str) -> str:
    """Admit ``value`` as a label for ``metric``, or reroute it to the
    shared ``other`` bucket once the metric's distinct-series cap is
    reached (counting the reroute).  The seen-set is itself bounded by
    the cap, so adversarial cardinality cannot grow THIS state either."""
    value = str(value) if value else "none"
    global _series_cap
    with _series_lock:
        if _series_cap is None:
            _series_cap = _resolve_series_cap()
        seen = _series_seen.get(metric)
        if seen is None:
            seen = _series_seen[metric] = set()
        if value in seen:
            return value
        if len(seen) >= _series_cap:
            dropped = True
        else:
            seen.add(value)
            dropped = False
    if dropped:
        series_dropped.inc(1.0, metric)
        return OTHER_LABEL
    return value


def _exp_buckets(start: float, factor: float, count: int) -> List[float]:
    out, v = [], start
    for _ in range(count):
        out.append(v)
        v *= factor
    return out


def _escape_label(value) -> str:
    """Prometheus text-format label-value escaping (backslash, double
    quote, newline).  Label values here are user-influenced — job names
    and error-site strings flow in verbatim — so raw interpolation would
    let one adversarial name break the whole scrape."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _escape_help(text: str) -> str:
    """HELP-line escaping (backslash and newline; quotes are legal)."""
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


def _label_str(names, values) -> str:
    return ",".join(f'{n}="{_escape_label(v)}"'
                    for n, v in zip(names, values))


class Histogram:
    def __init__(self, name: str, help_: str, buckets: List[float],
                 label_names: Tuple[str, ...] = ()):
        self.name = name
        self.help = help_
        self.buckets = buckets
        self.label_names = label_names
        self._lock = threading.Lock()
        self._counts: Dict[tuple, List[int]] = defaultdict(
            lambda: [0] * (len(buckets) + 1))        # guarded-by: _lock
        self._sums: Dict[tuple, float] = defaultdict(float)    # guarded-by: _lock
        self._totals: Dict[tuple, int] = defaultdict(int)      # guarded-by: _lock

    def observe(self, value: float, *labels: str) -> None:
        with self._lock:
            counts = self._counts[labels]
            for i, bound in enumerate(self.buckets):
                if value <= bound:
                    counts[i] += 1
                    break
            else:
                counts[-1] += 1
            self._sums[labels] += value
            self._totals[labels] += 1

    def observe_many(self, values, *labels: str) -> None:
        """Bulk observation (one lock + vectorized bucketing): the batched
        dispatch path records 50k task latencies per session."""
        import numpy as np
        arr = np.asarray(values, dtype=np.float64)
        if arr.size == 0:
            return
        idx = np.searchsorted(np.asarray(self.buckets), arr, side="left")
        bincounts = np.bincount(idx, minlength=len(self.buckets) + 1)
        with self._lock:
            counts = self._counts[labels]
            for i, c in enumerate(bincounts):
                if c:
                    counts[i] += int(c)
            self._sums[labels] += float(arr.sum())
            self._totals[labels] += int(arr.size)

    def expose(self) -> str:
        lines = [f"# HELP {self.name} {_escape_help(self.help)}",
                 f"# TYPE {self.name} histogram"]
        with self._lock:
            for labels, counts in self._counts.items():
                label_str = _label_str(self.label_names, labels)
                cumulative = 0
                for bound, c in zip(self.buckets, counts):
                    cumulative += c
                    le = f'le="{bound}"'
                    sep = "," if label_str else ""
                    lines.append(
                        f"{self.name}_bucket{{{label_str}{sep}{le}}} {cumulative}")
                cumulative += counts[-1]
                sep = "," if label_str else ""
                lines.append(
                    f'{self.name}_bucket{{{label_str}{sep}le="+Inf"}} {cumulative}')
                braces = f"{{{label_str}}}" if label_str else ""
                lines.append(f"{self.name}_sum{braces} {self._sums[labels]}")
                lines.append(f"{self.name}_count{braces} {self._totals[labels]}")
        return "\n".join(lines)


class Counter:
    # The exposition TYPE keyword; Gauge overrides it.  A class attribute
    # (not string surgery on the rendered output) so a HELP text that
    # happens to contain the word "counter" cannot corrupt the format.
    TYPE = "counter"

    def __init__(self, name: str, help_: str, label_names: Tuple[str, ...] = ()):
        self.name = name
        self.help = help_
        self.label_names = label_names
        self._lock = threading.Lock()
        self._values: Dict[tuple, float] = defaultdict(float)  # guarded-by: _lock

    def inc(self, amount: float = 1.0, *labels: str) -> None:
        with self._lock:
            self._values[labels] += amount

    def value(self, *labels: str) -> float:
        with self._lock:
            return self._values.get(labels, 0.0)

    def values(self) -> Dict[tuple, float]:
        """Snapshot of every labeled value (bench/debug readers)."""
        with self._lock:
            return dict(self._values)

    def expose(self) -> str:
        lines = [f"# HELP {self.name} {_escape_help(self.help)}",
                 f"# TYPE {self.name} {self.TYPE}"]
        with self._lock:
            if not self._values:
                lines.append(f"{self.name} 0")
            for labels, v in self._values.items():
                label_str = _label_str(self.label_names, labels)
                braces = f"{{{label_str}}}" if label_str else ""
                lines.append(f"{self.name}{braces} {v}")
        return "\n".join(lines)


class Gauge(Counter):
    TYPE = "gauge"

    def set(self, value: float, *labels: str) -> None:
        with self._lock:
            self._values[labels] = value


class Registry:
    def __init__(self):
        self.collectors: List = []

    def register(self, collector):
        self.collectors.append(collector)
        return collector

    def expose(self) -> str:
        return "\n".join(c.expose() for c in self.collectors) + "\n"


registry = Registry()

# Latency buckets: 5ms * 2^k (metrics.go:38-45) and 5us * 2^k (:47-63).
_MS_BUCKETS = _exp_buckets(5.0, 2.0, 10)
_US_BUCKETS = _exp_buckets(5.0, 2.0, 10)

e2e_scheduling_latency = registry.register(Histogram(
    f"{SUBSYSTEM}_e2e_scheduling_latency_milliseconds",
    "E2e scheduling latency in milliseconds (scheduling algorithm + binding)",
    _MS_BUCKETS))
plugin_scheduling_latency = registry.register(Histogram(
    f"{SUBSYSTEM}_plugin_scheduling_latency_microseconds",
    "Plugin scheduling latency in microseconds", _US_BUCKETS,
    ("plugin", "on_session")))
action_scheduling_latency = registry.register(Histogram(
    f"{SUBSYSTEM}_action_scheduling_latency_microseconds",
    "Action scheduling latency in microseconds", _US_BUCKETS, ("action",)))
task_scheduling_latency = registry.register(Histogram(
    f"{SUBSYSTEM}_task_scheduling_latency_microseconds",
    "Task scheduling latency in microseconds", _US_BUCKETS))
schedule_attempts = registry.register(Counter(
    f"{SUBSYSTEM}_schedule_attempts_total",
    "Number of attempts to schedule pods, by result.", ("result",)))
preemption_victims = registry.register(Gauge(
    f"{SUBSYSTEM}_pod_preemption_victims",
    "Number of selected preemption victims"))
preemption_attempts = registry.register(Counter(
    f"{SUBSYSTEM}_total_preemption_attempts",
    "Total preemption attempts in the cluster till now"))
unschedule_task_count = registry.register(Gauge(
    f"{SUBSYSTEM}_unschedule_task_count",
    "Number of tasks could not be scheduled", ("job",)))
unschedule_job_count = registry.register(Gauge(
    f"{SUBSYSTEM}_unschedule_job_count",
    "Number of jobs could not be scheduled"))
job_retry_counts = registry.register(Counter(
    f"{SUBSYSTEM}_job_retry_counts",
    "Number of retry counts for one job", ("job",)))
# TPU sidecar extras (no reference counterpart): device solve time and
# transfer time for the tensorized sessions.
tpu_solve_latency = registry.register(Histogram(
    f"{SUBSYSTEM}_tpu_solve_latency_milliseconds",
    "On-device batch solve latency in milliseconds", _MS_BUCKETS))
tpu_transfer_latency = registry.register(Histogram(
    f"{SUBSYSTEM}_tpu_transfer_latency_milliseconds",
    "Host<->device snapshot transfer latency in milliseconds", _MS_BUCKETS))
tpu_apply_latency = registry.register(Histogram(
    f"{SUBSYSTEM}_tpu_apply_latency_milliseconds",
    "Host-side batched placement apply latency in milliseconds",
    _MS_BUCKETS))
# Compile-ahead subsystem (ops/compile_cache.py): a session solve whose
# (solver, bucket, cfg) signature was pre-compiled (warmup or an earlier
# solve) is a hit; a miss paid a fresh in-process XLA compile.
compile_cache_hits = registry.register(Counter(
    f"{SUBSYSTEM}_compile_cache_hits_total",
    "Session solves served by an already-compiled solver executable"))
compile_cache_misses = registry.register(Counter(
    f"{SUBSYSTEM}_compile_cache_misses_total",
    "Session solves that triggered a fresh in-process XLA compile"))
compile_cache_inflight = registry.register(Gauge(
    f"{SUBSYSTEM}_compile_cache_inflight",
    "Warmup bucket compiles currently pending or in flight"))
bucket_pad_waste = registry.register(Gauge(
    f"{SUBSYSTEM}_bucket_pad_waste_ratio",
    "Fraction of the padded bucket unused by real rows, per axis",
    ("axis",)))
# Pipelined session engine (actions/tpu_allocate.py, models/shipping.py):
# the solve dispatch/fetch split exposes how much host-side apply
# preparation actually overlapped the device solve, and how long the
# action then blocked waiting on the device; the ship counters record
# full vs dirty-row-delta input shipments and the bytes each moved.
tpu_host_overlap_latency = registry.register(Histogram(
    f"{SUBSYSTEM}_tpu_host_overlap_latency_milliseconds",
    "Host-side apply preparation overlapped with the device solve, ms",
    _MS_BUCKETS))
tpu_device_wait_latency = registry.register(Histogram(
    f"{SUBSYSTEM}_tpu_device_wait_latency_milliseconds",
    "Time the action blocked on the device result after overlap work, ms",
    _MS_BUCKETS))
ship_total = registry.register(Counter(
    f"{SUBSYSTEM}_tpu_ship_total",
    "SolverInputs shipments by mode (full | delta | clean)", ("mode",)))
ship_bytes = registry.register(Counter(
    f"{SUBSYSTEM}_tpu_ship_bytes_total",
    "Bytes moved host->device by SolverInputs shipments, by mode",
    ("mode",)))
# Sharded steady state (doc/SHARDING.md): per-device delta traffic of the
# mesh-sharded resident buffer (which shards' node rows went dirty and
# how many bytes each received — clean shards stay at ~0), and the route
# every solver-family dispatch took at the choose_solver_mesh /
# eviction-scan chokepoints.
ship_shard_bytes = registry.register(Counter(
    f"{SUBSYSTEM}_tpu_ship_shard_bytes_total",
    "Delta bytes shipped to each mesh device's node-shard region",
    ("shard",)))
solver_route = registry.register(Counter(
    f"{SUBSYSTEM}_solver_route_total",
    "Solver-family dispatches by routing family and chosen engine",
    ("family", "choice")))
# Scheduler loop health (scheduler.py): a persistently failing cycle or
# repair worker is visible on /metrics instead of vanishing into a bare
# ``except Exception``.
scheduler_loop_errors = registry.register(Counter(
    f"{SUBSYSTEM}_scheduler_loop_errors_total",
    "Exceptions swallowed by the scheduling loop, by stage", ("stage",)))
# Per-session mutation footprint (framework/session.py close_session):
# the dirty-set sizes that drive the delta-shipping and block-reuse
# paths — how much of the cluster each cycle actually churns.
session_mutated_jobs = registry.register(Gauge(
    f"{SUBSYSTEM}_session_mutated_jobs",
    "Job clones mutated by the last scheduling session"))
session_mutated_nodes = registry.register(Gauge(
    f"{SUBSYSTEM}_session_mutated_nodes",
    "Node clones mutated by the last scheduling session"))
# Reviewed-swallow visibility (graftlint exception-policy, doc/LINT.md):
# broad handlers that neither re-raise nor have a dedicated counter count
# here by site, so a permanently failing best-effort path shows up on
# /metrics instead of disappearing into `except Exception: pass`.
swallowed_exceptions = registry.register(Counter(
    f"{SUBSYSTEM}_swallowed_exceptions_total",
    "Exceptions swallowed by reviewed best-effort paths, by site",
    ("site",)))
# Batched eviction engine (doc/EVICTION.md): cluster-committed evictions
# split by the action that decided them (the bench artifact's opaque
# ``pipeline_evictions`` total, made attributable), and the VictimIndex's
# life-cycle events (matrix rebuilds, live evict/restore invalidations).
evictions_total = registry.register(Counter(
    f"{SUBSYSTEM}_evictions_total",
    "Cluster-committed evictions, by deciding action", ("action",)))
victim_index_events = registry.register(Counter(
    f"{SUBSYSTEM}_victim_index_events_total",
    "VictimIndex life-cycle events (rebuild | evict | restore)",
    ("kind",)))
# Batched statement commit (doc/EVICTION.md "Batched commit"): the
# per-action effect flushes — how many flushed cleanly vs degraded to
# the per-task sequential path, and how many effects each flush carried
# (the batch-size distribution a storm regression shows up in).
commit_flushes = registry.register(Counter(
    "kube_batch_commit_flushes_total",
    "Per-action commit flushes, by outcome (batched = one fused bulk "
    "egress; degraded = mid-batch failure re-driven per task)",
    ("action", "mode")))
commit_batch_size = registry.register(Histogram(
    "kube_batch_commit_batch_size",
    "Effects carried per commit flush (evicts accumulated by one "
    "action before its single bulk egress)",
    _exp_buckets(1.0, 2.0, 14)))
# Chaos engine + graceful degradation (doc/CHAOS.md): the injected-fault
# ledger, the degraded-mode surface (which degradation source is active
# and what the device-solve breaker is doing), and the failure counters
# that drive backoff — a cluster limping through faults is fully visible
# on /metrics instead of just slower.
chaos_injected = registry.register(Counter(
    f"{SUBSYSTEM}_chaos_injected_total",
    "Faults injected by the chaos engine, by site", ("site",)))
chaos_cycles_survived = registry.register(Counter(
    f"{SUBSYSTEM}_chaos_cycles_survived_total",
    "Scheduling cycles completed while a chaos fault plan was active"))
degraded_mode = registry.register(Gauge(
    f"{SUBSYSTEM}_degraded_mode",
    "1 while the named degradation source is active (0 = healthy)",
    ("source",)))
breaker_state = registry.register(Gauge(
    f"{SUBSYSTEM}_breaker_state",
    "Circuit-breaker state (0 closed | 1 half-open | 2 open)",
    ("breaker",)))
breaker_transitions = registry.register(Counter(
    f"{SUBSYSTEM}_breaker_transitions_total",
    "Circuit-breaker state transitions, by target state",
    ("breaker", "to")))
cycle_failures = registry.register(Counter(
    f"{SUBSYSTEM}_cycle_failures_total",
    "Failed scheduler-loop stages (consecutive cycle failures drive the "
    "crash-loop backoff)", ("stage",)))
device_solve_failures = registry.register(Counter(
    f"{SUBSYSTEM}_device_solve_failures_total",
    "Device-path failures degraded to the host path, by stage",
    ("stage",)))
bind_ambiguous = registry.register(Counter(
    f"{SUBSYSTEM}_bind_ambiguous_total",
    "Binds whose POST was delivered but whose outcome needed proof, by "
    "resolution (landed = read-back proved it; unproven = routed to "
    "resync)", ("outcome",)))
bind_retries = registry.register(Counter(
    f"{SUBSYSTEM}_bind_retries_total",
    "Bind-egress retry waves after transient, unambiguous failures"))
watch_reconnects = registry.register(Counter(
    f"{SUBSYSTEM}_watch_reconnects_total",
    "Reflector watch-stream reconnects, by resource and cause "
    "(disconnect | malformed)", ("resource", "cause")))
# Wire-to-tensor fast path (edge/codec decode_delta, doc/INCREMENTAL.md
# "Wire fast path"): how each reflector frame decoded (delta = changed
# fields only against the cached baseline; full = first sight / control
# arm / no baseline), and why a delta attempt degraded to a full decode.
# Degradation is counted, never fatal — a malformed or surprising frame
# must not kill the reflector thread (tests/test_wire_fast.py fuzzes).
wire_fast_decode = registry.register(Counter(
    "kube_batch_wire_fast_decode_total",
    "Reflector frames by decode mode (delta = columnar fast path; "
    "full = complete materialization)", ("mode",)))
wire_fast_fallback = registry.register(Counter(
    "kube_batch_wire_fast_fallback_total",
    "Delta-decode attempts that degraded to a full decode, by reason "
    "(error = delta raised unexpectedly; baseline = no/mismatched "
    "cached doc; kind = resource kind outside the delta plans; "
    "evicted = baseline dropped by the byte budget; selector = a "
    "shard selector failed to compile and the stream degraded to an "
    "unfiltered watch)", ("reason",)))
# Shard-scoped ingest (edge/wire_shard.py, doc/INGEST.md): watch frames
# the client-side scope check refused to mirror — scope = a frame for a
# foreign queue the server's over-approximating selector still sent;
# handover = a frame that raced a lease loss (the `ingest.handover_race`
# chaos site pins this window open deterministically).
ingest_dropped = registry.register(Counter(
    "kube_batch_ingest_dropped_total",
    "Watch frames dropped by the client-side shard-scope check, by "
    "resource and reason (scope | handover)", ("resource", "reason")))
# Lazy mirror materialization (edge/client.flush_pending): MODIFIED pod
# frames deferred at receipt (deferred), follow-up frames folded into an
# existing deferral (coalesced), deferred frames materialized at the
# session/debug chokepoint (flushed), and deferred docs the flush could
# not decode (error — the mirror keeps the prior materialization until
# the next frame or relist heals it).
lazy_mirror = registry.register(Counter(
    "kube_batch_lazy_mirror_total",
    "Lazy-mirror deferral events (deferred | coalesced | flushed | "
    "error)", ("event",)))
# Baseline byte-budget enforcement (edge/baseline.py): cold baselines
# compressed in place, then evicted when compression alone cannot meet
# the budget.
baseline_budget_ops = registry.register(Counter(
    "kube_batch_wire_baseline_budget_total",
    "Baseline-budget enforcement actions by kind (compress | evict)",
    ("kind", "op")))
solve_deadline_exceeded = registry.register(Counter(
    f"{SUBSYSTEM}_solve_deadline_exceeded_total",
    "Session solves that overran the per-session deadline (counted as "
    "breaker failures; the late result is still applied)"))
# O(churn) incremental sessions (models/incremental.py,
# doc/INCREMENTAL.md): how each session classified (micro = persistent
# state patched, full = periodic floor / first build, fallback = a micro
# attempt invalidated by a layout/cfg change or >50% dirty), the dirty
# footprint the micro path actually restaged, and whether the device
# solve was served from the generation-keyed result cache (a byte-clean
# ship reuses the previous deterministic solve without a round-trip).
incremental_sessions = registry.register(Counter(
    f"{SUBSYSTEM}_incremental_sessions_total",
    "Scheduling sessions by incremental kind (micro | full | fallback)",
    ("kind",)))
incremental_dirty = registry.register(Gauge(
    f"{SUBSYSTEM}_incremental_dirty_rows",
    "Dirty rows the last incremental session restaged, per axis",
    ("axis",)))
incremental_generation_reuse = registry.register(Counter(
    f"{SUBSYSTEM}_incremental_generation_reuse_total",
    "Device solves served from (hit) or missing (miss) the "
    "generation-keyed result cache", ("result",)))
# Residual per-cycle floors (doc/INCREMENTAL.md "Killing the per-cycle
# floors"): what the last cycle actually paid for each formerly-O(N)
# stage, so a residual floor is attributable from /metrics without a
# profiler, and the O(N)-work counters the `make bench-churn` gate
# asserts scale with dirty objects (a regression that silently
# re-introduces a full walk fails CI, not just a latency graph).
cycle_floor_ms = registry.register(Gauge(
    f"{SUBSYSTEM}_tpu_cycle_floor_ms",
    "Last cycle's cost of each residual floor stage "
    "(solve_wait | snapshot | close | occupancy | decode | stage | "
    "plugin_close), milliseconds", ("floor",)))
candidate_solve = registry.register(Counter(
    f"{SUBSYSTEM}_candidate_solve_total",
    "Allocate solves by node-axis scope (fired = candidate-row "
    "prefiltered program; full = whole node bucket)", ("result",)))
# One-dispatch sessions (ops/fused_solver.py, doc/FUSED.md): every
# solve-family device dispatch is counted at its chokepoint, so "one
# dispatch per session" is a measured claim — the per-cycle ledger below
# rides /debug/sessions meta the same way cycle floors do.
session_dispatches = registry.register(Counter(
    f"{SUBSYSTEM}_tpu_session_dispatches_total",
    "Solve-family device dispatches by family (solve | evict | topo = "
    "per-family programs; fused = the one-dispatch super-program "
    "serving several families from a single round trip)", ("family",)))
fused_legs = registry.register(Counter(
    f"{SUBSYSTEM}_tpu_fused_legs_total",
    "Fused super-program legs by consumption outcome (served = the "
    "family's action consumed the precomputed tensors; invalidated = a "
    "host decision moved state after the fused dispatch and the family "
    "re-dispatched per-action)", ("family", "outcome")))
candidate_rows = registry.register(Gauge(
    f"{SUBSYSTEM}_candidate_solve_rows",
    "Candidate node rows the last prefiltered solve actually scanned"))
snapshot_objects = registry.register(Gauge(
    f"{SUBSYSTEM}_snapshot_objects",
    "Objects the last cache.snapshot() individually processed (walked) "
    "vs served from the generation-keyed snapshot map (reused)",
    ("mode",)))
close_objects_walked = registry.register(Gauge(
    f"{SUBSYSTEM}_close_objects_walked",
    "Jobs the last close_session individually processed (the remainder "
    "was provably quiet and skipped)"))
occupancy_rows_rebuilt = registry.register(Gauge(
    f"{SUBSYSTEM}_occupancy_rows_rebuilt",
    "Node occupancy (host-port/selector) rows rebuilt by the last "
    "tensorize; -1 = feature inactive this session"))
stage_rows_staged = registry.register(Gauge(
    f"{SUBSYSTEM}_stage_rows_staged",
    "Candidate-task rows the last tensorize rewrote into the persistent "
    "staging buffers (wire fast path); -1 = full concatenation path "
    "(control arm / non-persistent cache)"))
# Scheduling-SLO layer (trace/lineage.py, doc/OBSERVABILITY.md): the
# quantity the scheduler actually promises users — how long a pod waits
# from cluster arrival (edge-decode ingest stamp) to bind — plus where
# that wait went (before the scheduler first considered it vs inside
# scheduling/egress) and the per-tenant fairness surface computed from
# the proportion/drf session opens.  Queue labels are user-influenced,
# so every one passes through bounded_label above.
_SLO_BUCKETS = [0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
                5.0, 10.0, 30.0, 60.0, 120.0, 300.0, 600.0, 1800.0]
slo_time_to_bind = registry.register(Histogram(
    f"{SUBSYSTEM}_slo_time_to_bind_seconds",
    "Pod wall time from cluster-arrival ingest to the first successful "
    "bind, by queue", _SLO_BUCKETS, ("queue",)))
slo_first_consider = registry.register(Histogram(
    f"{SUBSYSTEM}_slo_time_to_first_consider_seconds",
    "Pod wall time from ingest to the first scheduling session opened "
    "after it (the scheduler's first look), by queue", _SLO_BUCKETS,
    ("queue",)))
slo_queue_wait = registry.register(Histogram(
    f"{SUBSYSTEM}_slo_queue_wait_seconds",
    "Where the pod's wait went: segment pre_consider (ingest -> first "
    "session open) vs scheduling (first session open -> bind)",
    _SLO_BUCKETS, ("queue", "segment")))
slo_samples_dropped = registry.register(Counter(
    f"{SUBSYSTEM}_slo_samples_dropped_total",
    "SLO samples not recorded, by reason (negative | ledger_evicted | "
    "ring_evicted)", ("reason",)))
series_dropped = registry.register(Counter(
    f"{SUBSYSTEM}_metric_series_dropped_total",
    "Observations rerouted to the shared 'other' series after the "
    "per-metric label-cardinality cap (KUBE_BATCH_TPU_METRIC_SERIES_CAP)"
    " was reached, by metric", ("metric",)))
# Per-tenant fairness accounting (plugins/proportion.py + plugins/drf.py
# session opens; /debug/tenants serves the same table as JSON).  Shares
# are dominant-resource fractions so allocated vs deserved is directly
# comparable per queue.
tenant_share = registry.register(Gauge(
    f"{SUBSYSTEM}_tenant_share",
    "Dominant-resource allocated/deserved ratio per queue (>1 = the "
    "queue holds more than its fair share)", ("queue",)))
tenant_deserved_share = registry.register(Gauge(
    f"{SUBSYSTEM}_tenant_deserved_share",
    "Deserved fraction of the cluster per queue (proportion "
    "water-filling outcome, dominant resource)", ("queue",)))
tenant_allocated_share = registry.register(Gauge(
    f"{SUBSYSTEM}_tenant_allocated_share",
    "Allocated fraction of the cluster per queue (dominant resource)",
    ("queue",)))
tenant_pending_jobs = registry.register(Gauge(
    f"{SUBSYSTEM}_tenant_pending_jobs",
    "Jobs with Pending tasks per queue at the last session open",
    ("queue",)))
tenant_starvation = registry.register(Gauge(
    f"{SUBSYSTEM}_tenant_starvation_seconds",
    "Age of the oldest job still holding Pending tasks per queue "
    "(0 = no pending work)", ("queue",)))
tenant_starved_sessions = registry.register(Counter(
    f"{SUBSYSTEM}_tenant_starved_sessions_total",
    "Sessions that opened with the queue under its deserved share while "
    "it still had pending demand", ("queue",)))
tenant_max_job_share = registry.register(Gauge(
    f"{SUBSYSTEM}_tenant_max_job_share",
    "Largest drf job share inside each queue at the last session open",
    ("queue",)))
# Queue-shard tenancy engine + replica federation (kube_batch_tpu/
# tenancy/, doc/TENANCY.md): which replica owns each queue-shard, how
# old its lease is, every lease transition (claim | steal | release |
# renew loss | fenced write), per-shard micro-session outcomes, bind
# egress stamped with the owning replica, and the federation's
# rebalance ledger (the bench artifact's shard_rebalances counter).
shard_owner_info = registry.register(Gauge(
    f"{SUBSYSTEM}_shard_owner_info",
    "1 while the labeled replica owns the queue-shard (0 after it loses "
    "or releases the lease)", ("shard", "replica")))
shard_lease_age = registry.register(Gauge(
    f"{SUBSYSTEM}_shard_lease_age_seconds",
    "Seconds since the shard's lease record was last renewed at the "
    "store (any holder)", ("shard",)))
shard_lease_transitions = registry.register(Counter(
    f"{SUBSYSTEM}_shard_lease_transitions_total",
    "Shard lease state transitions (claim | steal | release | shed | "
    "renew_timeout | stolen_from | clock_skew | fenced_write)",
    ("shard", "kind")))
shard_sessions = registry.register(Counter(
    f"{SUBSYSTEM}_shard_sessions_total",
    "Shard-scoped micro-sessions run, by outcome (ok | error)",
    ("shard", "result")))
shard_binds = registry.register(Counter(
    f"{SUBSYSTEM}_shard_binds_total",
    "Bind egress per shard, stamped with the owning replica",
    ("shard", "replica")))
shard_rebalance = registry.register(Counter(
    f"{SUBSYSTEM}_shard_rebalance_total",
    "Shard ownership rebalances across the federation (claim | steal | "
    "release | shed | lost)", ("kind",)))
# Concurrent shard micro-sessions (doc/TENANCY.md "Concurrent
# micro-sessions"): the bounded-depth shard pipeline's ledger — how many
# stages entered/retired, how often a predecessor's retire invalidated a
# successor's optimistic work (conflict_rerun), and how much host time
# ran inside a predecessor's device-dispatch window (the overlap the
# tentpole exists to create).
shard_pipeline = registry.register(Counter(
    f"{SUBSYSTEM}_shard_pipeline_total",
    "Shard-pipeline stage events (begun | retired | conflict_rerun | "
    "abandoned | overlapped)", ("event",)))
shard_overlap_seconds = registry.register(Counter(
    f"{SUBSYSTEM}_shard_overlap_seconds_total",
    "Host wall time spent running a successor shard's begin phases "
    "inside a predecessor's in-flight device-dispatch window"))
shard_overlap_last_ms = registry.register(Gauge(
    "kube_batch_tpu_shard_overlap_ms",
    "Overlapped host time of the last pipelined loop iteration (ms)"))
shard_inflight = registry.register(Gauge(
    "kube_batch_tpu_shard_inflight",
    "High-water in-flight shard micro-sessions of the last pipelined "
    "loop iteration (1 = sequential)"))
shard_load = registry.register(Gauge(
    "kube_batch_tpu_shard_load",
    "Per-shard load EWMA (pod count + churn rate) feeding the "
    "federation's load-weighted claim targets", ("shard",)))
solver_inflight = registry.register(Gauge(
    "kube_batch_tpu_solver_inflight",
    "Device solve dispatches issued but not yet fetched or discarded"))
# Wire-edge memory accounting (ROADMAP item 1, doc/INCREMENTAL.md "Wire
# fast path"): raw-doc delta baselines (`_wire_doc`) retained by the
# mirror stores, per resource kind — the measurable target of the
# 1M-pod memory-budget work.
wire_baseline = registry.register(Gauge(
    "kube_batch_wire_baseline_bytes",
    "Approximate bytes of raw wire-doc delta baselines retained by the "
    "mirror stores, per resource kind", ("kind",)))
# Fleet memory ledger (metrics/memledger.py, doc/OBSERVABILITY.md
# "Memory ledger"): per-subsystem byte accounting for every growable
# store, with a high-watermark series attributing the peak to the
# session that set it.  Written ONLY through memledger's publish path
# (lint rule 11, ledger-discipline).
mem_bytes = registry.register(Gauge(
    "kube_batch_tpu_mem_bytes",
    "Current accounted bytes per memory ledger (mirror, pending, "
    "baseline, tensor_cache, stage, resident, incremental, "
    "compile_cache, trace_ring, lineage_ring, event_ring, "
    "snapshot_pool)", ("ledger",)))
mem_watermark = registry.register(Gauge(
    "kube_batch_tpu_mem_watermark_bytes",
    "High-watermark of accounted bytes per memory ledger since process "
    "start (or the last ledger reset)", ("ledger",)))
# Topology / fragmentation SLO (models/topology.py, doc/TOPOLOGY.md):
# per-pool fragmentation computed in the topo action's occupancy walk
# and surfaced on /debug/topology + the bench-topo artifact.
topo_frag_ratio = registry.register(Gauge(
    f"{SUBSYSTEM}_topo_frag_ratio",
    "Fragmentation of each pool's free nodes: 1 - largest contiguous "
    "free block / free nodes (0 = one solid block or no free nodes)",
    ("pool",)))
topo_largest_free_block = registry.register(Gauge(
    f"{SUBSYSTEM}_topo_largest_free_block",
    "Largest contiguous free block (torus-connected nodes) per pool",
    ("pool",)))
topo_slices = registry.register(Counter(
    f"{SUBSYSTEM}_topo_slices_total",
    "Slice placement outcomes (placed | defrag_placed | pending | "
    "too_few_tasks | bad_shape | degraded)", ("outcome",)))
topo_bad_coords = registry.register(Counter(
    f"{SUBSYSTEM}_topo_bad_coords_total",
    "Nodes degraded to flat-list placement by malformed/missing/"
    "duplicate coordinate labels (incl. chaos topology.bad_coords)"))


# Helper API (metrics.go:123-191).

def observe_e2e_latency(seconds: float) -> None:
    e2e_scheduling_latency.observe(seconds * 1e3)


def observe_plugin_latency(plugin: str, on_session: str, seconds: float) -> None:
    plugin_scheduling_latency.observe(seconds * 1e6, plugin, on_session)


def observe_action_latency(action: str, seconds: float) -> None:
    action_scheduling_latency.observe(seconds * 1e6, action)


def observe_task_schedule_latency(seconds: float) -> None:
    task_scheduling_latency.observe(seconds * 1e6)


def observe_task_schedule_latencies(seconds_array) -> None:
    """Bulk form for the batched dispatch path."""
    import numpy as np
    task_scheduling_latency.observe_many(
        np.asarray(seconds_array, dtype=np.float64) * 1e6)


def register_schedule_attempt(result: str) -> None:
    schedule_attempts.inc(1.0, result)


def update_preemption_victims_count(count: int) -> None:
    preemption_victims.set(float(count))


def register_preemption_attempt() -> None:
    preemption_attempts.inc()


def update_unschedule_task_count(job: str, count: int) -> None:
    unschedule_task_count.set(float(count), job)


def update_unschedule_job_count(count: int) -> None:
    unschedule_job_count.set(float(count))


def register_job_retries(job: str) -> None:
    job_retry_counts.inc(1.0, job)


def observe_tpu_solve_latency(seconds: float) -> None:
    tpu_solve_latency.observe(seconds * 1e3)


def observe_tpu_transfer_latency(seconds: float) -> None:
    tpu_transfer_latency.observe(seconds * 1e3)


def observe_tpu_apply_latency(seconds: float) -> None:
    tpu_apply_latency.observe(seconds * 1e3)


def note_compile_cache(hit: bool) -> None:
    (compile_cache_hits if hit else compile_cache_misses).inc()


def compile_cache_counts() -> tuple:
    """(hits, misses) so far — bench.py's artifact split."""
    return (int(compile_cache_hits.value()),
            int(compile_cache_misses.value()))


def set_compile_inflight(count: int) -> None:
    compile_cache_inflight.set(float(count))


def observe_host_overlap_latency(seconds: float) -> None:
    tpu_host_overlap_latency.observe(seconds * 1e3)


def observe_device_wait_latency(seconds: float) -> None:
    tpu_device_wait_latency.observe(seconds * 1e3)


def overlap_split_totals() -> tuple:
    """(host_overlap_ms_sum, device_wait_ms_sum, sessions): bench.py reads
    per-session values as deltas of these running sums (one observation of
    each per pipelined session)."""
    with tpu_host_overlap_latency._lock:
        host = tpu_host_overlap_latency._sums.get((), 0.0)
        n = tpu_host_overlap_latency._totals.get((), 0)
    with tpu_device_wait_latency._lock:
        wait = tpu_device_wait_latency._sums.get((), 0.0)
    return host, wait, n


def note_ship(mode: str, nbytes: int) -> None:
    ship_total.inc(1.0, mode)
    ship_bytes.inc(float(nbytes), mode)


def ship_counts() -> dict:
    """{mode: (shipments, bytes)} so far — bench.py's artifact split."""
    out = {}
    for mode in ("full", "delta", "clean"):
        out[mode] = (int(ship_total.value(mode)),
                     int(ship_bytes.value(mode)))
    return out


def note_ship_shard(shard: int, nbytes: int) -> None:
    """Count node-shard-region bytes shipped to mesh device ``shard``
    (the per-device ledger the O(dirty-blocks) steady-state contract is
    proven against — doc/SHARDING.md)."""
    ship_shard_bytes.inc(float(nbytes), str(shard))


def ship_shard_counts() -> Dict[str, int]:
    """{shard: bytes} so far — bench artifact + check_shard_ab."""
    return {labels[0]: int(v)
            for labels, v in ship_shard_bytes.values().items() if labels}


def note_route(family: str, choice: str) -> None:
    """Count one solver-family dispatch routed at the
    choose_solver_mesh / eviction-scan chokepoints (family is
    allocate | evict | scan; choice is sharded | pallas | xla)."""
    solver_route.inc(1.0, family, choice)


def route_counts() -> Dict[str, int]:
    """{"family/choice": count} so far — bench artifact + /debug meta."""
    return {f"{labels[0]}/{labels[1]}": int(v)
            for labels, v in solver_route.values().items()
            if len(labels) == 2}


def inc_scheduler_loop_error(stage: str) -> None:
    scheduler_loop_errors.inc(1.0, stage)


def note_swallowed(site: str) -> None:
    """Count one reviewed exception swallow at ``site`` (the
    exception-policy counter route — see doc/LINT.md rule 5)."""
    swallowed_exceptions.inc(1.0, site)


def note_eviction(action: str) -> None:
    """Count one cluster-committed eviction for ``action`` ("preempt" |
    "reclaim" — the reason string every evict path already carries)."""
    evictions_total.inc(1.0, action)


def note_evictions(action: str, count: int) -> None:
    """Bulk form for the batched commit flush: ``count`` committed
    evictions decided by ``action`` in one counter update."""
    if count:
        evictions_total.inc(float(count), action)


def note_commit_flush(action: str, mode: str, size: int) -> None:
    """Record one per-action commit flush: ``mode`` is "batched" (the
    fused bulk egress landed every effect) or "degraded" (a mid-batch
    failure re-drove the remainder through the per-task sequential
    path); ``size`` is the effect count the flush carried."""
    commit_flushes.inc(1.0, action, mode)
    commit_batch_size.observe(float(size))


def commit_flush_counts() -> Dict[str, int]:
    """{"action/mode": count} so far — the bench-commit vacuous-gate
    guard (a commit A/B whose batched arm never flushed compared
    nothing) and the /debug surfaces."""
    return {f"{labels[0]}/{labels[1]}": int(v)
            for labels, v in commit_flushes.values().items()
            if len(labels) == 2}


def evictions_by_action() -> Dict[str, int]:
    """{action: count} so far — bench artifact + /debug/sessions."""
    return {labels[0]: int(v)
            for labels, v in evictions_total.values().items() if labels}


def note_victim_index(kind: str) -> None:
    victim_index_events.inc(1.0, kind)


def set_session_mutations(jobs: int, nodes: int) -> None:
    session_mutated_jobs.set(float(jobs))
    session_mutated_nodes.set(float(nodes))


def set_bucket_pad_waste(axis: str, ratio: float) -> None:
    bucket_pad_waste.set(round(float(ratio), 4), axis)


def note_chaos_injected(site: str) -> None:
    chaos_injected.inc(1.0, site)


def note_chaos_survived() -> None:
    chaos_cycles_survived.inc()


def set_degraded(source: str, active: bool) -> None:
    degraded_mode.set(1.0 if active else 0.0, source)


def set_breaker_state(breaker: str, code: float) -> None:
    breaker_state.set(code, breaker)


def note_breaker_transition(breaker: str, to: str) -> None:
    breaker_transitions.inc(1.0, breaker, to)


def note_cycle_failure(stage: str) -> None:
    cycle_failures.inc(1.0, stage)


def note_device_failure(stage: str) -> None:
    """Count one device-path failure degraded to the host path (the
    breaker's feed — stage is tensorize | solve | evict_solve)."""
    device_solve_failures.inc(1.0, stage)


def note_bind_ambiguous(outcome: str) -> None:
    """Count one delivered-but-needed-proof bind ("landed" when the
    read-back proved it; "unproven" when it was routed to resync)."""
    bind_ambiguous.inc(1.0, outcome)


def note_bind_retry() -> None:
    bind_retries.inc()


def note_watch_reconnect(resource: str, cause: str) -> None:
    watch_reconnects.inc(1.0, resource, cause)


def note_wire_decode(mode: str) -> None:
    """Count one reflector frame's decode mode (delta | full)."""
    wire_fast_decode.inc(1.0, mode)


def note_wire_fast_fallback(reason: str) -> None:
    """Count one delta-decode attempt degrading to a full decode."""
    wire_fast_fallback.inc(1.0, reason)


def wire_fast_counts() -> Dict[str, int]:
    """{mode/reason: count} — the `make bench-wire` vacuous-gate guard
    (a wire A/B whose fast arm never delta-decoded compared nothing)."""
    out = {f"decode_{labels[0]}": int(v)
           for labels, v in wire_fast_decode.values().items() if labels}
    for labels, v in wire_fast_fallback.values().items():
        if labels:
            out[f"fallback_{labels[0]}"] = int(v)
    return out


def note_ingest_drop(resource: str, reason: str) -> None:
    """Count one watch frame the shard-scope check refused to mirror
    (scope = steady over-approximation; handover = raced a lease
    loss)."""
    ingest_dropped.inc(1.0, resource, reason)


def ingest_drop_counts() -> Dict[str, int]:
    """{"resource/reason": count} — soak + handover-race assertions."""
    return {f"{labels[0]}/{labels[1]}": int(v)
            for labels, v in ingest_dropped.values().items()
            if len(labels) == 2}


def note_lazy_mirror(event: str) -> None:
    """Count one lazy-mirror deferral event (deferred | coalesced |
    flushed | error)."""
    lazy_mirror.inc(1.0, event)


def lazy_mirror_counts() -> Dict[str, int]:
    """{event: count} — the lazy-parity tests' non-vacuity guard."""
    return {labels[0]: int(v)
            for labels, v in lazy_mirror.values().items() if labels}


def note_baseline_budget(kind: str, op: str) -> None:
    """Count one baseline-budget enforcement action (compress |
    evict)."""
    baseline_budget_ops.inc(1.0, kind, op)


def baseline_budget_counts() -> Dict[str, int]:
    """{"kind/op": count} — eviction-recovery test assertions."""
    return {f"{labels[0]}/{labels[1]}": int(v)
            for labels, v in baseline_budget_ops.values().items()
            if len(labels) == 2}


# Wall time the reflector threads spent decoding watch frames since the
# scheduling thread last collected it (the per-cycle ``decode`` floor:
# open_session takes-and-resets, so the floor attributes asynchronous
# edge decode to the cycle that absorbed its churn).
_decode_time_lock = threading.Lock()
_decode_seconds_acc = 0.0  # guarded-by: _decode_time_lock


def note_decode_seconds(seconds: float) -> None:
    global _decode_seconds_acc
    with _decode_time_lock:
        _decode_seconds_acc += seconds


def take_decode_seconds() -> float:
    """Drain the accumulated decode wall time (scheduling thread only)."""
    global _decode_seconds_acc
    with _decode_time_lock:
        out = _decode_seconds_acc
        _decode_seconds_acc = 0.0
    return out


def note_solve_deadline() -> None:
    solve_deadline_exceeded.inc()


def note_incremental_session(kind: str) -> None:
    """Count one session by incremental kind (micro | full | fallback;
    classified once per session by the first tensorize build)."""
    incremental_sessions.inc(1.0, kind)


def set_incremental_dirty(nodes: int, jobs: int) -> None:
    incremental_dirty.set(float(nodes), "nodes")
    incremental_dirty.set(float(jobs), "jobs")


def note_generation_reuse(hit: bool) -> None:
    incremental_generation_reuse.inc(1.0, "hit" if hit else "miss")


def incremental_session_counts() -> Dict[str, int]:
    """{kind: count} so far — bench churn-sweep artifact."""
    return {labels[0]: int(v)
            for labels, v in incremental_sessions.values().items()
            if labels}


def generation_reuse_counts() -> Dict[str, int]:
    return {labels[0]: int(v)
            for labels, v in incremental_generation_reuse.values().items()
            if labels}


def set_cycle_floor(floor: str, seconds: float) -> None:
    """Record what the current cycle paid for one residual floor stage
    (solve_wait | snapshot | close | occupancy | decode | stage |
    plugin_close | commit | apply | fused)."""
    cycle_floor_ms.set(round(seconds * 1e3, 3), floor)


def cycle_floor_values() -> Dict[str, float]:
    """{floor: ms} of the last cycle — bench churn artifact + /debug."""
    return {labels[0]: v for labels, v in cycle_floor_ms.values().items()
            if labels}


_dispatch_cycle_lock = threading.Lock()
_dispatch_cycle: Dict[str, int] = {}  # guarded-by: _dispatch_cycle_lock


def note_session_dispatch(family: str) -> None:
    """Count one solve-family device dispatch at the family's chokepoint
    (dispatch_solve | dispatch_evict_batch_solve | dispatch_box_scan |
    the fused super-program) — the process-total counter plus the
    per-cycle ledger /debug/sessions reads back at close."""
    session_dispatches.inc(1.0, family)
    with _dispatch_cycle_lock:
        _dispatch_cycle[family] = _dispatch_cycle.get(family, 0) + 1


def session_dispatch_counts() -> Dict[str, int]:
    """{family: count} so far — bench artifact + check_fused_ab."""
    return {labels[0]: int(v)
            for labels, v in session_dispatches.values().items()
            if labels}


def take_cycle_dispatches() -> Dict[str, int]:
    """Drain the per-cycle dispatch ledger (session close -> /debug
    sessions meta).  Pipelined shard halves interleave on one thread, so
    like cycle floors the attribution is per retire, not per overlap."""
    with _dispatch_cycle_lock:
        out = dict(_dispatch_cycle)
        _dispatch_cycle.clear()
    return out


def note_fused_leg(family: str, outcome: str) -> None:
    """Count one fused-leg outcome (family solve | evict | topo |
    postevict — the storm half's post-eviction placements, served only
    when the host's committed victim order bit-matches the device's
    prediction, doc/FUSED.md "Storm half"; outcome served |
    invalidated)."""
    fused_legs.inc(1.0, family, outcome)


def fused_leg_counts() -> Dict[str, int]:
    """{"family/outcome": count} so far — tests + bench artifact."""
    return {f"{labels[0]}/{labels[1]}": int(v)
            for labels, v in fused_legs.values().items()
            if len(labels) == 2}


def note_candidate_solve(fired: bool, rows: int = 0) -> None:
    candidate_solve.inc(1.0, "fired" if fired else "full")
    # Gauge always moves (0 on full solves) so per-cycle readers never
    # see a stale candidate count from an earlier micro cycle.
    candidate_rows.set(float(rows))


def candidate_solve_counts() -> Dict[str, int]:
    """{result: count} so far — the check_churn_ab vacuous-gate guard."""
    return {labels[0]: int(v)
            for labels, v in candidate_solve.values().items() if labels}


def set_snapshot_objects(walked: int, reused: int) -> None:
    snapshot_objects.set(float(walked), "walked")
    snapshot_objects.set(float(reused), "reused")


def set_close_objects_walked(count: int) -> None:
    close_objects_walked.set(float(count))


def set_occupancy_rows_rebuilt(count: int) -> None:
    occupancy_rows_rebuilt.set(float(count))


def set_stage_rows(count: int) -> None:
    """Candidate-task rows the last tensorize restaged (-1 = the full
    concatenation path ran — control arm or non-persistent cache)."""
    stage_rows_staged.set(float(count))


def observe_time_to_bind(queue: str, seconds: float) -> None:
    """One pod's ingest->bind SLO sample (trace/lineage.py emits exactly
    one per pod lifetime; queue label cardinality-capped)."""
    slo_time_to_bind.observe(seconds, bounded_label("slo", queue))


def observe_first_consider(queue: str, seconds: float) -> None:
    slo_first_consider.observe(seconds, bounded_label("slo", queue))


def observe_queue_wait(queue: str, segment: str, seconds: float) -> None:
    slo_queue_wait.observe(seconds, bounded_label("slo", queue), segment)


def note_slo_dropped(reason: str) -> None:
    slo_samples_dropped.inc(1.0, reason)


def set_tenant_stats(queue: str, share: float, deserved_share: float,
                     allocated_share: float, pending_jobs: int,
                     starvation_s: float, starved: bool) -> None:
    """Publish one queue's fairness row (proportion's session open).
    The queue label is cardinality-capped under ONE shared 'tenant'
    budget, so all tenant gauges collapse the same overflow queues."""
    q = bounded_label("tenant", queue)
    tenant_share.set(round(float(share), 4), q)
    tenant_deserved_share.set(round(float(deserved_share), 4), q)
    tenant_allocated_share.set(round(float(allocated_share), 4), q)
    tenant_pending_jobs.set(float(pending_jobs), q)
    tenant_starvation.set(round(float(starvation_s), 3), q)
    if starved:
        tenant_starved_sessions.inc(1.0, q)


def set_tenant_max_job_share(queue: str, share: float) -> None:
    tenant_max_job_share.set(round(float(share), 4),
                             bounded_label("tenant", queue))


def clear_tenant_gauges(queues) -> None:
    """Zero the gauges of queues that left the cluster so /metrics does
    not keep reporting a departed tenant's last shares forever."""
    for queue in queues:
        q = bounded_label("tenant", queue)
        for gauge in (tenant_share, tenant_deserved_share,
                      tenant_allocated_share, tenant_pending_jobs,
                      tenant_starvation, tenant_max_job_share):
            gauge.set(0.0, q)


def onwork_values() -> Dict[str, float]:
    """The last cycle's O(N)-work counters in one dict — the bench churn
    artifact embeds these per round so `make bench-churn` can assert
    they scale with dirty objects, not cluster size."""
    out: Dict[str, float] = {}
    for labels, v in snapshot_objects.values().items():
        if labels:
            out[f"snapshot_{labels[0]}"] = v
    out["close_walked"] = close_objects_walked.value()
    out["occupancy_rebuilt"] = occupancy_rows_rebuilt.value()
    out["candidate_rows"] = candidate_rows.value()
    out["stage_rows"] = stage_rows_staged.value()
    return out


# Shard ownership gauge bookkeeping: set_shard_owner flips the previous
# holder's info row to 0 so exactly one (shard, replica) pair reads 1.
# Multiple writers (each replica's lease thread in the in-process soak),
# so the last-owner map takes a lock.
_shard_owner_lock = threading.Lock()
_shard_owner_last: Dict[str, str] = {}  # guarded-by: _shard_owner_lock


def set_shard_owner(shard: int, replica: str) -> None:
    s = str(shard)
    # Gauge writes INSIDE the lock: concurrent publishers (every
    # replica's lease thread reports store-observed ownership in the
    # in-process soak) must see zero-the-old + one-the-new as a unit,
    # or an interleaving leaves two replicas' rows at 1 — the lock is
    # what makes "exactly one (shard, replica) pair reads 1" true.
    with _shard_owner_lock:
        prev = _shard_owner_last.get(s)
        _shard_owner_last[s] = replica
        if prev is not None and prev != replica:
            shard_owner_info.set(0.0, s, prev)
        shard_owner_info.set(1.0, s, replica)


def clear_shard_owner(shard: int, replica: str) -> None:
    """The replica lost/released the shard; zero its info row (the next
    owner's set_shard_owner publishes the replacement)."""
    s = str(shard)
    with _shard_owner_lock:
        if _shard_owner_last.get(s) == replica:
            _shard_owner_last.pop(s, None)
        shard_owner_info.set(0.0, s, replica)


def set_shard_lease_age(shard: int, age_s: float) -> None:
    shard_lease_age.set(round(float(age_s), 3), str(shard))


def note_shard_lease(shard: int, kind: str) -> None:
    shard_lease_transitions.inc(1.0, str(shard), kind)


def note_shard_rebalance(kind: str) -> None:
    shard_rebalance.inc(1.0, kind)


def shard_rebalance_counts() -> Dict[str, int]:
    """{kind: count} so far — bench artifact + replica soak."""
    return {labels[0]: int(v)
            for labels, v in shard_rebalance.values().items() if labels}


def note_shard_session(shard: int, result: str) -> None:
    shard_sessions.inc(1.0, str(shard), result)


def shard_session_counts() -> Dict[str, int]:
    """{"shard/result": count} so far — soak + tests."""
    return {f"{labels[0]}/{labels[1]}": int(v)
            for labels, v in shard_sessions.values().items()
            if len(labels) == 2}


def note_shard_binds(shard: int, replica: str, count: int) -> None:
    if count:
        shard_binds.inc(float(count), str(shard), replica)


def note_shard_pipeline(event: str, count: int = 1) -> None:
    if count:
        shard_pipeline.inc(float(count), event)


def shard_pipeline_counts() -> Dict[str, int]:
    """{event: count} so far — bench artifact + the tenancy A/B's
    vacuous-overlap guard."""
    return {labels[0]: int(v)
            for labels, v in shard_pipeline.values().items() if labels}


def note_shard_overlap(seconds: float) -> None:
    if seconds > 0:
        shard_overlap_seconds.inc(float(seconds))


def shard_overlap_total_ms() -> float:
    """Running overlapped-host-time sum in ms (bench reads deltas)."""
    return float(shard_overlap_seconds.value()) * 1e3


def set_shard_cycle_stats(overlap_s: float, inflight_hw: int) -> None:
    """Last pipelined loop iteration's overlap + in-flight high water."""
    shard_overlap_last_ms.set(round(overlap_s * 1e3, 3))
    shard_inflight.set(float(inflight_hw))


def shard_cycle_stats() -> tuple:
    """(overlap_ms, inflight high-water) of the last pipelined loop
    iteration — bench artifact keys."""
    return (float(shard_overlap_last_ms.value()),
            int(shard_inflight.value()))


def set_shard_load(shard: int, load: float) -> None:
    shard_load.set(round(float(load), 3), str(shard))


def set_solver_inflight(count: int) -> None:
    solver_inflight.set(float(count))


def shard_bind_counts() -> Dict[str, int]:
    """{"shard/replica": binds} so far — the replica soak's stamped
    bind-egress ledger."""
    return {f"{labels[0]}/{labels[1]}": int(v)
            for labels, v in shard_binds.values().items()
            if len(labels) == 2}


def set_wire_baseline(kind: str, nbytes: int) -> None:
    wire_baseline.set(float(max(0, nbytes)), kind)


def wire_baseline_totals() -> Dict[str, int]:
    """{kind: retained baseline bytes} — /debug/sessions meta + the
    bench wire artifact (ROADMAP item 1's memory-budget target)."""
    return {labels[0]: int(v)
            for labels, v in wire_baseline.values().items() if labels}


def set_mem_bytes(ledger: str, nbytes: int) -> None:
    """memledger's ONLY gauge sink (lint rule 11): publish one ledger's
    current accounted bytes."""
    mem_bytes.set(float(max(0, nbytes)), ledger)


def set_mem_watermark(ledger: str, nbytes: int) -> None:
    mem_watermark.set(float(max(0, nbytes)), ledger)


_topo_pools_seen: set = set()  # single writer: the scheduling thread's topo action


def set_topo_frag(pool: str, frag_ratio: float, largest_block: int) -> None:
    """Publish one pool's fragmentation row (the topo action's
    occupancy walk; same shared cardinality budget as tenants)."""
    p = bounded_label("topo_pool", pool)
    topo_frag_ratio.set(round(float(frag_ratio), 4), p)
    topo_largest_free_block.set(float(largest_block), p)


def publish_topo_frag(pools: "Dict[str, dict]") -> None:
    """Replace the fragmentation table wholesale: pools that left the
    view (decommissioned / mislabeled nodes) have their gauges zeroed
    so /metrics does not report a departed pool's last fragmentation
    forever — the tenants-table staleness discipline."""
    global _topo_pools_seen
    for pool, row in pools.items():
        set_topo_frag(pool, row["frag_ratio"], row["largest_block"])
    for gone in _topo_pools_seen - set(pools):
        set_topo_frag(gone, 0.0, 0)
    _topo_pools_seen = set(pools)


def note_topo_slice(outcome: str) -> None:
    topo_slices.inc(1.0, outcome)


def topo_slice_counts() -> Dict[str, int]:
    """{outcome: count} so far — bench-topo artifact + tests."""
    return {labels[0]: int(v)
            for labels, v in topo_slices.values().items() if labels}


def note_topo_bad_coords() -> None:
    topo_bad_coords.inc()
