"""Per-tenant (queue) fairness accounting: the /debug/tenants surface.

The proportion plugin's session open already computes the one thing a
fairness report needs — each queue's ``deserved`` share (the weighted
water-filling fixed point) next to what it actually holds — and the drf
open computes per-job dominant shares.  This module is just the
publication point: proportion/drf hand their per-queue rows here once
per session (O(queues) work, no extra cluster walk), the gauges land on
/metrics (queue labels cardinality-capped, metrics.bounded_label), and
``/debug/tenants`` serves the same table as JSON.

Thread model: writers are the scheduling thread (plugin opens); readers
are the HTTP debug endpoints — one lock, wholesale snapshot swaps.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from . import metrics


def _in_universe(universe, queue: str) -> bool:
    """``universe`` is a queue set OR a membership predicate (the
    tenancy ShardView's ``owns_queue``).  A predicate is what lets a
    shard-scoped publish detect a DELETED queue as departed: the
    session's current queue set can never contain it, but the shard map
    still answers whose departure it is."""
    return universe(queue) if callable(universe) else queue in universe


class TenantTable:

    def __init__(self):
        self._lock = threading.Lock()
        self._rows: Dict[str, dict] = {}        # guarded-by: _lock
        self._drf_pending: Dict[str, float] = {}  # guarded-by: _lock
        self._session_uid = ""                  # guarded-by: _lock
        self._updated_wall = 0.0                # guarded-by: _lock

    def note_drf_job_shares(self, max_share_by_queue: Dict[str, float],
                            universe: Optional[set] = None) -> None:
        """drf's session open: the largest job share inside each queue.
        Held until proportion publishes the session's table (drf opens
        first in the shipped tier order); published standalone gauges
        immediately so a proportion-less conf still surfaces them.

        ``universe`` (a shard-scoped session, doc/TENANCY.md): a queue
        set or membership predicate — only queues INSIDE it are
        replaced/zeroed; other shards' pending shares survive the
        merge."""
        with self._lock:
            if universe is None:
                departed = [q for q in self._drf_pending
                            if q not in max_share_by_queue]
                self._drf_pending = dict(max_share_by_queue)
            else:
                departed = [q for q in self._drf_pending
                            if _in_universe(universe, q)
                            and q not in max_share_by_queue]
                merged = {q: s for q, s in self._drf_pending.items()
                          if not _in_universe(universe, q)}
                merged.update(max_share_by_queue)
                self._drf_pending = merged
        for queue, share in max_share_by_queue.items():
            metrics.set_tenant_max_job_share(queue, share)
        # Queues whose jobs all left keep their queue object but drop
        # out of the walk — zero them so the gauge can't stay stale.
        for queue in departed:
            metrics.set_tenant_max_job_share(queue, 0.0)

    def publish(self, rows: Dict[str, dict], session_uid: str = "",
                universe: Optional[set] = None) -> None:
        """Proportion's session open: one row per queue with
        share / deserved_share / allocated_share / pending_jobs /
        starvation_s / starved.  Replaces the previous session's table
        wholesale; queues that left have their gauges zeroed so /metrics
        does not report a departed tenant's last shares forever.

        ``universe`` (a shard-scoped session, doc/TENANCY.md): the merge
        form — rows outside the shard's queue universe (a set or
        membership predicate) survive, and only in-universe queues that
        vanished are zeroed."""
        with self._lock:
            drf = self._drf_pending
            if universe is None:
                departed = [q for q in self._rows if q not in rows]
                merged = {}
            else:
                departed = [q for q in self._rows
                            if _in_universe(universe, q)
                            and q not in rows]
                merged = {q: r for q, r in self._rows.items()
                          if not _in_universe(universe, q)}
            for queue, row in rows.items():
                row = dict(row)
                if queue in drf:
                    row["max_job_share"] = round(drf[queue], 4)
                merged[queue] = row
            # (departed rows are absent from `merged` by construction in
            # both branches; they only need their gauges zeroed below.)
            self._rows = merged
            self._session_uid = session_uid
            self._updated_wall = time.time()
        for queue, row in rows.items():
            metrics.set_tenant_stats(
                queue, row.get("share", 0.0),
                row.get("deserved_share", 0.0),
                row.get("allocated_share", 0.0),
                row.get("pending_jobs", 0),
                row.get("starvation_s", 0.0),
                bool(row.get("starved")))
        if departed:
            metrics.clear_tenant_gauges(departed)

    def snapshot(self) -> dict:
        """The /debug/tenants answer."""
        with self._lock:
            return {"queues": {q: dict(r) for q, r in self._rows.items()},
                    "session_uid": self._session_uid,
                    "updated": round(self._updated_wall, 3),
                    "age_s": (round(time.time() - self._updated_wall, 3)
                              if self._updated_wall else None)}

    def clear(self) -> None:
        with self._lock:
            self._rows = {}
            self._drf_pending = {}
            self._session_uid = ""
            self._updated_wall = 0.0


tenant_table = TenantTable()


def dominant_share(res, total) -> float:
    """max over dimensions of res/total — the dominant-resource fraction
    proportion/drf both use (api.share per dimension), 0.0 on an empty
    total."""
    from ..api import share
    best = 0.0
    for rn in res.resource_names():
        s = share(res.get(rn), total.get(rn))
        if s > best:
            best = s
    return best
