"""Fleet memory ledger: per-subsystem byte accounting for every
growable store in the replica (doc/OBSERVABILITY.md "Memory ledger").

``kube_batch_wire_baseline_bytes`` was the ONLY byte ledger in the
system; ROADMAP item 1's residual memory wall (the unbounded dataclass
mirror, resident device buffers, tensor caches, trace rings) grew
invisibly.  This module generalizes the ``audit_baseline_bytes``
discipline: every growable store registers one or more *components*
under a named ledger and keeps the ledger current with ``add``/``set``
delta hooks at its existing mutation chokepoints; ``audit_mem_ledgers``
recomputes true sizes from the stores themselves and fails loudly on
drift, so a forgotten hook is a test failure, not a silent leak.

Design rules:

* **Lock-cheap.**  Each ledger has one small leaf mutex; hooks do a
  dict write, an int add, and a watermark compare.  Gauge publication
  (``kube_batch_tpu_mem_bytes{ledger}``) happens outside the mutex and
  can be granularity-batched for hot rings (``publish_granularity``),
  while the internal ledger stays byte-exact for /debug/memory and the
  audit.  The ledger mutex is a *leaf*: hooks may run under a store's
  own lock, but the ledger never calls back into a store while holding
  its mutex (auditor sizers run unlocked — see ``audit``).
* **Lifetime-tied.**  Components are keyed to their owning store via
  ``track(owner, subkey, sizer)``; a ``weakref.finalize`` drops the
  bytes AND the auditor when the store is garbage collected, so
  per-test / per-arm store churn cannot accrete phantom bytes.
* **Watermarks carry provenance.**  Each ledger records its
  high-watermark and the session id (trace/spans) active when the
  watermark was set — "which session peaked the stage buffers" is a
  /debug/memory read, not a bisection.
* **Estimates are shared.**  Where a store accounts an estimate (flat
  per-object shell costs for the dataclass mirror, per-event ring
  costs), the hook and the auditor use the same sizer formula, so the
  audit checks *hook coverage*, never estimate quality.

Gauges are written ONLY through this module (graftlint rule 11,
ledger-discipline); instrumented classes carry a ``# mem-ledger:
<name>`` marker in their docstring, which the same rule pins to an
actual registration call in the owning file.
"""

from __future__ import annotations

import threading
import weakref
from typing import Callable, Dict, List, Optional, Tuple

from .. import knobs
from . import metrics

__all__ = [
    "LEDGER_CATALOGUE", "Ledger", "MemAuditError", "ledger", "ledgers",
    "totals", "watermarks", "snapshot", "debug_doc", "audit_mem_ledgers",
    "reset", "rss_bytes",
]

#: The fleet ledger catalogue.  Eagerly created at import so
#: /debug/memory always lists the full surface (a ledger at 0 bytes is
#: information: that store is empty, not unaccounted).
LEDGER_CATALOGUE: Tuple[Tuple[str, str], ...] = (
    ("mirror", "decoded dataclass mirror objects, all resource kinds "
               "(edge/client.py stores; flat per-object shell estimate)"),
    ("pending", "deferred lazy-mirror raw frames awaiting first read "
                "(edge/client.py _pending; raw wire bytes)"),
    ("baseline", "retained wire-doc delta baselines, hot + compressed "
                 "(edge/client.py; absorbs kube_batch_wire_baseline_bytes)"),
    ("tensor_cache", "persistent TensorCache job blocks + node pack "
                     "(models/tensor_snapshot.py; array nbytes)"),
    ("stage", "persistent candidate-row staging buffers "
              "(models/tensor_snapshot.py; array nbytes)"),
    ("resident", "device-resident shipper buffers, full + per-shard "
                 "(models/shipping.py; host+device array nbytes)"),
    ("incremental", "incremental session state: signature masks, bonus "
                    "and job aggregates (models/incremental.py)"),
    ("compile_cache", "warmed solve-signature keys "
                      "(ops/compile_cache.py; flat per-key estimate)"),
    ("trace_ring", "flight-recorder ring of completed session traces "
                   "(trace/recorder.py; per-span/verdict estimate)"),
    ("lineage_ring", "pod-lineage ring + session ledger "
                     "(trace/lineage.py; per-pod estimate)"),
    ("event_ring", "cache event deque (cache/cache.py; per-event "
                   "estimate)"),
    ("snapshot_pool", "pooled job/node clones reused across session "
                      "snapshots (cache/cache.py; per-clone estimate)"),
    ("fused_storm", "post-eviction storm-leg capture: victim staging "
                    "columns + proof buffers held until tpu-allocate "
                    "consumes (ops/fused_solver.py; array nbytes)"),
)


class MemAuditError(AssertionError):
    """A ledger disagrees with its store beyond tolerance: some
    mutation path is missing its hook (or double-counts)."""


class Ledger:
    """One named byte account.  Components are ``(id(owner), subkey)``
    keys whose bytes and auditors die with the owner."""

    __slots__ = ("name", "publish_granularity", "_lock", "_components",
                 "_auditors", "_total", "_watermark", "_watermark_sid",
                 "_published", "__weakref__")

    def __init__(self, name: str, publish_granularity: int = 0):
        self.name = name
        #: Publish the gauge only when the total moved at least this
        #: many bytes (0 = every change).  Keeps per-event ring hooks
        #: off the metrics lock; /debug/memory and audit read the exact
        #: internal total regardless.
        self.publish_granularity = int(publish_granularity)
        self._lock = threading.Lock()
        self._components: Dict[tuple, int] = {}    # guarded-by: _lock
        # key -> (weakref to owner, sizer(owner) -> int)
        self._auditors: Dict[tuple, tuple] = {}    # guarded-by: _lock
        self._total = 0                            # guarded-by: _lock
        self._watermark = 0                        # guarded-by: _lock
        self._watermark_sid: Optional[int] = None  # guarded-by: _lock
        self._published: Optional[int] = None      # guarded-by: _lock

    # -- registration --------------------------------------------------

    def track(self, owner, subkey: str = "",
              sizer: Optional[Callable] = None) -> tuple:
        """Register a component tied to ``owner``'s lifetime and return
        its key for ``set``/``add``.  ``sizer(owner) -> int`` recomputes
        the component's true bytes for ``audit`` (it runs with NO ledger
        lock held, so it may take the store's own lock).  When the owner
        is garbage collected the component's bytes and auditor drop
        automatically."""
        key = (id(owner), subkey)
        ref = weakref.ref(owner)
        with self._lock:
            self._components.setdefault(key, 0)
            if sizer is not None:
                self._auditors[key] = (ref, sizer)
        weakref.finalize(owner, self.drop, key)
        return key

    def drop(self, key: tuple) -> None:
        """Forget one component (owner died or store dismantled)."""
        with self._lock:
            gone = self._components.pop(key, 0)
            self._auditors.pop(key, None)
            self._total -= gone
            publish = self._decide_publish_locked()
        self._publish(publish)

    # -- delta hooks ---------------------------------------------------

    def set(self, key: tuple, nbytes: int) -> None:
        """Pin one component to an absolute size (set-hook stores that
        recompute at a chokepoint: tensorize end, snapshot walk end)."""
        nbytes = int(nbytes)
        with self._lock:
            old = self._components.get(key, 0)
            self._components[key] = nbytes
            self._total += nbytes - old
            publish = self._decide_publish_locked()
        self._publish(publish)

    def add(self, key: tuple, delta: int) -> None:
        """Apply a signed byte delta (delta-hook stores: per-frame
        mirror/pending/compile-cache mutations)."""
        if not delta:
            return
        with self._lock:
            self._components[key] = self._components.get(key, 0) + int(delta)
            self._total += int(delta)
            publish = self._decide_publish_locked()
        self._publish(publish)

    # -- reads ---------------------------------------------------------

    def total(self) -> int:
        with self._lock:
            return self._total

    def watermark(self) -> Tuple[int, Optional[int]]:
        with self._lock:
            return self._watermark, self._watermark_sid

    def component_count(self) -> int:
        with self._lock:
            return len(self._components)

    # -- audit ---------------------------------------------------------

    def audit(self) -> Optional[Tuple[int, int]]:
        """(accounted, actual) or None when nothing registered a sizer.
        Sizers run OUTSIDE the ledger lock (they take their store's own
        lock); components whose owner died between finalize scheduling
        and now are skipped on both sides."""
        with self._lock:
            auditors = list(self._auditors.items())
            accounted_by_key = dict(self._components)
        accounted = 0
        actual = 0
        audited_any = False
        for key, (ref, sizer) in auditors:
            owner = ref()
            if owner is None:
                continue
            audited_any = True
            accounted += accounted_by_key.get(key, 0)
            actual += int(sizer(owner))
        if not audited_any:
            return None
        return accounted, actual

    def reset(self) -> None:
        """Test hook: zero bytes and watermark, keep registrations."""
        with self._lock:
            for key in self._components:
                self._components[key] = 0
            self._total = 0
            self._watermark = 0
            self._watermark_sid = None
            self._published = None
        self._publish((0, 0))

    # -- internals -----------------------------------------------------

    # holds-lock: _lock
    def _decide_publish_locked(self) -> Optional[Tuple[int, int]]:
        """Watermark upkeep + the gauge-publication decision, returned
        so the actual metrics write happens outside the mutex."""
        grew = self._total > self._watermark
        if grew:
            self._watermark = self._total
            self._watermark_sid = _current_session_id()
        if (not grew and self._published is not None
                and self.publish_granularity > 0
                and abs(self._total - self._published)
                < self.publish_granularity and self._total != 0):
            return None
        self._published = self._total
        return self._total, self._watermark

    def _publish(self, publish: Optional[Tuple[int, int]]) -> None:
        if publish is None:
            return
        total, watermark = publish
        metrics.set_mem_bytes(self.name, total)
        metrics.set_mem_watermark(self.name, watermark)


def _current_session_id() -> Optional[int]:
    """Lazy alias for trace/spans.current_session_id — imported at
    first use so metrics stays importable before the trace package
    (and so a trace-less tool never pays the import)."""
    global _sid_fn
    if _sid_fn is None:
        from ..trace.spans import current_session_id
        _sid_fn = current_session_id
    return _sid_fn()


_sid_fn: Optional[Callable] = None

# Hot rings publish their gauges at 4 KiB granularity; everything else
# publishes every change (the baseline ledger must track
# kube_batch_wire_baseline_bytes exactly — tests pin the parity).
_GRANULARITY = {"event_ring": 4096, "lineage_ring": 4096,
                "snapshot_pool": 4096}

_LEDGERS: Dict[str, Ledger] = {
    name: Ledger(name, _GRANULARITY.get(name, 0))
    for name, _help in LEDGER_CATALOGUE}


def ledger(name: str) -> Ledger:
    """The named ledger; KeyError on a name outside the catalogue (an
    undeclared ledger is invisible to /debug/memory — declare it)."""
    return _LEDGERS[name]


def ledgers() -> List[Ledger]:
    return list(_LEDGERS.values())


def totals() -> Dict[str, int]:
    """{ledger: current bytes} — the per-session mem_delta source."""
    return {name: led.total() for name, led in _LEDGERS.items()}


def watermarks() -> Dict[str, int]:
    return {name: led.watermark()[0] for name, led in _LEDGERS.items()}


def reset() -> None:
    """Test hook: zero every ledger (registrations survive)."""
    for led in _LEDGERS.values():
        led.reset()


# ---------------------------------------------------------------------
# Audit: the generalized audit_baseline_bytes discipline.
# ---------------------------------------------------------------------

def audit_mem_ledgers(rel_tol: float = 0.01, abs_tol: int = 4096,
                      raise_on_drift: bool = True) -> Dict[str, dict]:
    """Recompute every ledger's true size from its stores and compare.

    Returns {ledger: {"accounted", "actual", "drift"}} for every ledger
    with at least one live auditor.  Drift beyond
    ``max(abs_tol, rel_tol * actual)`` raises :class:`MemAuditError`
    (``raise_on_drift=False`` returns the report for tolerant callers —
    the scheduler's periodic audit, which races reflector threads).
    Byte-exact reconciliation is only guaranteed at quiescent points;
    ``abs_tol`` absorbs in-flight frames.
    """
    report: Dict[str, dict] = {}
    bad: List[str] = []
    for name, led in _LEDGERS.items():
        pair = led.audit()
        if pair is None:
            continue
        accounted, actual = pair
        drift = accounted - actual
        report[name] = {"accounted": accounted, "actual": actual,
                        "drift": drift}
        if abs(drift) > max(abs_tol, rel_tol * max(actual, 1)):
            bad.append("%s: accounted=%d actual=%d drift=%+d"
                       % (name, accounted, actual, drift))
    if bad and raise_on_drift:
        raise MemAuditError(
            "memory ledger drift (a mutation path is missing its hook):\n"
            + "\n".join(bad))
    if bad:
        report["_drift"] = {"failures": bad}  # type: ignore[assignment]
    return report


# ---------------------------------------------------------------------
# /debug/memory
# ---------------------------------------------------------------------

def rss_bytes() -> Optional[int]:
    """Process resident set from /proc/self/status (None off-Linux)."""
    try:
        with open("/proc/self/status", encoding="ascii") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except (OSError, ValueError, IndexError):
        pass
    return None


_memtrace_lock = threading.Lock()
_memtrace_prev = None          # guarded-by: _memtrace_lock


def _tracemalloc_doc(top_k: int = 10) -> Optional[dict]:
    """Top-K allocation-diff rows when KUBE_BATCH_TPU_MEMTRACE=1; None
    (and tracemalloc never imported into action) otherwise — the
    TRACE=0 zero-overhead discipline."""
    if not knobs.MEMTRACE.enabled():
        return None
    import tracemalloc
    global _memtrace_prev
    with _memtrace_lock:
        if not tracemalloc.is_tracing():
            tracemalloc.start()
        snap = tracemalloc.take_snapshot()
        snap = snap.filter_traces((
            tracemalloc.Filter(False, tracemalloc.__file__),))
        if _memtrace_prev is None:
            stats = snap.statistics("lineno")[:top_k]
            rows = [{"site": str(s.traceback), "bytes": s.size,
                     "count": s.count} for s in stats]
            mode = "absolute"
        else:
            stats = snap.compare_to(_memtrace_prev, "lineno")[:top_k]
            rows = [{"site": str(s.traceback), "bytes_delta": s.size_diff,
                     "bytes": s.size, "count_delta": s.count_diff}
                    for s in stats]
            mode = "diff"
        _memtrace_prev = snap
        traced, traced_peak = tracemalloc.get_traced_memory()
    return {"mode": mode, "traced_bytes": traced,
            "traced_peak_bytes": traced_peak, "top": rows}


def snapshot() -> Dict[str, dict]:
    """Per-ledger table: bytes, watermark, watermark session id,
    live component count, and the catalogue help string."""
    out: Dict[str, dict] = {}
    for name, help_text in LEDGER_CATALOGUE:
        led = _LEDGERS[name]
        wm, wm_sid = led.watermark()
        out[name] = {
            "bytes": led.total(),
            "watermark_bytes": wm,
            "watermark_session": wm_sid,
            "components": led.component_count(),
            "what": help_text,
        }
    return out


def debug_doc() -> dict:
    """The /debug/memory document (cli/server.py)."""
    table = snapshot()
    return {
        "ledgers": table,
        "total_bytes": sum(row["bytes"] for row in table.values()),
        "rss_bytes": rss_bytes(),
        "tracemalloc": _tracemalloc_doc(),
    }
