"""Version info (reference pkg/version/version.go injects via ldflags; we
keep a plain module constant plus an optional git SHA probe)."""

__version__ = "0.1.0"

API_VERSION = "v1alpha1"


def version_string() -> str:
    return f"kube-batch-tpu {__version__} (api {API_VERSION})"
