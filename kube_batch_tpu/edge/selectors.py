"""Kubernetes LIST/WATCH selector semantics (labelSelector/fieldSelector).

Real tooling filters server-side: ``kubectl get pods -l app=web`` sends
``?labelSelector=app%3Dweb`` and client-go reflectors routinely watch with
field selectors (e.g. the reference's pod informer could use
``spec.schedulerName``).  This implements the apimachinery selector
grammar the edge needs:

- labelSelector: equality (``k=v``, ``k==v``, ``k!=v``), set-based
  (``k in (a,b)``, ``k notin (a,b)``) and existence (``k``, ``!k``)
  requirements, comma-separated (AND).  Per upstream semantics, ``!=``
  and ``notin`` also select objects *without* the key.
- fieldSelector: ``path=value`` / ``path!=value`` pairs over the small
  fixed set of fields real apiservers index (metadata.name,
  metadata.namespace, and for pods spec.nodeName / status.phase /
  spec.schedulerName).  Unsupported paths raise ValueError, mirroring
  the apiserver's "field label not supported" 400.
"""

from __future__ import annotations

import re
from typing import Callable, Dict, Optional

_SET_RE = re.compile(r"^(?P<key>[^\s!=,]+)\s+(?P<op>in|notin)\s*"
                     r"\((?P<vals>[^)]*)\)$")
_KEY_RE = re.compile(r"^[A-Za-z0-9._/-]+$")   # qualified label key subset
_VAL_RE = re.compile(r"^[A-Za-z0-9._-]*$")    # label value charset


def _key_val(req: str, key: str, val: str):
    key, val = key.strip(), val.strip()
    if not _KEY_RE.match(key) or not _VAL_RE.match(val):
        raise ValueError(f"bad selector requirement {req!r}")
    return key, val


def _split_top(spec: str) -> list:
    """Split on commas that are not inside a ``(...)`` value set."""
    parts, depth, cur = [], 0, []
    for ch in spec:
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        if ch == "," and depth == 0:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    parts.append("".join(cur))
    return [p.strip() for p in parts if p.strip()]


def parse_label_selector(spec: str) -> Callable[[Dict[str, str]], bool]:
    """Compile a labelSelector string into a predicate over a labels
    dict.  Raises ValueError on a malformed selector."""
    checks = []
    for req in _split_top(spec):
        m = _SET_RE.match(req)
        if m:
            key = m.group("key")
            vals = {v.strip() for v in m.group("vals").split(",")
                    if v.strip()}
            if not _KEY_RE.match(key) or not vals \
                    or not all(_VAL_RE.match(v) for v in vals):
                raise ValueError(f"bad selector requirement {req!r}")
            if m.group("op") == "in":
                checks.append(lambda ls, k=key, vs=vals:
                              k in ls and ls[k] in vs)
            else:  # notin: objects without the key also match
                checks.append(lambda ls, k=key, vs=vals:
                              ls.get(k) not in vs or k not in ls)
            continue
        if "!=" in req:
            key, val = _key_val(req, *req.split("!=", 1))
            # != selects objects without the key too (k8s docs).
            checks.append(lambda ls, k=key, v=val: ls.get(k) != v)
            continue
        if "=" in req:
            key, val = _key_val(
                req, *req.split("==" if "==" in req else "=", 1))
            checks.append(lambda ls, k=key, v=val: ls.get(k) == v)
            continue
        if req.startswith("!"):
            key = req[1:].strip()
            if not _KEY_RE.match(key):
                raise ValueError(f"bad selector requirement {req!r}")
            checks.append(lambda ls, k=key: k not in ls)
            continue
        # Bare existence requirement: must be a well-formed key — a
        # typo like `a!b` must answer 400, not silently never-match.
        if not _KEY_RE.match(req):
            raise ValueError(f"bad selector requirement {req!r}")
        checks.append(lambda ls, k=req: k in ls)
    return lambda labels: all(c(labels) for c in checks)


# The fixed per-resource field index real apiservers expose.
_COMMON_FIELDS = ("metadata.name", "metadata.namespace")
_FIELD_PATHS = {
    "pods": _COMMON_FIELDS + ("spec.nodeName", "spec.schedulerName",
                              "status.phase"),
    # Queue is the tenancy shard key: a chain of ``spec.queue!=<q>``
    # requirements is how a shard-scoped reflector excludes foreign
    # queues' podgroups server-side (doc/INGEST.md).
    "podgroups": _COMMON_FIELDS + ("spec.queue",),
}


def _field_value(resource: str, obj, path: str) -> str:
    md = obj.metadata if hasattr(obj, "metadata") else None
    if md is not None:
        if path == "metadata.name":
            return md.name
        if path == "metadata.namespace":
            return md.namespace
    if resource == "pods":
        if path == "spec.nodeName":
            # Coerce a null nodeName to "": `spec.nodeName=` (empty
            # value) must select every unassigned pod regardless of how
            # the doc spelled "no node" (doc/INGEST.md stream split).
            return obj.spec.node_name or ""
        if path == "spec.schedulerName":
            return obj.spec.scheduler_name
        if path == "status.phase":
            return obj.status.phase
    if resource == "podgroups" and path == "spec.queue":
        # Both PodGroup API versions carry spec.queue; an unset queue
        # reads as "" so `spec.queue!=<name>` keeps default-queue groups
        # (over-approximation: the client attributes those itself).
        return getattr(obj.spec, "queue", "") or ""
    raise ValueError(f"field label not supported: {path}")


def parse_field_selector(resource: str,
                         spec: str) -> Callable[[object], bool]:
    """Compile a fieldSelector string into a predicate over an object.
    Unsupported field paths raise ValueError HERE, at compile time, so
    a watch with a bad selector answers 400 before the stream opens
    (matching the LIST path) rather than silently filtering
    everything."""
    supported = _FIELD_PATHS.get(resource, _COMMON_FIELDS)
    pairs = []  # (path, value, negate)
    for req in _split_top(spec):
        if "!=" in req:
            path, _, val = req.partition("!=")
            pairs.append((path.strip(), val.strip(), True))
        elif "=" in req:
            path, _, val = req.partition("==" if "==" in req else "=")
            pairs.append((path.strip(), val.strip(), False))
        else:
            raise ValueError(f"bad field selector requirement {req!r}")
    for path, _, _ in pairs:
        if path not in supported:
            raise ValueError(f"field label not supported: {path}")

    def match(obj) -> bool:
        for path, val, neg in pairs:
            got = _field_value(resource, obj, path)
            if (got == val) == neg:
                return False
        return True

    return match


def compile_query(resource: str,
                  query: Dict[str, list]) -> Optional[Callable]:
    """Build the combined selector predicate for a parsed query string,
    or None when the query carries no selectors.  Raises ValueError on
    malformed selectors (callers answer 400)."""
    preds = []
    if query.get("labelSelector"):
        label_match = parse_label_selector(query["labelSelector"][0])
        preds.append(lambda o: label_match(
            getattr(o.metadata, "labels", None) or {}))
    if query.get("fieldSelector"):
        preds.append(parse_field_selector(resource,
                                          query["fieldSelector"][0]))
    if not preds:
        return None
    return lambda o: all(p(o) for p in preds)
