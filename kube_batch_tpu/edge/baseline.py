"""Bounded `_wire_doc` baseline store (doc/INGEST.md).

The wire fast path retains each mirror object's raw wire doc as its
delta baseline — roughly one raw dict per pod, the largest remaining
O(cluster) memory term at 1M pods.  ``KUBE_BATCH_TPU_BASELINE_BUDGET``
caps the retained bytes per kind; over budget the reflector compresses
the COLDEST baselines (zlib of the canonical JSON, ``_wire_zdoc``)
and, still over, evicts them outright (``_wire_evicted``).  A later
frame for a compressed baseline decompresses transparently
(codec.wire_baseline); a frame for an evicted one takes the counted
full-decode fallback (``kube_batch_wire_fast_fallback_total
{reason="evicted"}``) and re-retains hot.  The per-kind ledger
(`RemoteCluster._baseline_bytes` -> ``kube_batch_wire_baseline_bytes``)
tracks the compressed/evicted sizes, so the gauge only goes DOWN at a
fixed workload once a budget binds.

Budget grammar (bytes, case-insensitive k/M/G suffixes):

    KUBE_BATCH_TPU_BASELINE_BUDGET=32M            # every kind
    KUBE_BATCH_TPU_BASELINE_BUDGET=pods=32M,podgroups=512k

Unset or empty = unbounded (the pre-budget behavior).
"""

from __future__ import annotations

import json
import zlib
from typing import Dict, Optional

from .. import knobs

BASELINE_BUDGET_ENV = knobs.BASELINE_BUDGET.env

_SUFFIX = {"k": 1024, "m": 1024 ** 2, "g": 1024 ** 3}


def _parse_size(text: str) -> int:
    text = text.strip()
    mult = 1
    if text and text[-1].lower() in _SUFFIX:
        mult = _SUFFIX[text[-1].lower()]
        text = text[:-1]
    value = int(float(text) * mult)
    if value < 0:
        raise ValueError(f"negative baseline budget {text!r}")
    return value


def parse_budgets(spec: Optional[str] = None) -> Dict[str, int]:
    """{kind: byte budget} from the env grammar above; {} = unbounded.
    A bare number applies to every kind under the ``*`` key (the client
    resolves per-kind lookups through it).  Malformed specs raise
    ValueError at construction — a budget typo must fail loudly at
    boot, not silently disable the cap."""
    if spec is None:
        spec = knobs.BASELINE_BUDGET.raw() or ""
    spec = spec.strip()
    if not spec:
        return {}
    out: Dict[str, int] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" in part:
            kind, _, size = part.partition("=")
            out[kind.strip()] = _parse_size(size)
        else:
            out["*"] = _parse_size(part)
    return out


def budget_for(budgets: Dict[str, int], kind: str) -> Optional[int]:
    """The byte cap for one kind, or None when unbounded."""
    if kind in budgets:
        return budgets[kind]
    return budgets.get("*")


def compress(obj) -> Optional[int]:
    """Compress a mirror object's hot baseline (``_wire_doc`` ->
    ``_wire_zdoc``); returns the new retained byte size, or None when
    there is nothing hot to compress (already cold, already evicted, or
    never retained).  Key order is preserved by json, so a later
    decompress round-trips the exact doc the delta compare needs."""
    doc = getattr(obj, "_wire_doc", None)
    if not isinstance(doc, dict):
        return None
    z = zlib.compress(
        json.dumps(doc, separators=(",", ":")).encode(), 6)
    try:
        obj._wire_zdoc = z
        del obj._wire_doc
    except AttributeError:  # lint: allow-swallow(slotted/frozen object: leave it hot rather than half-converted)
        return None
    return len(z)


def evict(obj) -> bool:
    """Drop a mirror object's baseline entirely (over budget even after
    compression).  The next frame for this key takes the counted
    full-decode fallback and re-retains the fresh doc hot.  Returns
    False when the object is slotted/frozen and could not be marked."""
    try:
        if hasattr(obj, "_wire_doc"):
            del obj._wire_doc
        if hasattr(obj, "_wire_zdoc"):
            del obj._wire_zdoc
        obj._wire_evicted = True
    except AttributeError:  # lint: allow-swallow(slotted/frozen object: nothing was retained on it to begin with)
        return False
    return True
