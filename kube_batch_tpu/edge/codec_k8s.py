"""Kubernetes-convention wire codec: API objects <-> k8s-shaped JSON.

The edge's native codec (edge/codec.py) is a reflective ``__kind__``
format; this module speaks the Kubernetes API conventions instead —
``apiVersion``/``kind`` tags, camelCase fields, and the real structural
shapes (``spec.containers[].resources.requests``, nodeAffinity
``nodeSelectorTerms``/``matchExpressions``, ``persistentVolumeClaim``
volumes, RFC3339 timestamps) — so a manifest written for the reference
scheduler (kubectl-shaped Pod, PodGroup of group
``scheduling.incubator.k8s.io``/``scheduling.sigs.dev``, Queue,
PriorityClass) submits to the edge unchanged, and listings read back the
same way (SURVEY.md §2.2 comm backend: the API-compatibility contract at
the wire level, not just the CRD manifests).

Scope: the scheduling-relevant subset the object model carries.  Reading
a field this model does not represent raises ValueError rather than
silently dropping semantics the reference would honor (e.g. a
matchExpressions operator other than In with one value).
"""

from __future__ import annotations

import datetime
from typing import Any, Dict, List, Optional

from ..api import objects as O
from ..apis.scheduling import v1alpha1, v1alpha2

PODGROUP_GROUPS = {v1alpha1.GROUP: v1alpha1, v1alpha2.GROUP: v1alpha2}


# -- scalar helpers ----------------------------------------------------------

def _ts_out(ts: Optional[float]):
    if not ts:
        return None
    try:
        return datetime.datetime.fromtimestamp(
            ts, tz=datetime.timezone.utc).isoformat().replace("+00:00", "Z")
    except (OverflowError, OSError, ValueError):
        return None


def _ts_in(value) -> float:
    if value in (None, ""):
        return 0.0
    if isinstance(value, (int, float)):
        return float(value)
    return datetime.datetime.fromisoformat(
        str(value).replace("Z", "+00:00")).timestamp()


def _clean(doc: dict) -> dict:
    return {k: v for k, v in doc.items()
            if v not in (None, {}, []) or k in ("spec", "status", "metadata")}


# -- metadata ----------------------------------------------------------------

def _meta_out(md: O.ObjectMeta) -> dict:
    out: Dict[str, Any] = {"name": md.name, "namespace": md.namespace,
                           "uid": md.uid}
    if md.labels:
        out["labels"] = dict(md.labels)
    if md.annotations:
        out["annotations"] = dict(md.annotations)
    ts = _ts_out(md.creation_timestamp)
    if ts:
        out["creationTimestamp"] = ts
    ts = _ts_out(md.deletion_timestamp)
    if ts:
        out["deletionTimestamp"] = ts
    if md.owner_uid:
        out["ownerReferences"] = [{"uid": md.owner_uid}]
    return out


def _meta_in(doc: Optional[dict]) -> O.ObjectMeta:
    doc = doc or {}
    owners = doc.get("ownerReferences") or []
    return O.ObjectMeta(
        name=doc.get("name", ""),
        namespace=doc.get("namespace", "default"),
        uid=doc.get("uid", ""),
        labels=dict(doc.get("labels") or {}),
        annotations=dict(doc.get("annotations") or {}),
        creation_timestamp=_ts_in(doc.get("creationTimestamp")),
        deletion_timestamp=(_ts_in(doc["deletionTimestamp"])
                            if doc.get("deletionTimestamp") else None),
        owner_uid=owners[0].get("uid", "") if owners else "")


# -- label terms / selectors -------------------------------------------------

def _term_out(term: Dict[str, str]) -> dict:
    return {"matchExpressions": [{"key": k, "operator": "In", "values": [v]}
                                 for k, v in sorted(term.items())]}


def _term_in(doc: dict) -> Dict[str, str]:
    term = dict(doc.get("matchLabels") or {})
    for expr in doc.get("matchExpressions") or []:
        op = expr.get("operator", "In")
        values = expr.get("values") or []
        if op != "In" or len(values) != 1:
            raise ValueError(
                f"unsupported selector expression {expr!r} (only In with "
                f"one value maps onto the scheduling model)")
        term[expr["key"]] = values[0]
    return term


def _selector_out(sel: Dict[str, str]) -> dict:
    return {"matchLabels": dict(sel)}


# -- affinity ----------------------------------------------------------------

def _affinity_out(aff: Optional[O.Affinity]) -> Optional[dict]:
    if aff is None:
        return None
    out: Dict[str, Any] = {}
    node: Dict[str, Any] = {}
    if aff.required_node_terms:
        node["requiredDuringSchedulingIgnoredDuringExecution"] = {
            "nodeSelectorTerms": [_term_out(t)
                                  for t in aff.required_node_terms]}
    if aff.preferred_node_terms:
        node["preferredDuringSchedulingIgnoredDuringExecution"] = [
            {"weight": w, "preference": _term_out(t)}
            for w, t in aff.preferred_node_terms]
    if node:
        out["nodeAffinity"] = node

    def pod_terms(required, preferred):
        block: Dict[str, Any] = {}
        if required:
            block["requiredDuringSchedulingIgnoredDuringExecution"] = [
                {"labelSelector": _selector_out(sel),
                 "topologyKey": aff.topology_key} for sel in required]
        if preferred:
            block["preferredDuringSchedulingIgnoredDuringExecution"] = [
                {"weight": w,
                 "podAffinityTerm": {"labelSelector": _selector_out(sel),
                                     "topologyKey": aff.topology_key}}
                for w, sel in preferred]
        return block

    pa = pod_terms(aff.required_pod_affinity, aff.preferred_pod_affinity)
    if pa:
        out["podAffinity"] = pa
    panti = pod_terms(aff.required_pod_anti_affinity,
                      aff.preferred_pod_anti_affinity)
    if panti:
        out["podAntiAffinity"] = panti
    return out or None


def _affinity_in(doc: Optional[dict]) -> Optional[O.Affinity]:
    if not doc:
        return None
    aff = O.Affinity()
    node = doc.get("nodeAffinity") or {}
    req = node.get("requiredDuringSchedulingIgnoredDuringExecution") or {}
    aff.required_node_terms = [_term_in(t)
                               for t in req.get("nodeSelectorTerms") or []]
    aff.preferred_node_terms = [
        (p.get("weight", 1), _term_in(p.get("preference") or {}))
        for p in node.get(
            "preferredDuringSchedulingIgnoredDuringExecution") or []]

    def read_pod(block):
        block = block or {}
        required, preferred, topo = [], [], None
        for t in block.get(
                "requiredDuringSchedulingIgnoredDuringExecution") or []:
            required.append(_term_in(t.get("labelSelector") or {}))
            topo = topo or t.get("topologyKey")
        for p in block.get(
                "preferredDuringSchedulingIgnoredDuringExecution") or []:
            term = p.get("podAffinityTerm") or {}
            preferred.append((p.get("weight", 1),
                              _term_in(term.get("labelSelector") or {})))
            topo = topo or term.get("topologyKey")
        return required, preferred, topo

    aff.required_pod_affinity, aff.preferred_pod_affinity, topo1 = \
        read_pod(doc.get("podAffinity"))
    aff.required_pod_anti_affinity, aff.preferred_pod_anti_affinity, topo2 = \
        read_pod(doc.get("podAntiAffinity"))
    topo = topo1 or topo2
    if topo and topo != aff.topology_key:
        # The scheduling model evaluates pod affinity per hostname only
        # (plugins/predicates.pod_affinity_ok); other topology domains
        # would silently change semantics.
        raise ValueError(f"unsupported topologyKey {topo!r} "
                         f"(only kubernetes.io/hostname)")
    if not any((aff.required_node_terms, aff.preferred_node_terms,
                aff.required_pod_affinity, aff.preferred_pod_affinity,
                aff.required_pod_anti_affinity,
                aff.preferred_pod_anti_affinity)):
        return None
    return aff


# -- pod ---------------------------------------------------------------------

def _container_out(c: O.Container) -> dict:
    out: Dict[str, Any] = {"name": c.name}
    if c.requests:
        out["resources"] = {"requests": dict(c.requests)}
    if c.ports:
        out["ports"] = [_clean({"hostPort": p.host_port,
                                "protocol": p.protocol,
                                "hostIP": p.host_ip or None})
                        for p in c.ports]
    return out


def _container_in(doc: dict) -> O.Container:
    resources = doc.get("resources") or {}
    return O.Container(
        name=doc.get("name", "main"),
        requests=dict(resources.get("requests") or {}),
        ports=[O.ContainerPort(host_port=p.get("hostPort", 0),
                               protocol=p.get("protocol", "TCP"),
                               host_ip=p.get("hostIP", ""))
               for p in doc.get("ports") or []])


def _pod_out(pod: O.Pod) -> dict:
    spec = pod.spec
    spec_doc: Dict[str, Any] = {
        "schedulerName": spec.scheduler_name,
        "containers": [_container_out(c) for c in spec.containers],
    }
    if spec.node_name:
        spec_doc["nodeName"] = spec.node_name
    if spec.node_selector:
        spec_doc["nodeSelector"] = dict(spec.node_selector)
    if spec.priority is not None:
        spec_doc["priority"] = spec.priority
    if spec.priority_class_name:
        spec_doc["priorityClassName"] = spec.priority_class_name
    if spec.init_containers:
        spec_doc["initContainers"] = [_container_out(c)
                                      for c in spec.init_containers]
    if spec.tolerations:
        spec_doc["tolerations"] = [
            _clean({"key": t.key or None, "operator": t.operator,
                    "value": t.value or None, "effect": t.effect or None})
            for t in spec.tolerations]
    affinity = _affinity_out(spec.affinity)
    if affinity:
        spec_doc["affinity"] = affinity
    if spec.volumes:
        spec_doc["volumes"] = [
            {"name": f"vol-{i}",
             "persistentVolumeClaim": {"claimName": claim}}
            for i, claim in enumerate(spec.volumes)]
    status_doc: Dict[str, Any] = {"phase": pod.status.phase}
    if pod.status.conditions:
        status_doc["conditions"] = [
            _clean({"type": c.type, "status": c.status,
                    "reason": c.reason or None, "message": c.message or None})
            for c in pod.status.conditions]
    return {"apiVersion": "v1", "kind": "Pod",
            "metadata": _meta_out(pod.metadata),
            "spec": spec_doc, "status": status_doc}


def _pod_spec_in(spec: Optional[dict]) -> O.PodSpec:
    spec = spec or {}
    volumes = []
    for v in spec.get("volumes") or []:
        pvc = v.get("persistentVolumeClaim")
        if pvc and pvc.get("claimName"):
            volumes.append(pvc["claimName"])
    return O.PodSpec(
        node_name=spec.get("nodeName", ""),
        node_selector=dict(spec.get("nodeSelector") or {}),
        priority=spec.get("priority"),
        priority_class_name=spec.get("priorityClassName", ""),
        scheduler_name=spec.get("schedulerName", "kube-batch"),
        containers=[_container_in(c)
                    for c in spec.get("containers") or []],
        init_containers=[_container_in(c)
                         for c in spec.get("initContainers") or []],
        tolerations=[O.Toleration(key=t.get("key", ""),
                                  operator=t.get("operator", "Equal"),
                                  value=t.get("value", ""),
                                  effect=t.get("effect", ""))
                     for t in spec.get("tolerations") or []],
        affinity=_affinity_in(spec.get("affinity")),
        volumes=volumes)


def _pod_status_in(status: Optional[dict]) -> O.PodStatus:
    status = status or {}
    return O.PodStatus(
        phase=status.get("phase", "Pending"),
        conditions=[O.PodCondition(type=c.get("type", ""),
                                   status=c.get("status", ""),
                                   reason=c.get("reason", ""),
                                   message=c.get("message", ""))
                    for c in status.get("conditions") or []])


def _pod_in(doc: dict) -> O.Pod:
    return O.Pod(
        metadata=_meta_in(doc.get("metadata")),
        spec=_pod_spec_in(doc.get("spec")),
        status=_pod_status_in(doc.get("status")))


# -- node --------------------------------------------------------------------

def _node_out(node: O.Node) -> dict:
    spec: Dict[str, Any] = {}
    if node.spec.taints:
        spec["taints"] = [_clean({"key": t.key, "value": t.value or None,
                                  "effect": t.effect})
                          for t in node.spec.taints]
    if node.spec.unschedulable:
        spec["unschedulable"] = True
    return {"apiVersion": "v1", "kind": "Node",
            "metadata": _meta_out(node.metadata),
            "spec": spec,
            "status": {
                "allocatable": dict(node.status.allocatable),
                "capacity": dict(node.status.capacity),
                "conditions": [{"type": k, "status": v} for k, v in
                               sorted(node.status.conditions.items())]}}


def _node_spec_in(spec: Optional[dict]) -> O.NodeSpec:
    spec = spec or {}
    return O.NodeSpec(
        taints=[O.Taint(key=t.get("key", ""), value=t.get("value", ""),
                        effect=t.get("effect", "NoSchedule"))
                for t in spec.get("taints") or []],
        unschedulable=bool(spec.get("unschedulable", False)))


def _node_status_in(status: Optional[dict]) -> O.NodeStatus:
    status = status or {}
    return O.NodeStatus(
        allocatable=dict(status.get("allocatable") or {}),
        capacity=dict(status.get("capacity") or {}),
        conditions={c["type"]: c.get("status", "")
                    for c in status.get("conditions") or []})


def _node_in(doc: dict) -> O.Node:
    return O.Node(
        metadata=_meta_in(doc.get("metadata")),
        spec=_node_spec_in(doc.get("spec")),
        status=_node_status_in(doc.get("status")))


# -- CRDs + the rest ---------------------------------------------------------

def _pod_group_out(pg, module) -> dict:
    status = {"phase": pg.status.phase, "running": pg.status.running,
              "succeeded": pg.status.succeeded, "failed": pg.status.failed}
    if pg.status.conditions:
        status["conditions"] = [
            _clean({"type": c.type, "status": c.status,
                    "transitionID": c.transition_id or None,
                    "lastTransitionTime": _ts_out(c.last_transition_time),
                    "reason": c.reason or None,
                    "message": c.message or None})
            for c in pg.status.conditions]
    return {"apiVersion": f"{module.GROUP}/{module.VERSION}",
            "kind": "PodGroup",
            "metadata": _meta_out(pg.metadata),
            "spec": _clean({
                "minMember": pg.spec.min_member,
                "queue": pg.spec.queue,
                "priorityClassName": pg.spec.priority_class_name or None}),
            "status": status}


def _pod_group_in(doc: dict, module):
    spec = doc.get("spec") or {}
    status = doc.get("status") or {}
    return module.PodGroup(
        metadata=_meta_in(doc.get("metadata")),
        spec=module.PodGroupSpec(
            min_member=spec.get("minMember", 0),
            queue=spec.get("queue", "default"),
            priority_class_name=spec.get("priorityClassName", "")),
        status=module.PodGroupStatus(
            phase=status.get("phase", "Pending"),
            conditions=[module.PodGroupCondition(
                type=c.get("type", ""), status=c.get("status", "True"),
                transition_id=c.get("transitionID", ""),
                last_transition_time=_ts_in(c.get("lastTransitionTime")),
                reason=c.get("reason", ""), message=c.get("message", ""))
                for c in status.get("conditions") or []],
            running=status.get("running", 0),
            succeeded=status.get("succeeded", 0),
            failed=status.get("failed", 0)))


def _queue_out(queue, module) -> dict:
    return {"apiVersion": f"{module.GROUP}/{module.VERSION}",
            "kind": "Queue",
            "metadata": _meta_out(queue.metadata),
            "spec": _clean({"weight": queue.spec.weight,
                            "capability": dict(queue.spec.capability)
                            or None}),
            "status": {"pending": queue.status.pending,
                       "running": queue.status.running,
                       "unknown": queue.status.unknown}}


def _queue_in(doc: dict, module):
    spec = doc.get("spec") or {}
    status = doc.get("status") or {}
    return module.Queue(
        metadata=_meta_in(doc.get("metadata")),
        spec=module.QueueSpec(weight=spec.get("weight", 1),
                              capability=dict(spec.get("capability") or {})),
        status=module.QueueStatus(pending=status.get("pending", 0),
                                  running=status.get("running", 0),
                                  unknown=status.get("unknown", 0)))


def _simple_out(obj) -> dict:
    if isinstance(obj, O.PriorityClass):
        return {"apiVersion": "scheduling.k8s.io/v1", "kind": "PriorityClass",
                "metadata": _meta_out(obj.metadata), "value": obj.value,
                "globalDefault": obj.global_default}
    if isinstance(obj, O.PodDisruptionBudget):
        return {"apiVersion": "policy/v1beta1",
                "kind": "PodDisruptionBudget",
                "metadata": _meta_out(obj.metadata),
                "spec": {"minAvailable": obj.min_available}}
    if isinstance(obj, O.PersistentVolumeClaim):
        return {"apiVersion": "v1", "kind": "PersistentVolumeClaim",
                "metadata": _meta_out(obj.metadata),
                "spec": _clean({"storageClassName": obj.storage_class,
                                "volumeName": obj.volume_name or None}),
                "status": {"phase": obj.phase}}
    if isinstance(obj, O.Event):
        ns, _, name = obj.involved_object.partition("/")
        involved = ({"namespace": ns, "name": name} if name
                    else {"name": obj.involved_object})
        return _clean({"apiVersion": "v1", "kind": "Event",
                       "metadata": _meta_out(obj.metadata),
                       "involvedObject": involved,
                       "reason": obj.reason, "message": obj.message,
                       "type": obj.type,
                       "firstTimestamp": _ts_out(obj.timestamp)})
    raise ValueError(f"no k8s encoding for {type(obj).__name__}")


def to_k8s(obj) -> Dict[str, Any]:
    """Encode one API object as a Kubernetes-convention JSON document."""
    if isinstance(obj, O.Pod):
        return _pod_out(obj)
    if isinstance(obj, O.Node):
        return _node_out(obj)
    if isinstance(obj, (v1alpha1.PodGroup, v1alpha2.PodGroup)):
        module = (v1alpha2 if isinstance(obj, v1alpha2.PodGroup)
                  else v1alpha1)
        return _pod_group_out(obj, module)
    if isinstance(obj, (v1alpha1.Queue, v1alpha2.Queue)):
        module = v1alpha2 if isinstance(obj, v1alpha2.Queue) else v1alpha1
        return _queue_out(obj, module)
    return _simple_out(obj)


def from_k8s(doc: Dict[str, Any]):
    """Decode a Kubernetes-convention JSON document into an API object."""
    kind = doc.get("kind")
    api_version = doc.get("apiVersion", "")
    group = api_version.split("/")[0] if "/" in api_version else ""
    if kind == "Pod":
        return _pod_in(doc)
    if kind == "Node":
        return _node_in(doc)
    if kind == "PodGroup":
        module = PODGROUP_GROUPS.get(group)
        if module is None:
            raise ValueError(f"unknown PodGroup group {group!r}")
        return _pod_group_in(doc, module)
    if kind == "Queue":
        module = PODGROUP_GROUPS.get(group, v1alpha1)
        return _queue_in(doc, module)
    if kind == "PriorityClass":
        return O.PriorityClass(metadata=_meta_in(doc.get("metadata")),
                               value=doc.get("value", 0),
                               global_default=doc.get("globalDefault",
                                                      False))
    if kind == "PodDisruptionBudget":
        spec = doc.get("spec") or {}
        return O.PodDisruptionBudget(
            metadata=_meta_in(doc.get("metadata")),
            min_available=spec.get("minAvailable", 0))
    if kind == "PersistentVolumeClaim":
        spec = doc.get("spec") or {}
        status = doc.get("status") or {}
        return O.PersistentVolumeClaim(
            metadata=_meta_in(doc.get("metadata")),
            storage_class=spec.get("storageClassName", "standard"),
            phase=status.get("phase", "Pending"),
            volume_name=spec.get("volumeName", ""))
    if kind == "Event":
        involved = doc.get("involvedObject") or {}
        key = (f"{involved.get('namespace')}/{involved.get('name')}"
               if involved.get("namespace") else involved.get("name", ""))
        return O.Event(metadata=_meta_in(doc.get("metadata")),
                       involved_object=key,
                       reason=doc.get("reason", ""),
                       message=doc.get("message", ""),
                       type=doc.get("type", "Normal"),
                       timestamp=_ts_in(doc.get("firstTimestamp")))
    raise ValueError(f"unknown k8s kind {kind!r}")


def decode_any(doc: Dict[str, Any]):
    """Decode either wire format: the native ``__kind__`` documents or
    Kubernetes-convention ``kind``/``apiVersion`` documents."""
    from . import codec
    if "__kind__" in doc:
        return codec.decode(doc)
    if "kind" in doc:
        return from_k8s(doc)
    raise ValueError("document carries neither __kind__ nor kind")


# ---------------------------------------------------------------------------
# Columnar delta decode for the k8s wire (mirror of edge/codec.decode_delta
# and the same contract: raw-section compare against the cached previous
# wire doc, re-decoding only changed sections through the EXACT section
# decoders the full path uses — so a delta result equals the full decode
# bit for bit, and an unchanged ``spec`` section reuses the previous
# PodSpec OBJECT, keeping models/tensor_snapshot._pod_static's identity-
# keyed signature cache warm across watch echoes).  Scope: Pods and Nodes,
# the churn-heavy kinds the fast path exists for; every other kind raises
# LookupError and the client falls back to a counted full decode.
# ---------------------------------------------------------------------------

_DELTA_SECTIONS = {
    # kind -> ((doc_key, field_name, section_decoder), ...)
    "Pod": (("metadata", "metadata", _meta_in),
            ("spec", "spec", _pod_spec_in),
            ("status", "status", _pod_status_in)),
    "Node": (("metadata", "metadata", _meta_in),
             ("spec", "spec", _node_spec_in),
             ("status", "status", _node_status_in)),
}

_DELTA_CLASSES = {"Pod": O.Pod, "Node": O.Node}


def from_k8s_delta(doc: Dict[str, Any], prev):
    """Decode a k8s-convention doc against the previously decoded
    ``prev`` (whose raw doc edge/client stamped as ``_wire_doc``).
    Raises ValueError exactly where ``from_k8s`` would; LookupError when
    no delta is possible (unknown kind, missing/mismatched baseline) —
    the caller falls back to the full decode."""
    from .codec import _carry_tensor_static, remember_wire_doc

    kind = doc.get("kind")
    # Mirror from_k8s's group parse for effect: a frame whose apiVersion
    # is not a string must raise the SAME TypeError here that the full
    # decode raises, or the fast arm silently applies a frame the
    # control arm relists on (divergence; pinned by the fuzz suite).
    api_version = doc.get("apiVersion", "")
    "/" in api_version  # noqa: B015 — type check by evaluation
    try:
        sections = _DELTA_SECTIONS.get(kind)
        cls = _DELTA_CLASSES.get(kind)
    except TypeError:
        # Unhashable kind (malformed frame): the FULL decode owns the
        # error shape (its == dispatch raises ValueError) — refuse the
        # delta so the fallback reproduces it exactly.
        raise LookupError("baseline") from None
    if sections is None:
        # Resource kind outside the delta plans (PodGroups, Queues, …):
        # counted under its own fallback reason so operators can tell
        # "unsupported kind" from "missing baseline".
        raise LookupError("kind")
    if type(prev) is not cls:
        raise LookupError("baseline")
    from .codec import wire_baseline
    prev_data = wire_baseline(prev)  # LookupError: baseline | evicted
    if not isinstance(prev_data, dict):
        raise LookupError("baseline")
    kwargs = {}
    for doc_key, field, section_in in sections:
        v = doc.get(doc_key)
        if doc_key in prev_data and v == prev_data[doc_key]:
            kwargs[field] = getattr(prev, field)
        else:
            kwargs[field] = section_in(v)
    obj = cls(**kwargs)
    remember_wire_doc(obj, doc)
    _carry_tensor_static(prev, obj)
    return obj


def decode_any_delta(doc: Dict[str, Any], prev):
    """Delta-decode either wire format against ``prev``; LookupError
    means "fall back to the full decode", ValueError means the doc is
    malformed for the full path too."""
    from . import codec
    if "__kind__" in doc:
        return codec.decode_delta(doc, prev)
    if "kind" in doc:
        return from_k8s_delta(doc, prev)
    raise ValueError("document carries neither __kind__ nor kind")
