"""REST API server: the Cluster store over HTTP with list+watch.

The standalone analog of the Kubernetes API server surface the reference
consumes (SURVEY.md §2.2): LIST (GET), CREATE (POST), UPDATE (PUT),
DELETE, the pod ``bind`` and PodGroup ``status`` subresources
(cache.go:119-131, :763-775), and WATCH — a chunked stream of JSON-line
events ``{"type": ADDED|MODIFIED|DELETED|SYNC, "object": ...}`` where the
stream opens with ADDED events for current state and a SYNC marker (the
list+watch contract client-go's reflector relies on).

Run standalone:  python -m kube_batch_tpu.edge.server --port 8090 \
                     [--cluster-state example/job.json]
"""

from __future__ import annotations

import json
import queue
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from ..cache.cluster import Cluster
from . import codec, codec_k8s, selectors

_RESOURCES = ("pods", "nodes", "podgroups", "queues", "priorityclasses",
              "pdbs", "pvcs", "events", "leases")

# Kubernetes-convention paths (/api/v1/..., /apis/{group}/{version}/...)
# map onto the same stores; responses/bodies on these paths use the k8s
# wire codec (camelCase, kind/apiVersion — edge/codec_k8s.py).
_K8S_RESOURCES = {
    "pods": "pods", "nodes": "nodes", "events": "events",
    "persistentvolumeclaims": "pvcs", "priorityclasses": "priorityclasses",
    "poddisruptionbudgets": "pdbs", "podgroups": "podgroups",
    "queues": "queues",
}


def _merge_patch(target, patch):
    """RFC 7386 JSON merge-patch: dicts merge recursively, ``null``
    deletes a key, everything else (including lists) replaces."""
    if not isinstance(patch, dict):
        return patch
    if not isinstance(target, dict):
        target = {}
    out = dict(target)
    for key, value in patch.items():
        if value is None:
            out.pop(key, None)
        else:
            out[key] = _merge_patch(out.get(key), value)
    return out


# Strategic-merge list keys — the subset real apiservers declare via
# ``patchMergeKey``: these object lists merge by key; other lists
# replace, as in RFC 7386.
_MERGE_KEYS = {("status", "conditions"): "type"}

# Idle-watch keep-alive cadence.  Also the upper bound on how long a
# shard-scoped reflector can sit on a STALE selector after a lease
# claim/shed (the client checks its scope epoch per frame, PINGs
# included — doc/INGEST.md "Handover relist").  Module-level so tests
# can shrink the rescope latency.
_PING_INTERVAL_S = 5.0


def _strategic_merge(target, patch, path=()):
    """Kubernetes strategic merge patch (the fragment the edge needs):
    like merge-patch, but lists registered in _MERGE_KEYS upsert items
    by their merge key instead of replacing the whole list — so a
    writer can update ITS condition without clobbering concurrent
    writers' conditions (no read-modify-write race)."""
    if isinstance(patch, list):
        key = _MERGE_KEYS.get(path)
        if (key and isinstance(target, list)
                and all(isinstance(x, dict) for x in patch)):
            out = list(target)
            index = {x.get(key): i for i, x in enumerate(out)
                     if isinstance(x, dict)}
            for item in patch:
                i = index.get(item.get(key))
                if i is None:
                    out.append(item)
                else:
                    out[i] = _strategic_merge(out[i], item, path)
            return out
        return patch
    if not isinstance(patch, dict):
        return patch
    if not isinstance(target, dict):
        target = {}
    out = dict(target)
    for key, value in patch.items():
        if value is None:
            out.pop(key, None)
        else:
            out[key] = _strategic_merge(out.get(key), value,
                                        path + (key,))
    return out


def _store_of(cluster: Cluster, resource: str):
    return {"pods": cluster.pods, "nodes": cluster.nodes,
            "podgroups": cluster.pod_groups, "queues": cluster.queues,
            "priorityclasses": cluster.priority_classes,
            "pdbs": cluster.pdbs, "pvcs": cluster.pvcs,
            "events": cluster.events}[resource]


def _informer_of(cluster: Cluster, resource: str):
    return {"pods": cluster.pod_informer, "nodes": cluster.node_informer,
            "podgroups": cluster.pod_group_informer,
            "queues": cluster.queue_informer,
            "priorityclasses": cluster.priority_class_informer,
            "pdbs": cluster.pdb_informer}.get(resource)


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    # Keep-alive + small unbuffered writes (wbufsize=0) otherwise hit
    # Nagle/delayed-ACK stalls: a response written as status + headers +
    # body segments can wait ~40 ms per round for the peer's delayed ACK,
    # turning a bulk bind egress into minutes (measured 4 ms/bind ->
    # sub-ms with NODELAY on the loopback edge).
    disable_nagle_algorithm = True
    cluster: Cluster = None  # set by ApiServer subclassing
    history = None           # _EventHistory, set by ApiServer subclassing

    def log_message(self, *args):  # quiet; the scheduler has its own logs
        pass

    # -- helpers -----------------------------------------------------------

    def _json(self, status: int, payload) -> None:
        body = json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _body(self):
        length = int(self.headers.get("Content-Length", 0))
        return json.loads(self.rfile.read(length)) if length else None

    def _route(self):
        """(resource, rest, query, k8s, ns).  Native paths are
        /v1/{resource}/...; Kubernetes-convention paths are
        /api/v1/[namespaces/{ns}/]{resource}/... and
        /apis/{group}/{version}/[namespaces/{ns}/]{resource}/... —
        the latter select the k8s wire codec for bodies and responses."""
        parsed = urlparse(self.path)
        parts = [p for p in parsed.path.split("/") if p]
        query = parse_qs(parsed.query)
        if len(parts) >= 2 and parts[0] == "v1" and parts[1] in _RESOURCES:
            return parts[1], parts[2:], query, False, None
        if parts and parts[0] in ("api", "apis"):
            skip = 2 if parts[0] == "api" else 3
            tail = parts[skip:]
            ns = None
            if len(tail) >= 2 and tail[0] == "namespaces":
                ns, tail = tail[1], tail[2:]
            if tail:
                resource = _K8S_RESOURCES.get(tail[0])
                if resource is not None:
                    rest = tail[1:]
                    if ns is not None and rest:
                        # Internal convention is namespace-first; a bare
                        # namespaced collection path (create/list) keeps
                        # rest empty, with ns carried separately.
                        rest = [ns] + rest
                    return resource, rest, query, True, ns
        return None, None, None, False, None

    # -- verbs -------------------------------------------------------------

    def do_GET(self):
        resource, rest, query, k8s, ns = self._route()
        if resource is None:
            return self._json(404, {"error": "not found"})
        if resource == "leases":
            if len(rest) != 2:
                return self._json(404, {"error": "lease key required"})
            version, record = self.cluster.get_lease(rest[0], rest[1])
            return self._json(200, {"version": version, "record": record})
        try:  # server-side filtering (kubectl -l / --field-selector)
            match = selectors.compile_query(resource, query)
        except ValueError as exc:
            return self._json(400, {"error": str(exc)})
        if query.get("watch"):
            since = None
            if query.get("resourceVersion"):
                try:
                    since = int(query["resourceVersion"][0])
                except ValueError:
                    return self._json(400,
                                      {"error": "bad resourceVersion"})
            return self._watch(resource, k8s, ns, since, match)
        enc = codec_k8s.to_k8s if k8s else codec.encode
        single = None
        try:
            with self.cluster.lock:  # encode under the lock, send outside
                store = _store_of(self.cluster, resource)
                if rest:  # single-object GET
                    obj = (store.get("/".join(rest))
                           if hasattr(store, "get") else None)
                    if obj is not None:
                        single = enc(obj)
                else:
                    items = [enc(o) for o in store.values()
                             if (ns is None or o.metadata.namespace == ns)
                             and (match is None or match(o))]
        except ValueError as exc:  # unsupported fieldSelector path
            return self._json(400, {"error": str(exc)})
        if rest:
            if single is None:
                return self._json(404, {"error": "not found"})
            return self._json(200, single)
        if k8s:
            return self._json(200, {"apiVersion": "v1", "kind": "List",
                                    "items": items})
        self._json(200, {"items": items})

    def do_POST(self):
        resource, rest, _query, k8s, _ns = self._route()
        if resource is None:
            return self._json(404, {"error": "not found"})
        if (resource in ("pods", "pvcs") and len(rest) == 3
                and rest[2] in ("bind", "binding")):
            try:  # malformed body -> 400, distinct from store conflicts
                body = self._body()
                if rest[2] == "binding":  # k8s Binding subresource shape
                    target = (body.get("target") or {})["name"]
                else:
                    target = body["node" if resource == "pods"
                                  else "volume"]
            except (KeyError, ValueError, TypeError) as exc:
                return self._json(400, {"error": f"bad bind body: {exc}"})
            try:
                if resource == "pods":
                    self.cluster.bind_pod(rest[0], rest[1], target)
                else:
                    self.cluster.bind_pvc(rest[0], rest[1], target)
            except (KeyError, ValueError) as exc:
                return self._json(409, {"error": str(exc)})
            return self._json(200, {"status": "bound"})
        if rest:  # create routes take no path suffix
            return self._json(404, {"error": "not found"})
        if resource == "leases":  # leases are PUT-CAS only
            return self._json(405, {"error": "create not supported"})
        try:
            raw = self._body()
            if k8s and _ns is not None and isinstance(raw, dict):
                # kubectl convention: the path supplies the namespace when
                # the manifest omits it.
                raw.setdefault("metadata", {}).setdefault("namespace", _ns)
            obj = codec_k8s.decode_any(raw)
        except (ValueError, KeyError, TypeError) as exc:
            return self._json(400, {"error": str(exc)})
        create = {"pods": self.cluster.create_pod,
                  "nodes": self.cluster.create_node,
                  "podgroups": self.cluster.create_pod_group,
                  "queues": self.cluster.create_queue,
                  "priorityclasses": self.cluster.create_priority_class,
                  "pdbs": self.cluster.create_pdb,
                  "pvcs": self.cluster.create_pvc,
                  "events": self.cluster.create_event}[resource]
        try:
            create(obj)
        except (KeyError, ValueError) as exc:  # store conflict
            return self._json(409, {"error": str(exc)})
        return self._json(201, {"status": "created"})

    def do_PUT(self):
        resource, rest, _query, k8s, _ns = self._route()
        if resource is None:
            return self._json(404, {"error": "not found"})
        try:
            if resource == "leases":
                if len(rest) != 2:
                    return self._json(404, {"error": "lease key required"})
                try:
                    body = self._body()
                    record = body["record"]
                    expected = int(body["expectedVersion"])
                except (KeyError, TypeError, ValueError) as exc:
                    return self._json(400, {"error": f"bad lease body: {exc}"})
                try:
                    version = self.cluster.cas_lease(rest[0], rest[1],
                                                     record, expected)
                except ValueError as exc:  # version conflict
                    return self._json(409, {"error": str(exc)})
                return self._json(200, {"version": version})
            raw = self._body()
            if k8s and _ns is not None and isinstance(raw, dict):
                raw.setdefault("metadata", {}).setdefault("namespace", _ns)
            obj = codec_k8s.decode_any(raw)
            if resource == "podgroups" and rest and rest[-1] == "status":
                self.cluster.put_pod_group_status(obj)
                return self._json(200, {"status": "updated"})
            if (resource == "pods" and len(rest) == 3
                    and rest[2] == "status"):
                # Pod status subresource: a PodCondition upsert (native)
                # or a full k8s Pod whose entire status — phase AND
                # conditions — replaces the stored one, like a real
                # apiserver UpdateStatus (cache.go:548-568 writes
                # conditions; kubelets write phase through this path).
                from ..api.objects import Pod
                if isinstance(obj, Pod):
                    self.cluster.put_pod_status(rest[0], rest[1],
                                                obj.status)
                else:
                    self.cluster.update_pod_condition(rest[0], rest[1],
                                                      obj)
                return self._json(200, {"status": "updated"})
            update = {"pods": self.cluster.update_pod,
                      "nodes": self.cluster.update_node,
                      "podgroups": self.cluster.update_pod_group}.get(resource)
            if update is None:
                return self._json(405, {"error": "update not supported"})
            update(obj)
            return self._json(200, {"status": "updated"})
        except KeyError as exc:
            return self._json(404, {"error": str(exc)})
        except (ValueError, TypeError) as exc:  # malformed/missing body
            return self._json(400, {"error": str(exc)})

    def do_PATCH(self):
        """Merge-patch (``kubectl patch --type=merge``, RFC 7386) and
        strategic-merge-patch (conditions merged by ``type``).
        Supported on pods, podgroups (object + ``status`` subresource)
        and nodes: the stored object is encoded in the path's wire
        codec, deep-merged with the patch (null deletes a key), decoded,
        and applied through the same update/status paths as PUT."""
        resource, rest, _query, k8s, _ns = self._route()
        if resource is None or not rest:
            return self._json(404, {"error": "not found"})
        ctype = (self.headers.get("Content-Type") or "").split(";")[0]
        if ctype not in ("application/merge-patch+json",
                        "application/strategic-merge-patch+json",
                        "application/json", ""):
            return self._json(415, {"error": f"unsupported patch type "
                                             f"{ctype}"})
        merge = (_strategic_merge
                 if ctype == "application/strategic-merge-patch+json"
                 else _merge_patch)
        try:
            patch = self._body()
        except ValueError as exc:
            return self._json(400, {"error": str(exc)})
        if not isinstance(patch, dict):
            return self._json(400, {"error": "patch body must be an "
                                             "object"})
        if resource not in ("pods", "podgroups", "nodes"):
            return self._json(405, {"error": "patch not supported"})
        # Same shape guard as do_PUT (len == 3): a pod legitimately
        # NAMED "status" (rest == [ns, "status"]) is an object patch.
        status_sub = len(rest) == 3 and rest[-1] == "status"
        key_parts = rest[:-1] if status_sub else rest
        enc = codec_k8s.to_k8s if k8s else codec.encode
        try:
            with self.cluster.lock:  # mutate under the lock, send outside
                store = _store_of(self.cluster, resource)
                current = (store.get("/".join(key_parts))
                           if hasattr(store, "get") else None)
                if current is not None:
                    doc = merge(enc(current), patch)
                    obj = (codec_k8s.from_k8s(doc) if k8s
                           else codec.decode(doc))
                    if resource == "pods":
                        if status_sub:
                            self.cluster.put_pod_status(key_parts[0],
                                                        key_parts[1],
                                                        obj.status)
                        else:
                            self.cluster.update_pod(obj)
                    elif resource == "podgroups":
                        if status_sub:
                            self.cluster.put_pod_group_status(obj)
                        else:
                            self.cluster.update_pod_group(obj)
                    else:
                        self.cluster.update_node(obj)
            if current is None:
                return self._json(404, {"error": "not found"})
            return self._json(200, {"status": "patched"})
        except KeyError as exc:
            return self._json(404, {"error": str(exc)})
        except (ValueError, TypeError) as exc:
            return self._json(400, {"error": str(exc)})

    def do_DELETE(self):
        resource, rest, _query, _k8s, _ns = self._route()
        if resource is None or not rest:
            return self._json(404, {"error": "not found"})
        try:
            if resource == "pods":
                self.cluster.delete_pod(rest[0], rest[1])
            elif resource == "nodes":
                self.cluster.delete_node(rest[0])
            elif resource == "podgroups":
                self.cluster.delete_pod_group(rest[0], rest[1])
            elif resource == "queues":
                self.cluster.delete_queue(rest[0])
            elif resource == "pdbs":
                self.cluster.delete_pdb(rest[0], rest[1])
            else:
                return self._json(405, {"error": "delete not supported"})
            return self._json(200, {"status": "deleted"})
        except KeyError as exc:
            return self._json(404, {"error": str(exc)})

    # -- watch -------------------------------------------------------------

    def _watch(self, resource: str, k8s: bool = False,
               ns: "str | None" = None, since: "int | None" = None,
               match=None) -> None:
        informer = _informer_of(self.cluster, resource)
        if informer is None:
            return self._json(405, {"error": f"{resource} not watchable"})
        enc = codec_k8s.to_k8s if k8s else codec.encode
        history = self.history

        def in_scope(obj) -> bool:
            # Namespaced watch paths and selectors scope server-side,
            # matching the corresponding LIST (k8s list+watch contract).
            # Selectors are validated at compile time (do_GET), so
            # match() cannot raise here.
            if ns is not None and obj.metadata.namespace != ns:
                return False
            return match is None or match(obj)

        def last_rv() -> "int | None":
            # The per-connection handler runs right after the history
            # handler (registered first, same cluster lock), so the
            # buffer tail IS this event's rv.
            if history is None:
                return None
            buf = history.buffers.get(resource)
            return buf[-1][0] if buf else None

        events: "queue.Queue" = queue.Queue()
        handle = None
        initial: list = []
        list_rv = None
        # Register BEFORE snapshotting, under the store lock, so no event
        # can fall between the initial list and the live stream.
        with self.cluster.lock:
            def on_update(old, new):
                # Selector boundary transitions surface as ADDED/DELETED,
                # the way real apiserver filtered watches behave.
                was, now = in_scope(old), in_scope(new)
                if was and now:
                    events.put(("MODIFIED", new, last_rv()))
                elif now:
                    events.put(("ADDED", new, last_rv()))
                elif was:
                    events.put(("DELETED", new, last_rv()))

            handle = informer.add_handlers(
                on_add=lambda o: in_scope(o)
                and events.put(("ADDED", o, last_rv())),
                on_update=on_update,
                on_delete=lambda o: in_scope(o)
                and events.put(("DELETED", o, last_rv())))
            pending = (history.since(resource, since)
                       if since is not None and history is not None
                       else None)
            resumed = since is not None and pending is not None
            gone = since is not None and history is not None \
                and pending is None
            if not resumed and not gone:  # the 410 path needs no snapshot
                initial = [o for o in
                           _store_of(self.cluster, resource).values()
                           if in_scope(o)]
                list_rv = (history.current_rv()
                           if history is not None else None)

        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()

        def emit(etype, obj, rv=None, raw=None):
            frame = {"type": etype,
                     "object": (raw if raw is not None
                                else enc(obj) if obj is not None else None)}
            if rv is not None:
                frame["rv"] = rv
            line = json.dumps(frame).encode() + b"\n"
            self.wfile.write(f"{len(line):x}\r\n".encode() + line + b"\r\n")
            self.wfile.flush()

        try:
            if gone:
                # The client fell past the event buffer: k8s 410 Gone
                # semantics — relist (reconnect without resourceVersion).
                emit("ERROR", None, raw={"kind": "Status", "code": 410,
                                         "reason": "Expired"})
                return
            if resumed:
                # Delta resume: no ADDED replay, no SYNC reconciliation.
                # MODIFIED history carries the pre-update object so the
                # selector boundary-transition rewrite (ADDED/DELETED)
                # applies to replayed events exactly as to live ones —
                # a filtered client must not miss an object's exit.
                emit("RESUMED", None)
                for rv, etype, obj, old in pending:
                    if etype == "MODIFIED":
                        was = in_scope(old) if old is not None else True
                        now = in_scope(obj)
                        if was and now:
                            emit("MODIFIED", obj, rv)
                        elif now:
                            emit("ADDED", obj, rv)
                        elif was:
                            emit("DELETED", obj, rv)
                    elif in_scope(obj):
                        emit(etype, obj, rv)
            else:
                for obj in initial:
                    emit("ADDED", obj)
                emit("SYNC", None, rv=list_rv)
            while True:
                try:
                    etype, obj, rv = events.get(timeout=_PING_INTERVAL_S)
                except queue.Empty:
                    emit("PING", None)  # keep-alive; detects dead peers
                    continue
                emit(etype, obj, rv)
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass
        finally:
            informer.remove_handlers(handle)


class _EventHistory:
    """Per-resource ring buffer of (rv, type, object) change events, the
    backing store for resourceVersion watch resume (k8s list+watch
    contract: a reconnecting client replays only the delta, or gets 410
    Gone and relists when it has fallen past the buffer)."""

    def __init__(self, cluster: Cluster, maxlen: int = 8192):
        from collections import deque
        self.cluster = cluster
        self.maxlen = maxlen
        self.buffers: dict = {}
        # Watermark: the highest rv NOT covered by a resource's buffer —
        # events at or below it were never recorded (before this history
        # existed, e.g. a server restart) or have been evicted.  A client
        # may resume iff its rv >= watermark.
        self.start_rv = next(cluster._rv)
        self.watermark: dict = {}
        self._registrations: list = []
        for resource in _RESOURCES:
            informer = _informer_of(cluster, resource)
            if informer is None:
                continue
            buf = deque(maxlen=maxlen)
            self.buffers[resource] = buf
            self.watermark[resource] = self.start_rv

            def _rec(buf, resource):  # bind per resource
                def record(etype):
                    def fire(*args):
                        if len(buf) == self.maxlen:  # about to evict
                            self.watermark[resource] = buf[0][0]
                        # MODIFIED keeps the pre-update object too, so
                        # resumed filtered watches can detect selector
                        # boundary transitions.
                        old = args[0] if etype == "MODIFIED" else None
                        buf.append((next(cluster._rv), etype, args[-1],
                                    old))
                    return fire
                return (record("ADDED"), record("MODIFIED"),
                        record("DELETED"))

            on_add, on_update, on_delete = _rec(buf, resource)
            handle = informer.add_handlers(on_add=on_add,
                                           on_update=on_update,
                                           on_delete=on_delete)
            self._registrations.append((informer, handle))

    def close(self) -> None:
        """Unregister from the cluster's informers (a stopped server must
        not keep recording — or pinning objects — for the cluster's
        lifetime)."""
        for informer, handle in self._registrations:
            informer.remove_handlers(handle)
        self._registrations.clear()

    def current_rv(self) -> int:
        """The rv a fresh LIST/replay corresponds to: everything up to
        the newest recorded event (or history birth when quiet)."""
        return max((buf[-1][0] for buf in self.buffers.values() if buf),
                   default=self.start_rv)

    def since(self, resource: str, rv: int):
        """Events with rv > given, or None when continuity can't be
        proven (client must relist — 410 Gone).  A client of a PREVIOUS
        server instance resumes with an rv below this history's
        watermark and correctly falls into the relist path."""
        buf = self.buffers.get(resource)
        if buf is None or rv < self.watermark.get(resource, 0):
            return None
        return [e for e in buf if e[0] > rv]


class ApiServer:
    """Serve a Cluster store over HTTP (threaded; one thread per watch)."""

    def __init__(self, cluster: Cluster, host: str = "127.0.0.1",
                 port: int = 0):
        self.cluster = cluster
        with cluster.lock:
            self._history = _EventHistory(cluster)
        handler = type("BoundHandler", (_Handler,),
                       {"cluster": cluster, "history": self._history})
        self._httpd = ThreadingHTTPServer((host, port), handler)
        self._httpd.daemon_threads = True
        self._thread = None

    @property
    def url(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "ApiServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._history.close()
        self._httpd.shutdown()
        self._httpd.server_close()


def main(argv=None):
    import argparse

    parser = argparse.ArgumentParser(description="kube-batch-tpu API server")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8090)
    parser.add_argument("--cluster-state", default="",
                        help="JSON state file to preload (cli/server.py)")
    ns = parser.parse_args(argv)
    cluster = Cluster()
    if ns.cluster_state:
        from ..cli.server import load_cluster_state
        load_cluster_state(cluster, ns.cluster_state)
    server = ApiServer(cluster, ns.host, ns.port)
    print(f"kube-batch-tpu apiserver listening on {server.url}")
    server._httpd.serve_forever()


if __name__ == "__main__":
    main()
