"""REST API server: the Cluster store over HTTP with list+watch.

The standalone analog of the Kubernetes API server surface the reference
consumes (SURVEY.md §2.2): LIST (GET), CREATE (POST), UPDATE (PUT),
DELETE, the pod ``bind`` and PodGroup ``status`` subresources
(cache.go:119-131, :763-775), and WATCH — a chunked stream of JSON-line
events ``{"type": ADDED|MODIFIED|DELETED|SYNC, "object": ...}`` where the
stream opens with ADDED events for current state and a SYNC marker (the
list+watch contract client-go's reflector relies on).

Run standalone:  python -m kube_batch_tpu.edge.server --port 8090 \
                     [--cluster-state example/job.json]
"""

from __future__ import annotations

import json
import queue
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from ..cache.cluster import Cluster
from . import codec

_RESOURCES = ("pods", "nodes", "podgroups", "queues", "priorityclasses",
              "pdbs", "pvcs", "events", "leases")


def _store_of(cluster: Cluster, resource: str):
    return {"pods": cluster.pods, "nodes": cluster.nodes,
            "podgroups": cluster.pod_groups, "queues": cluster.queues,
            "priorityclasses": cluster.priority_classes,
            "pdbs": cluster.pdbs, "pvcs": cluster.pvcs,
            "events": cluster.events}[resource]


def _informer_of(cluster: Cluster, resource: str):
    return {"pods": cluster.pod_informer, "nodes": cluster.node_informer,
            "podgroups": cluster.pod_group_informer,
            "queues": cluster.queue_informer,
            "priorityclasses": cluster.priority_class_informer,
            "pdbs": cluster.pdb_informer}.get(resource)


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    cluster: Cluster = None  # set by ApiServer subclassing

    def log_message(self, *args):  # quiet; the scheduler has its own logs
        pass

    # -- helpers -----------------------------------------------------------

    def _json(self, status: int, payload) -> None:
        body = json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _body(self):
        length = int(self.headers.get("Content-Length", 0))
        return json.loads(self.rfile.read(length)) if length else None

    def _route(self):
        parsed = urlparse(self.path)
        parts = [p for p in parsed.path.split("/") if p]
        query = parse_qs(parsed.query)
        if len(parts) < 2 or parts[0] != "v1" or parts[1] not in _RESOURCES:
            return None, None, None
        return parts[1], parts[2:], query

    # -- verbs -------------------------------------------------------------

    def do_GET(self):
        resource, rest, query = self._route()
        if resource is None:
            return self._json(404, {"error": "not found"})
        if resource == "leases":
            if len(rest) != 2:
                return self._json(404, {"error": "lease key required"})
            version, record = self.cluster.get_lease(rest[0], rest[1])
            return self._json(200, {"version": version, "record": record})
        if query.get("watch"):
            return self._watch(resource)
        with self.cluster.lock:
            items = [codec.encode(o)
                     for o in _store_of(self.cluster, resource).values()]
        self._json(200, {"items": items})

    def do_POST(self):
        resource, rest, _ = self._route()
        if resource is None:
            return self._json(404, {"error": "not found"})
        if resource in ("pods", "pvcs") and len(rest) == 3 and rest[2] == "bind":
            want = "node" if resource == "pods" else "volume"
            try:  # malformed body -> 400, distinct from store conflicts
                target = self._body()[want]
            except (KeyError, ValueError, TypeError) as exc:
                return self._json(400, {"error": f"bad bind body: {exc}"})
            try:
                if resource == "pods":
                    self.cluster.bind_pod(rest[0], rest[1], target)
                else:
                    self.cluster.bind_pvc(rest[0], rest[1], target)
            except (KeyError, ValueError) as exc:
                return self._json(409, {"error": str(exc)})
            return self._json(200, {"status": "bound"})
        if rest:  # create routes take no path suffix
            return self._json(404, {"error": "not found"})
        if resource == "leases":  # leases are PUT-CAS only
            return self._json(405, {"error": "create not supported"})
        try:
            obj = codec.decode(self._body())
        except (ValueError, KeyError) as exc:  # malformed JSON / unknown kind
            return self._json(400, {"error": str(exc)})
        create = {"pods": self.cluster.create_pod,
                  "nodes": self.cluster.create_node,
                  "podgroups": self.cluster.create_pod_group,
                  "queues": self.cluster.create_queue,
                  "priorityclasses": self.cluster.create_priority_class,
                  "pdbs": self.cluster.create_pdb,
                  "pvcs": self.cluster.create_pvc,
                  "events": self.cluster.create_event}[resource]
        try:
            create(obj)
        except (KeyError, ValueError) as exc:  # store conflict
            return self._json(409, {"error": str(exc)})
        return self._json(201, {"status": "created"})

    def do_PUT(self):
        resource, rest, _ = self._route()
        if resource is None:
            return self._json(404, {"error": "not found"})
        try:
            if resource == "leases":
                if len(rest) != 2:
                    return self._json(404, {"error": "lease key required"})
                try:
                    body = self._body()
                    record = body["record"]
                    expected = int(body["expectedVersion"])
                except (KeyError, TypeError, ValueError) as exc:
                    return self._json(400, {"error": f"bad lease body: {exc}"})
                try:
                    version = self.cluster.cas_lease(rest[0], rest[1],
                                                     record, expected)
                except ValueError as exc:  # version conflict
                    return self._json(409, {"error": str(exc)})
                return self._json(200, {"version": version})
            obj = codec.decode(self._body())
            if resource == "podgroups" and rest and rest[-1] == "status":
                self.cluster.put_pod_group_status(obj)
                return self._json(200, {"status": "updated"})
            if (resource == "pods" and len(rest) == 3
                    and rest[2] == "status"):
                # Pod status subresource: a PodCondition upsert
                # (cache.go:548-568 taskUnschedulable writeback).
                self.cluster.update_pod_condition(rest[0], rest[1], obj)
                return self._json(200, {"status": "updated"})
            update = {"pods": self.cluster.update_pod,
                      "nodes": self.cluster.update_node,
                      "podgroups": self.cluster.update_pod_group}.get(resource)
            if update is None:
                return self._json(405, {"error": "update not supported"})
            update(obj)
            return self._json(200, {"status": "updated"})
        except KeyError as exc:
            return self._json(404, {"error": str(exc)})
        except ValueError as exc:  # malformed JSON / unknown kind
            return self._json(400, {"error": str(exc)})

    def do_DELETE(self):
        resource, rest, _ = self._route()
        if resource is None or not rest:
            return self._json(404, {"error": "not found"})
        try:
            if resource == "pods":
                self.cluster.delete_pod(rest[0], rest[1])
            elif resource == "nodes":
                self.cluster.delete_node(rest[0])
            elif resource == "podgroups":
                self.cluster.delete_pod_group(rest[0], rest[1])
            elif resource == "queues":
                self.cluster.delete_queue(rest[0])
            elif resource == "pdbs":
                self.cluster.delete_pdb(rest[0], rest[1])
            else:
                return self._json(405, {"error": "delete not supported"})
            return self._json(200, {"status": "deleted"})
        except KeyError as exc:
            return self._json(404, {"error": str(exc)})

    # -- watch -------------------------------------------------------------

    def _watch(self, resource: str) -> None:
        informer = _informer_of(self.cluster, resource)
        if informer is None:
            return self._json(405, {"error": f"{resource} not watchable"})
        events: "queue.Queue" = queue.Queue()
        handle = None
        # Register BEFORE snapshotting, under the store lock, so no event
        # can fall between the initial list and the live stream.
        with self.cluster.lock:
            handle = informer.add_handlers(
                on_add=lambda o: events.put(("ADDED", o)),
                on_update=lambda old, new: events.put(("MODIFIED", new)),
                on_delete=lambda o: events.put(("DELETED", o)))
            initial = list(_store_of(self.cluster, resource).values())

        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()

        def emit(etype, obj):
            line = json.dumps(
                {"type": etype,
                 "object": codec.encode(obj) if obj is not None else None}
            ).encode() + b"\n"
            self.wfile.write(f"{len(line):x}\r\n".encode() + line + b"\r\n")
            self.wfile.flush()

        try:
            for obj in initial:
                emit("ADDED", obj)
            emit("SYNC", None)
            while True:
                try:
                    etype, obj = events.get(timeout=5.0)
                except queue.Empty:
                    emit("PING", None)  # keep-alive; detects dead peers
                    continue
                emit(etype, obj)
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass
        finally:
            informer.remove_handlers(handle)


class ApiServer:
    """Serve a Cluster store over HTTP (threaded; one thread per watch)."""

    def __init__(self, cluster: Cluster, host: str = "127.0.0.1",
                 port: int = 0):
        self.cluster = cluster
        handler = type("BoundHandler", (_Handler,), {"cluster": cluster})
        self._httpd = ThreadingHTTPServer((host, port), handler)
        self._httpd.daemon_threads = True
        self._thread = None

    @property
    def url(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "ApiServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()


def main(argv=None):
    import argparse

    parser = argparse.ArgumentParser(description="kube-batch-tpu API server")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8090)
    parser.add_argument("--cluster-state", default="",
                        help="JSON state file to preload (cli/server.py)")
    ns = parser.parse_args(argv)
    cluster = Cluster()
    if ns.cluster_state:
        from ..cli.server import load_cluster_state
        load_cluster_state(cluster, ns.cluster_state)
    server = ApiServer(cluster, ns.host, ns.port)
    print(f"kube-batch-tpu apiserver listening on {server.url}")
    server._httpd.serve_forever()


if __name__ == "__main__":
    main()
