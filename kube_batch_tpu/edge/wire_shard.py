"""Shard-scoped reflector ingest (doc/INGEST.md).

A federated replica owns a subset of queue-shards (tenancy/leases.py)
but its reflectors historically mirrored the WHOLE cluster and filtered
at snapshot time — N replicas paid N x O(cluster) watch bandwidth and
mirror memory.  ``ShardScope`` turns the tenancy queue->shard map plus a
live owned-shards callable into the server-side watch selectors each
reflector connects with (edge/selectors.py grammar, served by
edge/server.py), so ingest scales with OWNED shards:

* pods ride TWO streams: *unassigned* (``spec.nodeName=`` + a
  ``queue notin (<foreign queues>)`` label selector — the replica's own
  schedulable work) and *assigned* (``spec.nodeName!=`` — every bound
  pod, kept for node-occupancy accounting; exactly the cache
  ``pod_filter`` contract, so the scheduler cache state is bit-identical
  to the unfiltered control).  ``notin`` also matches objects WITHOUT
  the key (selectors.py), so unlabeled pods are always received — a safe
  over-approximation the client-side scope check then attributes via the
  podgroup annotation.
* podgroups filter server-side on ``spec.queue!=<foreign>`` pairs.
* nodes/queues/priorityclasses/pdbs stay shared, unfiltered streams
  (the queue stream is also the selector's queue-name universe).

Lease acquisition/steal/shed bumps the scope ``epoch``; a reflector
notices the stale epoch on its next frame (keep-alive PINGs bound the
latency) and reconnects WITHOUT a resume version — a full scoped relist,
because the server's event history cannot replay a gained shard's
pre-existing objects.  The relist's SYNC reconciliation purges the shed
shard's mirror entries and releases their retained baselines.

``KUBE_BATCH_TPU_WIRE_SHARD=0`` is the bit-parity control: the scope is
simply never attached and every reflector runs the legacy unfiltered
single stream.  ``KUBE_BATCH_TPU_LAZY_MIRROR=0`` likewise pins the lazy
MODIFIED-frame deferral (edge/client.flush_pending) eager.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Iterable, List, Optional

from .. import knobs
from ..apis.scheduling.v1alpha1 import GroupNameAnnotationKey
from . import selectors as _selectors

WIRE_SHARD_ENV = knobs.WIRE_SHARD.env
LAZY_MIRROR_ENV = knobs.LAZY_MIRROR.env

# Pods carry their queue as a label so the SERVER can shard-filter the
# watch (annotations are not selectable — the k8s contract).  Pods
# without the label still reach every replica (``notin`` matches the
# missing key) and are attributed client-side via the podgroup
# annotation; labeling is a bandwidth optimization, never a correctness
# requirement.
QUEUE_LABEL = "queue.kube-batch.tpu/name"


def wire_shard_enabled() -> bool:
    return knobs.WIRE_SHARD.enabled()


def lazy_mirror_enabled() -> bool:
    return knobs.LAZY_MIRROR.enabled()


def queue_of_pod_doc(doc, pod_groups, wire: str) -> Optional[str]:
    """Resolve a raw pod wire doc to its queue name: the queue label
    first, then the podgroup annotation through the podgroup mirror
    (a group and its pods share one queue, so the shard-filtered
    podgroup mirror still covers every attributable pod).  None when
    unresolvable — callers must treat that as in-scope
    (over-approximation: never drop what we cannot attribute)."""
    md = doc.get("metadata") or {}
    labels = md.get("labels") or {}
    q = labels.get(QUEUE_LABEL)
    if q:
        return q
    ann = md.get("annotations") or {}
    group = ann.get(GroupNameAnnotationKey)
    if not group:
        return None
    ns = md.get("namespace", "default")
    pg = pod_groups.get(f"{ns}/{group}")
    if pg is None:
        return None
    return getattr(pg.spec, "queue", None) or None


def node_of_pod_doc(doc, wire: str) -> str:
    spec = doc.get("spec") or {}
    return (spec.get("nodeName" if wire == "k8s" else "node_name")
            or "")


def queue_of_podgroup_doc(doc, wire: str) -> Optional[str]:
    spec = doc.get("spec") or {}
    return spec.get("queue") or None


class ShardScope:
    """The live shard ownership window a RemoteCluster's reflectors
    filter by.  ``owned`` is re-read on every check (it tracks the lease
    manager); ``epoch`` increments on every ownership change so running
    watch connections notice their selector went stale."""

    def __init__(self, shard_map,
                 owned: Optional[Callable[[], Iterable[int]]] = None):
        self.map = shard_map
        self._owned = owned
        self._lock = threading.Lock()
        self._epoch = 1

    @property
    def epoch(self) -> int:
        return self._epoch

    def bump(self) -> int:
        """Ownership changed (claim/steal/shed/loss): invalidate every
        selector derived from the previous owned set."""
        with self._lock:
            self._epoch += 1
            return self._epoch

    def owned(self) -> frozenset:
        if self._owned is None:
            return frozenset(range(self.map.num_shards))
        return frozenset(self._owned())

    def allows(self, queue: str) -> bool:
        """Is this queue's shard currently owned?  Pure client-side hash
        (ShardMap.shard_of works for queue names never seen before), so
        the scope check never waits on the queue mirror."""
        return self.map.shard_of(queue) in self.owned()

    def foreign_queues(self, universe: Iterable[str]) -> List[str]:
        """The known queue names whose shard we do NOT own — the
        ``notin`` exclusion list.  Sorted so the derived selector string
        is deterministic for a given (universe, owned) pair."""
        owned = self.owned()
        return sorted(q for q in universe
                      if self.map.shard_of(q) not in owned)

    def pod_label_selector(self, universe: Iterable[str]) -> Optional[str]:
        """``<QUEUE_LABEL> notin (f1,f2,...)`` over the foreign queues,
        or None when every known queue is owned (nothing to exclude).
        Raises ValueError when a foreign queue name cannot be expressed
        in the selector value charset — the caller degrades that stream
        to an unfiltered watch (satellite: never kill the reflector)."""
        foreign = self.foreign_queues(universe)
        if not foreign:
            return None
        sel = f"{QUEUE_LABEL} notin ({','.join(foreign)})"
        # Compile through the real grammar: a queue name with a comma,
        # space, or other out-of-charset byte must fail HERE, not as a
        # server-side 400 loop.
        _selectors.parse_label_selector(sel)
        return sel

    def podgroup_field_selector(self,
                                universe: Iterable[str]) -> Optional[str]:
        """``spec.queue!=f1,spec.queue!=f2,...`` over the foreign
        queues (field selectors AND together, so a chain of != excludes
        the set), or None when nothing is foreign.  ValueError on an
        inexpressible queue name, same contract as the label form."""
        foreign = self.foreign_queues(universe)
        if not foreign:
            return None
        for q in foreign:
            if "," in q or not _selectors._VAL_RE.match(q):
                raise ValueError(
                    f"queue name {q!r} not expressible in a field "
                    f"selector value")
        return ",".join(f"spec.queue!={q}" for q in foreign)


def attach_shard_scope(remote, shard_map, lease_mgr=None,
                       owned: Optional[Callable[[], Iterable[int]]] = None):
    """Wire a RemoteCluster's reflectors to the tenancy shard map.

    Call AFTER ``TenancyEngine.attach_leases`` (ordering matters: a
    shard-filtered mirror undercounts foreign shards' load, so this
    helper pins ``lease_mgr.shard_load = None`` — the count-based spread
    rule — and attach_leases would re-install the full-mirror load
    probe if it ran later).  Returns the attached ShardScope, or None
    when ``KUBE_BATCH_TPU_WIRE_SHARD=0`` pinned the legacy unfiltered
    ingest."""
    if not wire_shard_enabled():
        return None
    if owned is None and lease_mgr is not None:
        owned = lease_mgr.owned_shards
    scope = ShardScope(shard_map, owned)
    if lease_mgr is not None:
        prev = getattr(lease_mgr, "on_change", None)

        def _ownership_changed(shard: int, kind: str, _prev=prev) -> None:
            if _prev is not None:
                _prev(shard, kind)
            scope.bump()

        lease_mgr.on_change = _ownership_changed
        # Load-weighted claim targets read per-shard load from the FULL
        # mirror; a filtered replica sees ~zero foreign load and would
        # shed-oscillate.  None selects the count-based spread rule.
        lease_mgr.shard_load = None
    remote.attach_scope(scope)
    return scope
