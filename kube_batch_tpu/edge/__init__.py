"""The network edge: REST API server + watch-based remote cluster client.

The reference's entire L2 ingest/egress is client-go against a live
Kubernetes API server (cache.go:255-352 informers in, Bind/Evict/status
REST out).  This package is the standalone framework's equivalent network
boundary: ``edge.server.ApiServer`` exposes a Cluster store over HTTP with
list+watch streaming, and ``edge.client.RemoteCluster`` is the client-go
analog — a reflector that mirrors the remote store into local informers
and turns effector verbs into REST calls — so the scheduler process can
run on a different machine than the cluster state.
"""

from .client import RemoteCluster
from .server import ApiServer
from .wire_shard import QUEUE_LABEL, ShardScope, attach_shard_scope

__all__ = ["ApiServer", "RemoteCluster", "QUEUE_LABEL", "ShardScope",
           "attach_shard_scope"]
