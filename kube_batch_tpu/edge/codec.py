"""Wire codec: the framework's API objects <-> JSON.

The reference rides Kubernetes' generated JSON marshalling; this standalone
framework encodes its dataclass object model reflectively.  Every wire
document carries a ``__kind__`` tag (module-qualified for the CRD versions,
whose class names collide across v1alpha1/v1alpha2); decoding rebuilds the
dataclass tree from type hints.  Tuples flatten to JSON lists — all
consumers unpack positionally, so round-tripping preserves semantics.
"""

from __future__ import annotations

import dataclasses
import functools
import typing
from typing import Any, Dict

from ..api import objects as _objects
from ..apis.scheduling import v1alpha1, v1alpha2


def _kind_of(cls) -> str:
    module = cls.__module__.rsplit(".", 1)[-1]
    if module in ("v1alpha1", "v1alpha2"):
        return f"{module}.{cls.__name__}"
    return cls.__name__


_TOP_LEVEL = [
    _objects.Pod, _objects.Node, _objects.PriorityClass,
    _objects.PodDisruptionBudget, _objects.PersistentVolumeClaim,
    _objects.Event, _objects.PodCondition,
    v1alpha1.PodGroup, v1alpha1.Queue,
    v1alpha2.PodGroup, v1alpha2.Queue,
]
_BY_KIND = {_kind_of(cls): cls for cls in _TOP_LEVEL}


@functools.lru_cache(maxsize=None)
def _field_names(cls) -> tuple:
    return tuple(f.name for f in dataclasses.fields(cls))


def _encode_value(v):
    """dataclasses.asdict semantics minus the per-leaf deepcopy: the
    result feeds json.dumps immediately, so sharing leaf references is
    safe and ~10x cheaper (the codec was the watch/LIST bottleneck)."""
    if dataclasses.is_dataclass(v) and not isinstance(v, type):
        return {name: _encode_value(getattr(v, name))
                for name in _field_names(type(v))}
    if isinstance(v, (list, tuple)):
        return [_encode_value(x) for x in v]
    if isinstance(v, dict):
        return {k: _encode_value(x) for k, x in v.items()}
    return v


def encode(obj) -> Dict[str, Any]:
    doc = _encode_value(obj)
    doc["__kind__"] = _kind_of(type(obj))
    return doc


def _decoder_for(typ):
    """Callable(value) -> decoded, or None (identity) — computed ONCE
    per field type by _decode_plan; the old path re-resolved
    typing.get_type_hints and get_origin per OBJECT, which dominated
    watch-echo and LIST ingest."""
    origin = typing.get_origin(typ)
    if origin is typing.Union:  # Optional[T]
        args = [a for a in typing.get_args(typ) if a is not type(None)]
        if not args:
            return None
        inner = _decoder_for(args[0])
        if inner is None:
            return None
        return lambda v, _i=inner: None if v is None else _i(v)
    if origin in (list, tuple) or typ is list:
        args = typing.get_args(typ)
        inner = _decoder_for(args[0]) if args else None
        if inner is None:
            # Copy unconditionally and pass None through: returning the
            # wire doc's own list would alias the decoded object to it,
            # and list(None) would raise where a null element inside a
            # nested List[List[T]] used to decode to None.
            return lambda v: None if v is None else list(v)
        return (lambda v, _i=inner:
                None if v is None else [_i(x) for x in v])
    if origin is dict or typ is dict:
        return lambda v: None if v is None else dict(v)
    if dataclasses.is_dataclass(typ):
        return (lambda v, _c=typ: _decode_dataclass(_c, v)
                if isinstance(v, dict) else v)
    return None


@functools.lru_cache(maxsize=None)
def _decode_plan(cls) -> tuple:
    """((field_name, decoder-or-None), ...) resolved once per class."""
    hints = typing.get_type_hints(cls)
    return tuple((f.name, _decoder_for(hints.get(f.name, Any)))
                 for f in dataclasses.fields(cls))


def _decode_dataclass(cls, data: Dict[str, Any]):
    kwargs = {}
    for name, dec in _decode_plan(cls):
        if name in data:
            v = data[name]
            kwargs[name] = v if dec is None or v is None else dec(v)
    return cls(**kwargs)


def decode(doc: Dict[str, Any]):
    kind = doc.get("__kind__")
    cls = _BY_KIND.get(kind)
    if cls is None:
        raise ValueError(f"unknown wire kind {kind!r}")
    data = {k: v for k, v in doc.items() if k != "__kind__"}
    return _decode_dataclass(cls, data)
