"""Wire codec: the framework's API objects <-> JSON.

The reference rides Kubernetes' generated JSON marshalling; this standalone
framework encodes its dataclass object model reflectively.  Every wire
document carries a ``__kind__`` tag (module-qualified for the CRD versions,
whose class names collide across v1alpha1/v1alpha2); decoding rebuilds the
dataclass tree from type hints.  Tuples flatten to JSON lists — all
consumers unpack positionally, so round-tripping preserves semantics.
"""

from __future__ import annotations

import dataclasses
import typing
from typing import Any, Dict

from ..api import objects as _objects
from ..apis.scheduling import v1alpha1, v1alpha2


def _kind_of(cls) -> str:
    module = cls.__module__.rsplit(".", 1)[-1]
    if module in ("v1alpha1", "v1alpha2"):
        return f"{module}.{cls.__name__}"
    return cls.__name__


_TOP_LEVEL = [
    _objects.Pod, _objects.Node, _objects.PriorityClass,
    _objects.PodDisruptionBudget, _objects.PersistentVolumeClaim,
    _objects.Event, _objects.PodCondition,
    v1alpha1.PodGroup, v1alpha1.Queue,
    v1alpha2.PodGroup, v1alpha2.Queue,
]
_BY_KIND = {_kind_of(cls): cls for cls in _TOP_LEVEL}


def encode(obj) -> Dict[str, Any]:
    doc = dataclasses.asdict(obj)
    doc["__kind__"] = _kind_of(type(obj))
    return doc


def _decode_value(typ, value):
    if value is None:
        return None
    origin = typing.get_origin(typ)
    if origin is typing.Union:  # Optional[T]
        args = [a for a in typing.get_args(typ) if a is not type(None)]
        return _decode_value(args[0], value) if args else value
    if origin in (list, tuple) or typ is list:
        args = typing.get_args(typ)
        inner = args[0] if args else Any
        return [_decode_value(inner, v) for v in value]
    if origin is dict or typ is dict:
        return dict(value)
    if dataclasses.is_dataclass(typ) and isinstance(value, dict):
        return _decode_dataclass(typ, value)
    return value


def _decode_dataclass(cls, data: Dict[str, Any]):
    hints = typing.get_type_hints(cls)
    kwargs = {}
    for f in dataclasses.fields(cls):
        if f.name in data:
            kwargs[f.name] = _decode_value(hints.get(f.name, Any),
                                           data[f.name])
    return cls(**kwargs)


def decode(doc: Dict[str, Any]):
    kind = doc.get("__kind__")
    cls = _BY_KIND.get(kind)
    if cls is None:
        raise ValueError(f"unknown wire kind {kind!r}")
    data = {k: v for k, v in doc.items() if k != "__kind__"}
    return _decode_dataclass(cls, data)
