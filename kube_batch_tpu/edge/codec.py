"""Wire codec: the framework's API objects <-> JSON.

The reference rides Kubernetes' generated JSON marshalling; this standalone
framework encodes its dataclass object model reflectively.  Every wire
document carries a ``__kind__`` tag (module-qualified for the CRD versions,
whose class names collide across v1alpha1/v1alpha2); decoding rebuilds the
dataclass tree from type hints.  Tuples flatten to JSON lists — all
consumers unpack positionally, so round-tripping preserves semantics.
"""

from __future__ import annotations

import dataclasses
import functools
import typing
from typing import Any, Dict

from ..api import objects as _objects
from ..apis.scheduling import v1alpha1, v1alpha2
# The wire-fast gate lives with the other incremental-control knobs
# (models/incremental.py): =0 restores the sequential control — every
# watch frame fully materializes a fresh dataclass tree, no field reuse,
# no raw-doc caching.  The CI wire A/B (`make bench-wire`) pins
# binds+events bit-identical across the flag at every churn level.
from ..models.incremental import (WIRE_FAST_ENV,  # noqa: F401
                                  wire_fast_enabled)


def _kind_of(cls) -> str:
    module = cls.__module__.rsplit(".", 1)[-1]
    if module in ("v1alpha1", "v1alpha2"):
        return f"{module}.{cls.__name__}"
    return cls.__name__


_TOP_LEVEL = [
    _objects.Pod, _objects.Node, _objects.PriorityClass,
    _objects.PodDisruptionBudget, _objects.PersistentVolumeClaim,
    _objects.Event, _objects.PodCondition,
    v1alpha1.PodGroup, v1alpha1.Queue,
    v1alpha2.PodGroup, v1alpha2.Queue,
]
_BY_KIND = {_kind_of(cls): cls for cls in _TOP_LEVEL}


@functools.lru_cache(maxsize=None)
def _field_names(cls) -> tuple:
    return tuple(f.name for f in dataclasses.fields(cls))


def _encode_value(v):
    """dataclasses.asdict semantics minus the per-leaf deepcopy: the
    result feeds json.dumps immediately, so sharing leaf references is
    safe and ~10x cheaper (the codec was the watch/LIST bottleneck)."""
    if dataclasses.is_dataclass(v) and not isinstance(v, type):
        return {name: _encode_value(getattr(v, name))
                for name in _field_names(type(v))}
    if isinstance(v, (list, tuple)):
        return [_encode_value(x) for x in v]
    if isinstance(v, dict):
        return {k: _encode_value(x) for k, x in v.items()}
    return v


def encode(obj) -> Dict[str, Any]:
    doc = _encode_value(obj)
    doc["__kind__"] = _kind_of(type(obj))
    return doc


def _decoder_for(typ):
    """Callable(value) -> decoded, or None (identity) — computed ONCE
    per field type by _decode_plan; the old path re-resolved
    typing.get_type_hints and get_origin per OBJECT, which dominated
    watch-echo and LIST ingest."""
    origin = typing.get_origin(typ)
    if origin is typing.Union:  # Optional[T]
        args = [a for a in typing.get_args(typ) if a is not type(None)]
        if not args:
            return None
        inner = _decoder_for(args[0])
        if inner is None:
            return None
        return lambda v, _i=inner: None if v is None else _i(v)
    if origin in (list, tuple) or typ is list:
        args = typing.get_args(typ)
        inner = _decoder_for(args[0]) if args else None
        if inner is None:
            # Copy unconditionally and pass None through: returning the
            # wire doc's own list would alias the decoded object to it,
            # and list(None) would raise where a null element inside a
            # nested List[List[T]] used to decode to None.
            return lambda v: None if v is None else list(v)
        return (lambda v, _i=inner:
                None if v is None else [_i(x) for x in v])
    if origin is dict or typ is dict:
        return lambda v: None if v is None else dict(v)
    if dataclasses.is_dataclass(typ):
        return (lambda v, _c=typ: _decode_dataclass(_c, v)
                if isinstance(v, dict) else v)
    return None


@functools.lru_cache(maxsize=None)
def _decode_plan(cls) -> tuple:
    """((field_name, decoder-or-None), ...) resolved once per class."""
    hints = typing.get_type_hints(cls)
    return tuple((f.name, _decoder_for(hints.get(f.name, Any)))
                 for f in dataclasses.fields(cls))


def _decode_dataclass(cls, data: Dict[str, Any]):
    kwargs = {}
    for name, dec in _decode_plan(cls):
        if name in data:
            v = data[name]
            kwargs[name] = v if dec is None or v is None else dec(v)
    return cls(**kwargs)


def decode(doc: Dict[str, Any]):
    kind = doc.get("__kind__")
    cls = _BY_KIND.get(kind)
    if cls is None:
        raise ValueError(f"unknown wire kind {kind!r}")
    data = {k: v for k, v in doc.items() if k != "__kind__"}
    return _decode_dataclass(cls, data)


# ---------------------------------------------------------------------------
# Columnar delta decode (the wire-to-tensor fast path, doc/INCREMENTAL.md):
# a watch frame for an ALREADY-KNOWN object re-decodes only its changed
# fields.  The previous decode cached its raw wire doc on the object
# (``_wire_doc``); the delta plan walks the columnar ``_decode_plan`` and
# compares RAW JSON values field by field — a C-level dict/list compare,
# ~10x cheaper than re-decoding — reusing the previously-decoded subtree
# for every unchanged field.  Reuse preserves sub-object IDENTITY, which
# is what keeps the tensorizer's per-pod signature cache
# (models/tensor_snapshot._pod_static, keyed on ``pod.spec`` identity)
# warm across the watch echo of a bind: the echo changes status/metadata,
# the spec bytes are identical, so the spec object itself is reused and
# no signature re-derivation runs.  A reused subtree is a pure function
# of its raw bytes (decode has no hidden inputs), so the delta result
# equals the full decode bit for bit (tests/test_wire_fast.py fuzzes
# this); sharing is safe under the object model's immutability contract
# (api/objects.PodSpec docstring — update paths replace, never mutate).
# ---------------------------------------------------------------------------

_WIRE_DOC_ATTR = "_wire_doc"


@functools.lru_cache(maxsize=None)
def _delta_plan(cls) -> tuple:
    """((field_name, decoder-or-None, dataclass-cls-or-None), ...): the
    columnar decode plan with the recursion target exposed, resolved
    once per class like ``_decode_plan``."""
    hints = typing.get_type_hints(cls)
    out = []
    for f in dataclasses.fields(cls):
        typ = hints.get(f.name, Any)
        sub = None
        if dataclasses.is_dataclass(typ):
            sub = typ
        elif typing.get_origin(typ) is typing.Union:  # Optional[T]
            args = [a for a in typing.get_args(typ) if a is not type(None)]
            if len(args) == 1 and dataclasses.is_dataclass(args[0]):
                sub = args[0]
        out.append((f.name, _decoder_for(typ), sub))
    return tuple(out)


def _decode_dataclass_delta(cls, data: Dict[str, Any], prev,
                            prev_data: Dict[str, Any]):
    kwargs = {}
    for name, dec, sub in _delta_plan(cls):
        if name not in data:
            # Absent on the wire -> class default, exactly like the full
            # decode (whatever prev carried is irrelevant: the full path
            # would not see it either).
            continue
        v = data[name]
        if name in prev_data and v == prev_data[name]:
            # Raw bytes identical: the decoded subtree is a pure
            # function of them — reuse it (identity-preserving).
            kwargs[name] = getattr(prev, name)
            continue
        if (sub is not None and isinstance(v, dict)
                and isinstance(prev_data.get(name), dict)):
            pv = getattr(prev, name, None)
            if dataclasses.is_dataclass(pv) and not isinstance(pv, type):
                kwargs[name] = _decode_dataclass_delta(
                    sub, v, pv, prev_data[name])
                continue
        kwargs[name] = v if dec is None or v is None else dec(v)
    return cls(**kwargs)


def remember_wire_doc(obj, doc: Dict[str, Any]) -> None:
    """Stamp the raw wire doc the object was decoded from — the delta
    baseline for the NEXT frame of the same key.  Instance attribute:
    dataclass ``__eq__`` ignores it, encode never re-emits it.  Objects
    that refuse attributes simply never serve as a delta baseline."""
    try:
        obj._wire_doc = doc
    except AttributeError:  # lint: allow-swallow(slotted/foreign object: the next frame falls back to a full decode, which is always correct)
        pass


def wire_baseline(prev) -> Dict[str, Any]:
    """The retained raw-doc delta baseline for ``prev``: the hot
    ``_wire_doc`` dict, or transparently decompressed from the budget
    store's cold form (``_wire_zdoc``, edge/baseline.py).  Raises
    LookupError("evicted") when the baseline budget evicted it (the
    client counts the full-decode fallback under that reason) and
    LookupError("baseline") when nothing was ever retained."""
    data = getattr(prev, _WIRE_DOC_ATTR, None)
    if isinstance(data, dict):
        return data
    z = getattr(prev, "_wire_zdoc", None)
    if z is not None:
        import json
        import zlib
        return json.loads(zlib.decompress(z))
    raise LookupError(
        "evicted" if getattr(prev, "_wire_evicted", False)
        else "baseline")


def decode_delta(doc: Dict[str, Any], prev):
    """Decode a native-wire doc against the previously decoded ``prev``,
    re-decoding only changed fields.  Raises ValueError on anything the
    full decode would reject; any OTHER trouble (missing baseline, type
    flip) must be handled by the caller falling back to ``decode`` —
    edge/client counts those falls via
    ``kube_batch_wire_fast_fallback_total``."""
    kind = doc.get("__kind__")
    cls = _BY_KIND.get(kind)
    if cls is None:
        raise ValueError(f"unknown wire kind {kind!r}")
    if type(prev) is not cls:
        raise LookupError("no delta baseline")
    prev_data = wire_baseline(prev)
    if not isinstance(prev_data, dict):
        raise LookupError("no delta baseline")
    data = {k: v for k, v in doc.items() if k != "__kind__"}
    obj = _decode_dataclass_delta(cls, data, prev, prev_data)
    remember_wire_doc(obj, data)
    _carry_tensor_static(prev, obj)
    return obj


def _carry_tensor_static(prev, obj) -> None:
    """Carry the tensorizer's per-pod static-signature cache across a
    delta decode that reused the spec OBJECT (the cache is keyed on spec
    identity — models/tensor_snapshot._pod_static; validity is exactly
    ``cached[0] is spec``, so the carry holds the same contract the
    cache's own probe enforces).  This is the wire→tensor handoff: a
    status-only watch echo re-derives NOTHING for the signature path."""
    cached = getattr(prev, "_tensor_static", None)
    if cached is not None and cached[0] is getattr(obj, "spec", None):
        try:
            obj._tensor_static = cached
        except AttributeError:  # lint: allow-swallow(slotted object: the signature simply re-derives, which is the full-decode behavior)
            pass
