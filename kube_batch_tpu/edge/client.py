"""RemoteCluster: the client-go analog over the REST edge.

A reflector per watched resource streams list+watch events from
edge.server.ApiServer into local mirror stores and Informer fan-outs, so
``cache.cluster.new_scheduler_cache(RemoteCluster(url).start())`` wires a
SchedulerCache to a REMOTE cluster exactly as it wires to the in-process
simulator — same informers in (cache.go:255-352), and the effector verbs
(bind/evict/status, cache.go:425-535) become REST calls out.
"""

from __future__ import annotations

import http.client
import json
import logging
import socket
import threading
import time
import urllib.error
import urllib.request
from collections import OrderedDict
from typing import Dict, Optional
from urllib.parse import quote as _quote

from ..api import objects as _objects
from ..cache.cluster import Informer
from ..cache.interface import AmbiguousOutcomeError
from ..chaos import plan as chaos_plan
from ..metrics import memledger, metrics
from . import baseline as baseline_store
from . import codec, codec_k8s, wire_shard

_LOG = logging.getLogger(__name__)

# Watch reconnect backoff (doc/CHAOS.md "Graceful degradation"): a
# flapping or erroring stream backs off exponentially instead of
# hammering the server twice a second forever; a successful sync resets.
_WATCH_BACKOFF_BASE_S = 0.1
_WATCH_BACKOFF_CAP_S = 5.0


class _NodelayConnection(http.client.HTTPConnection):
    """Keep-alive connection with Nagle off: headers and body go out as
    separate small sends, and without NODELAY the second send can sit
    behind Nagle until the server's delayed ACK (~40 ms per request).
    Subclassed so the retry path's auto-reconnect keeps the option."""

    def connect(self):
        super().connect()
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)


class _NodelayHTTPSConnection(http.client.HTTPSConnection):
    def connect(self):
        super().connect()
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)

_WATCHED = ("pods", "nodes", "podgroups", "queues", "priorityclasses",
            "pdbs")

# Kubernetes-convention collection paths (wire="k8s"): the scheduler
# speaks the same path grammar client-go does against a real apiserver.
_K8S_PATHS = {
    "pods": "/api/v1/pods",
    "nodes": "/api/v1/nodes",
    "events": "/api/v1/events",
    "pvcs": "/api/v1/persistentvolumeclaims",
    "priorityclasses": "/apis/scheduling.k8s.io/v1/priorityclasses",
    "pdbs": "/apis/policy/v1beta1/poddisruptionbudgets",
    "podgroups": "/apis/scheduling.incubator.k8s.io/v1alpha1/podgroups",
    "queues": "/apis/scheduling.incubator.k8s.io/v1alpha1/queues",
}

_MISSING = object()


class _PvcStore(dict):
    """PVC mirror that refetches the remote list on a miss (PVCs have no
    watch stream; volume binding must still see late-created claims).
    Misses are negative-cached for a few seconds: the refetch can run
    while the caller holds RemoteCluster.lock, so a pod referencing a
    genuinely absent PVC must not stall reflector ingest every cycle."""

    _NEG_TTL = 5.0

    def __init__(self, remote: "RemoteCluster"):
        super().__init__()
        self._remote = remote
        self._neg: Dict[str, float] = {}

    def replace(self, items) -> None:
        self.clear()
        self.update(items)
        self._neg.clear()

    def get(self, key, default=None):
        import time as _time
        value = dict.get(self, key)
        if value is None:
            now = _time.monotonic()
            if self._neg.get(key, 0.0) > now:
                return default
            try:
                self._remote._refresh_pvcs()
            except (OSError, KeyError):  # _request maps HTTPError→KeyError
                return default
            value = dict.get(self, key, default)
            if value is default:
                self._neg[key] = now + self._NEG_TTL
        return value

    # Mapping syntax must see the same on-miss refetch + negative cache
    # as .get(), so future callers can't silently read a stale miss.
    def __getitem__(self, key):
        value = self.get(key, _MISSING)
        if value is _MISSING:
            raise KeyError(key)
        return value

    def __contains__(self, key):
        return self.get(key, _MISSING) is not _MISSING


def _key_fn(resource: str):
    if resource in ("pods", "podgroups", "pdbs", "pvcs"):
        return lambda o: f"{o.metadata.namespace}/{o.metadata.name}"
    if resource == "nodes":
        return lambda o: o.name
    return lambda o: o.metadata.name


def _raw_key(resource: str, doc) -> str:
    """The mirror-store key straight from the RAW wire doc (both wire
    formats carry ``metadata`` as a plain dict) — the fast path must find
    the previous object BEFORE any decode runs.  Must agree with
    ``_key_fn`` of the decoded object; KeyError/TypeError on a malformed
    doc routes the frame to the full decode path."""
    md = doc["metadata"]
    if resource in ("pods", "podgroups", "pdbs", "pvcs"):
        return f"{md.get('namespace', 'default')}/{md['name']}"
    # nodes key on o.name == metadata.name (api/objects.Node.name);
    # queues/priorityclasses key on metadata.name directly.
    return md["name"]


#: Flat per-object shell estimate for a mirrored dataclass (pod shell +
#: metadata strings, excluding the separately-ledgered `_wire_doc`
#: baseline).  The mirror ledger's hook AND its auditor both price
#: objects at this constant, so the audit checks hook coverage, not
#: estimate quality (doc/OBSERVABILITY.md "Memory ledger").
_MIRROR_OBJ_EST = 512


def _mirror_actual_nbytes(c: "RemoteCluster") -> int:
    """Audit sizer: recompute the mirror ledger from the live stores."""
    with c.lock:
        return sum(len(c._store(r)) for r in _WATCHED) * _MIRROR_OBJ_EST


def _pending_actual_nbytes(c: "RemoteCluster") -> int:
    """Audit sizer: raw bytes of every deferred lazy-mirror frame."""
    with c.lock:
        return sum(entry[3] for pend in c._pending.values()
                   for entry in pend.values())


def _baseline_actual_nbytes(c: "RemoteCluster") -> int:
    """Audit sizer: `_wire_nbytes` actually retained on mirror objects
    — the same truth `audit_baseline_bytes` reconciles per kind."""
    with c.lock:
        return sum(getattr(o, "_wire_nbytes", 0)
                   for r in _WATCHED for o in c._store(r).values())


class RemoteCluster:
    """Duck-types the Cluster surface the scheduler wiring consumes:
    ``*_informer`` fan-outs + mirror stores (ingest) and the effector
    verbs (egress), all over HTTP.

    Memory accounting (metrics/memledger.py):
    # mem-ledger: mirror
    # mem-ledger: pending
    # mem-ledger: baseline
    """

    def __init__(self, base_url: str, timeout: float = 10.0,
                 wire: str = "native"):
        """``wire="k8s"`` speaks Kubernetes API conventions end to end:
        /api + /apis paths, camelCase kind/apiVersion bodies
        (codec_k8s), the Binding subresource for binds, and merge-patch
        for the stuck-pod condition writeback — the full client-go
        surface (SURVEY.md §2.2) instead of the native /v1 codec."""
        if wire not in ("native", "k8s"):
            raise ValueError(f"unknown wire mode {wire!r}")
        self.wire = wire
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout
        self.lock = threading.RLock()
        # Mirror stores: written by six reflector threads, read by the
        # scheduler's resync path — guarded-by enforced by graftlint.
        self.pods: Dict[str, object] = {}              # guarded-by: lock
        self.nodes: Dict[str, object] = {}             # guarded-by: lock
        self.pod_groups: Dict[str, object] = {}        # guarded-by: lock
        self.queues: Dict[str, object] = {}            # guarded-by: lock
        self.priority_classes: Dict[str, object] = {}  # guarded-by: lock
        self.pdbs: Dict[str, object] = {}              # guarded-by: lock
        self.pvcs = _PvcStore(self)
        self.pod_informer = Informer()
        self.node_informer = Informer()
        self.pod_group_informer = Informer()
        self.queue_informer = Informer()
        self.priority_class_informer = Informer()
        self.pdb_informer = Informer()
        self._stop = threading.Event()
        self._threads = []
        self._synced: Dict[str, threading.Event] = {}
        # Retained raw-doc baseline memory, per resource kind (ROADMAP
        # item 1 accounting): the wire fast path keeps each mirror
        # object's raw wire doc (`_wire_doc`, edge/codec.py) as its
        # delta baseline — roughly one raw dict per pod.  Each frame's
        # byte length approximates its doc's retained footprint; the
        # running per-kind totals land on the
        # ``kube_batch_wire_baseline_bytes{kind}`` gauge so the 1M-pod
        # memory-budget work has a measurable target.  One int per
        # resource, written only by that resource's reflector thread.
        self._baseline_bytes: Dict[str, int] = {
            r: 0 for r in _WATCHED}
        # Shard-scoped ingest (edge/wire_shard.py, doc/INGEST.md): once
        # a ShardScope attaches, pods split into the unassigned (scoped)
        # + assigned (occupancy) streams and podgroups filter by queue.
        self._scope = None  # attach_scope(); read per frame, no lock
        self._selector_warned: set = set()  # guarded-by: lock
        # Cumulative watch bytes per reflector stream key ("pods",
        # "pods@assigned", ...) — each stream's thread is its key's only
        # writer; ingest_bytes() folds the streams per resource.
        self._ingest_bytes: Dict[str, int] = {}
        # Lazy mirror (doc/INGEST.md): deferred MODIFIED pod frames,
        # {resource: {key: [prev_obj, doc, frame_ts, nbytes]}} — the raw
        # doc waits here until flush_pending() materializes it at the
        # session/debug chokepoint.  guarded-by: lock
        self._pending: Dict[str, Dict[str, list]] = {"pods": {}}
        # Wake hook the cache wiring installs (cache._note_churn): a
        # deferred frame still dirties its queue's shard at receipt so
        # the scheduler loop wakes.  None = no flush consumer is wired,
        # so ingest stays fully eager (lazy-mirror validity rule).
        self.pending_churn = None
        # Baseline byte budgets (edge/baseline.py) + per-kind LRU of
        # retained baselines, cold end first.  guarded-by: lock
        self._baseline_budget = baseline_store.parse_budgets()
        self._budget: Dict[str, Optional[int]] = {
            r: baseline_store.budget_for(self._baseline_budget, r)
            for r in _WATCHED}
        self._baseline_lru: Dict[str, OrderedDict] = {
            r: OrderedDict() for r in _WATCHED}
        # Fleet memory ledger components (metrics/memledger.py), keyed
        # to this client's lifetime: mirror prices dataclass shells at
        # a flat estimate, pending carries the deferred frames' raw
        # bytes, baseline absorbs the per-kind ``_baseline_bytes``
        # totals behind kube_batch_wire_baseline_bytes.  The auditors
        # recompute each from the stores under ``lock``.
        self._mem_mirror = memledger.ledger("mirror").track(
            self, sizer=_mirror_actual_nbytes)
        self._mem_pending = memledger.ledger("pending").track(
            self, sizer=_pending_actual_nbytes)
        self._mem_baseline = memledger.ledger("baseline").track(
            self, sizer=_baseline_actual_nbytes)

    # -- ingest: reflectors -------------------------------------------------

    def _store(self, resource: str) -> Dict[str, object]:
        return {"pods": self.pods, "nodes": self.nodes,
                "podgroups": self.pod_groups, "queues": self.queues,
                "priorityclasses": self.priority_classes,
                "pdbs": self.pdbs, "pvcs": self.pvcs}[resource]

    def _informer(self, resource: str) -> Informer:
        return {"pods": self.pod_informer, "nodes": self.node_informer,
                "podgroups": self.pod_group_informer,
                "queues": self.queue_informer,
                "priorityclasses": self.priority_class_informer,
                "pdbs": self.pdb_informer}[resource]

    def _reflect(self, resource: str, stream: Optional[str] = None) -> None:
        """One reflector: stream watch events into the mirror + informer.
        A fresh connect replays the server's current state as ADDED
        events ending in SYNC (objects deleted during a disconnect are
        reconciled out of the mirror then — client-go's relist).  A
        RECONNECT resumes from the last seen resourceVersion: the server
        replays only the missed delta (RESUMED frame, no reconciliation),
        or answers ERROR 410 when the client fell past its event buffer,
        forcing a full relist — the k8s list+watch contract.

        ``stream`` is the shard-scoped pod split (doc/INGEST.md): None
        serves the whole collection (the legacy single stream; once a
        ShardScope attaches it carries the UNASSIGNED half, scoped by
        queue), "assigned" is the static bound-pod occupancy stream.
        A scoped connection records the scope epoch its selector came
        from; a lease claim/steal/shed bumps the epoch and the next
        frame (keep-alive PINGs bound the wait) forces a reconnect
        WITHOUT a resume version — the full scoped relist whose SYNC
        reconciliation purges the shed shard and admits the gained
        one."""
        store = self._store(resource)
        informer = self._informer(resource)
        key_of = _key_fn(resource)
        skey = f"{resource}@{stream}" if stream else resource
        base = f"{self.base_url}{self._collection(resource)}?watch=1"
        last_rv = 0
        backoff = _WATCH_BACKOFF_BASE_S
        while not self._stop.is_set():
            replay_seen = set()
            replaying = True
            suffix, scope_epoch, domain = self._watch_params(resource,
                                                             stream)
            url = base + suffix + (f"&resourceVersion={last_rv}"
                                   if last_rv else "")
            try:
                # Read timeout >> the server's 5s keep-alive ping: a
                # half-open connection surfaces as socket.timeout (OSError)
                # and reconnects instead of freezing the mirror forever.
                with urllib.request.urlopen(url, timeout=30) as resp:
                    for raw in resp:
                        if self._stop.is_set():
                            return
                        # Watch bandwidth ledger (make bench-ingest):
                        # every received byte counts, dropped frames
                        # included — this measures wire cost, not
                        # mirror admission.  Sole writer of skey.
                        self._ingest_bytes[skey] = (
                            self._ingest_bytes.get(skey, 0) + len(raw))
                        # Frame-receipt stamp: the lineage ingest clock
                        # starts HERE, not after materialization — the
                        # fast path skips most of the decode and must
                        # not silently shift the SLO baseline relative
                        # to the full path (tests/test_wire_fast.py).
                        frame_ts = time.monotonic()
                        # Chaos sites (doc/CHAOS.md): stream disconnect,
                        # stale-resume forcing a full relist, and a
                        # truncated frame (exercises the malformed-frame
                        # relist below).  Site names carry the resource
                        # qualifier so each reflector consumes its own
                        # deterministic decision stream.  One no-op
                        # branch when the chaos engine is off.
                        plan = chaos_plan.PLAN
                        if plan is not None:
                            if plan.fire(f"watch.disconnect:{resource}"):
                                raise OSError(
                                    "chaos: watch stream disconnected "
                                    "(injected)")
                            if plan.fire(f"watch.stale:{resource}"):
                                last_rv = 0
                                raise OSError(
                                    "chaos: stale watch resume, forcing "
                                    "full relist (injected)")
                            if plan.fire(f"watch.truncate:{resource}"):
                                raw = raw[:max(1, len(raw) // 2)]
                        # Scope-epoch staleness: shard ownership changed
                        # since this connection derived its selector.
                        # Reconnect WITHOUT a resume version (the server
                        # history cannot replay a gained shard's
                        # pre-existing objects) — unless the
                        # handover-race chaos site holds the stale
                        # window open one frame so the in-scope drop
                        # below is exercised deterministically.
                        if self._scope_stale(resource, stream,
                                             scope_epoch):
                            if not (plan is not None and plan.fire(
                                    f"ingest.handover_race:{resource}")):
                                last_rv = 0
                                metrics.note_watch_reconnect(
                                    resource, "rescope")
                                break
                        event = json.loads(raw)
                        etype = event["type"]
                        # NOTE: last_rv advances only AFTER a frame is
                        # fully applied — advancing first would make a
                        # frame that fails to decode/apply permanently
                        # invisible to the resume path (no relist ever
                        # heals it).
                        frame_rv = event.get("rv")
                        if etype == "SYNC":
                            # Reconciliation is scoped to THIS stream's
                            # domain: the scoped pod split partitions the
                            # key space by assignment, and one stream's
                            # relist must not purge the other's objects.
                            # A shed shard's entries fall in the scoped
                            # domain but out of the replay — purged here,
                            # releasing their retained baselines.
                            with self.lock:
                                for stale in [
                                        k for k in store
                                        if k not in replay_seen
                                        and self._in_domain(
                                            resource, domain, store[k])]:
                                    gone = store.pop(stale)
                                    gone_pend = self._pending.get(
                                        resource, {}).pop(stale, None)
                                    if gone_pend is not None:
                                        memledger.ledger("pending").add(
                                            self._mem_pending,
                                            -gone_pend[3])
                                    self._drop_baseline_key(resource,
                                                            stale)
                                    self._note_baseline(resource, gone,
                                                        None)
                                    informer.fire_delete(gone)
                            replaying = False
                            self._synced[skey].set()
                            backoff = _WATCH_BACKOFF_BASE_S  # healthy again
                            if frame_rv is not None:
                                last_rv = max(last_rv, int(frame_rv))
                            continue
                        if etype == "RESUMED":
                            # Continuous delta stream: mirror is already
                            # current, no reconciliation needed.
                            replaying = False
                            self._synced[skey].set()
                            backoff = _WATCH_BACKOFF_BASE_S  # healthy again
                            continue
                        if etype == "ERROR":
                            # 410 Gone: fall back to a full relist.
                            last_rv = 0
                            break
                        if etype == "PING":
                            continue
                        edoc = event["object"]
                        if domain is not None:
                            # Client-side scope check (always on under a
                            # scope, selector or no selector): a frame
                            # for a foreign queue — the server's
                            # over-approximating selector still sends
                            # unlabeled pods, and a raced lease loss
                            # sends a just-shed shard's — must be
                            # dropped-and-counted, never mirrored.
                            if etype in ("ADDED", "MODIFIED") \
                                    and not self._frame_in_scope(
                                        resource, domain, edoc):
                                try:
                                    mirrored = _raw_key(resource,
                                                        edoc) in store
                                except (KeyError, TypeError,
                                        AttributeError):
                                    mirrored = False
                                if not mirrored:
                                    metrics.note_ingest_drop(
                                        resource,
                                        "handover" if self._scope_stale(
                                            resource, stream, scope_epoch)
                                        else "scope")
                                    if frame_rv is not None:
                                        last_rv = max(last_rv,
                                                      int(frame_rv))
                                    continue
                                # A MIRRORED object exiting the scope is
                                # a boundary transition, not a drop: the
                                # server's own selector rewrites it to
                                # DELETED, and the over-approximating
                                # client-side check must rewrite
                                # identically (e.g. a stream that
                                # connected before the queue universe
                                # synced carries no label selector).
                                etype = "DELETED"
                            # A DELETED on one pod stream whose carried
                            # object now belongs to the OTHER stream is
                            # a boundary transition (bind), not a
                            # removal: the peer stream delivers the
                            # matching ADDED, and the upsert below turns
                            # it into the same fire_update the
                            # unfiltered control emits for the MODIFIED.
                            if etype == "DELETED" and resource == "pods":
                                target = self._pod_domain_of(edoc)
                                if target is not None and target != domain:
                                    if frame_rv is not None:
                                        last_rv = max(last_rv,
                                                      int(frame_rv))
                                    continue
                        # Lazy mirror: absorb a MODIFIED pod frame into
                        # the deferred store instead of materializing —
                        # flush_pending() finishes the job at the
                        # session/debug chokepoint.
                        if etype == "MODIFIED" and self._maybe_defer(
                                resource, edoc, raw, frame_ts):
                            if frame_rv is not None:
                                last_rv = max(last_rv, int(frame_rv))
                            continue
                        # Previous mirror object for this key = the
                        # delta baseline.  Read without the lock: writes
                        # to a key come only from its own stream's
                        # thread (the scoped pod split partitions keys
                        # by assignment), and dict.get is atomic under
                        # the GIL.  A doc too malformed to key routes to
                        # the full decode, whose error handling is
                        # unchanged.
                        try:
                            prev = store.get(_raw_key(resource, edoc))
                        except (KeyError, TypeError, AttributeError):
                            # AttributeError included: a falsy/non-dict
                            # metadata (None/[]/"") the FULL k8s decode
                            # tolerates must route to it, not kill the
                            # reflector thread.
                            prev = None
                        t_dec = time.perf_counter()
                        obj = self._decode(edoc, prev=prev,
                                           ingest_ts=frame_ts)
                        metrics.note_decode_seconds(
                            time.perf_counter() - t_dec)
                        # Baseline footprint stamp: the retained
                        # `_wire_doc` came from (roughly) this frame's
                        # bytes; nothing is retained with the fast path
                        # off.  Instance attribute like _ingest_ts —
                        # dataclass __eq__ ignores it.
                        if codec.wire_fast_enabled():
                            obj._wire_nbytes = len(raw)
                        key = key_of(obj)
                        with self.lock:
                            if etype in ("ADDED", "MODIFIED"):
                                if etype == "ADDED" and replaying:
                                    replay_seen.add(key)
                                # This frame's doc supersedes any
                                # deferred one for the key (wire docs
                                # are complete snapshots, not diffs).
                                superseded = self._pending.get(
                                    resource, {}).pop(key, None)
                                if superseded is not None:
                                    memledger.ledger("pending").add(
                                        self._mem_pending,
                                        -superseded[3])
                                old = store.get(key)
                                store[key] = obj
                                self._note_baseline(resource, old, obj)
                                self._touch_baseline(resource, key)
                                if old is None:
                                    informer.fire_add(obj)
                                else:  # upsert of a known object
                                    informer.fire_update(old, obj)
                            elif etype == "DELETED":
                                # Deliver any deferred update first so
                                # the cache sees final-state-then-delete
                                # — the unfiltered control's order.
                                self._flush_key_locked(resource, key)
                                old = store.pop(key, None)
                                self._drop_baseline_key(resource, key)
                                self._note_baseline(resource, old, None)
                                informer.fire_delete(obj)
                            self._enforce_budget_locked(resource)
                        if frame_rv is not None:  # applied successfully
                            last_rv = max(last_rv, int(frame_rv))
            except (OSError, http.client.HTTPException):
                # Connection loss (incl. IncompleteRead mid-chunk):
                # reconnect with bounded exponential backoff (reset by
                # the next successful sync) and resume from last_rv.
                if self._stop.is_set():
                    return
                metrics.note_watch_reconnect(resource, "disconnect")
                self._stop.wait(backoff)
                backoff = min(backoff * 2.0, _WATCH_BACKOFF_CAP_S)
            except ValueError:
                # Malformed frame (truncated chunk, undecodable object):
                # the frame was never applied and last_rv did not
                # advance, so resuming would replay the same poisoned
                # frame forever — drop the resume point and relist from
                # scratch instead.
                if self._stop.is_set():
                    return
                last_rv = 0
                metrics.note_watch_reconnect(resource, "malformed")
                self._stop.wait(backoff)
                backoff = min(backoff * 2.0, _WATCH_BACKOFF_CAP_S)

    # -- shard scope + lazy mirror + baseline budget ------------------------

    def attach_scope(self, scope) -> "RemoteCluster":
        """Install a ShardScope (edge/wire_shard.py).  Before ``start()``
        the scoped streams come up scoped; after it, running reflectors
        notice the presence change on their next frame (keep-alive PINGs
        bound the wait) and reconnect scoped, and the assigned-pod
        occupancy stream is spawned here."""
        with self.lock:
            self._scope = scope
        if self._threads and "pods@assigned" not in self._synced:
            self._spawn("pods", "assigned")
        return self

    def _watch_params(self, resource: str, stream: Optional[str]):
        """(url-suffix, connect-epoch, domain) for one reflector
        connection.  domain None = unscoped legacy stream; "unassigned"
        / "assigned" = the scoped pod split; "scoped" = scoped
        podgroups.  connect-epoch None marks a connection whose selector
        does not depend on the owned-shard set (unscoped, or the static
        assigned stream)."""
        scope = self._scope
        if scope is None or resource not in ("pods", "podgroups"):
            return "", None, None
        if resource == "pods" and stream == "assigned":
            # Every bound pod, any queue: node-occupancy accounting
            # must see foreign pods or the replica double-books nodes.
            return ("&fieldSelector=" + _quote("spec.nodeName!="),
                    None, "assigned")
        epoch = scope.epoch
        # The queue stream is unfiltered, so its mirror is the
        # selector's queue-name universe — wait for its initial sync
        # (bounded) so the first scoped connection filters server-side
        # instead of degrading to the client-side check.  Queues created
        # AFTER this connect stay foreign-unfiltered until the next
        # rescope; the client-side check covers the gap.
        sync = self._synced.get("queues")
        if sync is not None:
            sync.wait(5.0)
        with self.lock:
            universe = list(self.queues)
        if resource == "podgroups":
            try:
                sel = scope.podgroup_field_selector(universe)
            except ValueError:
                self._warn_selector(resource)
                sel = None
            return (("&fieldSelector=" + _quote(sel)) if sel else "",
                    epoch, "scoped")
        parts = ["fieldSelector=" + _quote("spec.nodeName=")]
        try:
            sel = scope.pod_label_selector(universe)
        except ValueError:
            # Malformed shard selector: degrade THIS stream to the
            # unfiltered unassigned watch (the client-side scope check
            # still keeps the mirror scoped) — never kill the daemon.
            self._warn_selector(resource)
            sel = None
        if sel:
            parts.append("labelSelector=" + _quote(sel))
        return "&" + "&".join(parts), epoch, "unassigned"

    def _warn_selector(self, resource: str) -> None:
        metrics.note_wire_fast_fallback("selector")
        with self.lock:
            if resource in self._selector_warned:
                return
            self._selector_warned.add(resource)
        _LOG.warning(
            "shard selector for %r failed to compile (a queue name "
            "outside the selector charset?); degrading to an unfiltered "
            "%s watch — bandwidth scoping is OFF for this stream, the "
            "client-side scope check still applies", resource, resource)

    def _scope_stale(self, resource: str, stream: Optional[str],
                     scope_epoch) -> bool:
        """Did the owned-shard set change under this connection's
        selector?  Presence changes count (a scope attached mid-stream
        must rescope the legacy connection); the static assigned stream
        never goes stale."""
        if resource not in ("pods", "podgroups") or stream == "assigned":
            return False
        scope = self._scope
        if scope is None:
            return scope_epoch is not None
        if scope_epoch is None:
            return True  # connected unscoped, scope attached since
        return scope.epoch != scope_epoch

    def _frame_in_scope(self, resource: str, domain: str, edoc) -> bool:
        """Client-side shard admission for one ADDED/MODIFIED frame.
        Unresolvable queues pass (over-approximation: never drop what we
        cannot attribute); assigned-domain pods always pass
        (occupancy)."""
        scope = self._scope
        if scope is None:
            return True
        try:
            if resource == "pods":
                if domain != "unassigned" \
                        or wire_shard.node_of_pod_doc(edoc, self.wire):
                    return True
                with self.lock:
                    q = wire_shard.queue_of_pod_doc(
                        edoc, self.pod_groups, self.wire)
            else:
                q = wire_shard.queue_of_podgroup_doc(edoc, self.wire)
        except (AttributeError, TypeError):
            return True  # malformed doc: the decode path owns the error
        return q is None or scope.allows(q)

    def _pod_domain_of(self, edoc) -> Optional[str]:
        """Which scoped pod stream owns this doc NOW — "assigned",
        "unassigned", or None when it is out of scope entirely (foreign
        unassigned pod: a removal is a real removal)."""
        try:
            if wire_shard.node_of_pod_doc(edoc, self.wire):
                return "assigned"
            with self.lock:
                q = wire_shard.queue_of_pod_doc(
                    edoc, self.pod_groups, self.wire)
        except (AttributeError, TypeError):
            return None
        scope = self._scope
        if q is not None and scope is not None and not scope.allows(q):
            return None
        return "unassigned"

    def _in_domain(self, resource: str, domain: Optional[str],
                   obj) -> bool:
        """Does a MIRRORED object fall in this stream's relist-purge
        domain?  Unscoped streams (and scoped single-stream resources)
        own every key; the scoped pod split partitions by assignment."""
        if domain is None or resource != "pods" or domain == "scoped":
            return True
        assigned = bool(getattr(obj.spec, "node_name", "") or "")
        return assigned == (domain == "assigned")

    def _maybe_defer(self, resource: str, edoc, raw, frame_ts) -> bool:
        """Lazy mirror: queue a MODIFIED pod frame's raw doc instead of
        materializing a fresh dataclass nobody will read before the next
        frame.  Active only with a wired flush consumer (pending_churn,
        installed by the cache wiring), the fast path on, and a known
        previous object (first sight must fire_add eagerly).  Returns
        True when the frame was absorbed; the deferred doc still dirties
        its queue's shard so the scheduler wakes."""
        if resource != "pods" or self.pending_churn is None \
                or not wire_shard.lazy_mirror_enabled() \
                or not codec.wire_fast_enabled():
            return False
        try:
            key = _raw_key(resource, edoc)
        except (KeyError, TypeError, AttributeError):
            return False
        with self.lock:
            cur = self.pods.get(key)
            if cur is None:
                return False
            pend = self._pending[resource]
            entry = pend.get(key)
            if entry is None:
                pend[key] = [cur, edoc, frame_ts, len(raw)]
                memledger.ledger("pending").add(self._mem_pending,
                                                len(raw))
                metrics.note_lazy_mirror("deferred")
            else:
                # Coalesce: keep the prev the informer last delivered
                # (entry[0]); only the latest doc + receipt stamp
                # matter — wire docs are complete snapshots.
                memledger.ledger("pending").add(self._mem_pending,
                                                len(raw) - entry[3])
                entry[1] = edoc
                entry[2] = frame_ts
                entry[3] = len(raw)
                metrics.note_lazy_mirror("coalesced")
            queue = wire_shard.queue_of_pod_doc(edoc, self.pod_groups,
                                                self.wire)
        churn = self.pending_churn
        if churn is not None:  # outside the lock: churn takes cache.mutex
            churn(queue)
        return True

    def _flush_key_locked(self, resource: str, key: str) -> None:
        entry = self._pending.get(resource, {}).pop(key, None)
        if entry is not None:
            memledger.ledger("pending").add(self._mem_pending, -entry[3])
            self._materialize_locked(resource, key, entry)

    def _materialize_locked(self, resource: str, key: str,
                            entry: list) -> None:
        """Decode one deferred frame against its retained baseline and
        deliver the coalesced informer update.  ``_ingest_ts`` carries
        the stored frame-receipt stamp, so the lineage SLO clock is the
        one the eager path would have stamped."""
        store = self._store(resource)
        old = store.get(key)
        _prev, doc, frame_ts, nbytes = entry
        try:
            t_dec = time.perf_counter()
            obj = self._decode(doc, prev=old, ingest_ts=frame_ts)
            metrics.note_decode_seconds(time.perf_counter() - t_dec)
        except Exception:  # lint: allow-swallow(a malformed deferred doc must not poison the session chokepoint; the mirror keeps the prior materialization, the next frame or relist heals it, and the drop is counted)
            metrics.note_lazy_mirror("error")
            return
        if codec.wire_fast_enabled():
            obj._wire_nbytes = nbytes
        store[key] = obj
        self._note_baseline(resource, old, obj)
        self._touch_baseline(resource, key)
        metrics.note_lazy_mirror("flushed")
        informer = self._informer(resource)
        if old is None:
            informer.fire_add(obj)
        else:
            informer.fire_update(old, obj)

    def flush_pending(self) -> int:
        """Materialize every deferred MODIFIED frame into the mirror and
        informer fan-out — the lazy-mirror chokepoint.  Wired as
        ``cache.mirror_flush`` so ``snapshot()``/the session open and
        the debug surfaces see a current mirror; also safe to call
        directly.  Returns the number of frames materialized."""
        n = 0
        with self.lock:
            for resource in list(self._pending):
                pend = self._pending[resource]
                while pend:
                    key, entry = pend.popitem()
                    memledger.ledger("pending").add(self._mem_pending,
                                                    -entry[3])
                    self._materialize_locked(resource, key, entry)
                    n += 1
                if n:
                    self._enforce_budget_locked(resource)
        return n

    def pending_count(self) -> int:
        with self.lock:
            return sum(len(p) for p in self._pending.values())

    def _touch_baseline(self, resource: str, key: str) -> None:
        """Mark ``key`` hottest in its kind's baseline LRU (enforcement
        compresses/evicts from the cold end).  Lock held."""
        if self._budget.get(resource) is None:
            return
        lru = self._baseline_lru[resource]
        lru.pop(key, None)
        lru[key] = True

    def _drop_baseline_key(self, resource: str, key: str) -> None:
        lru = self._baseline_lru.get(resource)
        if lru:
            lru.pop(key, None)

    def _enforce_budget_locked(self, resource: str) -> None:
        """Hold the kind's retained baseline bytes to its budget:
        compress cold baselines in place first, evict (counted) only
        when compression cannot get there.  Runs under the lock so the
        ledger and the objects move together; the gauge publishes every
        step, so ``kube_batch_wire_baseline_bytes`` only goes DOWN at a
        fixed workload once the budget binds."""
        budget = self._budget.get(resource)
        if budget is None \
                or self._baseline_bytes.get(resource, 0) <= budget:
            return
        store = self._store(resource)
        lru = self._baseline_lru[resource]
        for op in ("compress", "evict"):
            for key in list(lru):
                if self._baseline_bytes[resource] <= budget:
                    return
                obj = store.get(key)
                if obj is None:
                    lru.pop(key, None)
                    continue
                old_n = getattr(obj, "_wire_nbytes", 0)
                if op == "compress":
                    if old_n < 128:
                        continue  # zlib overhead would inflate it
                    new_n = baseline_store.compress(obj)
                    if new_n is None:
                        continue  # already cold / nothing retained
                else:
                    popped = baseline_store.evict(obj)
                    lru.pop(key, None)
                    if not popped:
                        continue
                    new_n = 0
                try:
                    obj._wire_nbytes = new_n
                except AttributeError:  # lint: allow-swallow(slotted/foreign object: it never carried retained bytes, the ledger is untouched)
                    continue
                delta = new_n - old_n
                if delta:
                    total = self._baseline_bytes.get(resource, 0) + delta
                    self._baseline_bytes[resource] = total
                    metrics.set_wire_baseline(resource, total)
                    # Budget enforcement mutates `_wire_nbytes` in
                    # place, outside _note_baseline — the ledger must
                    # follow or audit_mem_ledgers drifts here.
                    memledger.ledger("baseline").add(
                        self._mem_baseline, delta)
                metrics.note_baseline_budget(resource, op)

    def audit_baseline_bytes(self) -> Dict[str, int]:
        """{kind: ledger - actual}: zero everywhere iff the
        ``_baseline_bytes`` ledger reconciles with the ``_wire_nbytes``
        actually retained on mirror objects — the relist/DELETE release
        invariant (tests/test_baseline_budget.py)."""
        out = {}
        with self.lock:
            for resource in _WATCHED:
                actual = sum(getattr(o, "_wire_nbytes", 0)
                             for o in self._store(resource).values())
                out[resource] = (self._baseline_bytes.get(resource, 0)
                                 - actual)
        return out

    def ingest_bytes(self) -> Dict[str, int]:
        """Cumulative watch bytes received per resource (the scoped pod
        streams folded together) — `make bench-ingest`'s directional
        key."""
        out: Dict[str, int] = {}
        for skey, v in self._ingest_bytes.items():
            base = skey.split("@", 1)[0]
            out[base] = out.get(base, 0) + v
        return out

    def mirrored_objects(self) -> Dict[str, int]:
        """{resource: mirror entry count} — the soak's O(own shards)
        scoping assertions."""
        with self.lock:
            return {r: len(self._store(r)) for r in _WATCHED}

    def _spawn(self, resource: str, stream: Optional[str] = None) -> None:
        skey = f"{resource}@{stream}" if stream else resource
        # setdefault: start() pre-registers every stream's sync event
        # before ANY reflector thread runs, so the scoped pod/podgroup
        # connections can wait on the queue stream's sync no matter the
        # spawn order.
        self._synced.setdefault(skey, threading.Event())
        self._ingest_bytes.setdefault(skey, 0)
        t = threading.Thread(target=self._reflect,
                             args=(resource, stream), daemon=True,
                             name=f"reflector-{skey}")
        t.start()
        self._threads.append(t)

    def start(self, timeout: float = 30.0) -> "RemoteCluster":
        for resource in _WATCHED:
            self._synced.setdefault(resource, threading.Event())
        for resource in _WATCHED:
            self._spawn(resource)
        if self._scope is not None:
            self._spawn("pods", "assigned")
        for skey in list(self._synced):
            if not self._synced[skey].wait(timeout):
                # Don't leak reflector threads into a caller that will
                # retry or give up: each holds a socket and keeps
                # mutating the mirrors.  Stop and join them before
                # surfacing WHICH streams never synced.
                unsynced = [s for s in self._synced
                            if not self._synced[s].is_set()]
                self._stop.set()
                for t in self._threads:
                    t.join(timeout=2.0)
                alive = [t.name for t in self._threads if t.is_alive()]
                raise TimeoutError(
                    f"watch sync timeout after {timeout:.1f}s; streams "
                    f"never synced: {', '.join(unsynced)}"
                    + (f" (reflectors still draining a blocked read: "
                       f"{', '.join(alive)})" if alive else ""))
        self._refresh_pvcs()
        return self

    def _note_baseline(self, resource: str, old, new) -> None:
        """Apply one mirror-store entry change (old -> new, either side
        None) to the per-kind retained-baseline byte total and publish
        the gauge.  Reflector thread only (each resource has exactly one
        writer)."""
        delta = (getattr(new, "_wire_nbytes", 0) if new is not None else 0) \
            - (getattr(old, "_wire_nbytes", 0) if old is not None else 0)
        if delta:
            total = self._baseline_bytes.get(resource, 0) + delta
            self._baseline_bytes[resource] = total
            metrics.set_wire_baseline(resource, total)
            memledger.ledger("baseline").add(self._mem_baseline, delta)
        # Every mirror-store entry change routes through here (upsert,
        # DELETED, SYNC purge, lazy-mirror materialize), so the mirror
        # ledger's count delta piggybacks on the same call.
        count_delta = (new is not None) - (old is not None)
        if count_delta:
            memledger.ledger("mirror").add(
                self._mem_mirror, count_delta * _MIRROR_OBJ_EST)

    def wire_baseline_bytes(self) -> Dict[str, int]:
        """{kind: retained raw-doc baseline bytes} — the mirror-memory
        accounting surfaced on /debug/sessions and the bench artifact."""
        return dict(self._baseline_bytes)

    def _refresh_pvcs(self) -> None:
        """PVCs are list-only; _PvcStore refetches on a miss so claims
        created after start() are still found at allocate time."""
        items = {}
        for doc in self._request("GET", self._collection("pvcs"))["items"]:
            pvc = self._decode(doc)
            items[f"{pvc.metadata.namespace}/{pvc.metadata.name}"] = pvc
        self.pvcs.replace(items)

    def stop(self) -> None:
        self._stop.set()

    # -- egress: REST verbs -------------------------------------------------

    def _collection(self, resource: str) -> str:
        return (_K8S_PATHS[resource] if self.wire == "k8s"
                else f"/v1/{resource}")

    def _object_path(self, resource: str, namespace, name: str) -> str:
        if self.wire != "k8s":
            return (f"/v1/{resource}/{name}" if namespace is None
                    else f"/v1/{resource}/{namespace}/{name}")
        base = _K8S_PATHS[resource]
        if namespace is None:
            return f"{base}/{name}"
        head, _, res = base.rpartition("/")
        return f"{head}/namespaces/{namespace}/{res}/{name}"

    def _encode(self, obj):
        return (codec_k8s.to_k8s(obj) if self.wire == "k8s"
                else codec.encode(obj))

    def _decode(self, doc, prev=None, ingest_ts=None):
        """Decode one wire doc; ``prev`` (the mirror's current object for
        the same key) arms the columnar fast path — changed fields only,
        unchanged subtrees reused by identity (edge/codec.decode_delta /
        codec_k8s.from_k8s_delta).  Any fast-path surprise degrades to
        the full decode, counted by reason — a weird frame must never
        kill the reflector thread (the ValueError contract below is
        unchanged: a doc the FULL decode rejects still raises)."""
        obj = None
        if prev is not None and codec.wire_fast_enabled():
            try:
                obj = (codec_k8s.from_k8s_delta(doc, prev)
                       if self.wire == "k8s"
                       else codec.decode_delta(doc, prev))
                metrics.note_wire_decode("delta")
            except LookupError as exc:
                # No usable baseline (first sight after a relist gap,
                # foreign object) or a kind outside the delta plans —
                # the codec names which; anything else folds into
                # "baseline" so the label set stays bounded.
                reason = str(exc)
                metrics.note_wire_fast_fallback(
                    reason if reason in ("kind", "evicted")
                    else "baseline")
            except ValueError:
                # The full decode would reject this doc too: let the
                # reflector's malformed-frame relist handle it.
                raise
            except Exception:  # lint: allow-swallow(fast-path isolation: the full decode below is always correct, and the degradation is counted)
                metrics.note_wire_fast_fallback("error")
        if obj is None:
            obj = (codec_k8s.from_k8s(doc) if self.wire == "k8s"
                   else codec.decode(doc))
            metrics.note_wire_decode("full")
            if codec.wire_fast_enabled():
                # Baseline for the NEXT frame of this key (the delta
                # compare needs the raw doc the object came from).
                codec.remember_wire_doc(obj, doc)
        # Pod-lineage ingest stamp (trace/lineage.py): monotonic so the
        # SLO clock survives wall-clock steps.  ``ingest_ts`` carries the
        # FRAME-RECEIPT stamp the reflector took before any decode ran,
        # so the lineage timestamp does not silently shift between the
        # fast path (near-zero decode) and the full path (the
        # materialization delay the old stamp-after-decode absorbed).
        # Stamped HERE (the client edge, both wire modes, one
        # chokepoint) and not in the codecs — the server decodes through
        # the same codec functions and must not mark ITS objects as
        # scheduler-ingested.  An instance attribute: dataclass __eq__
        # ignores it, the codec never re-encodes it.
        if isinstance(obj, _objects.Pod):
            obj._ingest_ts = (ingest_ts if ingest_ts is not None
                              else time.monotonic())
        return obj

    def _request(self, method: str, path: str, payload=None,
                 content_type: str = "application/json"):
        body = json.dumps(payload).encode() if payload is not None else None
        req = urllib.request.Request(
            f"{self.base_url}{path}", data=body, method=method,
            headers={"Content-Type": content_type})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return json.loads(resp.read())
        except urllib.error.HTTPError as exc:
            detail = exc.read().decode(errors="replace")
            err = KeyError(f"{method} {path}: {exc.code} {detail}")
            err.status = exc.code  # type: ignore[attr-defined]
            raise err from exc

    # effectors the SchedulerCache wiring uses (cluster.py effectors):
    def _bind_request(self, namespace: str, name: str, hostname: str):
        """(path, payload) for one bind, by wire mode."""
        if self.wire == "k8s":  # the real Binding subresource
            return (self._object_path("pods", namespace, name) + "/binding",
                    {"apiVersion": "v1", "kind": "Binding",
                     "metadata": {"name": name, "namespace": namespace},
                     "target": {"kind": "Node", "name": hostname}})
        return (f"/v1/pods/{namespace}/{name}/bind", {"node": hostname})

    def bind_pods_many(self, pairs, workers: int = 8) -> list:
        """Bind [(pod, hostname)] concurrently over persistent
        connections; returns [(pod, hostname, exc)] failures.

        The reference fires one goroutine per bind (cache.go:491-535 via
        the bind channel); the HTTP analog is a small worker pool where
        each worker keeps ONE keep-alive connection and streams its
        share of Binding POSTs down it — n_binds round trips become
        ~n_binds/workers serialized on each of ``workers`` sockets,
        without per-request TCP setup."""
        from urllib.parse import urlsplit

        if not pairs:
            return []
        parts = urlsplit(self.base_url)
        prefix = parts.path.rstrip("/")  # reverse-proxied edge prefix
        conn_cls = (_NodelayHTTPSConnection if parts.scheme == "https"
                    else _NodelayConnection)
        failures = []
        flock = threading.Lock()
        workers = max(1, min(workers, len(pairs)))

        def post(conn, pod, hostname, path, body):
            sent = False
            for attempt in (0, 1):
                try:
                    sent = False
                    conn.request("POST", prefix + path, body,
                                 {"Content-Type": "application/json"})
                    sent = True  # delivered; a later failure may have
                    # been applied server-side — don't blind-retry
                    resp = conn.getresponse()
                    data = resp.read()
                except (http.client.HTTPException, OSError) as exc:
                    conn.close()  # next request auto-reconnects
                    if attempt or sent:
                        # After delivery, binds are non-idempotent —
                        # check the pod instead of re-POSTing.
                        if sent and self._pod_bound_to(pod, hostname):
                            # Ambiguity resolved by the read-back: it
                            # landed, the skipped retry was correct.
                            metrics.note_bind_ambiguous("landed")
                            return
                        if sent:
                            # Delivered but unproven either way (the
                            # read-back probe could not confirm): surface
                            # the ambiguity explicitly — the cache routes
                            # it through resync instead of assuming the
                            # bind failed (counted there as "unproven").
                            raise AmbiguousOutcomeError(
                                f"bind POST for "
                                f"{pod.metadata.namespace}/"
                                f"{pod.metadata.name} was delivered but "
                                f"its outcome is unproven") from exc
                        raise
                    # Send-phase failure: the bytes PROBABLY never
                    # reached the server, but TCP cannot prove it (an
                    # RST can race a request that was delivered and
                    # applied).  Read the pod back before the resend:
                    # if the first POST landed, skip the retry rather
                    # than lean on duplicate binds being idempotent.
                    if self._pod_bound_to(pod, hostname):
                        metrics.note_bind_ambiguous("landed")
                        return
                    continue
                if resp.status >= 400:
                    if attempt and self._pod_bound_to(pod, hostname):
                        # First attempt did land; 409-shaped echo.
                        metrics.note_bind_ambiguous("landed")
                        return
                    err = KeyError(f"POST {path}: {resp.status} "
                                   f"{data.decode(errors='replace')}")
                    # Status carried for the cache's retry classifier:
                    # 4xx rejections are permanent, 5xx are transient.
                    err.status = resp.status  # type: ignore[attr-defined]
                    raise err
                return

        def run(chunk):
            conn = conn_cls(parts.hostname, parts.port,
                            timeout=self.timeout)
            try:
                for pod, hostname in chunk:
                    path, payload = self._bind_request(
                        pod.metadata.namespace, pod.metadata.name, hostname)
                    try:
                        post(conn, pod, hostname, path,
                             json.dumps(payload))
                    except Exception as exc:  # per-task failure isolation
                        with flock:
                            failures.append((pod, hostname, exc))
            finally:
                conn.close()

        threads = [threading.Thread(
            target=run, args=(pairs[i::workers],), daemon=True)
            for i in range(workers)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return failures

    def _pod_bound_to(self, pod, hostname: str) -> bool:
        """Read-back check for the bind retry path: was the first POST
        applied before the connection died?  GETs the SERVER (the local
        mirror lags the watch stream); GETs are idempotent, so this is
        safe where re-POSTing a Binding is not."""
        try:
            doc = self._request("GET", self._object_path(
                "pods", pod.metadata.namespace, pod.metadata.name))
            current = self._decode(doc)
            return current.spec.node_name == hostname
        except Exception:  # lint: allow-swallow(read-back probe: any failure means "unproven", and False makes the retry path surface the original error)
            return False

    def evict_pods_many(self, pods, workers: int = 8) -> list:
        """Evict (DELETE) pods concurrently over persistent
        connections; returns [(pod, exc)] failures — the bind_pods_many
        twin for the batched commit flush (framework/commit.py).

        Simpler than the bind pool: a pod DELETE is idempotent (the
        object either exists or it does not), so a connection that dies
        mid-request retries once on a fresh connection and a 404 on the
        retry proves the first attempt landed."""
        if not pods:
            return []
        from urllib.parse import urlsplit

        parts = urlsplit(self.base_url)
        prefix = parts.path.rstrip("/")
        conn_cls = (_NodelayHTTPSConnection if parts.scheme == "https"
                    else _NodelayConnection)
        failures = []
        flock = threading.Lock()
        pods = list(pods)
        workers = max(1, min(workers, len(pods)))

        def delete(conn, pod):
            path = prefix + self._object_path(
                "pods", pod.metadata.namespace, pod.metadata.name)
            for attempt in (0, 1):
                try:
                    conn.request("DELETE", path)
                    resp = conn.getresponse()
                    data = resp.read()
                except (http.client.HTTPException, OSError):
                    conn.close()  # next request auto-reconnects
                    if attempt:
                        raise
                    continue  # DELETE is idempotent: one clean retry
                if resp.status == 404 and attempt:
                    return  # first attempt landed; the retry's 404 proves it
                if resp.status >= 400:
                    err = KeyError(f"DELETE {path}: {resp.status} "
                                   f"{data.decode(errors='replace')}")
                    err.status = resp.status  # type: ignore[attr-defined]
                    raise err
                return

        def run(chunk):
            conn = conn_cls(parts.hostname, parts.port,
                            timeout=self.timeout)
            try:
                for pod in chunk:
                    try:
                        delete(conn, pod)
                    except Exception as exc:  # per-pod failure isolation
                        with flock:
                            failures.append((pod, exc))
            finally:
                conn.close()

        threads = [threading.Thread(
            target=run, args=(pods[i::workers],), daemon=True)
            for i in range(workers)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return failures

    def bind_pod(self, namespace: str, name: str, hostname: str) -> None:
        path, payload = self._bind_request(namespace, name, hostname)
        self._request("POST", path, payload)

    def delete_pod(self, namespace: str, name: str) -> None:
        self._request("DELETE",
                      self._object_path("pods", namespace, name))

    def put_pod_group_status(self, pg) -> None:
        self._request(
            "PUT",
            self._object_path("podgroups", pg.metadata.namespace,
                              pg.metadata.name) + "/status",
            self._encode(pg))

    def update_pod_condition(self, namespace: str, name: str,
                             condition) -> None:
        """Pod status subresource: PodCondition upsert (the stuck-pod
        writeback, cache.go:548-568).  Native wire PUTs the bare
        condition; k8s wire strategic-merge-patches ONLY this condition
        (merged by ``type`` server-side), so concurrent status writers'
        conditions are never clobbered by a stale read-modify-write."""
        if self.wire == "k8s":
            self._request(
                "PATCH",
                self._object_path("pods", namespace, name) + "/status",
                {"status": {"conditions": [
                    {"type": condition.type, "status": condition.status,
                     "reason": condition.reason,
                     "message": condition.message}]}},
                content_type="application/strategic-merge-patch+json")
        else:
            self._request("PUT", f"/v1/pods/{namespace}/{name}/status",
                          codec.encode(condition))

    def create_event(self, event) -> None:
        self._request("POST", self._collection("events"),
                      self._encode(event))

    # leader-election lease (ConfigMap-lock analog, server.go:115-139):
    def get_lease(self, namespace: str, name: str):
        doc = self._request("GET", f"/v1/leases/{namespace}/{name}")
        return int(doc["version"]), doc["record"]

    def cas_lease(self, namespace: str, name: str, record: dict,
                  expected_version: int) -> int:
        try:
            doc = self._request(
                "PUT", f"/v1/leases/{namespace}/{name}",
                {"record": record, "expectedVersion": expected_version})
        except KeyError as exc:  # 409 conflict surfaced by _request
            raise ValueError(str(exc)) from exc
        return int(doc["version"])

    def bind_pvc(self, namespace: str, name: str, volume_name: str) -> None:
        self._request(
            "POST",
            self._object_path("pvcs", namespace, name) + "/bind",
            {"volume": volume_name})

    def get_pod(self, namespace: str, name: str):
        """Authoritative ground-truth fetch — the resync path's read
        (cache.go:602-611 queries the apiserver, not an informer store).
        Resync exists precisely because the mirror may LAG the effect
        being repaired; answering from the mirror can resurrect a stale
        Pending for a bind that actually landed, and the re-placement
        then double-books the node (found by tools/chaos_soak.py under
        watch faults).  404 -> None (the pod is truly gone); transport
        errors propagate — the resync worker re-queues the task."""
        try:
            doc = self._request(
                "GET", self._object_path("pods", namespace, name))
        except KeyError as exc:
            if getattr(exc, "status", None) == 404:
                return None
            raise
        return self._decode(doc)

    def get_mirror_pod(self, namespace: str, name: str):
        """The local mirror's view (may lag truth): the zero-round-trip
        read for callers that only need informer-consistent state.  A
        debug/resync read is a materialization touch — any deferred
        frame for the key flushes first (lazy-mirror validity rule)."""
        with self.lock:
            self._flush_key_locked("pods", f"{namespace}/{name}")
            return self.pods.get(f"{namespace}/{name}")

    # mutation verbs (typed clientsets / workload submission clients):
    def update_pod_group(self, pg) -> None:
        if self.wire == "k8s":
            self._request(
                "PUT",
                self._object_path("podgroups", pg.metadata.namespace,
                                  pg.metadata.name),
                self._encode(pg))
        else:
            self._request("PUT", "/v1/podgroups", codec.encode(pg))

    def delete_pod_group(self, namespace: str, name: str) -> None:
        self._request("DELETE",
                      self._object_path("podgroups", namespace, name))

    def delete_queue(self, name: str) -> None:
        self._request("DELETE", self._object_path("queues", None, name))

    # creation verbs (tests / workload submission clients):
    def create_pod(self, pod) -> None:
        self._request("POST", self._collection("pods"), self._encode(pod))

    def create_node(self, node) -> None:
        self._request("POST", self._collection("nodes"),
                      self._encode(node))

    def create_pod_group(self, pg) -> None:
        self._request("POST", self._collection("podgroups"),
                      self._encode(pg))

    def create_queue(self, queue) -> None:
        self._request("POST", self._collection("queues"),
                      self._encode(queue))

    def create_priority_class(self, pc) -> None:
        self._request("POST", self._collection("priorityclasses"),
                      self._encode(pc))

    def create_pvc(self, pvc) -> None:
        self._request("POST", self._collection("pvcs"),
                      self._encode(pvc))
