"""RemoteCluster: the client-go analog over the REST edge.

A reflector per watched resource streams list+watch events from
edge.server.ApiServer into local mirror stores and Informer fan-outs, so
``cache.cluster.new_scheduler_cache(RemoteCluster(url).start())`` wires a
SchedulerCache to a REMOTE cluster exactly as it wires to the in-process
simulator — same informers in (cache.go:255-352), and the effector verbs
(bind/evict/status, cache.go:425-535) become REST calls out.
"""

from __future__ import annotations

import http.client
import json
import threading
import urllib.error
import urllib.request
from typing import Dict

from ..cache.cluster import Informer
from . import codec

_WATCHED = ("pods", "nodes", "podgroups", "queues", "priorityclasses",
            "pdbs")

_MISSING = object()


class _PvcStore(dict):
    """PVC mirror that refetches the remote list on a miss (PVCs have no
    watch stream; volume binding must still see late-created claims).
    Misses are negative-cached for a few seconds: the refetch can run
    while the caller holds RemoteCluster.lock, so a pod referencing a
    genuinely absent PVC must not stall reflector ingest every cycle."""

    _NEG_TTL = 5.0

    def __init__(self, remote: "RemoteCluster"):
        super().__init__()
        self._remote = remote
        self._neg: Dict[str, float] = {}

    def replace(self, items) -> None:
        self.clear()
        self.update(items)
        self._neg.clear()

    def get(self, key, default=None):
        import time as _time
        value = dict.get(self, key)
        if value is None:
            now = _time.monotonic()
            if self._neg.get(key, 0.0) > now:
                return default
            try:
                self._remote._refresh_pvcs()
            except (OSError, KeyError):  # _request maps HTTPError→KeyError
                return default
            value = dict.get(self, key, default)
            if value is default:
                self._neg[key] = now + self._NEG_TTL
        return value

    # Mapping syntax must see the same on-miss refetch + negative cache
    # as .get(), so future callers can't silently read a stale miss.
    def __getitem__(self, key):
        value = self.get(key, _MISSING)
        if value is _MISSING:
            raise KeyError(key)
        return value

    def __contains__(self, key):
        return self.get(key, _MISSING) is not _MISSING


def _key_fn(resource: str):
    if resource in ("pods", "podgroups", "pdbs", "pvcs"):
        return lambda o: f"{o.metadata.namespace}/{o.metadata.name}"
    if resource == "nodes":
        return lambda o: o.name
    return lambda o: o.metadata.name


class RemoteCluster:
    """Duck-types the Cluster surface the scheduler wiring consumes:
    ``*_informer`` fan-outs + mirror stores (ingest) and the effector
    verbs (egress), all over HTTP."""

    def __init__(self, base_url: str, timeout: float = 10.0):
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout
        self.lock = threading.RLock()
        self.pods: Dict[str, object] = {}
        self.nodes: Dict[str, object] = {}
        self.pod_groups: Dict[str, object] = {}
        self.queues: Dict[str, object] = {}
        self.priority_classes: Dict[str, object] = {}
        self.pdbs: Dict[str, object] = {}
        self.pvcs = _PvcStore(self)
        self.pod_informer = Informer()
        self.node_informer = Informer()
        self.pod_group_informer = Informer()
        self.queue_informer = Informer()
        self.priority_class_informer = Informer()
        self.pdb_informer = Informer()
        self._stop = threading.Event()
        self._threads = []
        self._synced: Dict[str, threading.Event] = {}

    # -- ingest: reflectors -------------------------------------------------

    def _store(self, resource: str) -> Dict[str, object]:
        return {"pods": self.pods, "nodes": self.nodes,
                "podgroups": self.pod_groups, "queues": self.queues,
                "priorityclasses": self.priority_classes,
                "pdbs": self.pdbs, "pvcs": self.pvcs}[resource]

    def _informer(self, resource: str) -> Informer:
        return {"pods": self.pod_informer, "nodes": self.node_informer,
                "podgroups": self.pod_group_informer,
                "queues": self.queue_informer,
                "priorityclasses": self.priority_class_informer,
                "pdbs": self.pdb_informer}[resource]

    def _reflect(self, resource: str) -> None:
        """One reflector: stream watch events into the mirror + informer.
        A fresh connect replays the server's current state as ADDED
        events ending in SYNC (objects deleted during a disconnect are
        reconciled out of the mirror then — client-go's relist).  A
        RECONNECT resumes from the last seen resourceVersion: the server
        replays only the missed delta (RESUMED frame, no reconciliation),
        or answers ERROR 410 when the client fell past its event buffer,
        forcing a full relist — the k8s list+watch contract."""
        store = self._store(resource)
        informer = self._informer(resource)
        key_of = _key_fn(resource)
        base = f"{self.base_url}/v1/{resource}?watch=1"
        last_rv = 0
        while not self._stop.is_set():
            replay_seen = set()
            replaying = True
            url = (f"{base}&resourceVersion={last_rv}" if last_rv else base)
            try:
                # Read timeout >> the server's 5s keep-alive ping: a
                # half-open connection surfaces as socket.timeout (OSError)
                # and reconnects instead of freezing the mirror forever.
                with urllib.request.urlopen(url, timeout=30) as resp:
                    for raw in resp:
                        if self._stop.is_set():
                            return
                        event = json.loads(raw)
                        etype = event["type"]
                        # NOTE: last_rv advances only AFTER a frame is
                        # fully applied — advancing first would make a
                        # frame that fails to decode/apply permanently
                        # invisible to the resume path (no relist ever
                        # heals it).
                        frame_rv = event.get("rv")
                        if etype == "SYNC":
                            with self.lock:
                                for stale in [k for k in store
                                              if k not in replay_seen]:
                                    informer.fire_delete(store.pop(stale))
                            replaying = False
                            self._synced[resource].set()
                            if frame_rv is not None:
                                last_rv = max(last_rv, int(frame_rv))
                            continue
                        if etype == "RESUMED":
                            # Continuous delta stream: mirror is already
                            # current, no reconciliation needed.
                            replaying = False
                            self._synced[resource].set()
                            continue
                        if etype == "ERROR":
                            # 410 Gone: fall back to a full relist.
                            last_rv = 0
                            break
                        if etype == "PING":
                            continue
                        obj = codec.decode(event["object"])
                        key = key_of(obj)
                        with self.lock:
                            if etype == "ADDED":
                                if replaying:
                                    replay_seen.add(key)
                                old = store.get(key)
                                store[key] = obj
                                if old is None:
                                    informer.fire_add(obj)
                                else:  # relist upsert of a known object
                                    informer.fire_update(old, obj)
                            elif etype == "MODIFIED":
                                old = store.get(key)
                                store[key] = obj
                                if old is None:
                                    informer.fire_add(obj)
                                else:
                                    informer.fire_update(old, obj)
                            elif etype == "DELETED":
                                store.pop(key, None)
                                informer.fire_delete(obj)
                        if frame_rv is not None:  # applied successfully
                            last_rv = max(last_rv, int(frame_rv))
            except (OSError, http.client.HTTPException, ValueError):
                # Connection loss (incl. IncompleteRead mid-chunk) or a
                # malformed frame: reconnect and relist.
                if self._stop.is_set():
                    return
                self._stop.wait(0.5)

    def start(self, timeout: float = 30.0) -> "RemoteCluster":
        for resource in _WATCHED:
            self._synced[resource] = threading.Event()
            t = threading.Thread(target=self._reflect, args=(resource,),
                                 daemon=True,
                                 name=f"reflector-{resource}")
            t.start()
            self._threads.append(t)
        for resource in _WATCHED:
            if not self._synced[resource].wait(timeout):
                raise TimeoutError(f"watch sync timeout for {resource}")
        self._refresh_pvcs()
        return self

    def _refresh_pvcs(self) -> None:
        """PVCs are list-only; _PvcStore refetches on a miss so claims
        created after start() are still found at allocate time."""
        items = {}
        for doc in self._get("pvcs")["items"]:
            pvc = codec.decode(doc)
            items[f"{pvc.metadata.namespace}/{pvc.metadata.name}"] = pvc
        self.pvcs.replace(items)

    def stop(self) -> None:
        self._stop.set()

    # -- egress: REST verbs -------------------------------------------------

    def _request(self, method: str, path: str, payload=None):
        body = json.dumps(payload).encode() if payload is not None else None
        req = urllib.request.Request(
            f"{self.base_url}{path}", data=body, method=method,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return json.loads(resp.read())
        except urllib.error.HTTPError as exc:
            detail = exc.read().decode(errors="replace")
            raise KeyError(f"{method} {path}: {exc.code} {detail}") from exc

    def _get(self, resource: str):
        return self._request("GET", f"/v1/{resource}")

    # effectors the SchedulerCache wiring uses (cluster.py effectors):
    def bind_pod(self, namespace: str, name: str, hostname: str) -> None:
        self._request("POST", f"/v1/pods/{namespace}/{name}/bind",
                      {"node": hostname})

    def delete_pod(self, namespace: str, name: str) -> None:
        self._request("DELETE", f"/v1/pods/{namespace}/{name}")

    def put_pod_group_status(self, pg) -> None:
        self._request(
            "PUT",
            f"/v1/podgroups/{pg.metadata.namespace}/{pg.metadata.name}/status",
            codec.encode(pg))

    def update_pod_condition(self, namespace: str, name: str,
                             condition) -> None:
        """Pod status subresource: PodCondition upsert (the stuck-pod
        writeback, cache.go:548-568)."""
        self._request("PUT", f"/v1/pods/{namespace}/{name}/status",
                      codec.encode(condition))

    def create_event(self, event) -> None:
        self._request("POST", "/v1/events", codec.encode(event))

    # leader-election lease (ConfigMap-lock analog, server.go:115-139):
    def get_lease(self, namespace: str, name: str):
        doc = self._request("GET", f"/v1/leases/{namespace}/{name}")
        return int(doc["version"]), doc["record"]

    def cas_lease(self, namespace: str, name: str, record: dict,
                  expected_version: int) -> int:
        try:
            doc = self._request(
                "PUT", f"/v1/leases/{namespace}/{name}",
                {"record": record, "expectedVersion": expected_version})
        except KeyError as exc:  # 409 conflict surfaced by _request
            raise ValueError(str(exc)) from exc
        return int(doc["version"])

    def bind_pvc(self, namespace: str, name: str, volume_name: str) -> None:
        self._request("POST", f"/v1/pvcs/{namespace}/{name}/bind",
                      {"volume": volume_name})

    def get_pod(self, namespace: str, name: str):
        with self.lock:
            return self.pods.get(f"{namespace}/{name}")

    # mutation verbs (typed clientsets / workload submission clients):
    def update_pod_group(self, pg) -> None:
        self._request("PUT", "/v1/podgroups", codec.encode(pg))

    def delete_pod_group(self, namespace: str, name: str) -> None:
        self._request("DELETE", f"/v1/podgroups/{namespace}/{name}")

    def delete_queue(self, name: str) -> None:
        self._request("DELETE", f"/v1/queues/{name}")

    # creation verbs (tests / workload submission clients):
    def create_pod(self, pod) -> None:
        self._request("POST", "/v1/pods", codec.encode(pod))

    def create_node(self, node) -> None:
        self._request("POST", "/v1/nodes", codec.encode(node))

    def create_pod_group(self, pg) -> None:
        self._request("POST", "/v1/podgroups", codec.encode(pg))

    def create_queue(self, queue) -> None:
        self._request("POST", "/v1/queues", codec.encode(queue))

    def create_priority_class(self, pc) -> None:
        self._request("POST", "/v1/priorityclasses", codec.encode(pc))

    def create_pvc(self, pvc) -> None:
        self._request("POST", "/v1/pvcs", codec.encode(pvc))
