"""Conformance plugin: protect critical pods from preempt/reclaim.

Mirrors /root/reference/pkg/scheduler/plugins/conformance/conformance.go:41-61.
"""

from __future__ import annotations

from typing import List

from ..api import TaskInfo
from ..framework import Arguments, Plugin

SYSTEM_CRITICAL_CLASSES = ("system-cluster-critical", "system-node-critical")
SYSTEM_NAMESPACE = "kube-system"


def _is_critical(task: TaskInfo) -> bool:
    return (task.pod.spec.priority_class_name in SYSTEM_CRITICAL_CLASSES
            or task.namespace == SYSTEM_NAMESPACE)


class ConformancePlugin(Plugin):

    def __init__(self, arguments: Arguments):
        self.arguments = arguments

    def name(self) -> str:
        return "conformance"

    def on_session_open(self, ssn) -> None:
        def evictable_fn(evictor: TaskInfo,
                         evictees: List[TaskInfo]) -> List[TaskInfo]:
            return [t for t in evictees if not _is_critical(t)]

        ssn.add_preemptable_fn(self.name(), evictable_fn)
        ssn.add_reclaimable_fn(self.name(), evictable_fn)

    def on_session_close(self, ssn) -> None:
        pass


def new(arguments: Arguments) -> ConformancePlugin:
    return ConformancePlugin(arguments)
