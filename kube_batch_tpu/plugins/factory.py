"""Plugin registration (reference plugins/factory.go:30-39)."""

from ..framework import register_plugin_builder
from . import (conformance, drf, gang, nodeorder, predicates, priority,
               proportion)


def register_default_plugins() -> None:
    register_plugin_builder("gang", gang.new)
    register_plugin_builder("priority", priority.new)
    register_plugin_builder("drf", drf.new)
    register_plugin_builder("proportion", proportion.new)
    register_plugin_builder("predicates", predicates.new)
    register_plugin_builder("nodeorder", nodeorder.new)
    register_plugin_builder("conformance", conformance.new)
    # TPU-side scoring plugin registers lazily to keep jax imports off the
    # critical path for host-only deployments.
    from . import tpu_score
    register_plugin_builder("tpu-score", tpu_score.new)
    # Topology-aware fragmentation scoring (doc/TOPOLOGY.md).
    from . import topology
    register_plugin_builder("topology", topology.new)
