"""Scheduler plugins (L3): gang, drf, proportion, priority, predicates,
nodeorder, conformance, tpu-score.

TPU-native counterpart of /root/reference/pkg/scheduler/plugins/.
"""
