"""tpu-score plugin: node scoring served by the device kernels.

The north star (BASELINE.json) asks for a ``tpu-score`` plugin registered
through the normal plugin boundary.  For host actions it registers the same
weighted scoring functions as nodeorder (so any action works with it); for
the tpu-allocate action its weights flow into the batched scoring kernel
(ops/scoring.py) via tensorize_session.  This keeps one source of truth for
the scoring math across both execution paths.
"""

from __future__ import annotations

from ..framework import Arguments
from .nodeorder import NodeOrderPlugin


class TpuScorePlugin(NodeOrderPlugin):

    def name(self) -> str:
        return "tpu-score"


def new(arguments: Arguments) -> TpuScorePlugin:
    return TpuScorePlugin(arguments)
