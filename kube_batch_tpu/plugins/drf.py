"""DRF plugin: dominant-resource fairness across jobs.

Mirrors /root/reference/pkg/scheduler/plugins/drf/drf.go: per-job dominant
share = max over resources of allocated/total (:161-171); job order ascending
by share; preemption allowed only when it improves fairness; incremental
share maintenance through allocate/deallocate events (:135-154).

The same shares are computed on-device by ``ops.fairness.drf_shares``
(segment-max over a [jobs, resources] tensor); this host plugin is the
oracle and serves the sequential actions.
"""

from __future__ import annotations

import math
from typing import Dict, List

from ..api import JobInfo, Resource, TaskInfo, allocated_status, share
from ..framework import Arguments, EventHandler, Plugin

SHARE_DELTA = 0.000001


class _DrfAttr:
    """Per-job DRF state.  ``allocated`` materializes lazily on the fast
    path: the open-time vectorized share (models/incremental.
    drf_open_shares) needs only the float columns, so the per-job
    Resource clone — O(jobs) allocations per session — is deferred until
    something actually reads it (preemption path, allocate/deallocate
    event handlers).  The materialized value is the cached per-clone
    open walk cloned out, exactly what the control arm assigns
    eagerly."""

    __slots__ = ("share", "_alloc", "_job")

    def __init__(self):
        self.share = 0.0
        self._alloc = Resource.empty()
        self._job = None

    @property
    def allocated(self) -> Resource:
        res = self._alloc
        if res is None:
            from ..models.incremental import _drf_alloc_of
            res = self._alloc = _drf_alloc_of(self._job).clone()
        return res

    @allocated.setter
    def allocated(self, res: Resource) -> None:
        self._alloc = res


class DrfPlugin(Plugin):

    def __init__(self, arguments: Arguments):
        self.arguments = arguments
        self.total_resource = Resource.empty()
        self.job_attrs: Dict[str, _DrfAttr] = {}

    def name(self) -> str:
        return "drf"

    def _calculate_share(self, allocated: Resource) -> float:
        res = 0.0
        for rn in self.total_resource.resource_names():
            s = share(allocated.get(rn), self.total_resource.get(rn))
            if s > res:
                res = s
        return res

    def _update_share(self, attr: _DrfAttr) -> None:
        attr.share = self._calculate_share(attr.allocated)

    def on_session_open(self, ssn) -> None:
        from ..models.incremental import (cluster_total_allocatable,
                                          plugin_cache_enabled)
        reuse = plugin_cache_enabled(ssn.cache)

        # Total allocatable from the snapshot map's exact-int running
        # sum when available (doc/INCREMENTAL.md "floors"); the O(nodes)
        # walk stays for the control arm and fractional clusters.
        cached_total = cluster_total_allocatable(ssn)
        if cached_total is not None:
            self.total_resource = cached_total
        else:
            for node in ssn.nodes.values():
                self.total_resource.add(node.allocatable)

        # Incremental open (doc/INCREMENTAL.md): the per-job allocated
        # aggregate is cached on the job CLONE, so the O(all allocated
        # tasks) walk runs only for clones the informers (or a session)
        # touched — clone identity is the validity token (a mutated
        # clone is discarded from the snapshot pool and never served
        # again).  Exact by construction: the cached Resource was built
        # by this very walk and is cloned back out, so shares equal the
        # uncached path bit for bit.  KUBE_BATCH_TPU_INCREMENTAL=0
        # restores the unconditional walk (the parity control).
        # Per-tenant accounting rider (metrics/tenants.py): the largest
        # job share inside each queue, collected in the SAME walk (one
        # compare per job, both churn-A/B arms identical).
        #
        # Wire fast path (doc/INCREMENTAL.md "Wire fast path"): the
        # per-job ``_calculate_share`` recompute — a Python loop over
        # resource names per job, the drf half of the plugin floor —
        # collapses into ONE vectorized column op over the persistent
        # per-job allocation matrix, patched for dirty jobs only
        # (models/incremental.drf_open_shares documents the bit-parity
        # argument).  KUBE_BATCH_TPU_WIRE_FAST=0 restores this loop.
        from ..models.incremental import drf_open_shares
        agg = drf_open_shares(ssn, self.total_resource) if reuse else None
        q_max: dict = {}
        if agg is not None:
            shares = agg.shares
            index = agg.index
            for uid, job in ssn.jobs.items():
                attr = _DrfAttr()
                attr._alloc = None  # lazy: _drf_open_alloc.clone()
                attr._job = job
                attr.share = float(shares[index[uid]])
                self.job_attrs[uid] = attr
                q_cur = q_max.get(job.queue)
                if q_cur is None or attr.share > q_cur:
                    q_max[job.queue] = attr.share
        else:
            for job in ssn.jobs.values():
                attr = _DrfAttr()
                cached = getattr(job, "_drf_open_alloc", None) if reuse \
                    else None
                if cached is not None:
                    attr.allocated = cached.clone()
                else:
                    for status, tasks in job.task_status_index.items():
                        if allocated_status(status):
                            for t in tasks.values():
                                attr.allocated.add(t.resreq)
                    if reuse:
                        job._drf_open_alloc = attr.allocated.clone()
                self._update_share(attr)
                self.job_attrs[job.uid] = attr
                q_cur = q_max.get(job.queue)
                if q_cur is None or attr.share > q_cur:
                    q_max[job.queue] = attr.share
        from ..metrics.tenants import tenant_table
        # Shard-scoped sessions merge over their own queue universe —
        # the shard map's membership test, so deleted queues still
        # depart (doc/TENANCY.md): see the proportion open's publish.
        universe = (ssn.cache.owns_queue if getattr(ssn, "shard", None)
                    is not None else None)
        tenant_table.note_drf_job_shares(q_max, universe=universe)

        def preemptable_fn(preemptor: TaskInfo,
                           preemptees: List[TaskInfo]) -> List[TaskInfo]:
            """Victim ok iff preemptor's post-allocation share stays below
            victim's post-eviction share (drf.go:85-112)."""
            latt = self.job_attrs[preemptor.job]
            lalloc = latt.allocated.clone().add(preemptor.resreq)
            ls = self._calculate_share(lalloc)

            allocations: Dict[str, Resource] = {}
            victims = []
            for preemptee in preemptees:
                if preemptee.job not in allocations:
                    ratt = self.job_attrs[preemptee.job]
                    allocations[preemptee.job] = ratt.allocated.clone()
                ralloc = allocations[preemptee.job].sub(preemptee.resreq)
                rs = self._calculate_share(ralloc)
                if ls < rs or math.isclose(ls, rs, abs_tol=SHARE_DELTA):
                    victims.append(preemptee)
            return victims

        ssn.add_preemptable_fn(self.name(), preemptable_fn)

        def job_order_fn(l: JobInfo, r: JobInfo) -> int:
            ls = self.job_attrs[l.uid].share
            rs = self.job_attrs[r.uid].share
            if ls == rs:
                return 0
            return -1 if ls < rs else 1

        ssn.add_job_order_fn(self.name(), job_order_fn)

        def on_allocate(event):
            attr = self.job_attrs[event.task.job]
            attr.allocated.add(event.task.resreq)
            self._update_share(attr)

        def on_deallocate(event):
            attr = self.job_attrs[event.task.job]
            attr.allocated.sub(event.task.resreq)
            self._update_share(attr)

        def on_batch_allocate(batch):
            # Linear in tasks: one aggregate add + share update per job.
            if batch.job_sums is not None:
                for uid, res in batch.job_sums.items():
                    attr = self.job_attrs.get(uid)
                    if attr is not None:
                        attr.allocated.add(res)
                        self._update_share(attr)
                return
            touched = set()
            for task in batch.tasks:
                attr = self.job_attrs[task.job]
                attr.allocated.add(task.resreq)
                touched.add(task.job)
            for uid in touched:
                self._update_share(self.job_attrs[uid])

        ssn.add_event_handler(EventHandler(allocate_func=on_allocate,
                                           deallocate_func=on_deallocate,
                                           batch_allocate_func=on_batch_allocate))

    def on_session_close(self, ssn) -> None:
        self.total_resource = Resource.empty()
        self.job_attrs = {}


def new(arguments: Arguments) -> DrfPlugin:
    return DrfPlugin(arguments)
