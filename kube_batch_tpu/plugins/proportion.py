"""Proportion plugin: weighted max-min fair queue shares.

Mirrors /root/reference/pkg/scheduler/plugins/proportion/proportion.go:
iterative water-filling of per-queue ``deserved`` by weight, capped at each
queue's total request, redistributing surplus until nothing remains
(:101-154); queue order by share; Reclaimable keeps queues at >= deserved;
Overused when deserved <= allocated.

The water-filling fixed point is also implemented on-device as a
``lax.while_loop`` in ``ops.fairness.proportion_deserved``; this host version
is the parity oracle.
"""

from __future__ import annotations

import time
from typing import Dict, List

from ..api import (QueueInfo, Resource, TaskInfo, TaskStatus,
                   allocated_status, minimum, share)
from ..framework import Arguments, EventHandler, Plugin


class _QueueAttr:
    __slots__ = ("queue_id", "name", "weight", "share", "deserved",
                 "allocated", "request")

    def __init__(self, queue_id: str, name: str, weight: int):
        self.queue_id = queue_id
        self.name = name
        self.weight = weight
        self.share = 0.0
        self.deserved = Resource.empty()
        self.allocated = Resource.empty()
        self.request = Resource.empty()


class ProportionPlugin(Plugin):

    def __init__(self, arguments: Arguments):
        self.arguments = arguments
        self.total_resource = Resource.empty()
        self.queue_attrs: Dict[str, _QueueAttr] = {}

    def name(self) -> str:
        return "proportion"

    def _update_share(self, attr: _QueueAttr) -> None:
        res = 0.0
        for rn in attr.deserved.resource_names():
            s = share(attr.allocated.get(rn), attr.deserved.get(rn))
            if s > res:
                res = s
        attr.share = res

    def on_session_open(self, ssn) -> None:
        from ..models.incremental import cluster_total_allocatable
        cached_total = cluster_total_allocatable(ssn)
        if cached_total is not None:
            # Snapshot-map running sum (exact-int gated): identical
            # floats to the walk below (doc/INCREMENTAL.md "floors").
            self.total_resource = cached_total
        else:
            for node in ssn.nodes.values():
                self.total_resource.add(node.allocatable)

        # Aggregate allocated/request per queue (proportion.go:69-99).
        # Incremental open (doc/INCREMENTAL.md): a job clone the
        # informers have not touched contributes the same per-task add
        # sequence every cycle, so its (allocated, request) subtotal is
        # cached on the clone and added in ONE step.  Caching is gated
        # on every contributing value being an exact binary integer
        # (models/incremental.resource_exact): integer partial sums are
        # exactly representable, so the collapsed add equals the
        # per-task sequence bit for bit — fractional quantities keep
        # the original walk and are never cached.  The clone is the
        # validity token (mutated clones leave the snapshot pool).
        # KUBE_BATCH_TPU_INCREMENTAL=0 restores the unconditional walk.
        from ..models.incremental import (plugin_cache_enabled,
                                          resource_exact)
        reuse = plugin_cache_enabled(ssn.cache)
        # Per-queue rolling exactness: a collapsed add is only exact
        # while the queue ACCUMULATOR is still an exact integer — one
        # fractional job earlier in the walk poisons every later
        # collapsed add of that queue (acc + (t1+..+tn) reassociates vs
        # ((acc+t1)+..)+tn once acc is fractional).  The prefix before
        # the first fractional contribution is integer-exact in both
        # arms, so gating consumption on the running flag is airtight.
        q_exact: Dict[str, bool] = {}
        # Per-tenant fairness accounting (metrics/tenants.py): pending
        # demand + the oldest still-waiting job per queue, tracked inside
        # the SAME O(jobs) walk the open already does (two dict ops per
        # job — no new cluster walk, identical in both churn-A/B arms).
        q_pending: Dict[str, list] = {}  # queue -> [n_jobs, oldest_ts]
        for job in ssn.jobs.values():
            if job.queue not in self.queue_attrs:
                queue = ssn.queues.get(job.queue)
                if queue is None:
                    continue
                self.queue_attrs[job.queue] = _QueueAttr(
                    queue.uid, queue.name, queue.weight)
            attr = self.queue_attrs[job.queue]
            if job.task_status_index.get(TaskStatus.Pending):
                # A zero/missing creationTimestamp is UNKNOWN, not the
                # epoch: it must not win the oldest-waiter min, or a
                # wire PodGroup without the field reports ~55 years of
                # starvation.  inf never wins and yields 0.0 age when
                # every pending job's timestamp is unknown.
                ts = job.creation_timestamp or float("inf")
                pend = q_pending.get(job.queue)
                if pend is None:
                    q_pending[job.queue] = [1, ts]
                else:
                    pend[0] += 1
                    if ts < pend[1]:
                        pend[1] = ts
            qe = q_exact.get(job.queue, True)
            cached = getattr(job, "_prop_open_agg", None) \
                if reuse and qe else None
            if cached is not None:
                # Cached subtotals are exact by construction, so the
                # queue accumulator stays exact.
                attr.allocated.add(cached[0])
                attr.request.add(cached[1])
                continue
            if reuse and qe:
                alloc_sub = Resource.empty()
                req_sub = Resource.empty()
                exact = True
                for status, tasks in job.task_status_index.items():
                    if allocated_status(status):
                        for t in tasks.values():
                            attr.allocated.add(t.resreq)
                            attr.request.add(t.resreq)
                            alloc_sub.add(t.resreq)
                            req_sub.add(t.resreq)
                            if exact and not resource_exact(t.resreq):
                                exact = False
                    elif status == TaskStatus.Pending:
                        for t in tasks.values():
                            attr.request.add(t.resreq)
                            req_sub.add(t.resreq)
                            if exact and not resource_exact(t.resreq):
                                exact = False
                # Subtotal bound too: requests are non-negative, so an
                # in-range subtotal bounds every partial sum the control
                # walk passes through — the collapsed add stays exact.
                if exact and resource_exact(alloc_sub) \
                        and resource_exact(req_sub):
                    job._prop_open_agg = (alloc_sub, req_sub)
                else:
                    # The accumulator may be fractional from here on:
                    # no later job of this queue may consume a cached
                    # subtotal this session.
                    q_exact[job.queue] = False
                continue
            for status, tasks in job.task_status_index.items():
                if allocated_status(status):
                    for t in tasks.values():
                        attr.allocated.add(t.resreq)
                        attr.request.add(t.resreq)
                elif status == TaskStatus.Pending:
                    for t in tasks.values():
                        attr.request.add(t.resreq)

        # Water-filling of deserved (proportion.go:101-154).
        remaining = self.total_resource.clone()
        meet: Dict[str, bool] = {}
        while True:
            total_weight = sum(a.weight for a in self.queue_attrs.values()
                               if a.queue_id not in meet)
            if total_weight == 0:
                break
            increased = Resource.empty()
            decreased = Resource.empty()
            for attr in self.queue_attrs.values():
                if attr.queue_id in meet:
                    continue
                old_deserved = attr.deserved.clone()
                attr.deserved.add(
                    remaining.clone().multi(attr.weight / total_weight))
                if attr.request.less(attr.deserved):
                    attr.deserved = minimum(attr.deserved, attr.request)
                    meet[attr.queue_id] = True
                self._update_share(attr)
                inc, dec = attr.deserved.diff(old_deserved)
                increased.add(inc)
                decreased.add(dec)
            remaining.sub(increased).add(decreased)
            if remaining.is_empty():
                break

        # Publish the session's fairness table (ROADMAP item 3's
        # "fairness across tenants surfaced in /metrics and /debug"):
        # every number below already exists in the attrs the
        # water-filling just produced — this only formats and hands it
        # to metrics/tenants.py.  A queue is STARVED this session when
        # it still has pending demand while holding less than its
        # deserved share (share < 1 means under-deserved on every
        # dimension proportion tracks).
        from ..metrics.tenants import dominant_share, tenant_table
        now = time.time()
        rows: Dict[str, dict] = {}
        for attr in self.queue_attrs.values():
            pend = q_pending.get(attr.name, (0, now))
            starvation = max(0.0, now - pend[1]) if pend[0] else 0.0
            rows[attr.name] = {
                "weight": attr.weight,
                "share": round(attr.share, 4),
                "deserved_share": round(dominant_share(
                    attr.deserved, self.total_resource), 4),
                "allocated_share": round(dominant_share(
                    attr.allocated, self.total_resource), 4),
                "request_share": round(dominant_share(
                    attr.request, self.total_resource), 4),
                "pending_jobs": pend[0],
                "starvation_s": round(starvation, 3),
                "starved": bool(pend[0]) and attr.share < 1.0,
            }
        # Shard-scoped sessions (doc/TENANCY.md) publish a MERGE over
        # their own queue universe: shard A's table write must not zero
        # shard B's gauges the way a wholesale replace would.  The
        # universe is the shard map's MEMBERSHIP TEST, not the session's
        # queue set — a deleted queue is in no session's queues but its
        # stale row is still this shard's departure to zero.
        universe = (ssn.cache.owns_queue if getattr(ssn, "shard", None)
                    is not None else None)
        tenant_table.publish(rows, session_uid=ssn.uid, universe=universe)

        def queue_order_fn(l: QueueInfo, r: QueueInfo) -> int:
            ls = self.queue_attrs[l.uid].share
            rs = self.queue_attrs[r.uid].share
            if ls == rs:
                return 0
            return -1 if ls < rs else 1

        ssn.add_queue_order_fn(self.name(), queue_order_fn)

        def reclaimable_fn(reclaimer: TaskInfo,
                           reclaimees: List[TaskInfo]) -> List[TaskInfo]:
            """Victim ok if its queue stays at or above deserved
            (proportion.go:171-196)."""
            victims = []
            allocations: Dict[str, Resource] = {}
            for reclaimee in reclaimees:
                job = ssn.jobs[reclaimee.job]
                attr = self.queue_attrs[job.queue]
                if job.queue not in allocations:
                    allocations[job.queue] = attr.allocated.clone()
                allocated = allocations[job.queue]
                if allocated.less(reclaimee.resreq):
                    continue
                allocated.sub(reclaimee.resreq)
                if attr.deserved.less_equal(allocated):
                    victims.append(reclaimee)
            return victims

        ssn.add_reclaimable_fn(self.name(), reclaimable_fn)

        def overused_fn(queue: QueueInfo) -> bool:
            attr = self.queue_attrs.get(queue.uid)
            if attr is None:
                return False
            return attr.deserved.less_equal(attr.allocated)

        ssn.add_overused_fn(self.name(), overused_fn)

        def on_allocate(event):
            job = ssn.jobs[event.task.job]
            attr = self.queue_attrs[job.queue]
            attr.allocated.add(event.task.resreq)
            self._update_share(attr)

        def on_deallocate(event):
            job = ssn.jobs[event.task.job]
            attr = self.queue_attrs[job.queue]
            attr.allocated.sub(event.task.resreq)
            self._update_share(attr)

        def on_batch_allocate(batch):
            # Linear in tasks: one aggregate add + share update per queue.
            touched = set()
            if batch.job_sums is not None:
                for uid, res in batch.job_sums.items():
                    job = ssn.jobs.get(uid)
                    if job is None:
                        continue
                    attr = self.queue_attrs.get(job.queue)
                    if attr is not None:
                        attr.allocated.add(res)
                        touched.add(job.queue)
            else:
                for task in batch.tasks:
                    job = ssn.jobs[task.job]
                    attr = self.queue_attrs[job.queue]
                    attr.allocated.add(task.resreq)
                    touched.add(job.queue)
            for qid in touched:
                self._update_share(self.queue_attrs[qid])

        ssn.add_event_handler(EventHandler(allocate_func=on_allocate,
                                           deallocate_func=on_deallocate,
                                           batch_allocate_func=on_batch_allocate))

    def on_session_close(self, ssn) -> None:
        self.total_resource = Resource.empty()
        self.queue_attrs = {}


def new(arguments: Arguments) -> ProportionPlugin:
    return ProportionPlugin(arguments)
