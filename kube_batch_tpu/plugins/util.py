"""Session-view helpers for affinity-style plugins.

Counterpart of /root/reference/pkg/scheduler/plugins/util/util.go: a
PodLister whose pods reflect *in-session* placements (NodeName overridden to
the session's assignment) and a cached node-info adapter, used by
data-dependent predicates like inter-pod affinity.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional

from ..api import NodeInfo, TaskInfo, TaskStatus, allocated_status


class PodLister:
    """Lists session pods with node names reflecting current assignments
    (util.go:33-85)."""

    def __init__(self, ssn):
        self.ssn = ssn

    def list(self, selector: Optional[Dict[str, str]] = None) -> List:
        pods = []
        for job in self.ssn.jobs.values():
            for task in job.tasks.values():
                pod = task.pod
                if selector and not all(
                        pod.metadata.labels.get(k) == v
                        for k, v in selector.items()):
                    continue
                # Present the session's placement, not the cluster's.
                if task.node_name and task.node_name != pod.spec.node_name:
                    clone = type(pod)(metadata=pod.metadata,
                                      spec=type(pod.spec)(**vars(pod.spec)),
                                      status=pod.status)
                    clone.spec.node_name = task.node_name
                    pod = clone
                pods.append(pod)
        return pods


class CachedNodeInfo:
    """Node lookup for predicate adapters (util.go:87-114)."""

    def __init__(self, ssn):
        self.ssn = ssn

    def get_node_info(self, name: str) -> NodeInfo:
        node = self.ssn.nodes.get(name)
        if node is None:
            raise KeyError(f"failed to find node {name}")
        return node
