"""Predicates plugin: node feasibility checks.

The reference wraps the upstream kube-scheduler predicate library
(/root/reference/pkg/scheduler/plugins/predicates/predicates.go:123-265):
pod-count cap, unschedulable node, node selector + required node affinity,
host ports, taints/tolerations, and inter-pod (anti-)affinity evaluated
against the session's in-flight assignments (plugins/util/util.go PodLister).
This is a standalone reimplementation of those checks over our object model.

Each check is also expressible as a static [tasks x nodes] boolean mask on
TPU (ops/feasibility.py); inter-pod affinity is the one dynamic mask that
must refresh as the assignment loop progresses, which both paths honor (the
host path by scanning ``node.tasks``, the device path by re-masking inside
the solver loop).
"""

from __future__ import annotations

from ..api import FitError, NodeInfo, TaskInfo
from ..framework import Arguments, Plugin

# Argument keys (predicates.go:33-40).
MEMORY_PRESSURE_PREDICATE = "predicate.MemoryPressureEnable"
DISK_PRESSURE_PREDICATE = "predicate.DiskPressureEnable"
PID_PRESSURE_PREDICATE = "predicate.PIDPressureEnable"


def pod_matches_node_selector(task: TaskInfo, node: NodeInfo) -> bool:
    labels = node.node.metadata.labels if node.node else {}
    for key, value in task.pod.spec.node_selector.items():
        if labels.get(key) != value:
            return False
    affinity = task.pod.spec.affinity
    if affinity is not None and affinity.required_node_terms:
        # OR of ANDs over label terms.
        for term in affinity.required_node_terms:
            if all(labels.get(k) == v for k, v in term.items()):
                break
        else:
            return False
    return True


def tolerates_node_taints(task: TaskInfo, node: NodeInfo) -> bool:
    taints = node.node.spec.taints if node.node else []
    for taint in taints:
        if taint.effect == "PreferNoSchedule":
            continue
        if not any(t.tolerates(taint) for t in task.pod.spec.tolerations):
            return False
    return True


def host_ports_conflict(task: TaskInfo, node: NodeInfo) -> bool:
    wanted = {(p.host_port, p.protocol)
              for c in task.pod.spec.containers for p in c.ports
              if p.host_port > 0}
    if not wanted:
        return False
    for other in node.tasks.values():
        for c in other.pod.spec.containers:
            for p in c.ports:
                if p.host_port > 0 and (p.host_port, p.protocol) in wanted:
                    return True
    return False


def _labels_match(selector: dict, labels: dict) -> bool:
    return all(labels.get(k) == v for k, v in selector.items())


def pod_affinity_ok(task: TaskInfo, node: NodeInfo) -> bool:
    """Required pod affinity / anti-affinity against the node's current
    session-view tasks (topology key = hostname).  Reads ``node.tasks``,
    which includes in-session assignments — the moral equivalent of the
    reference's session-backed PodLister (plugins/util/util.go:33-114)."""
    affinity = task.pod.spec.affinity
    if affinity is None:
        return True
    if affinity.required_pod_affinity:
        for selector in affinity.required_pod_affinity:
            if not any(_labels_match(selector, other.pod.metadata.labels)
                       for other in node.tasks.values()):
                return False
    if affinity.required_pod_anti_affinity:
        for selector in affinity.required_pod_anti_affinity:
            for other in node.tasks.values():
                if other.uid == task.uid:
                    continue
                if _labels_match(selector, other.pod.metadata.labels):
                    return False
    return True


class PredicatesPlugin(Plugin):

    def __init__(self, arguments: Arguments):
        self.arguments = arguments
        # Pressure checks are opt-in via Arguments (predicates.go:71-110,
        # defaults false).
        self.check_memory = arguments.get_bool(MEMORY_PRESSURE_PREDICATE)
        self.check_disk = arguments.get_bool(DISK_PRESSURE_PREDICATE)
        self.check_pid = arguments.get_bool(PID_PRESSURE_PREDICATE)

    def name(self) -> str:
        return "predicates"

    def on_session_open(self, ssn) -> None:
        # NODE READ-SET CONTRACT: the static checks below read, per node,
        # exactly {the five named conditions, allocatable.max_task_num vs
        # len(node.tasks), spec.unschedulable, spec.taints (non-
        # PreferNoSchedule), labels at keys the task references} — plus
        # the dynamic ports/pod-affinity occupancy re-evaluated in-loop.
        # models/tensor_snapshot.py collapses nodes into static profiles
        # keyed on THIS read-set before evaluating the chain; if a new
        # node-dependent check is added here, the profile key there MUST
        # gain the field or nodes differing only in it will silently share
        # a verdict (tests/test_tensorize_hetero.py pins exactness only
        # over fields the key already covers).
        def predicate_fn(task: TaskInfo, node: NodeInfo) -> None:
            if node.node is None:
                raise FitError(task, node, "node not initialized")
            conditions = node.node.status.conditions
            # NodeCondition predicate (predicates.go:132-146; upstream
            # CheckNodeConditionPredicate, vendored predicates.go:1675-1698):
            # schedulable only when a REPORTED Ready condition is "True" and
            # a reported NetworkUnavailable is "False" (absent conditions
            # pass — upstream iterates only present ones).  The snapshot
            # usually excludes such nodes already; this is the
            # per-predicate form with its distinct messages.
            ready = conditions.get("Ready")
            if ready is not None and ready != "True":
                raise FitError(task, node, "node(s) were not ready")
            net = conditions.get("NetworkUnavailable")
            if net is not None and net != "False":
                raise FitError(task, node,
                               "node(s) had unavailable network")
            # Node pressure conditions (predicates.go:201-247).
            if self.check_memory and conditions.get("MemoryPressure") == "True":
                raise FitError(task, node, "node has memory pressure")
            if self.check_disk and conditions.get("DiskPressure") == "True":
                raise FitError(task, node, "node has disk pressure")
            if self.check_pid and conditions.get("PIDPressure") == "True":
                raise FitError(task, node, "node has pid pressure")
            # Pod-count cap (predicates.go:127).
            if node.allocatable.max_task_num <= len(node.tasks):
                raise FitError(task, node, "node has too many pods")
            # Unschedulable node (predicates.go:146).
            if node.node.spec.unschedulable:
                raise FitError(task, node, "node unschedulable")
            # Node selector + required node affinity (predicates.go:160).
            if not pod_matches_node_selector(task, node):
                raise FitError(task, node, "node didn't match node selector")
            # Host ports (predicates.go:174).
            if host_ports_conflict(task, node):
                raise FitError(task, node, "node didn't have free ports")
            # Taints/tolerations (predicates.go:188).
            if not tolerates_node_taints(task, node):
                raise FitError(task, node, "taints not tolerated")
            # Inter-pod (anti-)affinity (predicates.go:249-262).
            if not pod_affinity_ok(task, node):
                raise FitError(task, node, "pod affinity/anti-affinity mismatch")

        ssn.add_predicate_fn(self.name(), predicate_fn)

    def on_session_close(self, ssn) -> None:
        pass


def new(arguments: Arguments) -> PredicatesPlugin:
    return PredicatesPlugin(arguments)
