"""Priority plugin: task/job ordering and preemption by priority.

Mirrors /root/reference/pkg/scheduler/plugins/priority/priority.go.
"""

from __future__ import annotations

from typing import List

from ..api import JobInfo, TaskInfo
from ..framework import Arguments, Plugin


class PriorityPlugin(Plugin):

    def __init__(self, arguments: Arguments):
        self.arguments = arguments

    def name(self) -> str:
        return "priority"

    def on_session_open(self, ssn) -> None:
        def task_order_fn(l: TaskInfo, r: TaskInfo) -> int:
            # Higher pod priority first (priority.go:39-58).
            if l.priority == r.priority:
                return 0
            return -1 if l.priority > r.priority else 1

        ssn.add_task_order_fn(self.name(), task_order_fn)
        # Static-key form of the same order (higher priority first, so
        # ascending key = negated priority); enables sorted-drain task
        # queues in the actions.
        ssn.add_task_order_key_fn(self.name(), lambda t: -t.priority)

        def job_order_fn(l: JobInfo, r: JobInfo) -> int:
            # Higher PriorityClass value first (priority.go:61-79).
            if l.priority > r.priority:
                return -1
            if l.priority < r.priority:
                return 1
            return 0

        ssn.add_job_order_fn(self.name(), job_order_fn)

        def preemptable_fn(preemptor: TaskInfo,
                           preemptees: List[TaskInfo]) -> List[TaskInfo]:
            # Only strictly-lower-priority jobs are victims (priority.go:81-100).
            preemptor_job = ssn.jobs[preemptor.job]
            victims = []
            for preemptee in preemptees:
                preemptee_job = ssn.jobs[preemptee.job]
                if preemptee_job.priority < preemptor_job.priority:
                    victims.append(preemptee)
            return victims

        ssn.add_preemptable_fn(self.name(), preemptable_fn)


def new(arguments: Arguments) -> PriorityPlugin:
    return PriorityPlugin(arguments)
