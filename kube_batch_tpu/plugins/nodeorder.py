"""Nodeorder plugin: node scoring.

The reference wraps upstream kube-scheduler priorities with YAML-tunable
weights (/root/reference/pkg/scheduler/plugins/nodeorder/nodeorder.go:27-38,
107-168): LeastRequested (w=1), MostRequested (w=0), BalancedResource (w=1),
NodeAffinity (w=1), InterPodAffinity (w=1).  These are standalone
reimplementations of those scoring formulas; the identical math runs
vectorized on TPU in ops/scoring.py, which parity tests check against this
host path.
"""

from __future__ import annotations

from ..api import NodeInfo, TaskInfo
from ..framework import Arguments, Plugin

# Argument keys (nodeorder.go:41-66).
NODE_AFFINITY_WEIGHT = "nodeaffinity.weight"
POD_AFFINITY_WEIGHT = "podaffinity.weight"
LEAST_REQUESTED_WEIGHT = "leastrequested.weight"
BALANCED_RESOURCE_WEIGHT = "balancedresource.weight"
MOST_REQUESTED_WEIGHT = "mostrequested.weight"

MAX_PRIORITY = 10.0


def _fractions(task: TaskInfo, node: NodeInfo):
    """Projected cpu/memory utilization fractions if task lands on node."""
    cpu_alloc = node.allocatable.milli_cpu
    mem_alloc = node.allocatable.memory
    cpu_req = node.used.milli_cpu + task.resreq.milli_cpu
    mem_req = node.used.memory + task.resreq.memory
    cpu_frac = 1.0 if cpu_alloc == 0 else min(cpu_req / cpu_alloc, 1.0)
    mem_frac = 1.0 if mem_alloc == 0 else min(mem_req / mem_alloc, 1.0)
    return cpu_frac, mem_frac


def least_requested_score(task: TaskInfo, node: NodeInfo) -> float:
    """Mean over cpu/mem of (free after placement) * 10 / allocatable
    (upstream least_requested.go semantics)."""
    cpu_frac, mem_frac = _fractions(task, node)
    return ((1.0 - cpu_frac) * MAX_PRIORITY + (1.0 - mem_frac) * MAX_PRIORITY) / 2.0


def most_requested_score(task: TaskInfo, node: NodeInfo) -> float:
    cpu_frac, mem_frac = _fractions(task, node)
    return (cpu_frac * MAX_PRIORITY + mem_frac * MAX_PRIORITY) / 2.0


def balanced_resource_score(task: TaskInfo, node: NodeInfo) -> float:
    """10 - |cpuFraction - memFraction| * 10 (upstream
    balanced_resource_allocation.go)."""
    cpu_frac, mem_frac = _fractions(task, node)
    return MAX_PRIORITY - abs(cpu_frac - mem_frac) * MAX_PRIORITY


def node_affinity_score(task: TaskInfo, node: NodeInfo) -> float:
    """Sum of matching preferred-node-affinity term weights (upstream
    node_affinity.go map phase; we skip the max-normalizing reduce so the
    score stays a pure per-(task,node) function — weights act directly)."""
    affinity = task.pod.spec.affinity
    if affinity is None or not affinity.preferred_node_terms:
        return 0.0
    labels = node.node.metadata.labels if node.node else {}
    score = 0.0
    for weight, term in affinity.preferred_node_terms:
        if all(labels.get(k) == v for k, v in term.items()):
            score += weight
    return score


class NodeOrderPlugin(Plugin):

    def __init__(self, arguments: Arguments):
        self.arguments = arguments

    def name(self) -> str:
        return "nodeorder"

    def weights(self):
        a = self.arguments
        return {
            "leastrequested": a.get_float(LEAST_REQUESTED_WEIGHT, 1.0),
            "mostrequested": a.get_float(MOST_REQUESTED_WEIGHT, 0.0),
            "balancedresource": a.get_float(BALANCED_RESOURCE_WEIGHT, 1.0),
            "nodeaffinity": a.get_float(NODE_AFFINITY_WEIGHT, 1.0),
        }

    def on_session_open(self, ssn) -> None:
        w = self.weights()
        prioritizers = []
        if w["leastrequested"]:
            prioritizers.append((w["leastrequested"], least_requested_score))
        if w["mostrequested"]:
            prioritizers.append((w["mostrequested"], most_requested_score))
        if w["balancedresource"]:
            prioritizers.append((w["balancedresource"], balanced_resource_score))
        if w["nodeaffinity"]:
            prioritizers.append((w["nodeaffinity"], node_affinity_score))
        ssn.add_node_order_fns(self.name(), prioritizers)

    def on_session_close(self, ssn) -> None:
        pass


def new(arguments: Arguments) -> NodeOrderPlugin:
    return NodeOrderPlugin(arguments)
