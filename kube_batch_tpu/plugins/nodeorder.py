"""Nodeorder plugin: node scoring on the integer grid.

The reference wraps upstream kube-scheduler priorities with YAML-tunable
weights (/root/reference/pkg/scheduler/plugins/nodeorder/nodeorder.go:27-38,
107-168): LeastRequested (w=1), MostRequested (w=0), BalancedResource (w=1),
NodeAffinity (w=1), InterPodAffinity (w=1).  These are standalone
reimplementations of those scoring formulas.

Scores are **exact integers** on the shared SCORE_GRID_K fraction grid
(ops/resources.py): utilization is tracked in quantized int quanta —
initialized from the snapshot, updated per placement through session event
handlers (the same incremental pattern drf/proportion use) — so this host
path and the vectorized device path (ops/scoring.py) produce identical
score integers on every platform.  Affinity term scores scale by the same
grid constant, preserving the reference's relative weighting.
"""

from __future__ import annotations

from typing import Dict, Tuple

from ..api import NodeInfo, TaskInfo
from ..framework import Arguments, Plugin
from ..framework.events import EventHandler
import numpy as np

from ..ops.resources import (SCORE_GRID_K, grid_fraction_int,
                             quantize_columns, quantize_value,
                             score_shift_for)

# Argument keys (nodeorder.go:41-66).
NODE_AFFINITY_WEIGHT = "nodeaffinity.weight"
POD_AFFINITY_WEIGHT = "podaffinity.weight"
LEAST_REQUESTED_WEIGHT = "leastrequested.weight"
BALANCED_RESOURCE_WEIGHT = "balancedresource.weight"
MOST_REQUESTED_WEIGHT = "mostrequested.weight"

MAX_PRIORITY = 10


class GridUsage:
    """Quantized per-node (cpu, mem) usage mirror for grid scoring.

    Must accumulate the same int quanta the device adds (q(a)+q(b), not
    q(a+b)) or sub-quantum requests would round differently on the two
    paths."""

    def __init__(self, ssn):
        self.cap: Dict[str, Tuple[int, int]] = {}
        self.used: Dict[str, Tuple[int, int]] = {}
        # Snapshot-map fast path (doc/INCREMENTAL.md "floors"): the
        # quantized per-node entries and the shift are maintained from
        # map-entry changes — same ints as the column pass below (the
        # per-value/column quantization identity this class documents).
        # The accessor hands private copies, so the live ``used``
        # mutation by the event handlers touches nothing shared.
        from ..models.incremental import node_open_aggregates
        agg = node_open_aggregates(ssn)
        if agg is not None:
            _total, cap, used, shift = agg
            self.cap = cap
            self.used = used
            self.shift = shift
            return
        names = list(ssn.nodes)
        if names:
            # Column-wise quantization (identical ints to per-value
            # quantize_value: same exact power-of-two scale + rint);
            # 4 numpy passes beat 4 Python calls per node.
            nodes = [ssn.nodes[n] for n in names]
            arr = np.empty((len(names), 2), np.float64)
            arr[:, 0] = [nd.allocatable.milli_cpu for nd in nodes]
            arr[:, 1] = [nd.allocatable.memory for nd in nodes]
            caps = quantize_columns(arr)
            arr[:, 0] = [nd.used.milli_cpu for nd in nodes]
            arr[:, 1] = [nd.used.memory for nd in nodes]
            useds = quantize_columns(arr)
            self.cap = {n: (int(c), int(m)) for n, (c, m)
                        in zip(names, caps.tolist())}
            self.used = {n: (int(c), int(m)) for n, (c, m)
                         in zip(names, useds.tolist())}
            max_cpu = int(caps[:, 0].max())
            max_mem = int(caps[:, 1].max())
        else:
            max_cpu = max_mem = 0
        self.shift = (score_shift_for(max_cpu), score_shift_for(max_mem))

    def task_quanta(self, task: TaskInfo) -> Tuple[int, int]:
        return (quantize_value(task.resreq.milli_cpu, 0),
                quantize_value(task.resreq.memory, 1))

    def add(self, task: TaskInfo) -> None:
        if task.node_name in self.used:
            uc, um = self.used[task.node_name]
            dc, dm = self.task_quanta(task)
            self.used[task.node_name] = (uc + dc, um + dm)

    def batch_add(self, batch) -> None:
        if batch.node_quanta is not None:
            # Exact: int sums of the same per-task quanta the device adds.
            for name, (dc, dm) in batch.node_quanta.items():
                if name in self.used:
                    uc, um = self.used[name]
                    self.used[name] = (uc + dc, um + dm)
            return
        for task in batch.tasks:
            self.add(task)

    def sub(self, task: TaskInfo) -> None:
        if task.node_name in self.used:
            uc, um = self.used[task.node_name]
            dc, dm = self.task_quanta(task)
            self.used[task.node_name] = (uc - dc, um - dm)

    def fractions(self, task: TaskInfo, node: NodeInfo) -> Tuple[int, int]:
        """Projected cpu/mem grid fractions if task lands on node."""
        cap = self.cap.get(node.name)
        if cap is None:  # node unknown to the session snapshot
            cap = (quantize_value(node.allocatable.milli_cpu, 0),
                   quantize_value(node.allocatable.memory, 1))
            self.cap[node.name] = cap
            self.used[node.name] = (quantize_value(node.used.milli_cpu, 0),
                                    quantize_value(node.used.memory, 1))
        uc, um = self.used[node.name]
        dc, dm = self.task_quanta(task)
        return (grid_fraction_int(uc + dc, cap[0], self.shift[0]),
                grid_fraction_int(um + dm, cap[1], self.shift[1]))


def least_requested_score(grid: GridUsage, task: TaskInfo,
                          node: NodeInfo) -> int:
    """Mean over cpu/mem of (free after placement) * 10 / allocatable,
    scaled by the grid (upstream least_requested.go semantics)."""
    gc, gm = grid.fractions(task, node)
    return 5 * (2 * SCORE_GRID_K - gc - gm)


def most_requested_score(grid: GridUsage, task: TaskInfo,
                         node: NodeInfo) -> int:
    gc, gm = grid.fractions(task, node)
    return 5 * (gc + gm)


def balanced_resource_score(grid: GridUsage, task: TaskInfo,
                            node: NodeInfo) -> int:
    """10 - |cpuFraction - memFraction| * 10, grid-scaled (upstream
    balanced_resource_allocation.go)."""
    gc, gm = grid.fractions(task, node)
    return 10 * SCORE_GRID_K - 10 * abs(gc - gm)


def interpod_affinity_score(task: TaskInfo, node: NodeInfo) -> int:
    """InterPodAffinity priority (the reference registers upstream
    CalculateInterPodAffinityPriority, nodeorder.go:107-131): sum of
    preferred pod-affinity term weights times matching-pod counts on the
    node (hostname topology), minus the anti-affinity terms.  Like the
    node-affinity scorer we skip upstream's max-normalizing reduce so the
    score stays a pure per-(task, node) integer, grid-scaled to combine
    with the fraction scores.  The session view of ``node.tasks`` includes
    in-flight placements, mirroring the reference's session PodLister."""
    affinity = task.pod.spec.affinity
    if affinity is None or not (affinity.preferred_pod_affinity
                                or affinity.preferred_pod_anti_affinity):
        return 0
    score = 0
    for weight, sel in affinity.preferred_pod_affinity:
        score += weight * sum(
            1 for o in node.tasks.values()
            if all(o.pod.metadata.labels.get(k) == v for k, v in sel.items()))
    for weight, sel in affinity.preferred_pod_anti_affinity:
        score -= weight * sum(
            1 for o in node.tasks.values()
            if all(o.pod.metadata.labels.get(k) == v for k, v in sel.items()))
    return score * SCORE_GRID_K


def node_affinity_score(task: TaskInfo, node: NodeInfo) -> int:
    """Sum of matching preferred-node-affinity term weights (upstream
    node_affinity.go map phase; we skip the max-normalizing reduce so the
    score stays a pure per-(task,node) function — weights act directly),
    grid-scaled to combine with the fraction scores."""
    affinity = task.pod.spec.affinity
    if affinity is None or not affinity.preferred_node_terms:
        return 0
    labels = node.node.metadata.labels if node.node else {}
    score = 0
    for weight, term in affinity.preferred_node_terms:
        if all(labels.get(k) == v for k, v in term.items()):
            score += weight
    return score * SCORE_GRID_K


class NodeOrderPlugin(Plugin):

    def __init__(self, arguments: Arguments):
        self.arguments = arguments

    def name(self) -> str:
        return "nodeorder"

    def weights(self):
        a = self.arguments
        return {
            "leastrequested": a.get_float(LEAST_REQUESTED_WEIGHT, 1.0),
            "mostrequested": a.get_float(MOST_REQUESTED_WEIGHT, 0.0),
            "balancedresource": a.get_float(BALANCED_RESOURCE_WEIGHT, 1.0),
            "nodeaffinity": a.get_float(NODE_AFFINITY_WEIGHT, 1.0),
            "podaffinity": a.get_float(POD_AFFINITY_WEIGHT, 1.0),
        }

    def on_session_open(self, ssn) -> None:
        w = self.weights()
        grid = GridUsage(ssn)
        ssn.add_event_handler(EventHandler(allocate_func=lambda e: grid.add(e.task),
                                           deallocate_func=lambda e: grid.sub(e.task),
                                           batch_allocate_func=grid.batch_add))
        prioritizers = []
        if w["leastrequested"]:
            prioritizers.append((w["leastrequested"],
                                 lambda t, n: least_requested_score(grid, t, n)))
        if w["mostrequested"]:
            prioritizers.append((w["mostrequested"],
                                 lambda t, n: most_requested_score(grid, t, n)))
        if w["balancedresource"]:
            prioritizers.append((w["balancedresource"],
                                 lambda t, n: balanced_resource_score(grid, t, n)))
        if w["nodeaffinity"]:
            prioritizers.append((w["nodeaffinity"], node_affinity_score))
        if w["podaffinity"]:
            prioritizers.append((w["podaffinity"], interpod_affinity_score))
        ssn.add_node_order_fns(self.name(), prioritizers)

    def on_session_close(self, ssn) -> None:
        pass


def new(arguments: Arguments) -> NodeOrderPlugin:
    return NodeOrderPlugin(arguments)
