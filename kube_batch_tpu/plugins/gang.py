"""Gang plugin: all-or-nothing co-scheduling on minAvailable.

Mirrors /root/reference/pkg/scheduler/plugins/gang/gang.go.
"""

from __future__ import annotations

import time
from typing import List

from ..api import JobInfo, TaskInfo, ValidateResult
from ..api.pod_group_info import PodGroupCondition, PodGroupUnschedulableType
from ..apis.scheduling.v1alpha1 import (NotEnoughPodsReason,
                                        NotEnoughResourcesReason)
from ..framework import Arguments, Plugin
from ..metrics import metrics


class GangPlugin(Plugin):

    def __init__(self, arguments: Arguments):
        self.arguments = arguments

    def name(self) -> str:
        return "gang"

    def on_session_open(self, ssn) -> None:
        def valid_job_fn(job: JobInfo):
            """JobValid: enough valid tasks to ever reach minAvailable
            (gang.go:48-69)."""
            vtn = job.valid_task_num()
            if vtn < job.min_available:
                return ValidateResult(
                    pass_=False, reason=NotEnoughPodsReason,
                    message=(f"Not enough valid tasks for gang-scheduling, "
                             f"valid: {vtn}, min: {job.min_available}"))
            return None

        ssn.add_job_valid_fn(self.name(), valid_job_fn)

        def preemptable_fn(preemptor: TaskInfo,
                           preemptees: List[TaskInfo]) -> List[TaskInfo]:
            """Veto victims whose job would drop below minAvailable
            (gang.go:71-94)."""
            victims = []
            for preemptee in preemptees:
                job = ssn.jobs[preemptee.job]
                occupied = job.ready_task_num()
                preemptable = (job.min_available <= occupied - 1
                               or job.min_available == 1)
                if preemptable:
                    victims.append(preemptee)
            return victims

        ssn.add_reclaimable_fn(self.name(), preemptable_fn)
        ssn.add_preemptable_fn(self.name(), preemptable_fn)

        def job_order_fn(l: JobInfo, r: JobInfo) -> int:
            """Not-ready jobs before ready jobs (gang.go:96-121)."""
            l_ready, r_ready = l.ready(), r.ready()
            if l_ready and r_ready:
                return 0
            if l_ready:
                return 1
            if r_ready:
                return -1
            return 0

        ssn.add_job_order_fn(self.name(), job_order_fn)
        ssn.add_job_ready_fn(self.name(), lambda job: job.ready())
        ssn.add_job_pipelined_fn(self.name(), lambda job: job.pipelined())

    def on_session_close(self, ssn) -> None:
        """Write Unschedulable conditions + metrics for not-ready jobs
        (gang.go:132-162).

        Wire fast path (doc/INCREMENTAL.md "Wire fast path"): the
        reference walks EVERY job to find the not-ready ones; the
        vectorized form reads the persistent per-job ready/minAvailable
        columns (models/incremental.gang_close_unready — open columns
        plus a re-read of this session's mutated jobs) so ready jobs
        cost no Python visit.  Unready jobs run the identical per-job
        body; KUBE_BATCH_TPU_WIRE_FAST=0 restores the full walk."""
        from ..models.incremental import gang_close_unready
        unready_jobs = gang_close_unready(ssn)
        if unready_jobs is None:
            unready_jobs = [job for job in ssn.jobs.values()
                            if not job.ready()]
        unschedulable_jobs = 0
        for job in unready_jobs:
            unready = job.min_available - job.ready_task_num()
            unschedulable_jobs += 1
            metrics.update_unschedule_task_count(job.name, int(unready))
            metrics.register_job_retries(job.name)
            if job.pod_group is None:
                continue
            msg = (f"{unready}/{len(job.tasks)} tasks in gang unschedulable: "
                   f"{job.fit_error()}")
            cond = PodGroupCondition(
                type=PodGroupUnschedulableType, status="True",
                transition_id=ssn.uid, last_transition_time=time.time(),
                reason=NotEnoughResourcesReason, message=msg)
            try:
                ssn.update_job_condition(job, cond)
            except KeyError:
                pass
        metrics.update_unschedule_job_count(unschedulable_jobs)


def new(arguments: Arguments) -> GangPlugin:
    return GangPlugin(arguments)
