"""Topology plugin: fragmentation-aware node scoring.

Registered as ``topology`` in the conf tiers (the same machinery every
other plugin rides), this plugin makes nodeorder prefer placements that
preserve large contiguous free blocks: a node whose torus neighbors are
already occupied (or absent) scores higher than one in the middle of a
free region, so flat (non-slice) pods pack tightly and leave room for
future slices (doc/TOPOLOGY.md "Fragmentation score").

Exactness contract: the bonus is computed ONCE per session at open —
``TopologyView.frag_bonus`` over the at-open occupancy — and stashed on
``ssn.prescan`` so models/tensor_snapshot.py folds the IDENTICAL
integers into the device solver's ``sig_bonus``.  Host prioritizer and
device score therefore cannot drift (same array, both sides); the bonus
is static for the session by design, like the preferred-node-affinity
static bonus it rides next to.

Weight: ``topology.frag.weight`` (default 1; integer — fractional
weights fall back to the host path like every other scoring weight).
With ``KUBE_BATCH_TPU_TOPOLOGY=0`` or no coordinate labels the plugin
registers nothing and both paths see zero — bit-parity with a conf that
never listed it.
"""

from __future__ import annotations

import numpy as np

from ..framework import Arguments, Plugin

FRAG_WEIGHT = "topology.frag.weight"


class TopologyPlugin(Plugin):

    def __init__(self, arguments: Arguments):
        self.arguments = arguments

    def name(self) -> str:
        return "topology"

    def frag_weight(self) -> float:
        return self.arguments.get_float(FRAG_WEIGHT, 1.0)

    def on_session_open(self, ssn) -> None:
        from ..models.topology import build_view, topology_enabled

        w = self.frag_weight()
        if not topology_enabled() or not w or w != int(w):
            return
        # Reuse the session's view when the topo action (which runs
        # after open) hasn't built one yet — open order means the plugin
        # builds it and the action reuses it via the same stash.
        view = ssn.prescan.get("topo_view")
        if view is None:
            # Cheap probe first (the topo action's discipline): an
            # unlabeled cluster must not pay an O(N) view build per
            # session just because the plugin is in the conf.
            from ..models.topology import POD_LABEL
            if not any(n.node is not None
                       and POD_LABEL in n.node.metadata.labels
                       for n in ssn.nodes.values()):
                return
            view = build_view(ssn.nodes)
            ssn.prescan["topo_view"] = view
        if not view.n_valid:
            return
        occupied = np.asarray(
            [len(ssn.nodes[name].tasks) > 0 for name in view.node_names],
            bool)
        bonus = view.frag_bonus(occupied, int(w))
        # The exact integers the device fold consumes (tensor_snapshot).
        ssn.prescan["topo_frag_bonus"] = bonus
        by_row = {name: int(bonus[i])
                  for i, name in enumerate(view.node_names)}

        def frag_score(_task, node) -> int:
            return by_row.get(node.name, 0)

        # Weight 1.0: the bonus array is already weight-multiplied, so
        # the combiner's weight * score equals the device's folded term
        # exactly.
        ssn.add_node_order_fns(self.name(), [(1.0, frag_score)])

    def on_session_close(self, ssn) -> None:
        pass


def new(arguments: Arguments) -> TopologyPlugin:
    return TopologyPlugin(arguments)
