"""Allocate action: the primary placement loop (host path).

Mirrors /root/reference/pkg/scheduler/actions/allocate/allocate.go: queue PQ
ordered by QueueOrderFn, per-queue job PQs, lazily-built per-job pending-task
PQs skipping BestEffort tasks; per task predicate -> prioritize -> select-best
-> Allocate on Idle or Pipeline onto Releasing; jobs/queues re-pushed for
fairness interleave.  This is the parity oracle for the ``tpu-allocate``
action, which executes the same semantics as a batched device program.
"""

from __future__ import annotations

from typing import Dict

from ..api import FitError, TaskStatus
from ..framework import Action
from ..trace import spans as trace
from ..utils import (PriorityQueue, get_node_list, predicate_nodes,
                     prioritize_nodes, select_best_node)


class AllocateAction(Action):

    def name(self) -> str:
        return "allocate"

    def execute(self, ssn) -> None:
        with trace.span("allocate.build_queues"):
            queues = PriorityQueue(ssn.queue_order_fn)
            jobs_map: Dict[str, PriorityQueue] = {}

            for job in ssn.jobs.values():
                queue = ssn.queues.get(job.queue)
                if queue is None:
                    continue
                queues.push(queue)
                if job.queue not in jobs_map:
                    jobs_map[job.queue] = PriorityQueue(ssn.job_order_fn)
                jobs_map[job.queue].push(job)

        pending_tasks: Dict[str, PriorityQueue] = {}
        all_nodes = get_node_list(ssn.nodes)

        def predicate_fn(task, node):
            # Resource fit against Idle or Releasing (allocate.go:73-87),
            # then the plugin predicate chain.
            if (not task.init_resreq.less_equal(node.idle)
                    and not task.init_resreq.less_equal(node.releasing)):
                raise FitError(task, node, "resource fit failed")
            ssn.predicate_fn(task, node)

        with trace.span("allocate.place_loop"):
            while not queues.empty():
                queue = queues.pop()
                if ssn.overused(queue):
                    continue
                jobs = jobs_map.get(queue.uid)
                if jobs is None or jobs.empty():
                    continue

                job = jobs.pop()
                if job.uid not in pending_tasks:
                    # BestEffort tasks wait for backfill
                    # (allocate.go:112-117).
                    pending_tasks[job.uid] = ssn.task_queue(
                        task for task in job.task_status_index.get(
                            TaskStatus.Pending, {}).values()
                        if not task.resreq.is_empty())
                tasks = pending_tasks[job.uid]

                while not tasks.empty():
                    task = tasks.pop()

                    # Stale fit deltas are for tasks that eventually fit
                    # (allocate.go:134-141).
                    if job.nodes_fit_delta:
                        ssn._dirty_job(job.uid)
                        job.nodes_fit_delta = {}

                    candidates = predicate_nodes(task, all_nodes,
                                                 predicate_fn)
                    if not candidates:
                        # Tasks are priority-ordered: if this one can't
                        # fit, don't try later tasks of the same job.
                        break

                    priority_list = prioritize_nodes(task, candidates,
                                                     ssn.node_prioritizers())
                    node_name = select_best_node(priority_list)
                    node = ssn.nodes[node_name]

                    if task.init_resreq.less_equal(node.idle):
                        try:
                            ssn.allocate(task, node.name)
                        except (KeyError, ValueError):
                            # Log-and-continue like the reference
                            # (allocate.go:162-166); failed volume
                            # allocation or stale state leaves the task
                            # pending for resync.
                            pass
                    else:
                        # Record why the best node did not fit idle.
                        delta = node.idle.clone()
                        delta.fit_delta(task.init_resreq)
                        ssn._dirty_job(job.uid)
                        job.nodes_fit_delta[node.name] = delta
                        # Speculate onto releasing resources
                        # (allocate.go:175-182).
                        if task.init_resreq.less_equal(node.releasing):
                            ssn.pipeline(task, node.name)

                    if ssn.job_ready(job) and not tasks.empty():
                        jobs.push(job)
                        break

                # Queue gets another round until it has no jobs left.
                queues.push(queue)


def new() -> AllocateAction:
    return AllocateAction()
