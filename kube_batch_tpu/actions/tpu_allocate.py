"""tpu-allocate: the allocate action solved as one device program.

The action the north star (BASELINE.json) asks for: tensorize the session
snapshot (models/tensor_snapshot.py), run the batched solver on TPU
(ops/solver.py), then apply the placements back through the session so
plugins, gang dispatch, and binders observe exactly the same sequence of
events as the host allocate action.  Selectable from the YAML conf as
``actions: "tpu-allocate, backfill"`` with zero CRD changes.  Host ports
and required inter-pod (anti-)affinity run ON DEVICE via dynamic occupancy
tensors; only the remaining gaps (preferred-node-affinity scoring,
fractional/oversized score weights, int32-overflowing magnitudes, or
pathological port/selector cardinality) fall back to the host allocate
action transparently.
"""

from __future__ import annotations

import contextlib
import logging
import time

from .. import knobs
from ..framework import Action
from ..metrics import metrics
from ..trace import spans as trace

log = logging.getLogger(__name__)

# Set to a directory path to capture an XLA profiler trace of each session
# solve (the sidecar profiling hook, SURVEY.md §5).
PROFILE_ENV = knobs.PROFILE.env
# =0 runs the pre-pipeline sequential path (solve barrier, then apply
# preparation): the A/B control and parity oracle for the pipelined
# engine (doc/PIPELINE.md; tests/test_pipeline.py proves both paths
# produce identical placements, events, and binds).
PIPELINE_ENV = knobs.PIPELINE.env


def _maybe_profile():
    profile_dir = knobs.PROFILE.raw()
    if not profile_dir:
        return contextlib.nullcontext()
    import jax
    return jax.profiler.trace(profile_dir)


class TpuAllocateAction(Action):

    def __init__(self):
        self._fallback_action = None

    def name(self) -> str:
        return "tpu-allocate"

    def _run_host_fallback(self, ssn) -> None:
        """The host allocate oracle: placement-identical to the device
        path by the parity suite, only the engine differs."""
        # A commit flush deferred into this action's dispatch window
        # (framework/commit.py) must land BEFORE the fallback mutates and
        # binds — evict events precede binds on every path, degraded
        # included (doc/FUSED.md "Storm half").
        from ..ops import fused_solver
        fused_solver.flush_deferred(ssn)
        if self._fallback_action is None:
            from .allocate import AllocateAction
            self._fallback_action = AllocateAction()
        self._fallback_action.execute(ssn)

    def _fallback_on_failure(self, ssn, breaker, stage: str, exc) -> None:
        """Graceful degradation for a device-pipeline failure BEFORE any
        session mutation: feed the breaker (repeated failures trip it
        open — doc/CHAOS.md "Breaker semantics"), invalidate the resident
        ship image (a partial ship must not serve as the next delta
        baseline), surface the degraded cycle, and run the host path."""
        from ..models.shipping import resident_shipper
        breaker.failure()
        metrics.note_device_failure(stage)
        trace.note_degraded(
            f"device {stage} failed ({type(exc).__name__}: {exc}); "
            "host allocate fallback")
        log.warning("tpu-allocate degraded to the host path after a "
                    "device %s failure: %s", stage, exc)
        resident_shipper(ssn.cache).invalidate()
        self._run_host_fallback(ssn)

    @staticmethod
    def _validate_result(snap, assignment, kind, order, ordered) -> None:
        """Reject a malformed device result BEFORE it touches the session:
        a poisoned readback (wrong row count, out-of-range indices) must
        degrade to the host path, never corrupt placements."""
        import numpy as np

        p = int(snap.inputs.task_req.shape[0])
        shapes = (assignment.shape, kind.shape, order.shape)
        if shapes != ((p,), (p,), (p,)):
            raise RuntimeError(
                f"malformed device solve result: expected [P={p}] "
                f"vectors, got {shapes}")
        if ordered.size:
            if int(ordered.min()) < 0 or int(ordered.max()) >= p:
                raise RuntimeError(
                    "malformed device solve result: placement "
                    "permutation out of range")
            sel = assignment[ordered]
            if (int(sel.min()) < 0
                    or int(sel.max()) >= len(snap.node_names)):
                raise RuntimeError(
                    "malformed device solve result: node index out of "
                    "range")
            if np.any(kind[ordered] <= 0):
                raise RuntimeError(
                    "malformed device solve result: permutation selects "
                    "unplaced tasks")

    def execute(self, ssn) -> None:
        finish = self.execute_begin(ssn)
        if finish is not None:
            finish()

    def execute_begin(self, ssn):
        """The HOST half of the action — tensorize, ship, async solve
        dispatch, device-wait-window apply preparation — with every
        cluster-mutating step deferred into the returned continuation.

        Returns None when the action fully completed (nothing to solve),
        else a zero-argument continuation that finishes it: device fetch,
        result validation, placement apply, fit deltas — or the host
        fallback when the begin half already decided to degrade.  The
        split is what the concurrent shard pipeline overlaps: shard K+1
        runs this begin half while shard K's dispatch executes on device,
        and the continuations retire in shard order so binds and events
        stay sequential-identical (doc/TENANCY.md "Concurrent
        micro-sessions").  ``execute`` composes the halves back-to-back,
        which is the exact pre-split control flow."""
        from ..chaos.breaker import device_breaker
        from ..models.tensor_snapshot import tensorize_session

        breaker = device_breaker()
        if not breaker.allow():
            # OPEN within cooldown: the device path is quarantined and
            # the host oracle serves this cycle.  Once the cooldown
            # elapses, allow() turns the breaker half-open and the next
            # cycle probes the device path again.  The fallback mutates
            # the session and binds, so it is retire-phase work.
            def finish_breaker_open():
                trace.note_degraded(
                    "device breaker open: tpu-allocate ran the host path")
                self._run_host_fallback(ssn)
            ssn._pipeline_reads_all = True
            return finish_breaker_open

        start = time.time()
        try:
            with trace.span("tensorize"):
                snap = tensorize_session(ssn)
        except Exception as exc:
            ssn._pipeline_reads_all = True
            # Bind via default: `exc` is unbound once the except block
            # exits, and the continuation runs later.
            return lambda err=exc: self._fallback_on_failure(
                ssn, breaker, "tensorize", err)
        if snap.needs_fallback:
            # A tensorization GAP, not a device failure: the breaker
            # stays untouched (needs_fallback is the expressiveness
            # boundary, the breaker is the health boundary).
            ssn._pipeline_reads_all = True
            return lambda: self._run_host_fallback(ssn)
        metrics.observe_tpu_transfer_latency(time.time() - start)

        # Backfill pre-scan: the tensorizer already collected every
        # BestEffort pending task (snap.tasks_extra), so the backfill
        # action's O(all pending) discovery walk is answered here for
        # free.  Valid for the whole session: the candidate set is fixed
        # at snapshot time and this action only places non-BestEffort
        # tasks.  A negative answer is only trustworthy when the
        # tensorizer saw EVERY job — it skips jobs whose queue is missing
        # (allocate.go:52-56), which backfill's own walk still visits.
        if snap.tasks_extra:
            ssn.prescan["has_best_effort"] = True
        elif len(snap.job_uids) == len(ssn.jobs):
            ssn.prescan["has_best_effort"] = False

        if not snap.tasks:
            # No finish continuation will run: flush any commit sink
            # deferred into this action's window now (an earlier action
            # may have pipelined away every pending task), so later
            # actions' binds cannot precede the deferred evict events.
            from ..ops import fused_solver
            fused_solver.flush_deferred(ssn)
            self._publish_read_fence(ssn, snap, empty=True)
            return None

        from ..models.shipping import resident_shipper
        from ..ops.solver import (best_solve_allocate, dispatch_solve,
                                  fetch_result, fetch_solve)

        import numpy as np

        # Ship -> dispatch -> fetch -> validate is the degradation
        # boundary: no session state is mutated inside it, so any failure
        # (device error, poisoned readback, dead tunnel) safely degrades
        # this cycle to the host path and feeds the breaker.  From the
        # apply phase on, failures propagate as before — the session is
        # mutated and a re-run would double-place.  The begin half below
        # stops at the async dispatch; fetch/validate/apply live in the
        # returned continuation.
        pending = None
        assignment = kind = order = ordered = None
        begin_solve_elapsed = 0.0
        try:
            ship_start = time.time()
            # Device-resident delta shipping: steady cycles move only the
            # dirty blocks of the packed buffer (models/shipping.py; the
            # shipper annotates this span with mode and bytes).
            shipper = resident_shipper(ssn.cache)
            with trace.span("ship"):
                inputs = shipper.ship(snap.inputs, snap.config)
            metrics.observe_tpu_transfer_latency(time.time() - ship_start)

            # Routing observability (doc/SHARDING.md): which engine this
            # session's solve takes and over how many devices — on the
            # session meta for /debug/sessions; best_solve_allocate
            # annotates the dispatch span and counts
            # kube_batch_solver_route_total at the chokepoint itself.
            from ..ops.solver import choose_solver_mesh
            route, mesh = choose_solver_mesh(snap.inputs)
            trace.set_meta(solver_route=route,
                           mesh_devices=mesh.size if mesh else 1)

            from ..models.tensor_snapshot import (build_apply_aggregates,
                                                  prepare_apply_scaffold)
            # Generation-keyed solve reuse (models/incremental.py,
            # doc/INCREMENTAL.md): a CLEAN ship at an unchanged shipper
            # generation proves the inputs are byte-identical to the
            # previous dispatch, and the solver is deterministic — so the
            # cached result IS this session's result, no device
            # round-trip needed.  KUBE_BATCH_TPU_INCREMENTAL=0 (or any
            # byte change, or an invalidated shipper) disables reuse.
            from ..models import incremental
            inc_state = (incremental.state_for(ssn.cache, create=False)
                         if incremental.incremental_enabled() else None)
            cached_solve = None
            if (inc_state is not None
                    and shipper.last_mode == "clean"
                    and inc_state.solve_gen == shipper.generation
                    and inc_state.solve_cfg == snap.config
                    and inc_state.solve_result is not None):
                cached_solve = inc_state.solve_result
            pipelined = knobs.PIPELINE.enabled()
            # Candidate-row solve prefilter (ops/prefilter.py,
            # doc/INCREMENTAL.md "floors"): on a micro build the host
            # derives the provably-sufficient candidate node set from
            # the staged start tensors, and the dispatch gathers only
            # those rows out of the resident buffer — the per-placement
            # device scan drops from O(N) to O(C).  Full sessions (and
            # the INCREMENTAL=0 / CANDIDATE_SOLVE=0 controls) keep the
            # whole node bucket.
            candidates = None
            if (pipelined and cached_solve is None
                    and inc_state is not None
                    and inc_state.last_kind == "micro"):
                from ..ops.prefilter import derive_candidates
                with trace.span("prefilter"):
                    candidates = derive_candidates(snap, route, mesh)
                if candidates is not None:
                    trace.set_meta(candidate_rows=candidates.count)
            solve_start = time.time()
            with _maybe_profile():
                if cached_solve is not None:
                    with trace.span("solve.reuse",
                                    generation=shipper.generation,
                                    route=inc_state.solve_route):
                        assignment, kind, order, ordered = cached_solve
                        scaffold = prepare_apply_scaffold(snap)
                    metrics.note_generation_reuse(True)
                    metrics.set_cycle_floor("solve_wait", 0.0)
                elif pipelined:
                    # Dispatch, overlap the result-independent apply
                    # preparation with the executing device program, then
                    # block only when the result is actually consumed
                    # (the continuation below).  The packed readback also
                    # forces completion (block_until_ready is unreliable
                    # on the axon tunnel).  A fused session dispatch
                    # (ops/fused_solver.py) may already hold this solve:
                    # consume it iff the ship above came back CLEAN at
                    # the fused generation with the same config and
                    # candidate gather — else the per-family dispatch.
                    with trace.span("dispatch"):
                        from ..ops import fused_solver
                        pending = fused_solver.take_alloc(
                            ssn, shipper, snap, route, candidates)
                        if pending is not None:
                            trace.annotate(fused=True)
                        else:
                            pending = dispatch_solve(inputs, snap.config,
                                                     candidates=candidates)
                    metrics.note_candidate_solve(
                        candidates is not None,
                        candidates.count if candidates is not None else 0)
                    overlap_start = time.perf_counter()
                    with trace.span("host_overlap"):
                        scaffold = prepare_apply_scaffold(snap)
                    metrics.observe_host_overlap_latency(
                        time.perf_counter() - overlap_start)
                else:
                    with trace.span("solve"):
                        result = best_solve_allocate(inputs, snap.config)
                        assignment, kind, order = fetch_result(result)
                    metrics.note_candidate_solve(False, 0)
                    metrics.set_cycle_floor("solve_wait",
                                            time.time() - solve_start)
                    placed = np.nonzero(kind > 0)[0]
                    ordered = placed[np.argsort(order[placed],
                                                kind="stable")]
                    scaffold = None
            begin_solve_elapsed = time.time() - solve_start
        except Exception as exc:
            if pending is not None:
                # The dispatch landed before the failure (e.g. the
                # scaffold prep raised): retire the handle from the
                # in-flight ledger — nothing will ever fetch it.
                from ..ops.solver import discard_solve
                discard_solve(pending)
            ssn._pipeline_reads_all = True
            return lambda err=exc: self._fallback_on_failure(
                ssn, breaker, "solve", err)

        # Publish the successor-conflict read fence BEFORE pausing: the
        # pipeline compares predecessors' mutated nodes against this
        # session's statically-feasible node union (doc/TENANCY.md
        # "Concurrent micro-sessions" — the solve's outcome provably
        # depends on node state only inside sig-feasible columns).
        self._publish_read_fence(ssn, snap)

        def finish():
            nonlocal scaffold, assignment, kind, order, ordered
            from ..chaos.breaker import solve_deadline_s
            # Storm half (doc/FUSED.md): a commit flush deferred from an
            # earlier action rides this window — egress the evicts FIRST
            # so the cluster call overlaps the device wait below, and the
            # event stream keeps evicts before this session's binds on
            # the served, invalidated, and fallback paths alike.
            from ..ops import fused_solver
            fused_solver.flush_deferred(ssn)
            try:
                if pending is not None:
                    wait_start = time.perf_counter()
                    with trace.span("device_wait"):
                        assignment, kind, order, ordered = \
                            fetch_solve(pending)
                    wait_elapsed = time.perf_counter() - wait_start
                    metrics.observe_device_wait_latency(wait_elapsed)
                    metrics.set_cycle_floor("solve_wait", wait_elapsed)
                    solve_elapsed = begin_solve_elapsed + wait_elapsed
                else:
                    solve_elapsed = begin_solve_elapsed
                metrics.observe_tpu_solve_latency(solve_elapsed)
                self._validate_result(snap, assignment, kind, order,
                                      ordered)
            except Exception as exc:
                if ssn._pipeline_stale:
                    # A predecessor committed after this session's
                    # snapshot, and the conflict fence only cleared the
                    # NARROW solve footprint: the host fallback would
                    # read arbitrary (stale) node state.  Nothing has
                    # been mutated yet, so abort for the pipeline's
                    # fresh sequential rerun instead of degrading here
                    # (tenancy/pipeline.StaleSessionAbort).  The breaker
                    # still sees the device failure.
                    from ..tenancy.pipeline import StaleSessionAbort
                    breaker.failure()
                    metrics.note_device_failure("solve")
                    raise StaleSessionAbort(
                        f"device solve failed mid-pipeline over a stale "
                        f"snapshot ({type(exc).__name__}: {exc})") from exc
                self._fallback_on_failure(ssn, breaker, "solve", exc)
                return

            if inc_state is not None and cached_solve is None:
                # Cache AFTER validation only: a poisoned readback must
                # never become a reusable "known-good" result.
                inc_state.solve_gen = shipper.generation
                inc_state.solve_cfg = snap.config
                inc_state.solve_result = (assignment, kind, order, ordered)
                inc_state.solve_route = route
                metrics.note_generation_reuse(False)

            deadline = solve_deadline_s()
            if cached_solve is not None:
                # A reused result is no device health evidence either
                # way: the breaker and the solve deadline see nothing.
                pass
            elif deadline and solve_elapsed > deadline:
                # Detective, not preemptive: the (valid) late result is
                # still applied, but a repeatedly-slow device trips the
                # breaker to the host path exactly like an erroring one.
                # (Pipelined pause time is excluded: solve_elapsed is
                # dispatch-half plus fetch wall time, never the window a
                # successor shard's begin half ran in.)
                breaker.failure()
                metrics.note_solve_deadline()
                trace.note_degraded(
                    f"session solve exceeded deadline "
                    f"({solve_elapsed * 1e3:.0f} ms > "
                    f"{deadline * 1e3:.0f} ms)")
            else:
                breaker.success()

            # Apply placements in device-solve order through the columnar
            # batched path: end state (status indexes, node accounting,
            # plugin shares, gang dispatch) is identical to per-task
            # ssn.allocate/pipeline calls, fed straight from the solver's
            # arrays and the staged index->TaskInfo table — no
            # per-placement tuple materialization
            # (Session.batch_apply_solved).
            apply_start = time.perf_counter()
            with trace.span("apply", placed=int(ordered.size)):
                if scaffold is None:
                    scaffold = prepare_apply_scaffold(snap)
                agg = build_apply_aggregates(snap, assignment, kind,
                                             ordered, scaffold=scaffold)
                # Pod lineage: batch_apply records the bulk "placed"
                # stage; the cycle context names which engine decided it
                # (shown on /debug/lineage as e.g.
                # "via tpu-allocate/sharded").
                from ..framework.commit import batch_commit_enabled
                from ..trace.lineage import lineage as pod_lineage
                pod_lineage.cycle_context = f"via {self.name()}/{route}"
                try:
                    if batch_commit_enabled():
                        ssn.batch_apply_solved(
                            scaffold.tasks_arr, scaffold.node_names_arr,
                            assignment, kind, ordered, snap.task_job,
                            snap.job_uids, agg)
                    else:
                        # KUBE_BATCH_TPU_BATCH_COMMIT=0: the pre-columnar
                        # tuple fan-out — the bit-parity control for the
                        # whole commit/apply tail (doc/EVICTION.md
                        # "Batched commit").
                        kinds = kind[ordered].tolist()
                        hostnames = scaffold.node_names_arr[
                            assignment[ordered]].tolist()
                        ssn.batch_apply(
                            zip(scaffold.tasks_arr[ordered].tolist(),
                                hostnames, kinds),
                            agg=agg)
                finally:
                    pod_lineage.cycle_context = ""
            # The ``apply`` floor is the placement apply alone (the stage
            # the columnar path vectorizes); the histogram keeps its
            # historical span (apply + fit-delta recording).
            ssn._floor_apply += time.perf_counter() - apply_start
            with trace.span("fit_deltas"):
                self._record_fit_deltas(ssn, snap, kind, assignment, order,
                                        scaffold=scaffold)
            metrics.observe_tpu_apply_latency(
                time.perf_counter() - apply_start)
            # After the latency observation: the tally walk must not
            # inflate the histogram the recorder's spans are validated
            # against.
            if trace.current_session_id() is not None:
                self._record_why_tallies(ssn, snap, kind)

        finish.pending = pending
        return finish

    @staticmethod
    def _publish_read_fence(ssn, snap, empty: bool = False) -> None:
        """Stash this session's retire-phase node READ footprint for the
        shard pipeline's conflict fence: the union over pending task
        signatures of statically-feasible nodes.  Infeasible nodes can
        carry any state without changing the solve (their score is
        masked to -inf and they can never be the argmax), so a
        predecessor mutation outside this union provably leaves the
        optimistic result identical to the sequential arm's.  Sessions
        whose retire can read arbitrary node state — volumed tasks
        (global binder state), an unanswered BestEffort prescan (the
        backfill walk), any fallback — publish reads-all instead.

        Only pipelined sessions pay for this: outside the shard
        pipeline (the global engine, the CONCURRENT_SHARDS=0 control, a
        single dirty shard) nothing reads the fence, and the control
        arm must keep its exact per-session work profile."""
        import numpy as np
        if not ssn._pipeline_active:
            return
        if ssn._pipeline_fence is not None:
            # A begin-half footprint (tenancy/footprint.py) already
            # published the whole conf's bound — it is a superset of
            # this action's tasks-only union; keep it.
            return
        if empty:
            # No candidate tasks: the retire phase touches nodes only if
            # backfill places BestEffort work.
            if ssn.prescan.get("has_best_effort") is False:
                ssn._pipeline_fence = ((), None)
            else:
                ssn._pipeline_reads_all = True
            return
        try:
            if ssn.prescan.get("has_best_effort") is not False or any(
                    t.pod.spec.volumes for t in snap.tasks):
                ssn._pipeline_reads_all = True
                return
            p = len(snap.tasks)
            sigs = np.unique(np.asarray(snap.inputs.task_sig)[:p])
            mask = np.logical_or.reduce(
                np.asarray(snap.inputs.sig_mask)[sigs], axis=0)
            mask = mask & np.asarray(snap.inputs.node_exists)
            n = len(snap.node_names)
            ssn._pipeline_fence = (snap.node_names, mask[:n])
        except Exception:  # lint: allow-swallow(fence derivation is an optimization gate: an unknown footprint degrades to reads-all, which only forces a sequential rerun — counted, never wrong)
            metrics.note_swallowed("pipeline_fence")
            ssn._pipeline_reads_all = True

    @staticmethod
    def _record_why_tallies(ssn, snap, kind) -> None:
        """Why-pending tallies from the solver's own outputs: per job with
        unplaced candidates, how many tasks allocated/pipelined/stalled,
        and — from the static [S, N] predicate mask — whether ANY node
        passed the first stalled task's static predicates.  Distinguishes
        "no node admits this task at all" (selector/taint mismatch) from
        "admissible nodes had no room" without re-running anything; the
        flight recorder serves it via /debug/why."""
        import numpy as np

        inp = snap.inputs
        nj = len(snap.job_uids)
        job_start = np.asarray(inp.job_start)[:nj].astype(np.int64)
        job_count = np.asarray(inp.job_count)[:nj].astype(np.int64)
        # Vectorized per-job kind counts via cumulative sums (job blocks
        # are contiguous): O(P + J) host work, then a Python iteration
        # over STALLED jobs only — a healthy cluster pays two cumsums.
        ends = job_start + job_count
        cum0 = np.concatenate(([0], np.cumsum(kind == 0)))
        cum1 = np.concatenate(([0], np.cumsum(kind == 1)))
        cum2 = np.concatenate(([0], np.cumsum(kind == 2)))
        unplaced_per_job = cum0[ends] - cum0[job_start]
        stalled = np.nonzero((job_count > 0) & (unplaced_per_job > 0))[0]
        if stalled.size == 0:
            return
        # One [S, N] pass for the static-mask node counts, indexed per
        # stalled task below (not one mask reduction per job).
        task_sig = np.asarray(inp.task_sig)
        node_exists = np.asarray(inp.node_exists)
        sig_feasible = np.count_nonzero(
            np.asarray(inp.sig_mask) & node_exists[None, :], axis=1)
        for ji in (int(j) for j in stalled):
            job = ssn.jobs.get(snap.job_uids[ji])
            if job is None:
                continue
            start, end = job_start[ji], ends[ji]
            first = start + int(np.argmax(kind[start:end] == 0))
            feasible = int(sig_feasible[int(task_sig[first])])
            trace.note_tally(
                f"{job.namespace}/{job.name}",
                candidates=int(job_count[ji]),
                allocated=int(cum1[end] - cum1[start]),
                pipelined=int(cum2[end] - cum2[start]),
                unplaced=int(unplaced_per_job[ji]),
                static_feasible_nodes=feasible,
                reason=("PredicateMismatch" if feasible == 0
                        else "NoFeasibleNode"))

    @staticmethod
    def _record_fit_deltas(ssn, snap, kind, assignment, order,
                           scaffold=None) -> None:
        """Fit-error diagnostics (allocate.go:139-141, job_info.go:348-380).

        The host path records NodesFitDelta when the selected node fails
        the idle fit (the task is then pipelined onto releasing), and the
        entry SURVIVES the action only when that was the job's last
        processed task — every subsequent task's iteration clears it
        (allocate.go:134-141).  Mirror: per job, a delta survives iff the
        final candidate task was pipelined (kind 2) and actually applied;
        the node idle is reconstructed AT THE RECORD POINT by adding back
        allocations that landed on the node later in solve order.
        (The once-suspected no-candidate-break corner is unreachable:
        both paths process tasks in block order, so a pipelined LAST task
        implies every earlier task had candidates — no break happened —
        and a break before the last task leaves it unprocessed (kind 0),
        recording nothing on either path.  Pinned by
        test_fit_deltas.py::test_fuzz_no_candidate_task_jobs.)"""
        import numpy as np

        from ..api import TaskStatus, allocated_status
        from ..models.tensor_snapshot import _res_from_vec

        names = snap.node_names
        inp = snap.inputs
        if scaffold is not None:
            job_start, job_count = scaffold.job_start, scaffold.job_count
        else:
            job_start = np.asarray(inp.job_start)
            job_count = np.asarray(inp.job_count)
        for ji, uid in enumerate(snap.job_uids):
            count = int(job_count[ji])
            if not count:
                continue
            last = int(job_start[ji]) + count - 1
            if kind[last] != 2:
                continue
            task = snap.tasks[last]
            if task.status != TaskStatus.Pipelined:
                continue  # batch_apply skipped this placement
            job = ssn.jobs.get(uid)
            nix = int(assignment[last])
            node = ssn.nodes.get(names[nix])
            if job is None or node is None:
                continue
            # Idle at the record point: the node's post-batch idle plus
            # the requests of kind-1 placements that happened AFTER this
            # task in solve order (the host records mid-sequence).  Only
            # placements batch_apply actually applied count — skipped
            # ones (e.g. volume failure) never touched node.idle.
            later = ((kind == 1) & (assignment == nix)
                     & (order > order[last]))
            rows = [int(i) for i in np.nonzero(later)[0]
                    if allocated_status(snap.tasks[int(i)].status)]
            delta = node.idle.clone()
            if rows:
                delta.add(_res_from_vec(
                    snap.task_res_f64[rows].sum(axis=0),
                    snap.resource_names))
            delta.fit_delta(task.init_resreq)
            ssn._dirty_job(job.uid)
            job.nodes_fit_delta[node.name] = delta


def new() -> TpuAllocateAction:
    return TpuAllocateAction()
