"""tpu-allocate: the allocate action solved as one device program.

The action the north star (BASELINE.json) asks for: tensorize the session
snapshot (models/tensor_snapshot.py), run the batched solver on TPU
(ops/solver.py), then apply the placements back through the session so
plugins, gang dispatch, and binders observe exactly the same sequence of
events as the host allocate action.  Selectable from the YAML conf as
``actions: "tpu-allocate, backfill"`` with zero CRD changes; sessions using
features the device path doesn't express yet (host ports, inter-pod
affinity) fall back to the host allocate action transparently.
"""

from __future__ import annotations

import contextlib
import os
import time

from ..framework import Action
from ..metrics import metrics

# Set to a directory path to capture an XLA profiler trace of each session
# solve (the sidecar profiling hook, SURVEY.md §5).
PROFILE_ENV = "KUBE_BATCH_TPU_PROFILE"


def _maybe_profile():
    profile_dir = os.environ.get(PROFILE_ENV)
    if not profile_dir:
        return contextlib.nullcontext()
    import jax
    return jax.profiler.trace(profile_dir)


class TpuAllocateAction(Action):

    def __init__(self):
        self._fallback = None

    def name(self) -> str:
        return "tpu-allocate"

    def execute(self, ssn) -> None:
        from ..models.tensor_snapshot import tensorize_session

        start = time.time()
        snap = tensorize_session(ssn)
        if snap.needs_fallback:
            if self._fallback is None:
                from .allocate import AllocateAction
                self._fallback = AllocateAction()
            self._fallback.execute(ssn)
            return
        metrics.observe_tpu_transfer_latency(time.time() - start)

        if not snap.tasks:
            return

        from ..models.shipping import ship_inputs
        from ..ops.solver import best_solve_allocate

        import numpy as np
        ship_start = time.time()
        inputs = ship_inputs(snap.inputs)
        metrics.observe_tpu_transfer_latency(time.time() - ship_start)

        solve_start = time.time()
        with _maybe_profile():
            result = best_solve_allocate(inputs, snap.config)
            # np.asarray forces completion; block_until_ready is unreliable
            # on the experimental axon TPU tunnel.
            assignment = np.asarray(result.assignment)
        metrics.observe_tpu_solve_latency(time.time() - solve_start)
        kind = np.asarray(result.kind)
        order = np.asarray(result.order)

        # Apply placements in device-solve order so event handlers and the
        # gang dispatch barrier fire in the same sequence as the host loop.
        placed = np.nonzero(kind > 0)[0]
        for idx in placed[np.argsort(order[placed], kind="stable")]:
            task = snap.tasks[idx]
            node_name = snap.node_names[int(assignment[idx])]
            try:
                if kind[idx] == 1:
                    ssn.allocate(task, node_name)
                else:
                    ssn.pipeline(task, node_name)
            except (KeyError, ValueError):
                # Mirror the reference's log-and-continue on bind errors
                # (allocate.go:162-166); cache resync repairs divergence.
                continue


def new() -> TpuAllocateAction:
    return TpuAllocateAction()
