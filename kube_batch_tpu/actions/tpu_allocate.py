"""tpu-allocate: the allocate action solved as one device program.

The action the north star (BASELINE.json) asks for: tensorize the session
snapshot (models/tensor_snapshot.py), run the batched solver on TPU
(ops/solver.py), then apply the placements back through the session so
plugins, gang dispatch, and binders observe exactly the same sequence of
events as the host allocate action.  Selectable from the YAML conf as
``actions: "tpu-allocate, backfill"`` with zero CRD changes.  Host ports
and required inter-pod (anti-)affinity run ON DEVICE via dynamic occupancy
tensors; only the remaining gaps (preferred-node-affinity scoring,
fractional/oversized score weights, int32-overflowing magnitudes, or
pathological port/selector cardinality) fall back to the host allocate
action transparently.
"""

from __future__ import annotations

import contextlib
import os
import time

from ..framework import Action
from ..metrics import metrics
from ..trace import spans as trace

# Set to a directory path to capture an XLA profiler trace of each session
# solve (the sidecar profiling hook, SURVEY.md §5).
PROFILE_ENV = "KUBE_BATCH_TPU_PROFILE"
# =0 runs the pre-pipeline sequential path (solve barrier, then apply
# preparation): the A/B control and parity oracle for the pipelined
# engine (doc/PIPELINE.md; tests/test_pipeline.py proves both paths
# produce identical placements, events, and binds).
PIPELINE_ENV = "KUBE_BATCH_TPU_PIPELINE"


def _maybe_profile():
    profile_dir = os.environ.get(PROFILE_ENV)
    if not profile_dir:
        return contextlib.nullcontext()
    import jax
    return jax.profiler.trace(profile_dir)


class TpuAllocateAction(Action):

    def __init__(self):
        self._fallback = None

    def name(self) -> str:
        return "tpu-allocate"

    def execute(self, ssn) -> None:
        from ..models.tensor_snapshot import tensorize_session

        start = time.time()
        with trace.span("tensorize"):
            snap = tensorize_session(ssn)
        if snap.needs_fallback:
            if self._fallback is None:
                from .allocate import AllocateAction
                self._fallback = AllocateAction()
            self._fallback.execute(ssn)
            return
        metrics.observe_tpu_transfer_latency(time.time() - start)

        # Backfill pre-scan: the tensorizer already collected every
        # BestEffort pending task (snap.tasks_extra), so the backfill
        # action's O(all pending) discovery walk is answered here for
        # free.  Valid for the whole session: the candidate set is fixed
        # at snapshot time and this action only places non-BestEffort
        # tasks.  A negative answer is only trustworthy when the
        # tensorizer saw EVERY job — it skips jobs whose queue is missing
        # (allocate.go:52-56), which backfill's own walk still visits.
        if snap.tasks_extra:
            ssn.prescan["has_best_effort"] = True
        elif len(snap.job_uids) == len(ssn.jobs):
            ssn.prescan["has_best_effort"] = False

        if not snap.tasks:
            return

        from ..models.shipping import resident_shipper
        from ..ops.solver import (best_solve_allocate, dispatch_solve,
                                  fetch_result, fetch_solve)

        import numpy as np
        ship_start = time.time()
        # Device-resident delta shipping: steady cycles move only the
        # dirty blocks of the packed buffer (models/shipping.py; the
        # shipper annotates this span with mode and bytes).
        with trace.span("ship"):
            inputs = resident_shipper(ssn.cache).ship(snap.inputs,
                                                      snap.config)
        metrics.observe_tpu_transfer_latency(time.time() - ship_start)

        from ..models.tensor_snapshot import (build_apply_aggregates,
                                              prepare_apply_scaffold)
        pipelined = os.environ.get(PIPELINE_ENV, "1") != "0"
        solve_start = time.time()
        with _maybe_profile():
            if pipelined:
                # Dispatch, overlap the result-independent apply
                # preparation with the executing device program, then
                # block only when the result is actually consumed.  The
                # packed readback also forces completion
                # (block_until_ready is unreliable on the axon tunnel).
                with trace.span("dispatch"):
                    pending = dispatch_solve(inputs, snap.config)
                overlap_start = time.perf_counter()
                with trace.span("host_overlap"):
                    scaffold = prepare_apply_scaffold(snap)
                metrics.observe_host_overlap_latency(
                    time.perf_counter() - overlap_start)
                wait_start = time.perf_counter()
                with trace.span("device_wait"):
                    assignment, kind, order, ordered = fetch_solve(pending)
                metrics.observe_device_wait_latency(
                    time.perf_counter() - wait_start)
            else:
                with trace.span("solve"):
                    result = best_solve_allocate(inputs, snap.config)
                    assignment, kind, order = fetch_result(result)
                placed = np.nonzero(kind > 0)[0]
                ordered = placed[np.argsort(order[placed], kind="stable")]
                scaffold = None
        metrics.observe_tpu_solve_latency(time.time() - solve_start)

        # Apply placements in device-solve order through the batched path:
        # end state (status indexes, node accounting, plugin shares, gang
        # dispatch) is identical to per-task ssn.allocate/pipeline calls,
        # at one vector op per node instead of seven per task.
        apply_start = time.time()
        with trace.span("apply", placed=int(ordered.size)):
            if scaffold is None:
                scaffold = prepare_apply_scaffold(snap)
            agg = build_apply_aggregates(snap, assignment, kind, ordered,
                                         scaffold=scaffold)
            kinds = kind[ordered].tolist()
            hostnames = scaffold.node_names_arr[assignment[ordered]].tolist()
            ssn.batch_apply(
                zip(scaffold.tasks_arr[ordered].tolist(), hostnames, kinds),
                agg=agg)
        with trace.span("fit_deltas"):
            self._record_fit_deltas(ssn, snap, kind, assignment, order,
                                    scaffold=scaffold)
        metrics.observe_tpu_apply_latency(time.time() - apply_start)
        # After the latency observation: the tally walk must not inflate
        # the histogram the recorder's spans are validated against.
        if trace.current_session_id() is not None:
            self._record_why_tallies(ssn, snap, kind)

    @staticmethod
    def _record_why_tallies(ssn, snap, kind) -> None:
        """Why-pending tallies from the solver's own outputs: per job with
        unplaced candidates, how many tasks allocated/pipelined/stalled,
        and — from the static [S, N] predicate mask — whether ANY node
        passed the first stalled task's static predicates.  Distinguishes
        "no node admits this task at all" (selector/taint mismatch) from
        "admissible nodes had no room" without re-running anything; the
        flight recorder serves it via /debug/why."""
        import numpy as np

        inp = snap.inputs
        nj = len(snap.job_uids)
        job_start = np.asarray(inp.job_start)[:nj].astype(np.int64)
        job_count = np.asarray(inp.job_count)[:nj].astype(np.int64)
        # Vectorized per-job kind counts via cumulative sums (job blocks
        # are contiguous): O(P + J) host work, then a Python iteration
        # over STALLED jobs only — a healthy cluster pays two cumsums.
        ends = job_start + job_count
        cum0 = np.concatenate(([0], np.cumsum(kind == 0)))
        cum1 = np.concatenate(([0], np.cumsum(kind == 1)))
        cum2 = np.concatenate(([0], np.cumsum(kind == 2)))
        unplaced_per_job = cum0[ends] - cum0[job_start]
        stalled = np.nonzero((job_count > 0) & (unplaced_per_job > 0))[0]
        if stalled.size == 0:
            return
        # One [S, N] pass for the static-mask node counts, indexed per
        # stalled task below (not one mask reduction per job).
        task_sig = np.asarray(inp.task_sig)
        node_exists = np.asarray(inp.node_exists)
        sig_feasible = np.count_nonzero(
            np.asarray(inp.sig_mask) & node_exists[None, :], axis=1)
        for ji in (int(j) for j in stalled):
            job = ssn.jobs.get(snap.job_uids[ji])
            if job is None:
                continue
            start, end = job_start[ji], ends[ji]
            first = start + int(np.argmax(kind[start:end] == 0))
            feasible = int(sig_feasible[int(task_sig[first])])
            trace.note_tally(
                f"{job.namespace}/{job.name}",
                candidates=int(job_count[ji]),
                allocated=int(cum1[end] - cum1[start]),
                pipelined=int(cum2[end] - cum2[start]),
                unplaced=int(unplaced_per_job[ji]),
                static_feasible_nodes=feasible,
                reason=("PredicateMismatch" if feasible == 0
                        else "NoFeasibleNode"))

    @staticmethod
    def _record_fit_deltas(ssn, snap, kind, assignment, order,
                           scaffold=None) -> None:
        """Fit-error diagnostics (allocate.go:139-141, job_info.go:348-380).

        The host path records NodesFitDelta when the selected node fails
        the idle fit (the task is then pipelined onto releasing), and the
        entry SURVIVES the action only when that was the job's last
        processed task — every subsequent task's iteration clears it
        (allocate.go:134-141).  Mirror: per job, a delta survives iff the
        final candidate task was pipelined (kind 2) and actually applied;
        the node idle is reconstructed AT THE RECORD POINT by adding back
        allocations that landed on the node later in solve order.
        (The once-suspected no-candidate-break corner is unreachable:
        both paths process tasks in block order, so a pipelined LAST task
        implies every earlier task had candidates — no break happened —
        and a break before the last task leaves it unprocessed (kind 0),
        recording nothing on either path.  Pinned by
        test_fit_deltas.py::test_fuzz_no_candidate_task_jobs.)"""
        import numpy as np

        from ..api import TaskStatus, allocated_status
        from ..models.tensor_snapshot import _res_from_vec

        names = snap.node_names
        inp = snap.inputs
        if scaffold is not None:
            job_start, job_count = scaffold.job_start, scaffold.job_count
        else:
            job_start = np.asarray(inp.job_start)
            job_count = np.asarray(inp.job_count)
        for ji, uid in enumerate(snap.job_uids):
            count = int(job_count[ji])
            if not count:
                continue
            last = int(job_start[ji]) + count - 1
            if kind[last] != 2:
                continue
            task = snap.tasks[last]
            if task.status != TaskStatus.Pipelined:
                continue  # batch_apply skipped this placement
            job = ssn.jobs.get(uid)
            nix = int(assignment[last])
            node = ssn.nodes.get(names[nix])
            if job is None or node is None:
                continue
            # Idle at the record point: the node's post-batch idle plus
            # the requests of kind-1 placements that happened AFTER this
            # task in solve order (the host records mid-sequence).  Only
            # placements batch_apply actually applied count — skipped
            # ones (e.g. volume failure) never touched node.idle.
            later = ((kind == 1) & (assignment == nix)
                     & (order > order[last]))
            rows = [int(i) for i in np.nonzero(later)[0]
                    if allocated_status(snap.tasks[int(i)].status)]
            delta = node.idle.clone()
            if rows:
                delta.add(_res_from_vec(
                    snap.task_res_f64[rows].sum(axis=0),
                    snap.resource_names))
            delta.fit_delta(task.init_resreq)
            ssn._dirty_job(job.uid)
            job.nodes_fit_delta[node.name] = delta


def new() -> TpuAllocateAction:
    return TpuAllocateAction()
