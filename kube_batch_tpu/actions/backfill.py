"""Backfill action: place BestEffort tasks on any predicate-passing node.

Mirrors /root/reference/pkg/scheduler/actions/backfill/backfill.go:44-68.
"""

from __future__ import annotations

from ..api import FitError, TaskStatus
from ..framework import Action
from ..utils import get_node_list


class BackfillAction(Action):

    def name(self) -> str:
        return "backfill"

    def execute(self, ssn) -> None:
        for job in list(ssn.jobs.values()):
            pending = list(job.task_status_index.get(TaskStatus.Pending,
                                                     {}).values())
            for task in pending:
                if not task.init_resreq.is_empty():
                    continue  # only BestEffort tasks backfill
                for node in get_node_list(ssn.nodes):
                    try:
                        ssn.predicate_fn(task, node)
                    except FitError:
                        continue
                    try:
                        ssn.allocate(task, node.name)
                    except Exception:
                        continue
                    break


def new() -> BackfillAction:
    return BackfillAction()
