"""Backfill action: place BestEffort tasks on any predicate-passing node.

Mirrors /root/reference/pkg/scheduler/actions/backfill/backfill.go:44-68
(sequential first-fit, no scoring — the upstream TODO at backfill.go:50).
The per-node Python predicate walk is answered by the DeviceNodeScanner
(one vectorized scan per task over all nodes) when the session
tensorizes; node order and outcomes are identical to the host walk
(get_node_list name order == the scanner's node_names order).
"""

from __future__ import annotations

from ..api import FitError, TaskStatus
from ..framework import Action
from ..trace import spans as trace
from ..utils import get_node_list


class BackfillAction(Action):

    def name(self) -> str:
        return "backfill"

    def execute(self, ssn) -> None:
        from ..models.scanner import maybe_scanner
        # Don't tensorize a second time in the common no-BestEffort cycle:
        # the scanner only pays off when there is a sweep to answer.  The
        # pipelined tpu-allocate action already answered the discovery
        # question from the tensorizer's BestEffort rows during its
        # device-wait window (ssn.prescan); only sessions it didn't see
        # (host fallback, different pipeline) pay the O(pending) walk.
        with trace.span("backfill.discover") as sp:
            has_best_effort = ssn.prescan.get("has_best_effort")
            prescanned = has_best_effort is not None
            if not prescanned:
                has_best_effort = any(
                    t.init_resreq.is_empty()
                    for job in ssn.jobs.values()
                    for t in job.task_status_index.get(TaskStatus.Pending,
                                                       {}).values())
            sp.annotate(prescanned=prescanned,
                        has_best_effort=bool(has_best_effort))
            # shared=True: reuse the batched eviction engine's session
            # scanner (dirty-node refreshed) when reclaim already built
            # it, instead of paying a third tensorize this cycle.
            scanner = (maybe_scanner(ssn, shared=True)
                       if has_best_effort else None)
        with trace.span("backfill.place"):
            for job in list(ssn.jobs.values()):
                pending = list(job.task_status_index.get(TaskStatus.Pending,
                                                         {}).values())
                for task in pending:
                    if not task.init_resreq.is_empty():
                        continue  # only BestEffort tasks backfill
                    if scanner is not None:
                        candidates = scanner.candidate_nodes(task,
                                                             scored=False)
                        if candidates is not None:
                            for name, _score in candidates:
                                try:
                                    ssn.allocate(task, name)
                                except Exception:  # lint: allow-swallow(per-node probe: allocate failure means try the next scanned candidate)
                                    continue
                                # Membership occupancy (count/ports/
                                # selcnt) for subsequent scans; resource
                                # `used` rides the allocate event (empty
                                # here anyway).
                                scanner.apply_pipeline(task, name)
                                break
                            continue
                    for node in get_node_list(ssn.nodes):
                        try:
                            ssn.predicate_fn(task, node)
                        except FitError:
                            continue
                        try:
                            ssn.allocate(task, node.name)
                        except Exception:  # lint: allow-swallow(per-node probe on the host walk: failure means try the next node)
                            continue
                        break


def new() -> BackfillAction:
    return BackfillAction()
