"""Reclaim action: cross-queue eviction for starved queues.

Mirrors /root/reference/pkg/scheduler/actions/reclaim/reclaim.go: per pending
task of a non-overused queue, walk nodes, collect Running tasks of *other*
queues, ask Reclaimable, evict until the request is covered, then Pipeline.
"""

from __future__ import annotations

from typing import Dict, List

from ..api import FitError, Resource, TaskStatus
from ..framework import Action
from ..trace import spans as trace
from ..utils import PriorityQueue, get_node_list


class ReclaimAction(Action):

    def name(self) -> str:
        return "reclaim"

    def execute(self, ssn) -> None:
        # Batched commit (framework/commit.py): the session-side evicts
        # of this walk accumulate in the per-action sink and flush as
        # ONE bulk egress + fused cache update at exit (including the
        # exception path — mirrored effects must reach the cluster).
        from ..framework.commit import action_commit
        with action_commit(ssn, self.name()):
            self._execute(ssn)

    def _execute(self, ssn) -> None:
        scanner = None
        scanner_built = False
        queues = PriorityQueue(ssn.queue_order_fn)
        queue_map: Dict[str, object] = {}
        preemptors_map: Dict[str, PriorityQueue] = {}
        preemptor_tasks: Dict[str, PriorityQueue] = {}

        for job in ssn.jobs.values():
            queue = ssn.queues.get(job.queue)
            if queue is None:
                continue
            if queue.uid not in queue_map:
                queue_map[queue.uid] = queue
                queues.push(queue)
            if job.task_status_index.get(TaskStatus.Pending):
                if job.queue not in preemptors_map:
                    preemptors_map[job.queue] = PriorityQueue(ssn.job_order_fn)
                preemptors_map[job.queue].push(job)
                preemptor_tasks[job.uid] = ssn.task_queue(
                    job.task_status_index[TaskStatus.Pending].values())

        while not queues.empty():
            queue = queues.pop()
            if ssn.overused(queue):
                continue

            jobs = preemptors_map.get(queue.uid)
            if jobs is None or jobs.empty():
                continue
            job = jobs.pop()

            tasks = preemptor_tasks.get(job.uid)
            if tasks is None or tasks.empty():
                continue
            task = tasks.pop()

            assigned = False
            if not scanner_built:
                # Tensorize lazily: only when a starving task actually
                # needs a node walk (span: the stallable phase).
                with trace.span("reclaim.prepare"):
                    # shared=True: the batched eviction engine tensorizes
                    # and batch-seeds ONCE here (reclaim runs first in
                    # the shipped pipeline); preempt and backfill then
                    # re-attach with a dirty-node refresh instead of
                    # re-tensorizing (doc/EVICTION.md).
                    from ..models.scanner import maybe_scanner
                    scanner = maybe_scanner(ssn, shared=True)
                    scanner_built = True
                    from ..models.victim_index import VictimIndex
                    vindex = VictimIndex.for_session(ssn)
                    if scanner is not None:
                        vindex.attach_nodes(scanner.snap.node_names)
            if not vindex.any_for_other_queues(job.queue):
                continue  # no node anywhere holds a reclaimable victim
            # Candidate walk in node order; the device scan answers the
            # predicate chain for all nodes at once (reclaim.go:115).
            # Nodes without a Running resident of another queue are
            # skipped lazily — they provably yield no reclaimees.
            if scanner is not None:
                mask = vindex.other_queues_mask(job.queue)
                names = scanner.candidate_nodes(task, scored=False,
                                                admissible=mask)
            else:
                mask, names = None, None
            if names is not None:
                if mask is not None:
                    node_walk = (ssn.nodes[n] for n, _ in names
                                 if n in ssn.nodes)
                else:
                    node_walk = (ssn.nodes[n] for n, _ in names
                                 if vindex.node_for_other_queues(
                                     n, job.queue)
                                 and n in ssn.nodes)
            else:
                def _host_walk(task=task, queue=job.queue):
                    for node in get_node_list(ssn.nodes):
                        if not vindex.node_for_other_queues(node.name,
                                                            queue):
                            continue
                        try:
                            ssn.predicate_fn(task, node)
                        except FitError:
                            continue
                        yield node
                node_walk = _host_walk()
            for node in node_walk:

                resreq = task.init_resreq.clone()
                reclaimed = Resource.empty()

                # Candidates: Running tasks of other queues (reclaim.go:126-138).
                reclaimees: List = []
                for t in node.tasks.values():
                    if t.status != TaskStatus.Running:
                        continue
                    j = ssn.jobs.get(t.job)
                    if j is None:
                        continue
                    if j.queue != job.queue:
                        reclaimees.append(t.clone())
                victims = ssn.reclaimable(task, reclaimees)
                if not victims:
                    continue

                total = Resource.empty()
                for v in victims:
                    total.add(v.resreq)
                if not resreq.less_equal(total):
                    continue

                for reclaimee in victims:
                    try:
                        ssn.evict(reclaimee, "reclaim")
                    except Exception:  # lint: allow-swallow(per-victim isolation: a failed evict skips the victim; cache.evict queued its resync)
                        continue
                    vjob = ssn.jobs.get(reclaimee.job)
                    vindex.on_evict(node.name,
                                    vjob.queue if vjob is not None else "",
                                    reclaimee.job)
                    reclaimed.add(reclaimee.resreq)
                    if resreq.less_equal(reclaimed):
                        break

                if task.init_resreq.less_equal(reclaimed):
                    ssn.pipeline(task, node.name)
                    if scanner is not None:
                        scanner.apply_pipeline(task, node.name)
                    assigned = True
                    break

            if assigned:
                queues.push(queue)


def new() -> ReclaimAction:
    return ReclaimAction()
