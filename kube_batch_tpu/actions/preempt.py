"""Preempt action: within-queue preemption under a Statement transaction.

Mirrors /root/reference/pkg/scheduler/actions/preempt/preempt.go: inter-job
preemption within each queue (commit only if the preemptor job reaches
JobPipelined, else discard), then intra-job preemption.

The per-preemptor candidate-node walk (predicates + scores over every
node — the reference's 16-goroutine fan-out, preempt.go:180-189) runs as
one device call per preemptor on big clusters (models/scanner.py), with
checkpoint/restore mirroring the Statement transaction; victim selection
and commit semantics stay on the host.
"""

from __future__ import annotations

from typing import Dict, List

from ..api import Resource, TaskInfo, TaskStatus
from ..framework import Action
from ..metrics import metrics
from ..trace import spans as trace
from ..utils import (PriorityQueue, get_node_list, predicate_nodes,
                     prioritize_nodes, sort_nodes)


class PreemptAction(Action):

    def name(self) -> str:
        return "preempt"

    def execute(self, ssn) -> None:
        # Batched commit (framework/commit.py): every Statement.commit
        # of this walk hands its evictions to the per-action sink; ONE
        # bulk egress + fused cache update flushes them at exit, in the
        # exact commit order (doc/EVICTION.md "Batched commit").
        from ..framework.commit import action_commit
        with action_commit(ssn, self.name()):
            self._execute(ssn)

    def _execute(self, ssn) -> None:
        preemptors_map: Dict[str, PriorityQueue] = {}
        preemptor_tasks: Dict[str, PriorityQueue] = {}
        under_request: List = []
        queues: Dict[str, object] = {}

        for job in ssn.jobs.values():
            queue = ssn.queues.get(job.queue)
            if queue is None:
                continue
            queues.setdefault(queue.uid, queue)
            if job.task_status_index.get(TaskStatus.Pending):
                if job.queue not in preemptors_map:
                    preemptors_map[job.queue] = PriorityQueue(ssn.job_order_fn)
                preemptors_map[job.queue].push(job)
                under_request.append(job)
                preemptor_tasks[job.uid] = ssn.task_queue(
                    job.task_status_index[TaskStatus.Pending].values())

        if not preemptors_map:
            return
        # The expensive pre-work (tensorize + resident index) gets its
        # own span: on big clusters it is the phase that stalls.
        with trace.span("preempt.prepare",
                        preemptor_jobs=len(under_request)):
            # Tensorize only when there is work: the scanner costs a
            # session flatten, pure overhead on healthy clusters.
            # shared=True: under the batched eviction engine this reuses
            # (and dirty-refreshes) the session scanner reclaim already
            # built and batch-seeded — no second tensorize, no second
            # per-profile solve (doc/EVICTION.md).
            from ..models.scanner import maybe_scanner
            scanner = maybe_scanner(ssn, shared=True)
            # One pass over residents: lets the walk skip nodes (and
            # whole preemptors) that provably cannot yield a victim —
            # the starved queue's O(tasks x nodes) empty walk collapses
            # to O(tasks).  Session-shared: reclaim (which runs first in
            # the shipped pipeline) already built and live-updated it.
            from ..models.victim_index import VictimIndex
            vindex = VictimIndex.for_session(ssn)
            if scanner is not None:
                vindex.attach_nodes(scanner.snap.node_names)

        # Preemption between jobs within a queue (preempt.go:76-134).
        for queue in queues.values():
            while True:
                preemptors = preemptors_map.get(queue.uid)
                if preemptors is None or preemptors.empty():
                    break
                preemptor_job = preemptors.pop()

                stmt = ssn.statement()
                if scanner is not None:
                    scanner.checkpoint()
                assigned = False
                while True:
                    if preemptor_tasks[preemptor_job.uid].empty():
                        break
                    preemptor = preemptor_tasks[preemptor_job.uid].pop()

                    def job_filter(task: TaskInfo) -> bool:
                        if task.status != TaskStatus.Running:
                            return False
                        job = ssn.jobs.get(task.job)
                        if job is None:
                            return False
                        return (job.queue == preemptor_job.queue
                                and preemptor.job != task.job)

                    if not vindex.any_for_queue(preemptor_job.queue,
                                                preemptor.job):
                        continue  # no node anywhere holds a victim
                    node_ok = (lambda name, q=preemptor_job.queue,
                               ju=preemptor.job:
                               vindex.node_for_queue(name, q, ju))
                    mask_fn = (lambda q=preemptor_job.queue,
                               ju=preemptor.job:
                               vindex.queue_mask(q, ju))
                    if _preempt(ssn, stmt, preemptor, ssn.nodes, job_filter,
                                scanner, node_ok, vindex, mask_fn):
                        assigned = True
                    # Pipelined checked at loop BOTTOM (preempt.go:
                    # 117-121): a re-popped already-pipelined job still
                    # preempts for one more task per pop.
                    if ssn.job_pipelined(preemptor_job):
                        break

                # Commit/discard decided once after the walk — every
                # checkpoint frame is balanced by exactly one commit or
                # restore, including the re-popped pipelined job whose
                # task queue is empty (an empty commit; the old
                # commit-inside-the-loop leaked that frame).
                if ssn.job_pipelined(preemptor_job):
                    stmt.commit()
                    if scanner is not None:
                        scanner.commit()
                    if assigned:
                        preemptors.push(preemptor_job)
                else:
                    stmt.discard()  # also counts victims back into vindex
                    if scanner is not None:
                        scanner.restore()

            # Preemption between tasks within a job (preempt.go:136-165).
            for job in under_request:
                while True:
                    tasks = preemptor_tasks.get(job.uid)
                    if tasks is None or tasks.empty():
                        break
                    preemptor = tasks.pop()
                    if not vindex.any_for_job(job.uid):
                        break  # the job has no Running task to sacrifice
                    stmt = ssn.statement()
                    assigned = _preempt(
                        ssn, stmt, preemptor, ssn.nodes,
                        lambda task: (task.status == TaskStatus.Running
                                      and preemptor.job == task.job),
                        scanner,
                        lambda name, ju=job.uid:
                        vindex.node_for_job(name, ju),
                        vindex)
                    stmt.commit()
                    if not assigned:
                        break


def _preempt(ssn, stmt, preemptor: TaskInfo, nodes, filter_fn,
             scanner=None, node_ok=None, vindex=None,
             mask_fn=None) -> bool:
    """Try to free room for preemptor on some node (preempt.go:171-254).

    ``node_ok(name)``: optional admissibility pre-filter (VictimIndex):
    nodes it rejects provably yield no candidates under ``filter_fn``,
    so they are skipped before materialization — the walk stops at the
    first workable node, so the lazy generator touches only the nodes
    actually visited."""
    scored = None
    mask = None
    if scanner is not None:
        if mask_fn is not None:
            mask = mask_fn()  # vectorized admissibility, may be None
        scored = scanner.candidate_nodes(preemptor, scored=True,
                                         admissible=mask)
    if scored is not None:
        if mask is not None:  # admissibility already applied in bulk
            selected_nodes = (ssn.nodes[name] for name, _ in scored
                              if name in ssn.nodes)
        else:
            selected_nodes = (ssn.nodes[name] for name, _ in scored
                              if (node_ok is None or node_ok(name))
                              and name in ssn.nodes)
    else:
        all_nodes = get_node_list(nodes)
        candidates = predicate_nodes(preemptor, all_nodes, ssn.predicate_fn)
        priority_list = prioritize_nodes(preemptor, candidates,
                                         ssn.node_prioritizers())
        selected_nodes = (node for node in
                          sort_nodes(priority_list, ssn.nodes)
                          if node_ok is None or node_ok(node.name))

    assigned = False
    for node in selected_nodes:
        preemptees = [task.clone() for task in node.tasks.values()
                      if filter_fn is None or filter_fn(task)]
        if not preemptees:
            continue  # no candidates -> no victims, provably
        victims = ssn.preemptable(preemptor, preemptees)
        metrics.update_preemption_victims_count(len(victims))

        if not _validate_victims(victims, preemptor.init_resreq):
            continue

        # Lowest-priority victims evicted first: reversed task order
        # (preempt.go:213-218).  The batched engine precomputed that
        # order for every Running resident (one ranking in the session's
        # single eviction dispatch); per-preemptor the sort collapses to
        # an index lookup — bit-identical because the key is total (uid
        # fallback) and immutable within the session.
        ordered_victims = _order_victims(ssn, victims, scanner)

        preempted = Resource.empty()
        resreq = preemptor.init_resreq.clone()
        for preemptee in ordered_victims:
            stmt.evict(preemptee, "preempt")
            if vindex is not None:
                vjob = ssn.jobs.get(preemptee.job)
                vindex.on_evict(node.name,
                                vjob.queue if vjob is not None else "",
                                preemptee.job)
            preempted.add(preemptee.resreq)
            if resreq.less_equal(preempted):
                break

        metrics.register_preemption_attempt()
        if preemptor.init_resreq.less_equal(preempted):
            stmt.pipeline(preemptor, node.name)
            if scanner is not None:
                scanner.apply_pipeline(preemptor, node.name)
            assigned = True
            break

    return assigned


def _order_victims(ssn, victims: List[TaskInfo], scanner) -> List[TaskInfo]:
    """Victims in eviction order (reversed task order, lowest priority
    first — Session.victims_queue semantics).  Prefers the batched
    engine's precomputed per-resident ranking; a victim outside it (or
    no ranking at all) falls back to the session queue, which is always
    exact."""
    rank = getattr(scanner, "victim_rank", None) if scanner is not None \
        else None
    if rank is not None:
        try:
            return sorted(victims, key=lambda t: rank[t.uid])
        except KeyError:
            pass  # a victim the ranking never saw: use the exact queue
    queue = ssn.victims_queue(victims)
    ordered: List[TaskInfo] = []
    while not queue.empty():
        ordered.append(queue.pop())
    return ordered


def _validate_victims(victims: List[TaskInfo], resreq: Resource) -> bool:
    """Victims exist and cover the requested resources (preempt.go:256-271)."""
    if not victims:
        return False
    total = Resource.empty()
    for v in victims:
        total.add(v.resreq)
    return resreq.less_equal(total)


def new() -> PreemptAction:
    return PreemptAction()
