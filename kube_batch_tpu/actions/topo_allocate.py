"""topo-allocate: contiguous TPU slice placement onto the torus.

Runs BEFORE the flat allocate family in the actions conf
(``actions: "topo-allocate, tpu-allocate, backfill"``): PodGroups
carrying a ``kube-batch.tpu/slice-shape`` annotation are placed as
axis-aligned contiguous boxes of the coordinate-labeled torus
(models/topology.py), and everything else falls through to the flat
actions untouched.  Placement decisions come from ONE batched device
dispatch per slice job (ops/topo_solver.box_scan over every candidate
origin); ``KUBE_BATCH_TPU_TOPO_BATCH=0`` routes the identical question
through the pure-numpy sequential oracle — placements, victims, and
victim order are bit-identical between the two engines
(tests/test_topology.py).

Decision order per slice job (all keys exact integers, ties broken on
the lowest origin row — deterministic):

1. **Free box** — every member free (empty + fits + predicates): pick
   the box with the FEWEST free boundary neighbors (tightest packing —
   the placement that preserves the largest contiguous free blocks
   elsewhere), then lowest origin.
2. **Defrag eviction** (``KUBE_BATCH_TPU_TOPO_DEFRAG=1``, default) —
   no free box: pick the cheapest fully-clearable box (fewest victims,
   then lowest victim priority sum, then boundary, then origin), evict
   its residents in the session's victim order (lowest priority first,
   exactly ``Session.victims_queue``), and pipeline the slice onto the
   releasing nodes — evicting to CREATE a contiguous slice, not just
   capacity.
3. **Capacity eviction** (the ``=0`` A/B control): evict the same
   victim ordering cluster-wide until enough nodes are cleared by
   COUNT, ignoring contiguity — the arm `make bench-topo` contrasts:
   it frees capacity but no contiguous block, so the slice stays
   pending and the fragmentation gauges show the difference.

A slice job that cannot be placed this session records a PodGroup
Unschedulable condition (``NoContiguousSlice`` / ``SliceTooFewTasks``)
and leaves the session — its tasks must NOT be scattered by the flat
actions.  ``KUBE_BATCH_TPU_TOPOLOGY=0`` makes the whole action a no-op
(bit-parity with a conf that never listed it).
"""

from __future__ import annotations

import functools
import logging
import time
from typing import List, Optional

import numpy as np

from ..framework import Action
from ..metrics import metrics
from ..trace import spans as trace

log = logging.getLogger(__name__)


def box_members(view, origin: int, shape) -> List[int]:
    """The box's node rows in (dx, dy, dz) offset order — the ONE
    member-enumeration order placement and the sequential oracle share
    (a different order would pair tasks with different hosts)."""
    sx, sy, sz = shape
    pod, _r, x, y, z, dx, dy, dz = (int(v) for v in view.coords[origin])
    rows: List[int] = []
    seen = set()
    for ox in range(sx):
        for oy in range(sy):
            for oz in range(sz):
                j = view._index.get(
                    (pod, (x + ox) % dx, (y + oy) % dy, (z + oz) % dz))
                if j is not None and j not in seen:
                    seen.add(j)
                    rows.append(j)
    return rows


class TopoAllocateAction(Action):

    def name(self) -> str:
        return "topo-allocate"

    # -- per-job node masks -------------------------------------------

    @staticmethod
    def _job_masks(ssn, view, job, task0):
        """(free, evictable, vic_cnt, vic_cost) over the view's rows.

        free: empty node, launch requirement fits idle, static predicate
        chain passes.  evictable: every resident is a Running task of
        strictly lower priority (and the empty node would fit the
        task).  Exact session-state reads only — both engines and both
        A/B arms see identical masks."""
        from ..api import TaskStatus

        n = len(view.node_names)
        free = np.zeros((n,), bool)
        evictable = np.zeros((n,), bool)
        vic_cnt = np.zeros((n,), np.int32)
        vic_cost = np.zeros((n,), np.int32)
        for i in range(n):
            if not view.valid[i]:
                continue
            node = ssn.nodes.get(view.node_names[i])
            if node is None or not node.ready():
                continue
            try:
                ssn.predicate_fn(task0, node)
            except Exception:  # lint: allow-swallow(predicate veto: any raise means infeasible, exactly like the host walk treats it)
                continue
            if not node.tasks:
                if task0.init_resreq.less_equal(node.idle):
                    free[i] = True
                continue
            if not task0.init_resreq.less_equal(node.allocatable):
                continue
            residents = list(node.tasks.values())
            if all(t.status == TaskStatus.Running
                   and t.priority < job.priority for t in residents):
                evictable[i] = True
                vic_cnt[i] = len(residents)
                # Clamp: a handful of system-range priorities (~2e9)
                # would overflow the int32 assignment into an
                # OverflowError that kills the cycle.  Both engines see
                # the same clamped value, so parity holds; ordering only
                # coarsens between astronomically-priced boxes.
                vic_cost[i] = min(sum(int(t.priority) for t in residents),
                                  np.iinfo(np.int32).max)
        return free, evictable, vic_cnt, vic_cost

    @staticmethod
    def _box_stats(view, free, evictable, vic_cnt, vic_cost, shape,
                   ssn=None):
        """Route the scan: batched kernel (one dispatch over the padded
        bucket) or the sequential oracle under TOPO_BATCH=0.  A device
        failure degrades to the oracle — identical integers, so the
        cycle's decisions are unchanged (counted, not silent)."""
        from ..models.topology import topo_batch_enabled
        from ..ops import topo_solver as ts
        from ..ops.compile_cache import bucket

        if not topo_batch_enabled():
            return ts.box_scan_seq(view, free, evictable, vic_cnt,
                                   vic_cost, shape)
        n = len(view.node_names)
        n_pad = bucket(max(n, 1))
        coords = np.full((n_pad, 8), -1, np.int32)
        coords[:n] = view.coords[:n]

        def pad(a):
            out = np.zeros((n_pad,), a.dtype)
            out[:n] = a
            return out

        inp = ts.BoxInputs(coords, pad(free), pad(evictable),
                           pad(vic_cnt), pad(vic_cost))
        if ssn is not None:
            # One-dispatch sessions (ops/fused_solver.py): the first
            # scan of the session stages here and rides the fused
            # program with the eviction/allocate legs; a served leg IS
            # this dispatch's [N, 6] rows (same kernel, same inputs).
            from ..ops import fused_solver
            stats = fused_solver.take_topo(ssn, inp, shape, n)
            if stats is not None:
                return stats
        try:
            with trace.span("topo.box_scan", shape="x".join(
                    str(s) for s in shape)):
                return ts.dispatch_box_scan(inp, shape)[:n]
        except Exception as exc:  # lint: allow-swallow(device scan failure degrades to the bit-identical numpy oracle; counted via swallowed_exceptions + degraded note)
            metrics.note_swallowed("topo_box_scan")
            trace.note_degraded(
                f"topo box scan degraded to the host oracle "
                f"({type(exc).__name__}: {exc})")
            return ts.box_scan_seq(view, free, evictable, vic_cnt,
                                   vic_cost, shape)

    # -- decision keys -------------------------------------------------

    @staticmethod
    def _pick_free(stats, vol: int) -> Optional[int]:
        from ..ops import topo_solver as ts
        ok = (stats[:, ts.COL_COMPLETE] == 1) & (stats[:, ts.COL_FREE]
                                                 == vol)
        if not ok.any():
            return None
        rows = np.nonzero(ok)[0]
        boundary = stats[rows, ts.COL_BOUNDARY]
        return int(rows[np.lexsort((rows, boundary))][0])

    @staticmethod
    def _pick_defrag(stats, vol: int) -> Optional[int]:
        from ..ops import topo_solver as ts
        ok = ((stats[:, ts.COL_COMPLETE] == 1)
              & (stats[:, ts.COL_BLOCKED] == 0)
              & (stats[:, ts.COL_FREE] < vol))
        if not ok.any():
            return None
        rows = np.nonzero(ok)[0]
        order = np.lexsort((rows, stats[rows, ts.COL_BOUNDARY],
                            stats[rows, ts.COL_VCOST],
                            stats[rows, ts.COL_VCNT]))
        return int(rows[order][0])

    # -- eviction ------------------------------------------------------

    @staticmethod
    def _evict_ordered(ssn, victims, reason: str) -> int:
        """Evict ``victims`` in the session's victim order (lowest
        priority first — Session.victims_queue, the same order the
        preempt action commits)."""
        q = ssn.victims_queue(victims)
        count = 0
        while not q.empty():
            v = q.pop()
            try:
                ssn.evict(v, reason)
            except (KeyError, ValueError):
                # Log-and-continue, the reference's commit discipline.
                log.warning("topo defrag evict of %s/%s failed",
                            v.namespace, v.name)
                continue
            count += 1
        return count

    def _capacity_evict(self, ssn, view, evictable, vol: int,
                        n_free: int) -> int:
        """The capacity-only control arm: clear whole nodes by COUNT
        (cheapest victims first) until enough nodes are free, with no
        contiguity requirement — the A/B baseline the defrag-aware
        evictor is measured against (tools/check_topo_ab.py)."""
        needed = vol - n_free
        if needed <= 0:
            return 0
        victims = []
        for i in np.nonzero(evictable)[0]:
            node = ssn.nodes.get(view.node_names[int(i)])
            if node is not None:
                # Clones, the preempt action's discipline: eviction
                # mutates job/node state via uid lookups, never through
                # the node's resident clone itself.
                victims.extend(t.clone() for t in node.tasks.values())
        if not victims:
            return 0
        q = ssn.victims_queue(victims)
        remaining = {}
        for v in victims:
            remaining[v.node_name] = remaining.get(v.node_name, 0) + 1
        cleared = 0
        evicted = 0
        while not q.empty() and cleared < needed:
            v = q.pop()
            try:
                ssn.evict(v, "topo-capacity")
            except (KeyError, ValueError):
                continue
            evicted += 1
            remaining[v.node_name] -= 1
            if remaining[v.node_name] == 0:
                cleared += 1
        return evicted

    # -- placement -----------------------------------------------------

    @staticmethod
    def _place_box(ssn, view, origin: int, shape, tasks, free) -> int:
        """Assign ``tasks`` onto the box's nodes in offset order:
        originally-free members allocate, freshly-evicted members
        pipeline onto their releasing resources (the preempt
        discipline).  Returns placed count."""
        rows = box_members(view, origin, shape)
        placed = 0
        for task, row in zip(tasks, rows):
            hostname = view.node_names[row]
            try:
                if free[row]:
                    ssn.allocate(task, hostname)
                else:
                    ssn.pipeline(task, hostname)
            except (KeyError, ValueError) as exc:
                log.warning("topo slice placement of %s/%s onto %s "
                            "failed: %s", task.namespace, task.name,
                            hostname, exc)
                continue
            placed += 1
        return placed

    @staticmethod
    def _mark_unschedulable(ssn, job, reason: str, message: str) -> None:
        """Record the verdict and remove the job from the session — a
        slice job must wait for its slice, not be scattered by the flat
        actions (the open_session job_valid discipline)."""
        from ..api.pod_group_info import (PodGroupCondition,
                                          PodGroupUnschedulableType)
        if job.pod_group is not None:
            cond = PodGroupCondition(
                type=PodGroupUnschedulableType, status="True",
                transition_id=ssn.uid, last_transition_time=time.time(),
                reason=reason, message=message)
            ssn.update_job_condition(job, cond)
            try:
                ssn.cache.update_job_status(job)
            except Exception:  # lint: allow-swallow(status-write failure must not abort the action; counted like open_session's gate)
                metrics.note_swallowed("job_status_update")
        ssn.jobs.pop(job.uid, None)

    # -- the action ----------------------------------------------------

    def execute(self, ssn) -> None:
        from ..models.topology import topology_enabled
        if not topology_enabled():
            return
        # Batched commit (framework/commit.py): the defrag/capacity
        # evictions of this walk accumulate in the per-action sink and
        # flush as ONE bulk egress + fused cache update at exit, like
        # preempt/reclaim (doc/EVICTION.md "Batched commit").
        from ..framework.commit import action_commit
        with action_commit(ssn, self.name()):
            self._execute(ssn)

    def _execute(self, ssn) -> None:
        from ..api import TaskStatus
        from ..models.topology import (build_view, job_slice_shape,
                                       topo_defrag_enabled, topo_max_nodes,
                                       topo_table, topology_enabled)
        slice_jobs = []
        for job in ssn.jobs.values():
            shape = job_slice_shape(job)
            if shape is not None and job.queue in ssn.queues:
                slice_jobs.append((job, shape))
        view = ssn.prescan.get("topo_view")
        if view is None:
            # Cheap probe first: an unlabeled cluster must not pay an
            # O(N) view build per cycle just because the action is in
            # the conf.
            from ..models.topology import POD_LABEL
            if not any(
                    n.node is not None
                    and POD_LABEL in n.node.metadata.labels
                    for n in ssn.nodes.values()):
                return
            view = build_view(ssn.nodes)
            ssn.prescan["topo_view"] = view
        if not view.n_valid:
            # Every coordinate degraded (or none parsed): there is no
            # torus this session, so slice jobs schedule flat — the
            # same semantics as KUBE_BATCH_TPU_TOPOLOGY=0 / an
            # unlabeled cluster, NOT a pending verdict.
            return

        placed_slices = 0
        if view.n_valid > topo_max_nodes() and slice_jobs:
            # The cap degrades slice placement, never slice SEMANTICS:
            # each slice job stays pending (removed from the session so
            # the flat actions cannot scatter its tasks), exactly like
            # a no-feasible-box verdict.
            trace.note_degraded(
                f"topology: {view.n_valid} coordinate nodes exceed "
                f"KUBE_BATCH_TPU_TOPO_MAX_NODES; slice placement skipped")
            for job, shape in slice_jobs:
                metrics.note_topo_slice("degraded")
                self._mark_unschedulable(
                    ssn, job, "SliceDegraded",
                    f"{view.n_valid} coordinate nodes exceed the "
                    f"KUBE_BATCH_TPU_TOPO_MAX_NODES box-scan cap; the "
                    f"slice waits rather than scattering flat")
            slice_jobs = []

        if slice_jobs:
            def cmp(a, b):
                if ssn.job_order_fn(a[0], b[0]):
                    return -1
                if ssn.job_order_fn(b[0], a[0]):
                    return 1
                return 0

            slice_jobs.sort(key=functools.cmp_to_key(cmp))
        for job, shape in slice_jobs:
            if job.uid not in ssn.jobs:
                continue
            vol = shape[0] * shape[1] * shape[2]
            tasks = ssn.task_queue(
                t for t in job.task_status_index.get(
                    TaskStatus.Pending, {}).values()
                if not t.resreq.is_empty())
            ordered_tasks = []
            while not tasks.empty():
                ordered_tasks.append(tasks.pop())
            if len(ordered_tasks) < vol:
                metrics.note_topo_slice("too_few_tasks")
                self._mark_unschedulable(
                    ssn, job, "SliceTooFewTasks",
                    f"slice {shape[0]}x{shape[1]}x{shape[2]} needs "
                    f"{vol} pending tasks, job has {len(ordered_tasks)}")
                continue
            task0 = ordered_tasks[0]
            free, evictable, vic_cnt, vic_cost = self._job_masks(
                ssn, view, job, task0)
            stats = self._box_stats(view, free, evictable, vic_cnt,
                                    vic_cost, shape, ssn=ssn)
            origin = self._pick_free(stats, vol)
            if origin is not None:
                placed = self._place_box(ssn, view, origin, shape,
                                         ordered_tasks[:vol], free)
                metrics.note_topo_slice("placed")
                placed_slices += 1
                trace.annotate(topo_slice=f"{job.namespace}/{job.name}",
                               origin=view.node_names[origin],
                               placed=placed)
                continue
            if topo_defrag_enabled():
                origin = self._pick_defrag(stats, vol)
                if origin is not None:
                    rows = box_members(view, origin, shape)
                    victims = []
                    for row in rows:
                        if free[row]:
                            continue
                        node = ssn.nodes.get(view.node_names[row])
                        if node is not None:
                            victims.extend(t.clone()
                                           for t in node.tasks.values())
                    self._evict_ordered(ssn, victims, "topo-defrag")
                    placed = self._place_box(ssn, view, origin, shape,
                                             ordered_tasks[:vol], free)
                    metrics.note_topo_slice("defrag_placed")
                    placed_slices += 1
                    trace.annotate(
                        topo_slice=f"{job.namespace}/{job.name}",
                        origin=view.node_names[origin],
                        victims=len(victims), placed=placed)
                    continue
            else:
                n_free = int(free.sum())
                evicted = self._capacity_evict(ssn, view, evictable, vol,
                                               n_free)
                if evicted:
                    trace.annotate(topo_capacity_evicted=evicted)
            metrics.note_topo_slice("pending")
            self._mark_unschedulable(
                ssn, job, "NoContiguousSlice",
                f"no feasible {shape[0]}x{shape[1]}x{shape[2]} "
                "contiguous block (free or clearable) in any pool")

        # Fragmentation SLO (doc/TOPOLOGY.md): free = no resident holding
        # resources (empty node, or every resident Releasing after a
        # defrag evict) — computed in this action's occupancy walk and
        # published per pool.
        free_now = np.zeros((len(view.node_names),), bool)
        for i, name in enumerate(view.node_names):
            if not view.valid[i]:
                continue
            node = ssn.nodes.get(name)
            if node is None:
                continue
            free_now[i] = (not node.tasks) or all(
                t.status == TaskStatus.Releasing
                for t in node.tasks.values())
        pools = view.frag_stats(free_now)
        metrics.publish_topo_frag(pools)
        topo_table.publish(pools, extra={
            "coord_nodes": view.n_valid,
            "slices_placed_this_session": placed_slices,
        })
        trace.set_meta(topo_pools=len(pools),
                       topo_slices_placed=placed_slices)


def new() -> TopoAllocateAction:
    return TopoAllocateAction()
