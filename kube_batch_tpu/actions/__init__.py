"""Scheduling actions (L4): allocate, preempt, reclaim, backfill,
tpu-allocate.

TPU-native counterpart of /root/reference/pkg/scheduler/actions/.
"""
