"""Action registration (reference actions/factory.go:28-33)."""

from ..framework import register_action
from . import allocate, backfill, preempt, reclaim


def register_default_actions() -> None:
    register_action(allocate.new())
    register_action(preempt.new())
    register_action(reclaim.new())
    register_action(backfill.new())
    # The TPU-batched allocate action (imports jax lazily).
    from . import tpu_allocate
    register_action(tpu_allocate.new())
    # Topology-aware slice placement (imports jax lazily via the
    # batched box scan; doc/TOPOLOGY.md).
    from . import topo_allocate
    register_action(topo_allocate.new())
