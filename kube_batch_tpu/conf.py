"""Scheduler configuration schema.

Mirrors /root/reference/pkg/scheduler/conf/scheduler_conf.go:19-56 (actions
string + plugin tiers with per-callback enable flags + untyped arguments) and
plugins/defaults.go:22-50 (flags default to enabled).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .framework.arguments import Arguments


@dataclass
class PluginOption:
    name: str = ""
    enabled_job_order: Optional[bool] = None
    enabled_job_ready: Optional[bool] = None
    enabled_job_pipelined: Optional[bool] = None
    enabled_task_order: Optional[bool] = None
    enabled_preemptable: Optional[bool] = None
    enabled_reclaimable: Optional[bool] = None
    enabled_queue_order: Optional[bool] = None
    enabled_predicate: Optional[bool] = None
    enabled_node_order: Optional[bool] = None
    arguments: Arguments = field(default_factory=Arguments)


@dataclass
class Tier:
    plugins: List[PluginOption] = field(default_factory=list)


@dataclass
class SchedulerConfiguration:
    actions: str = ""
    tiers: List[Tier] = field(default_factory=list)


_FLAG_KEYS = {
    "enableJobOrder": "enabled_job_order",
    "enableJobReady": "enabled_job_ready",
    "enableJobPipelined": "enabled_job_pipelined",
    "enableTaskOrder": "enabled_task_order",
    "enablePreemptable": "enabled_preemptable",
    "enableReclaimable": "enabled_reclaimable",
    "enableQueueOrder": "enabled_queue_order",
    "enablePredicate": "enabled_predicate",
    "enableNodeOrder": "enabled_node_order",
}


def apply_plugin_conf_defaults(option: PluginOption) -> None:
    """Unset enable flags default to True (plugins/defaults.go:22-50)."""
    for attr in _FLAG_KEYS.values():
        if getattr(option, attr) is None:
            setattr(option, attr, True)


def configuration_from_dict(data: dict) -> SchedulerConfiguration:
    """Build a SchedulerConfiguration from a parsed YAML/JSON mapping."""
    conf = SchedulerConfiguration(actions=data.get("actions", "") or "")
    for tier_data in data.get("tiers") or []:
        tier = Tier()
        for plugin_data in tier_data.get("plugins") or []:
            option = PluginOption(name=plugin_data.get("name", ""))
            for yaml_key, attr in _FLAG_KEYS.items():
                if yaml_key in plugin_data:
                    setattr(option, attr, bool(plugin_data[yaml_key]))
            option.arguments = Arguments(plugin_data.get("arguments") or {})
            tier.plugins.append(option)
        conf.tiers.append(tier)
    return conf
