"""ShardView: the shard-scoped face of a SchedulerCache.

One persistent view per shard wraps the shared cache and narrows exactly
three surfaces:

* ``snapshot()`` — the session sees only the shard's queues and their
  jobs (all nodes: capacity is shared cluster-wide), so tensorize/solve/
  close are O(shard), not O(cluster);
* the incremental-close bookkeeping — ``close_plan`` intersects the
  cache-wide plan with the shard's job universe and
  ``note_close_results`` merges (instead of replacing) the cache's
  active set, so shard A's close cannot clobber shard B's quiet-skip
  license;
* the write egress (``bind``/``bind_batch``/``evict``/
  ``update_job_status``) — fenced on the shard's lease when a
  federation lease manager is attached (the per-shard form of the
  cache-wide ``write_fence``), and bind egress is stamped with the
  owning replica.

Everything else delegates to the underlying cache.  The per-cache
solver-state attachments (``_tensor_cache`` / ``_inc_state`` /
``_ship_cache``) are declared as class attributes so each view grows its
OWN persistent device state: a shard's tensors, dirty rows, and
device-resident buffers never thrash against another shard's.
"""

from __future__ import annotations

import logging
from typing import Callable, Optional, Set

from ..api import ClusterInfo
from ..metrics import metrics

log = logging.getLogger(__name__)


class ShardView:
    # Per-cache solver-state attachment points (models/tensor_snapshot,
    # models/incremental, models/shipping look these up with getattr):
    # declared None here so the lookups do NOT fall through __getattr__
    # to the shared cache — each view keeps its own persistent state.
    _tensor_cache = None
    _inc_state = None
    _ship_cache = None

    def __init__(self, cache, shard: int, shard_map, replica: str = "",
                 lease_live: Optional[Callable[[int], bool]] = None):
        self._cache = cache
        self.shard = int(shard)
        self._map = shard_map
        self.replica = replica
        self._lease_live = lease_live
        # Job uids / queue names the LAST shard snapshot served: the
        # close-bookkeeping merge universe (scheduler loop thread only —
        # shard sessions within one engine snapshot strictly serially,
        # even when the concurrent pipeline overlaps their device
        # windows).  _last_pods feeds the shard-load EWMA (ROADMAP 2c).
        self._last_jobs: Set[str] = set()
        self._last_queues: tuple = ()
        self._last_pods: int = 0

    def __getattr__(self, name):
        return getattr(self._cache, name)

    def __repr__(self) -> str:
        return (f"ShardView(shard={self.shard}, replica={self.replica!r}, "
                f"cache={self._cache!r})")

    # -- shard-scoped snapshot ----------------------------------------------

    def _mine(self, queue: str) -> bool:
        return self._map.shard_of(queue) == self.shard

    def owns_queue(self, queue: str) -> bool:
        """Whether this shard owns ``queue`` under the shard map — the
        tenant-table publication universe (metrics/tenants.py): a
        MEMBERSHIP TEST rather than the session's current queue set, so
        a queue that was deleted from the cluster still counts as this
        shard's departure to detect and zero."""
        return self._mine(queue)

    def snapshot(self) -> ClusterInfo:
        """The shard's slice of the cache snapshot: this shard's queues,
        those queues' jobs, ALL nodes (shared capacity — another
        tenant's binds are visible as used resources, exactly as they
        are to a later cycle of the global engine)."""
        info = self._cache.snapshot()
        out = ClusterInfo()
        out.nodes = info.nodes
        out.queues = {name: q for name, q in info.queues.items()
                      if self._mine(name)}
        queues = out.queues
        out.jobs = {uid: job for uid, job in info.jobs.items()
                    if job.queue in queues}
        self._last_jobs = set(out.jobs)
        self._last_queues = tuple(queues)
        self._last_pods = sum(len(job.tasks) for job in out.jobs.values())
        return out

    # -- incremental-close bookkeeping, shard-scoped ------------------------

    def close_plan(self):
        plan = self._cache.close_plan()
        if plan is None:
            return None
        active, recloned, seqmap = plan
        jobs = self._last_jobs
        return (active & jobs, recloned & jobs, seqmap)

    def note_close_results(self, active: set) -> None:
        # Merge against THIS shard's job universe: jobs of other shards
        # keep their cache-wide quiet/active verdicts untouched.
        self._cache.note_close_results(
            set(active), universe=self._last_jobs | set(active))

    # -- fenced write egress ------------------------------------------------

    def _check_shard_fence(self) -> None:
        """Per-shard write fence (doc/TENANCY.md "Failover contract"):
        once this replica can no longer prove a live lease on the shard
        — renewal failed past the deadline, the lease was stolen, or an
        injected clock skew says our clock ran past it — every cluster
        write for the shard refuses.  The new owner may already be
        scheduling these queues; racing it would turn failover into a
        double-bind attempt (the truth store's 409 would still reject
        it, but the fence keeps the loser from ever sending)."""
        if self._lease_live is not None and not self._lease_live(self.shard):
            metrics.note_shard_lease(self.shard, "fenced_write")
            raise RuntimeError(
                f"shard {self.shard} lease lost: refusing cluster write "
                "(another replica may already own this shard)")

    def bind(self, task, hostname: str) -> None:
        self._check_shard_fence()
        self._cache.bind(task, hostname)
        metrics.note_shard_binds(self.shard, self.replica, 1)

    def bind_batch(self, tasks) -> None:
        self._check_shard_fence()
        self._cache.bind_batch(tasks)
        metrics.note_shard_binds(self.shard, self.replica, len(tasks))

    def evict(self, task, reason: str) -> None:
        self._check_shard_fence()
        self._cache.evict(task, reason)

    def evict_many(self, pairs) -> list:
        """Fenced form of the batched commit flush's bulk evict
        (SchedulerCache.evict_many): without this override the flush
        would fall through __getattr__ to the unfenced cache method and
        a lease-lost replica could bulk-DELETE a whole victim batch
        into a shard another replica already owns."""
        self._check_shard_fence()
        return self._cache.evict_many(pairs)

    def update_job_status(self, job):
        self._check_shard_fence()
        return self._cache.update_job_status(job)
