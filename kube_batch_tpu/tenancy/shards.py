"""Queue -> shard assignment and per-shard churn attribution.

The shard map is DETERMINISTIC across processes and restarts: by default
a queue hashes to ``blake2b(queue) % num_shards`` (keyed hashing, so the
assignment is independent of PYTHONHASHSEED and identical on every
replica — two replicas that disagree about a queue's shard would both
schedule it), with explicit per-queue overrides from
``KUBE_BATCH_TPU_SHARD_MAP`` for operators that want tenant pinning
(e.g. a whale tenant alone on its own shard).

``ShardChurn`` is the per-shard form of ``SchedulerCache.churn_event``:
the cache's external ingestion paths attribute each mutation to the
affected queue's shard (queue-less mutations — nodes, PriorityClasses —
dirty every shard), and the tenancy engine drains the dirty-shard set to
decide which micro-sessions the next loop iteration runs.
"""

from __future__ import annotations

import hashlib
import threading
import time
from typing import Dict, Iterable, Optional, Set

from .. import knobs

TENANCY_ENV = knobs.TENANCY.env
SHARD_MAP_ENV = knobs.SHARD_MAP.env


def tenancy_shards() -> int:
    """Configured shard count, 0 = tenancy disabled (the single-engine
    control arm).  A malformed value raises: running a silently
    different tenancy topology than configured is the conf-parsing
    failure mode scheduler._mini_yaml refuses too."""
    raw = (knobs.TENANCY.raw() or "").strip()
    if not raw or raw.lower() in ("0", "off", "false"):
        return 0
    shards = int(raw)
    if shards < 1:
        raise ValueError(
            f"{TENANCY_ENV}={raw!r}: shard count must be >= 1 (or 0/off "
            "to disable tenancy)")
    return shards


def parse_shard_overrides(spec: Optional[str],
                          num_shards: int) -> Dict[str, int]:
    """``queue:shard|queue:shard`` explicit pins.  Malformed entries and
    out-of-range shards raise — a typo must not silently strand a tenant
    on the hash default."""
    out: Dict[str, int] = {}
    if not spec:
        return out
    for entry in spec.split("|"):
        entry = entry.strip()
        if not entry:
            continue
        queue, sep, shard = entry.rpartition(":")
        if not sep or not queue:
            raise ValueError(
                f"{SHARD_MAP_ENV} entry {entry!r}: expected <queue>:<shard>")
        idx = int(shard)
        if not 0 <= idx < num_shards:
            raise ValueError(
                f"{SHARD_MAP_ENV} entry {entry!r}: shard {idx} out of "
                f"range for {num_shards} shards")
        out[queue] = idx
    return out


class ShardMap:
    """Deterministic queues -> shard assignment (hash by default,
    explicit conf override).  Immutable once built: every replica of a
    federation derives the identical map from the same configuration."""

    def __init__(self, num_shards: int,
                 overrides: Optional[Dict[str, int]] = None):
        if num_shards < 1:
            raise ValueError(f"num_shards must be >= 1, got {num_shards}")
        self.num_shards = int(num_shards)
        self.overrides = dict(overrides or {})
        for queue, shard in self.overrides.items():
            if not 0 <= shard < self.num_shards:
                raise ValueError(
                    f"shard override {queue}:{shard} out of range for "
                    f"{self.num_shards} shards")
        # Queue -> shard memo: shard_of sits on hot paths (inside the
        # ShardChurn lock on every cache mutation, and in the per-shard
        # snapshot queue filter), and the map is immutable, so each
        # queue hashes exactly once.  Bounded by the cluster's queue
        # count (operator-created objects, not adversarial input).
        self._memo: Dict[str, int] = {}

    @classmethod
    def from_env(cls, num_shards: int) -> "ShardMap":
        return cls(num_shards, parse_shard_overrides(
            knobs.SHARD_MAP.raw(), num_shards))

    def shard_of(self, queue: str) -> int:
        shard = self._memo.get(queue)
        if shard is not None:
            return shard
        pinned = self.overrides.get(queue)
        if pinned is not None:
            shard = pinned
        else:
            digest = hashlib.blake2b(str(queue).encode(),
                                     digest_size=8).digest()
            shard = int.from_bytes(digest, "big") % self.num_shards
        # dict writes are atomic under the GIL; a racing duplicate
        # compute stores the identical value.
        self._memo[queue] = shard
        return shard

    def shards_of(self, queues: Iterable[str]) -> Dict[int, list]:
        """{shard: [queues]} for a queue collection (debug surfaces)."""
        out: Dict[int, list] = {}
        for queue in queues:
            out.setdefault(self.shard_of(queue), []).append(queue)
        return out


class ShardLoad:
    """Per-shard load EWMA of (pod count, churn rate) — the claim-target
    weighting for replica federation (ROADMAP 2c, doc/TENANCY.md): a
    whale tenant's shard should count for what it costs (pods to
    snapshot/tensorize, churn events to absorb), not as one unit of N.

    ``note_churn`` ticks from the cache ingestion hot path (inside the
    ShardChurn lock, one list increment); ``note_session`` folds the
    accumulated events into a per-second rate and EWMA-blends both
    signals after each shard session.  ``load`` is read by the lease
    manager's spread deferral and /debug/shards."""

    ALPHA = 0.3          # EWMA blend per session
    CHURN_WEIGHT = 5.0   # one churn event/s ~ five resident pods of load
    MIN_RATE_WINDOW = 0.25  # s: shorter windows keep accumulating —
    # rate = events/elapsed over a milliseconds window would turn a
    # couple of events into a triple-digit rate spike, poisoning the
    # claim-target fair-share math

    def __init__(self, num_shards: int):
        self._lock = threading.Lock()
        n = int(num_shards)
        self._pods = [0.0] * n        # EWMA pods       guarded-by: _lock
        self._rate = [0.0] * n        # EWMA churn/s    guarded-by: _lock
        self._events = [0] * n        # since last fold guarded-by: _lock
        self._folded = [0.0] * n      # last fold time  guarded-by: _lock

    def note_churn(self, shard: int) -> None:
        with self._lock:
            self._events[shard] += 1

    def note_session(self, shard: int, pods: int) -> float:
        """Fold one finished shard session's observation in; returns the
        refreshed load estimate (also published as a gauge)."""
        from ..metrics import metrics
        now = time.time()
        a = self.ALPHA
        with self._lock:
            last = self._folded[shard]
            if not last:
                # First observation: start the rate window, no fold.
                self._events[shard] = 0
                self._folded[shard] = now
            elif now - last >= self.MIN_RATE_WINDOW:
                rate = self._events[shard] / max(now - last, 1e-6)
                self._events[shard] = 0
                self._folded[shard] = now
                self._rate[shard] = a * rate \
                    + (1.0 - a) * self._rate[shard]
            # else: window too short — keep accumulating events.
            self._pods[shard] = a * float(pods) \
                + (1.0 - a) * self._pods[shard]
            load = self._load_locked(shard)
        metrics.set_shard_load(shard, load)
        return load

    def _load_locked(self, shard: int) -> float:
        return self._pods[shard] + self.CHURN_WEIGHT * self._rate[shard]

    def load(self, shard: int) -> float:
        with self._lock:
            return self._load_locked(shard)

    def loads(self) -> list:
        with self._lock:
            return [self._load_locked(s) for s in range(len(self._pods))]


class ShardChurn:
    """Dirty-shard set fed by the cache's external ingestion paths.

    ``note`` is the cache-side hook (installed as
    ``SchedulerCache.shard_churn``): queue-attributed churn dirties one
    shard, queue-less churn (node/PriorityClass/unresolvable) dirties
    all — an over-approximation is always safe (a spurious micro-session
    finds nothing to do), an under-approximation would strand work."""

    def __init__(self, shard_map: ShardMap,
                 load: Optional["ShardLoad"] = None):
        self._map = shard_map
        self._load = load
        self._lock = threading.Lock()
        self._dirty: Set[int] = set(range(shard_map.num_shards))  # guarded-by: _lock

    def note(self, queue: Optional[str] = None) -> None:
        with self._lock:
            if queue is None:
                self._dirty.update(range(self._map.num_shards))
            else:
                shard = self._map.shard_of(queue)
                self._dirty.add(shard)
                if self._load is not None:
                    # Queue-attributed churn only: broadcast dirtying is
                    # bookkeeping, not per-tenant demand.
                    self._load.note_churn(shard)

    def note_shard(self, shard: int) -> None:
        """Re-mark a shard dirty (engine-side: a skipped or failed
        micro-session must not absorb the churn that requested it)."""
        with self._lock:
            self._dirty.add(shard)

    def take(self) -> Set[int]:
        """Drain the dirty-shard set (scheduler loop thread)."""
        with self._lock:
            out = self._dirty
            self._dirty = set()
            return out
