"""Queue-shard tenancy engine (doc/TENANCY.md).

Tenancy as a SCALING axis instead of an accounting row (ROADMAP item 2):
queues are deterministically assigned to shards (``ShardMap``), cache
churn is attributed to the owning shard (``ShardChurn``), and the
scheduler loop runs one shard-scoped micro-session per dirty shard
(``TenancyEngine`` + ``ShardView``) instead of one global cycle — so a
churn storm in tenant A's queues cannot serialize tenant B's
time-to-bind behind it.  ``ShardPipeline`` overlaps those micro-sessions
through the async device-dispatch window (host phases of shard K+1 run
while shard K's solve executes; retire halves stay in shard order —
"Concurrent micro-sessions").  ``ShardLeaseManager`` takes the same axis
horizontal: N active-active replicas each claim queue-shards via
per-shard CAS leases in the shared store (the per-shard form of the
ConfigMap-lock LeaderElector already ported in cli/leader_election.py),
with steal-on-expiry failover, load-weighted claim targets
(``ShardLoad``), and the truth store's 409 re-bind rejection as the
cross-replica double-bind backstop.

Kill switches: ``KUBE_BATCH_TPU_TENANCY`` unset/``0`` keeps the single
global engine — the bit-parity control arm the tenancy tests pin —
and ``KUBE_BATCH_TPU_CONCURRENT_SHARDS=0`` keeps the strictly
sequential shard walk (the concurrency parity control).
"""

from .debug import shard_table
from .engine import TenancyEngine, engine_from_env
from .leases import ShardLeaseManager
from .pipeline import (CONCURRENT_ENV, INFLIGHT_ENV, ShardPipeline,
                       concurrent_shards_enabled, shard_inflight_depth)
from .shards import (SHARD_MAP_ENV, TENANCY_ENV, ShardChurn, ShardLoad,
                     ShardMap, tenancy_shards)
from .view import ShardView

__all__ = [
    "CONCURRENT_ENV", "INFLIGHT_ENV", "SHARD_MAP_ENV", "TENANCY_ENV",
    "ShardChurn", "ShardLeaseManager", "ShardLoad", "ShardMap",
    "ShardPipeline", "ShardView", "TenancyEngine",
    "concurrent_shards_enabled", "engine_from_env", "shard_inflight_depth",
    "shard_table", "tenancy_shards",
]
