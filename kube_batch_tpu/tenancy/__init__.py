"""Queue-shard tenancy engine (doc/TENANCY.md).

Tenancy as a SCALING axis instead of an accounting row (ROADMAP item 2):
queues are deterministically assigned to shards (``ShardMap``), cache
churn is attributed to the owning shard (``ShardChurn``), and the
scheduler loop pipelines one shard-scoped micro-session per dirty shard
(``TenancyEngine`` + ``ShardView``) instead of one global cycle — so a
churn storm in tenant A's queues cannot serialize tenant B's
time-to-bind behind it.  ``ShardLeaseManager`` takes the same axis
horizontal: N active-active replicas each claim queue-shards via
per-shard CAS leases in the shared store (the per-shard form of the
ConfigMap-lock LeaderElector already ported in cli/leader_election.py),
with steal-on-expiry failover and the truth store's 409 re-bind
rejection as the cross-replica double-bind backstop.

Kill switch: ``KUBE_BATCH_TPU_TENANCY`` unset/``0`` keeps the single
global engine — the bit-parity control arm the tenancy tests pin.
"""

from .debug import shard_table
from .engine import TenancyEngine, engine_from_env
from .leases import ShardLeaseManager
from .shards import (SHARD_MAP_ENV, TENANCY_ENV, ShardChurn, ShardMap,
                     tenancy_shards)
from .view import ShardView

__all__ = [
    "SHARD_MAP_ENV", "TENANCY_ENV", "ShardChurn", "ShardLeaseManager",
    "ShardMap", "ShardView", "TenancyEngine", "engine_from_env",
    "shard_table", "tenancy_shards",
]
