"""Per-shard leases: active-active replica federation.

Instead of ONE leader owning the whole cluster (cli/leader_election.py,
the reference's ConfigMap-lock LeaderElector), each queue-shard is its
own lease object in the shared store — ``kube-batch-shard-<i>`` —
claimed, renewed, and stolen via the same StoreLock CAS the global
elector uses (Cluster and RemoteCluster both serialize the CAS; over
the edge it rides the version-guarded PUT that 409s on conflict).  N
replicas each own a subset of shards and schedule only those; a crashed
replica's shards expire and are stolen by survivors within one lease
duration, warm-starting from the shared persistent compile cache
(``--compile-cache-dir``) so failover never pays the first XLA compile.

Lease state machine per (replica, shard) — doc/TENANCY.md:

    free/expired --claim/steal--> owned --renew--> owned
    owned --renew failures past renew_deadline--> lost (fenced)
    owned --lease observed under another holder--> lost (fenced)
    owned --release (clean shutdown)--> free

The fence is WALL-CLOCK based like LeaderElector.has_live_lease: a
replica that cannot prove a renewal within ``renew_deadline`` refuses
all writes for the shard (ShardView._check_shard_fence) even before the
lease thread runs again.  The truth store's 409 re-bind rejection
remains the cross-replica backstop for the ambiguity window.

Chaos sites (doc/CHAOS.md): ``lease.cas_conflict:<shard>`` makes a
claim/renew CAS lose as if another replica raced it;
``lease.clock_skew:<shard>`` makes this replica's clock appear to have
run past its own lease — it must ABANDON the shard (fence closes)
instead of racing the next owner.
"""

from __future__ import annotations

import logging
import os
import socket
import threading
import time
from typing import Callable, Dict, List, Optional

from ..chaos import plan as chaos_plan
from ..cli.leader_election import StoreLock
from ..metrics import metrics
from .debug import shard_table

log = logging.getLogger(__name__)

SHARD_LOCK_PREFIX = "kube-batch-shard"

DEFAULT_SHARD_LEASE_DURATION = 5.0
DEFAULT_SHARD_RENEW_DEADLINE = 3.0
DEFAULT_SHARD_RETRY_PERIOD = 1.0


def shard_lock_name(shard: int) -> str:
    return f"{SHARD_LOCK_PREFIX}-{int(shard)}"


def _default_identity() -> str:
    import uuid
    return (f"{socket.gethostname()}-{os.getpid()}-"
            f"{uuid.uuid4().hex[:8]}")


class ShardLeaseManager:
    """Claim-and-renew loop over one CAS lease per shard."""

    def __init__(self, cluster, namespace: str, num_shards: int,
                 identity: str = "",
                 lease_duration: float = DEFAULT_SHARD_LEASE_DURATION,
                 renew_deadline: float = DEFAULT_SHARD_RENEW_DEADLINE,
                 retry_period: float = DEFAULT_SHARD_RETRY_PERIOD,
                 target_shards: Optional[int] = None,
                 on_claim: Optional[Callable[[int], None]] = None,
                 shard_load: Optional[Callable[[int], float]] = None):
        if renew_deadline >= lease_duration:
            raise ValueError(
                "renew_deadline must be < lease_duration (a replica must "
                "fence itself before its lease can expire under it)")
        self.identity = identity or _default_identity()
        self.lease_duration = float(lease_duration)
        self.renew_deadline = float(renew_deadline)
        self.retry_period = float(retry_period)
        # Soft spread target: a replica at/over target defers claiming a
        # FREE shard for one extra lease duration so an under-loaded
        # replica can take it first — but never forever (an orphan shard
        # beats a balanced outage).
        self.target_shards = target_shards
        # Load-weighted claim targets (ROADMAP 2c): when a shard-load
        # estimator is attached (the tenancy engine's pods+churn EWMA —
        # every replica mirrors the whole cluster, so its own estimate
        # covers every shard), the spread deferral compares owned LOAD
        # against the fair load share target_shards implies, instead of
        # raw shard counts — a whale tenant's shard weighs what it
        # costs, so the whale's owner defers claiming extra shards while
        # its peers soak up the small ones.
        self.shard_load = shard_load
        self.num_shards = int(num_shards)
        self.locks: List[StoreLock] = [
            StoreLock(cluster, namespace, name=shard_lock_name(i))
            for i in range(num_shards)]
        self._on_claim = on_claim
        # Ownership-change hook, fired on EVERY transition — claim,
        # steal, shed, loss — with (shard, kind).  The shard-scoped
        # reflector wiring (edge/wire_shard.attach_shard_scope) installs
        # its scope-epoch bump here so a filtered watch rescopes the
        # moment the owned set moves; _on_claim above stays claim-only
        # (the engine's churn wake).  Assignable any time; called from
        # the lease thread.
        self.on_change: Optional[Callable[[int, str], None]] = None
        self._lock = threading.Lock()
        self._renewed: Dict[int, float] = {}   # shard -> last renew  guarded-by: _lock
        # Spread-target deferral bookkeeping (lease thread only): when
        # this replica first saw each claimable shard while sitting at
        # or over its target.
        self._deferred_since: Dict[int, float] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Deterministic per-replica claim order (rotate by identity
        # hash): replicas racing a cold federation start claiming from
        # different shards, so the initial CAS races spread ownership
        # instead of serializing every replica onto shard 0 first.
        import hashlib
        rot = int.from_bytes(hashlib.blake2b(
            self.identity.encode(), digest_size=4).digest(), "big")
        self._order = [(i + rot) % num_shards for i in range(num_shards)]

    # -- ownership queries (any thread) -------------------------------------

    def owned_shards(self) -> List[int]:
        now = time.time()
        with self._lock:
            return sorted(s for s, renewed in self._renewed.items()
                          if now - renewed < self.renew_deadline)

    def lease_live(self, shard: int) -> bool:
        """Wall-clock write fence: True only while the shard's lease was
        renewed within renew_deadline (LeaderElector.has_live_lease
        semantics — a paused process fences itself the moment the clock
        says so, before the lease thread ever runs again)."""
        with self._lock:
            renewed = self._renewed.get(shard)
        return renewed is not None and \
            time.time() - renewed < self.renew_deadline

    # -- the claim/renew loop -----------------------------------------------

    def tick(self) -> None:
        """One pass over every shard (also driven directly by tests and
        the replica soak for deterministic stepping)."""
        for shard in self._order:
            try:
                self._tick_shard(shard)
            except Exception:  # lint: allow-swallow(one shard's store hiccup must not stall the other shards' renewals; the failed shard retries next tick and the renew deadline fences it meanwhile)
                metrics.note_swallowed("shard_lease_tick")
        try:
            self._maybe_shed_load(time.time())
        except Exception:  # lint: allow-swallow(load shedding is balance polish, never liveness: a failed shed retries next tick; counted)
            metrics.note_swallowed("shard_lease_shed")
        self._publish()

    def _maybe_shed_load(self, now: float) -> None:
        """Load-weighted rebalance, shed side (ROADMAP 2c): with a load
        estimator attached, a replica whose owned LOAD exceeds its fair
        share even after giving up its lightest shard cleanly releases
        that shard (at most one per tick) so an under-loaded replica
        claims it — a whale tenant's owner converges to owning the whale
        alone while its peers soak up the small shards.  The guard
        ``mine - lightest >= fair`` is the oscillation fence: after the
        shed we are still at/over fair, so our own claim deferral keeps
        us from immediately taking the shard back, and a replica at
        exactly fair (the uniform-load fleet) never sheds at all.
        Count-based targets (no estimator) never shed — the PR 13
        behavior unchanged."""
        if self.shard_load is None or self.target_shards is None:
            return
        with self._lock:
            owned = [s for s, renewed in self._renewed.items()
                     if now - renewed < self.renew_deadline]
        if len(owned) <= 1:
            return  # never shed the last owned shard
        loads = {s: max(float(self.shard_load(s)), 0.0) + 1.0
                 for s in range(self.num_shards)}
        total = sum(loads.values())
        fair = total * (float(self.target_shards)
                        / max(self.num_shards, 1))
        mine = sum(loads[s] for s in owned)
        victim = min(owned, key=lambda s: loads[s])
        if mine - loads[victim] < fair:
            return
        # Absorption check: shed only when some OTHER live replica could
        # take the victim without itself going over fair — read the
        # store's current lease records and sum each holder's owned
        # load.  Without this, a shrunken fleet (post-kill: 2 survivors
        # over 3 shards, both necessarily over the stale static fair
        # share) livelocks: shed -> peer defers the free shard -> the
        # claim-anyway floor re-claims it -> shed again, and the shard
        # spends most of its time unowned.  A peer that holds NOTHING is
        # invisible to this scan, so we conservatively keep the shard —
        # the free-shard claim deferral already gives idle replicas
        # their window.
        peer_load: dict = {}
        for shard in range(self.num_shards):
            try:
                _version, record = self.locks[shard].get()
            except Exception:  # lint: allow-swallow(an unreadable lease record just vetoes shedding this tick; counted, retried next tick)
                metrics.note_swallowed("shard_lease_shed")
                return
            holder = (record or {}).get("holderIdentity") or ""
            expires = ((record or {}).get("renewTime", 0.0)
                       + (record or {}).get("leaseDurationSeconds",
                                            self.lease_duration))
            if holder and holder != self.identity and now < expires:
                peer_load[holder] = peer_load.get(holder, 0.0) \
                    + loads[shard]
        if not any(pl + loads[victim] <= fair
                   for pl in peer_load.values()):
            return
        from ..cli.leader_election import cas_release
        if cas_release(self.locks[victim], self.identity,
                       self.lease_duration):
            with self._lock:
                self._renewed.pop(victim, None)
            log.info("shard %d shed by %s (owned load %.1f > fair %.1f)",
                     victim, self.identity, mine, fair)
            metrics.note_shard_lease(victim, "shed")
            metrics.note_shard_rebalance("shed")
            metrics.clear_shard_owner(victim, self.identity)
            self._notify_change(victim, "shed")

    def _notify_change(self, shard: int, kind: str) -> None:
        hook = self.on_change
        if hook is None:
            return
        try:
            hook(shard, kind)
        except Exception:  # lint: allow-swallow(an observer must never kill the lease loop mid-transition; the miss is counted and the next tick re-notifies nothing worse than a late rescope)
            metrics.note_swallowed("lease_on_change")

    def _record(self, now: float) -> dict:
        return {"holderIdentity": self.identity,
                "renewTime": now,
                "leaseDurationSeconds": self.lease_duration}

    def _lose(self, shard: int, kind: str) -> None:
        with self._lock:
            was_owned = self._renewed.pop(shard, None) is not None
        if was_owned:
            log.warning("shard %d lease lost (%s): fencing writes and "
                        "abandoning the shard", shard, kind)
            metrics.note_shard_lease(shard, kind)
            metrics.note_shard_rebalance("lost")
            metrics.clear_shard_owner(shard, self.identity)
            self._notify_change(shard, kind)

    def _tick_shard(self, shard: int) -> None:
        plan = chaos_plan.PLAN
        now = time.time()
        with self._lock:
            renewed = self._renewed.get(shard)
        owned = renewed is not None
        if owned and plan is not None and \
                plan.fire(f"lease.clock_skew:{shard}"):
            # Injected clock skew: our clock claims the lease already
            # expired under us.  The only safe move is to abandon the
            # shard — the fence refuses its bind egress — and re-claim
            # through the normal CAS path (doc/CHAOS.md).
            self._lose(shard, "clock_skew")
            return
        lock = self.locks[shard]
        version, record = lock.get()
        holder = (record or {}).get("holderIdentity") or ""
        expires = ((record or {}).get("renewTime", 0.0)
                   + (record or {}).get("leaseDurationSeconds",
                                        self.lease_duration))
        if owned:
            if record is not None and holder != self.identity:
                # Another replica's CAS landed (our lease expired and
                # was stolen): we are no longer the owner, regardless of
                # what our clock thinks.
                self._lose(shard, "stolen_from")
                return
            cas_ok = False
            if not (plan is not None
                    and plan.fire(f"lease.cas_conflict:{shard}")):
                cas_ok = self._cas(lock, self._record(now), version)
            if cas_ok:
                with self._lock:
                    self._renewed[shard] = now
                return
            if now - renewed > self.renew_deadline:
                self._lose(shard, "renew_timeout")
            return
        # Not owned: claim free/expired leases (and our own stale record
        # — re-acquiring a lease we still hold at the store is the
        # normal recovery from an injected clock skew).
        if record is not None and holder and holder != self.identity \
                and now < expires:
            self._deferred_since.pop(shard, None)
            return  # live lease elsewhere
        if self.target_shards is not None and not holder:
            # Soft spread over FREE shards only (never claimed, or
            # cleanly released): at/over target, sit out one lease
            # duration so an under-loaded replica claims first — then
            # claim anyway (an orphan shard beats balance).  An EXPIRED
            # lease (holder set) is a dead replica's shard: steal it
            # immediately, spread be damned — the reclaim-within-one-
            # lease-duration failover bound outranks balance
            # (doc/TENANCY.md).
            with self._lock:
                owned = list(self._renewed)
            if self._over_target(owned):
                since = self._deferred_since.setdefault(shard, now)
                if now - since < self.lease_duration:
                    return
            else:
                self._deferred_since.pop(shard, None)
        if plan is not None and plan.fire(f"lease.cas_conflict:{shard}"):
            return  # injected: another replica won the claim race
        if not self._cas(lock, self._record(now), version):
            return  # genuinely lost the race; next tick re-reads
        self._deferred_since.pop(shard, None)
        kind = ("steal" if holder and holder != self.identity
                else "claim")
        with self._lock:
            self._renewed[shard] = now
        log.info("shard %d lease %sed by %s", shard, kind, self.identity)
        metrics.note_shard_lease(shard, kind)
        metrics.note_shard_rebalance(kind)
        metrics.set_shard_owner(shard, self.identity)
        if self._on_claim is not None:
            self._on_claim(shard)
        self._notify_change(shard, kind)

    def _over_target(self, owned) -> bool:
        """Whether claiming one more shard should defer for spread.
        Count-based without a load estimator (the PR 13 behavior);
        load-weighted with one: defer once this replica's owned load
        reaches the fair share its target fraction implies.  A +1 floor
        per shard keeps empty shards claimable-but-weighted (every shard
        costs at least a session to own), and any estimator failure
        degrades to the count rule — never to a stuck shard."""
        if self.target_shards is None:
            return False
        if self.shard_load is not None:
            try:
                loads = [max(float(self.shard_load(s)), 0.0) + 1.0
                         for s in range(self.num_shards)]
                total = sum(loads)
                mine = sum(loads[s] for s in owned)
                fair = total * (float(self.target_shards)
                                / max(self.num_shards, 1))
                return mine >= fair
            except Exception:  # lint: allow-swallow(load estimator failure degrades the deferral to the count rule; counted, and the orphan-beats-balance bound is unaffected)
                metrics.note_swallowed("shard_load_estimate")
        return len(owned) >= self.target_shards

    @staticmethod
    def _cas(lock: StoreLock, record: dict, version: int) -> bool:
        try:
            return lock.cas(record, version)
        except Exception:  # lint: allow-swallow(CAS conflict or unreachable store both mean "did not acquire"; the renew deadline fences a persistently failing renewal)
            return False

    def _publish(self) -> None:
        """Metrics + /debug/shards rows from the store's current lease
        records (covers shards owned by OTHER replicas too)."""
        now = time.time()
        with self._lock:
            renewed = dict(self._renewed)
        for shard in range(self.num_shards):
            try:
                _version, record = self.locks[shard].get()
            except Exception:  # lint: allow-swallow(debug/metrics publication is best-effort; an unreachable store already degrades the renew path visibly)
                metrics.note_swallowed("shard_lease_publish")
                continue
            holder = (record or {}).get("holderIdentity") or ""
            renew_time = (record or {}).get("renewTime", 0.0)
            duration = (record or {}).get("leaseDurationSeconds",
                                          self.lease_duration)
            owned_here = shard in renewed
            if holder:
                metrics.set_shard_owner(shard, holder)
                metrics.set_shard_lease_age(shard, max(0.0,
                                                       now - renew_time))
            shard_table.note_lease(shard, holder, renew_time, duration,
                                   owned_here)

    def _loop(self) -> None:
        while not self._stop.is_set():
            self.tick()
            self._stop.wait(self.retry_period)

    def start(self) -> "ShardLeaseManager":
        thread = threading.Thread(target=self._loop, daemon=True,
                                  name=f"shard-leases-{self.identity[:8]}")
        thread.start()
        self._thread = thread
        return self

    def stop(self, release: bool = True, timeout: float = 5.0) -> None:
        """Stop renewing.  ``release=True`` (clean shutdown) CAS-clears
        every owned lease so survivors claim immediately instead of
        waiting out the expiry; ``release=False`` simulates a crash —
        the soak's mid-run replica kill."""
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=timeout)
        if not release:
            with self._lock:
                self._renewed.clear()
            return
        from ..cli.leader_election import cas_release
        for shard in list(self.owned_shards()):
            if cas_release(self.locks[shard], self.identity,
                           self.lease_duration):
                metrics.note_shard_lease(shard, "release")
                metrics.note_shard_rebalance("release")
                metrics.clear_shard_owner(shard, self.identity)
        with self._lock:
            self._renewed.clear()
