"""Shard ownership table: the /debug/shards surface.

Follows the /debug/tenants pattern (metrics/tenants.py): writers are the
tenancy engine (per-session queue membership) and the lease manager
(ownership + lease timing); readers are the HTTP debug endpoints — one
lock, wholesale row swaps, JSON-ready snapshot.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional


class ShardTable:

    def __init__(self):
        self._lock = threading.Lock()
        self._rows: Dict[int, dict] = {}   # guarded-by: _lock
        self._replica = ""                 # guarded-by: _lock
        self._updated_wall = 0.0           # guarded-by: _lock

    def note_session(self, shard: int, queues, jobs: int,
                     replica: str = "", load: Optional[float] = None
                     ) -> None:
        """One shard micro-session closed: record what it actually
        scoped (the queues the shard map resolved this cycle) and the
        refreshed load EWMA feeding the claim targets (ROADMAP 2c)."""
        with self._lock:
            row = self._rows.setdefault(int(shard), {})
            row["queues"] = sorted(queues)
            row["jobs"] = int(jobs)
            row["sessions"] = row.get("sessions", 0) + 1
            row["last_session"] = round(time.time(), 3)
            if load is not None:
                row["load"] = round(float(load), 3)
            if replica:
                row["owner"] = replica
            self._replica = replica or self._replica
            self._updated_wall = time.time()

    def note_lease(self, shard: int, owner: Optional[str],
                   renew_time: float, lease_duration: float,
                   owned_here: bool) -> None:
        """The lease manager's view of one shard's lease record."""
        with self._lock:
            row = self._rows.setdefault(int(shard), {})
            row["owner"] = owner or ""
            row["owned_here"] = bool(owned_here)
            row["lease_renewed"] = round(renew_time, 3)
            row["lease_expires"] = round(renew_time + lease_duration, 3)
            self._updated_wall = time.time()

    def snapshot(self) -> dict:
        """The /debug/shards answer: shard -> owner -> queues ->
        lease expiry."""
        now = time.time()
        with self._lock:
            shards = {}
            for shard, row in sorted(self._rows.items()):
                doc = dict(row)
                expires = doc.get("lease_expires")
                if expires is not None:
                    doc["lease_expires_in_s"] = round(expires - now, 3)
                shards[str(shard)] = doc
            return {"shards": shards,
                    "replica": self._replica,
                    "updated": round(self._updated_wall, 3),
                    "age_s": (round(now - self._updated_wall, 3)
                              if self._updated_wall else None)}

    def clear(self) -> None:
        with self._lock:
            self._rows = {}
            self._replica = ""
            self._updated_wall = 0.0


shard_table = ShardTable()
