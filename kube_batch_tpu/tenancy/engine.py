"""TenancyEngine: per-shard micro-sessions in place of the global cycle.

The scheduler loop keeps its event-driven shape (churn wakes it,
coalescing window, periodic revalidation, crash-loop backoff — see
scheduler.py); the engine changes WHAT one loop iteration runs:

* a churn-woken iteration runs one shard-scoped session per DIRTY shard
  (the per-shard form of the coalesced micro-session), in ascending
  shard order — tenant A's storm schedules A's shard over and over
  while B's quiet shard is untouched until B churns;
* a periodic iteration (schedule_period expired with no churn) and the
  full-session floor run EVERY owned shard — the same revalidation
  cadence the global engine gets from its timeout cycles;
* each shard carries its OWN crash-loop backoff: a persistently failing
  shard (poisoned job, wedged tensorize) is skipped with exponential
  backoff while the other shards keep their schedule — chaos/SLO
  isolation, pinned by tests/test_tenancy.py.

With a ShardLeaseManager attached (active-active federation), only
OWNED shards run and every write is fenced on the shard lease
(view.py); without one, a single replica owns all shards.
"""

from __future__ import annotations

import logging
import time
from typing import Dict, Optional

from ..metrics import metrics
from .debug import shard_table
from .leases import ShardLeaseManager
from .pipeline import ShardPipeline, concurrent_shards_enabled
from .shards import ShardChurn, ShardLoad, ShardMap, tenancy_shards
from .view import ShardView

log = logging.getLogger(__name__)


def engine_from_env(scheduler) -> Optional["TenancyEngine"]:
    """Build the engine when KUBE_BATCH_TPU_TENANCY asks for shards;
    None keeps the single global engine (the control arm)."""
    shards = tenancy_shards()
    if not shards:
        return None
    return TenancyEngine(scheduler, ShardMap.from_env(shards))


class TenancyEngine:

    def __init__(self, scheduler, shard_map: ShardMap, replica: str = "",
                 lease_mgr: Optional[ShardLeaseManager] = None):
        self.scheduler = scheduler
        self.cache = scheduler.cache
        self.map = shard_map
        self.replica = replica or (lease_mgr.identity if lease_mgr
                                   else "single")
        self.leases: Optional[ShardLeaseManager] = None
        # Per-shard load EWMA (pods + churn rate): feeds the federation's
        # load-weighted claim targets (ROADMAP 2c) and /debug/shards.
        self.load = ShardLoad(shard_map.num_shards)
        self.churn = ShardChurn(shard_map, load=self.load)
        self.views = [ShardView(self.cache, shard, shard_map,
                                replica=self.replica)
                      for shard in range(shard_map.num_shards)]
        # Concurrent shard micro-sessions (doc/TENANCY.md "Concurrent
        # micro-sessions"): dirty shards pipeline their host phases
        # through each other's async dispatch windows, retiring in
        # deterministic shard order.  KUBE_BATCH_TPU_CONCURRENT_SHARDS=0
        # keeps the strictly sequential control arm.
        self.pipeline: Optional[ShardPipeline] = (
            ShardPipeline(self) if concurrent_shards_enabled() else None)
        # Per-shard crash-loop backoff (scheduler loop thread only).
        self._failures: Dict[int, int] = {}
        self._next_ok: Dict[int, float] = {}
        # Per-shard periodic floor (scheduler loop thread only): when a
        # shard last ran, so SUSTAINED churn in one shard cannot
        # suppress the quiet shards' schedule_period revalidation —
        # back-to-back churn-woken iterations would otherwise never see
        # an empty dirty set.
        self._last_run: Dict[int, float] = {}
        # Last full-cluster load refresh (scheduler loop thread only).
        self._loads_refreshed = 0.0
        if lease_mgr is not None:
            self.attach_leases(lease_mgr)
        # Per-shard churn attribution: the cache's external ingestion
        # paths call shard_churn(queue) alongside the churn_event wake.
        # Foreign cache objects without the attribute degrade to the
        # always-all-dirty periodic pass, like churn_event's fallback.
        try:
            self.cache.shard_churn = self.churn.note
        except AttributeError:  # lint: allow-swallow(read-only cache object: every loop iteration then runs as a periodic all-shards pass, the pre-tenancy cadence)
            pass

    def attach_leases(self, lease_mgr: ShardLeaseManager) -> None:
        """Wire active-active federation: ownership filters the shard
        walk, the lease fences the write egress, and a freshly claimed
        shard is marked dirty so its first session under this replica
        runs immediately (warm-started from the shared compile cache)."""
        self.leases = lease_mgr
        self.replica = lease_mgr.identity
        if lease_mgr._on_claim is None:
            lease_mgr._on_claim = self.churn.note_shard
        if getattr(lease_mgr, "shard_load", None) is None:
            # Load-weighted claim targets (ROADMAP 2c): the replica
            # mirrors the whole cluster, so its own EWMA is a usable
            # estimate of every shard's load — claim deferral weighs
            # load, not raw shard counts.
            lease_mgr.shard_load = self.load.load
        for view in self.views:
            view.replica = lease_mgr.identity
            view._lease_live = lease_mgr.lease_live

    def owned_shards(self):
        if self.leases is None:
            return range(self.map.num_shards)
        return self.leases.owned_shards()

    def run_cycle(self, force_full: bool = False) -> None:
        """One loop iteration: the dirty (or, on a periodic/full pass,
        every owned) shard's micro-session, failure-isolated per shard.
        Never raises — per-shard backoff replaces the global crash-loop
        backoff for shard-session failures."""
        dirty = self.churn.take()
        owned = list(self.owned_shards())
        now = time.time()
        if force_full or not dirty:
            # Periodic revalidation / full-session floor: every owned
            # shard runs (the global engine's timeout-cycle analog).
            run_set = list(owned)
        else:
            # Dirty shards, PLUS any owned shard that has not run for a
            # full schedule_period: one tenant's continuous storm keeps
            # the dirty set non-empty forever, and without this floor
            # the quiet shards would only revalidate at the FULL_EVERY
            # cadence — the global engine gives every job a look each
            # period, and so must the sharded one.
            period = max(self.scheduler.schedule_period, 1e-3)
            run_set = [s for s in owned
                       if s in dirty
                       or now - self._last_run.get(s, 0.0) >= period]
        if force_full:
            from ..models import incremental
            for shard in run_set:
                incremental.request_full(self.views[shard])
        runnable = []
        for shard in sorted(run_set):
            if self._next_ok.get(shard, 0.0) > now:
                # Backing off: the churn that asked for this session is
                # NOT absorbed — the shard stays dirty for the retry.
                self.churn.note_shard(shard)
                continue
            runnable.append(shard)
        self._refresh_loads(now)
        if self.pipeline is not None and len(runnable) > 1 \
                and "session_once" not in self.scheduler.__dict__:
            # Concurrent micro-sessions: successive shards' host phases
            # overlap their predecessors' device-dispatch windows; the
            # cluster-mutating retire halves run in this exact order.
            # An instance-level session_once (a test double / embedder
            # wrapper) cannot be split into halves, so it keeps the
            # sequential walk — the run_once test-double contract,
            # extended.
            self.pipeline.run(runnable)
        else:
            for shard in runnable:
                self._run_shard(shard)
        self._publish()

    # -- stop()/drain plumbing (any thread) ---------------------------------

    def request_drain(self) -> None:
        """Scheduler.stop(): the pipeline must stop issuing new shard
        dispatches and drain in flight before the loop joins."""
        if self.pipeline is not None:
            self.pipeline.request_drain()

    def abandon_inflight(self):
        """Scheduler.stop() after the join: abandon whatever a wedged
        loop left registered.  Returns the stuck shard ids."""
        if self.pipeline is None:
            return []
        return self.pipeline.abandon_inflight()

    # -- per-shard outcome bookkeeping (shared by the sequential arm and
    #    the pipeline's begin/retire halves) --------------------------------

    def _note_shard_failure(self, shard: int) -> None:
        """Failure bookkeeping — MUST run inside the except block (the
        log path reads sys.exc_info)."""
        failures = self._failures.get(shard, 0) + 1
        self._failures[shard] = failures
        period = max(self.scheduler.schedule_period, 1e-3)
        delay = min(self.scheduler._max_backoff,
                    period * (2.0 ** min(failures, 32)))
        self._next_ok[shard] = time.time() + delay
        self.churn.note_shard(shard)
        metrics.note_shard_session(shard, "error")
        metrics.register_schedule_attempt("error")
        metrics.note_cycle_failure("shard")
        metrics.set_degraded(f"shard{shard}_backoff", True)
        self.scheduler._log_cycle_error(f"shard{shard}")

    def _note_shard_ok(self, shard: int, view) -> None:
        if self._failures.pop(shard, None):
            metrics.set_degraded(f"shard{shard}_backoff", False)
        self._next_ok.pop(shard, None)
        metrics.note_shard_session(shard, "ok")
        load = self.load.note_session(shard, view._last_pods)
        shard_table.note_session(shard, view._last_queues,
                                 len(view._last_jobs),
                                 replica=self.replica, load=load)

    def _refresh_loads(self, now: float) -> None:
        """Fold EVERY shard's pod count into the load EWMA — owned or
        not — from this replica's full-cluster mirror (ROADMAP 2c).
        Per-session folds only cover shards this engine runs, and a
        fair-share computed from own-shards-only estimates (everyone
        else's shards floored at ~zero) made every replica think it was
        hogging the fleet — the shed oscillation the soak caught.  One
        O(jobs) walk under the cache mutex, at most once per second."""
        if now - self._loads_refreshed < 1.0:
            return
        self._loads_refreshed = now
        counts = [0] * self.map.num_shards
        shard_of = self.map.shard_of
        mutex = getattr(self.cache, "mutex", None)
        jobs = getattr(self.cache, "jobs", None)
        if jobs is None:
            return
        import contextlib
        with (mutex if mutex is not None else contextlib.nullcontext()):
            for job in jobs.values():
                if job.queue:
                    counts[shard_of(job.queue)] += len(job.tasks)
        for shard, pods in enumerate(counts):
            self.load.note_session(shard, pods)

    def _run_shard(self, shard: int) -> None:
        view = self.views[shard]
        self._last_run[shard] = time.time()
        try:
            self.scheduler.session_once(view, shard=shard)
        except Exception:  # per-shard failure isolation: the loop-survival contract, scoped
            self._note_shard_failure(shard)
        else:
            self._note_shard_ok(shard, view)

    def _publish(self) -> None:
        if self.leases is None:
            # Single-replica mode: this process owns every shard with no
            # lease; /debug/shards still answers ownership.
            for shard in range(self.map.num_shards):
                metrics.set_shard_owner(shard, self.replica)
